"""Benchmark: 3-hop GO traversal rate, TPU engine vs CPU storage path.

Prints ONE JSON line:
  {"metric": "3hop_go_edges_traversed_per_sec_per_chip",
   "value": <TPU edges/sec>, "unit": "edges/s",
   "vs_baseline": <TPU rate / CPU-storage-path rate>}

The graph is a synthetic LDBC-SNB-like social graph: every person has
at least one "knows" edge and out-degrees follow a clipped power law
(LDBC's knows distribution), so multi-hop expansion behaves like the
real workload instead of dead-ending on degree-0 seeds. Both paths run
the same semantics over the same store: the CPU baseline is this
framework's storage-processor scatter/gather loop (the role of the
reference's CPU storaged, QueryBoundProcessor); the TPU path is the
CSR snapshot + compiled multi-hop kernel, measured the way it serves
production load: a batch of independent queries per dispatch
(traverse.multi_hop_count_batch) to amortize launch overhead, exactly
as a graphd worker pool batches concurrent sessions.

Env knobs: BENCH_V, BENCH_E, BENCH_PARTS, BENCH_SEEDS, BENCH_STEPS,
BENCH_ITERS, BENCH_BATCH.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

V = int(os.environ.get("BENCH_V", 50_000))
E = int(os.environ.get("BENCH_E", 500_000))
PARTS = int(os.environ.get("BENCH_PARTS", 8))
SEEDS = int(os.environ.get("BENCH_SEEDS", 64))
STEPS = int(os.environ.get("BENCH_STEPS", 3))
ITERS = int(os.environ.get("BENCH_ITERS", 10))
BATCH = int(os.environ.get("BENCH_BATCH", 64))  # concurrent GO queries per dispatch


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def gen_edges(rng):
    """Power-law out-degrees with a floor of 1 (LDBC-knows-like): when
    E >= V every vertex keeps at least one out-edge (one reserved slot
    per vertex, the remaining E-V drawn from a clipped zipf(1.7) degree
    distribution); when E < V the floor is impossible — a warning is
    logged and degree-0 vertices are expected."""
    if E < V:
        log(f"WARNING: E={E} < V={V}; degree-1 floor impossible, "
            f"seeds may dead-end")
        srcs = rng.integers(0, V, E)
    else:
        deg = np.minimum(rng.zipf(1.7, V), 1000).astype(np.float64)
        extra = E - V
        deg = np.round(deg * (extra / deg.sum())).astype(np.int64)
        srcs = np.concatenate([
            np.arange(V, dtype=np.int64),          # the floor: 1 per vertex
            np.repeat(np.arange(V, dtype=np.int64), deg)])
        if len(srcs) > E:   # rounding overshoot: trim only floor-extras
            srcs = np.concatenate([srcs[:V], rng.permutation(srcs[V:])[:E - V]])
        elif len(srcs) < E:
            srcs = np.concatenate([srcs, rng.integers(0, V, E - len(srcs))])
    dsts = rng.integers(0, V, E)
    return srcs, dsts


def build_store():
    from nebula_tpu.kvstore import GraphStore
    from nebula_tpu.meta.schema_manager import AdHocSchemaManager
    from nebula_tpu.codec import Schema, RowWriter
    from nebula_tpu.storage import StorageService, StorageClient, NewVertex, NewEdge

    sm = AdHocSchemaManager()
    sm.set_num_parts(1, PARTS)
    person = Schema([])           # prop-free: bench isolates traversal
    knows = Schema([])
    sm.add_tag(1, 1, "person", person)
    sm.add_edge(1, 1, "knows", knows)
    store = GraphStore()
    for p in range(1, PARTS + 1):
        store.add_part(1, p)
    svc = StorageService(store, sm)
    client = StorageClient(sm, local_service=svc)

    rng = np.random.default_rng(42)
    log(f"generating power-law graph V={V} E={E} ...")
    srcs, dsts = gen_edges(rng)
    empty_row = RowWriter(person).encode()
    t0 = time.time()
    vertices = [NewVertex(int(v), [(1, empty_row)]) for v in range(V)]
    client.add_vertices(1, vertices)
    edge_row = RowWriter(knows).encode()
    edges = [NewEdge(int(s), 1, int(i), int(d), edge_row)
             for i, (s, d) in enumerate(zip(srcs, dsts))]
    B = 100_000
    for i in range(0, E, B):
        client.add_edges(1, edges[i:i + B])
    log(f"store loaded in {time.time()-t0:.1f}s")
    seed_sets = [[int(s) for s in rng.choice(V, SEEDS, replace=False)]
                 for _ in range(BATCH)]
    return store, sm, client, seed_sets


def bench_tpu(store, sm, seed_sets):
    import jax
    import jax.numpy as jnp
    from nebula_tpu.engine_tpu import traverse
    from nebula_tpu.engine_tpu.csr import build_snapshot

    log(f"jax devices: {jax.devices()}")
    t0 = time.time()
    snap = build_snapshot(store, sm, 1, PARTS)
    log(f"CSR snapshot built in {time.time()-t0:.1f}s "
        f"({snap.total_edges} stored edges, cap_v={snap.cap_v}, "
        f"cap_e={snap.cap_e})")
    f_batch = jnp.asarray(np.stack(
        [snap.frontier_from_vids(s) for s in seed_sets]))
    req = jnp.asarray(traverse.pad_edge_types([1]))
    args = (f_batch, jnp.int32(STEPS), snap.aligned_kernel(), req)
    t0 = time.time()
    counts = np.asarray(traverse.multi_hop_count_batch(*args))
    per_batch = int(counts.sum())
    log(f"first run (compile): {time.time()-t0:.1f}s, "
        f"{per_batch} edges traversed per {len(seed_sets)}-query batch "
        f"(q0={int(counts[0])})")
    t0 = time.time()
    for _ in range(ITERS):
        out = traverse.multi_hop_count_batch(*args)
    out.block_until_ready()
    dt = time.time() - t0
    eps = per_batch * ITERS / dt
    qps = len(seed_sets) * ITERS / dt
    log(f"TPU: {ITERS} x {len(seed_sets)}-query batches of {STEPS}-hop GO "
        f"in {dt*1000:.1f}ms -> {eps:,.0f} edges/s, {qps:,.1f} QPS")
    return eps, int(counts[0])


def bench_cpu(client, seeds, expected_total):
    """The CPU storage scatter/gather path: per-hop get_neighbors fan-out
    with frontier dedup, exactly what GoExecutor drives. Same seed set as
    the TPU measurement's first batch entry (one pass — the rate is what
    is compared)."""
    t0 = time.time()
    edges_traversed = 0
    frontier = seeds
    for _ in range(STEPS):
        resp = client.get_neighbors(1, frontier, [1], edge_props=[])
        seen = set()
        nxt = []
        for v in resp.vertices:
            for e in v.edges:
                edges_traversed += 1
                if e.dst not in seen:
                    seen.add(e.dst)
                    nxt.append(e.dst)
        frontier = nxt
    dt = time.time() - t0
    eps = edges_traversed / dt
    log(f"CPU: {STEPS}-hop GO from {len(seeds)} seeds: "
        f"{edges_traversed} edges in {dt:.2f}s -> {eps:,.0f} edges/s")
    if edges_traversed != expected_total:
        log(f"WARNING: CPU/TPU edge count mismatch "
            f"({edges_traversed} vs {expected_total})")
    return eps


def main():
    store, sm, client, seed_sets = build_store()
    tpu_eps, q0_edges = bench_tpu(store, sm, seed_sets)
    cpu_eps = bench_cpu(client, seed_sets[0], q0_edges)
    print(json.dumps({
        "metric": "3hop_go_edges_traversed_per_sec_per_chip",
        "value": round(tpu_eps, 1),
        "unit": "edges/s",
        "vs_baseline": round(tpu_eps / cpu_eps, 2),
    }))


if __name__ == "__main__":
    main()
