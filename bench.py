"""Flagship benchmark: 3-hop GO on an LDBC-SNB-shaped graph, TPU engine
vs this framework's own CPU storage paths.

Prints ONE JSON line:
  {"metric": "3hop_go_edges_traversed_per_sec_per_chip",
   "value": <TPU batched traversal rate>, "unit": "edges/s",
   "vs_baseline": <TPU rate / cpp-scan CPU storaged rate>, ...extras}

Methodology (ref: storage/test/QueryBoundBenchmark.cpp:181-191 measures
the getBound processor over a loaded store; here every tier runs over
the SAME store through the real service layers):

- Graph: LDBC-SNB-shaped person/knows at SF-300-ish scale by default —
  V=1.2M persons with `age`, E=50M forward knows edges with a
  `ts` property (clipped-zipf out-degrees, the knows distribution
  shape). Stored rows = 100M (out + reverse copies) -> >=1e8 device
  edge slots. Loaded through the native C++ engine's sorted bulk
  ingest (the SST-ingest path, RocksEngine.cpp:360 role).
- Tier 1 (headline): batched 3-hop traversal throughput, BATCH
  concurrent GO queries per dispatch (the graphd worker-pool batching
  model), edges-traversed/s + QPS + modeled HBM bytes/s vs peak.
- Tier 2: FULL query latency through the real query engine (parse ->
  plan -> device traversal -> pushed-down filter compile -> columnar
  materialization of edge+dst props): batch=1 p50/p99/QPS for
    GO 3 STEPS FROM <seed> OVER knows WHERE knows.ts > <cut>
    YIELD knows._dst, knows.ts, $$.person.age
  with <cut> tuned so each query yields ~TARGET_ROWS rows; the same
  query also timed once on the CPU path (tpu disabled) for contrast.
- Tier 3: concurrent sessions — N closed-loop threads through the
  cross-session group-commit dispatcher (dense routing pinned);
  aggregate QPS plus how many queries shared device dispatches
  (lane-matrix rounds).
- Baselines (labeled): [cpp-scan storaged] = this framework's storage
  scatter/gather hot loop over the native C++ engine (prefix_dedup
  scan); [python-loop storaged] = the same loop over the pure-python
  MemEngine, measured at reduced scale and reported as a rate.
  vs_baseline compares against the STRONGER (cpp-scan) baseline.

Env knobs: BENCH_V, BENCH_E, BENCH_PARTS, BENCH_SEEDS, BENCH_STEPS,
BENCH_ITERS, BENCH_BATCH, BENCH_PY_E (python-baseline edge count),
BENCH_TARGET_ROWS, BENCH_LAT_N, BENCH_KERNEL (packed|int8|auto —
auto times both batched-hop variants and reports the faster).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

V = int(os.environ.get("BENCH_V", 1_200_000))
E = int(os.environ.get("BENCH_E", 50_000_000))
PARTS = int(os.environ.get("BENCH_PARTS", 8))
SEEDS = int(os.environ.get("BENCH_SEEDS", 64))
STEPS = int(os.environ.get("BENCH_STEPS", 3))
ITERS = int(os.environ.get("BENCH_ITERS", 10))
BATCH = int(os.environ.get("BENCH_BATCH", 128))  # concurrent GO queries/dispatch
PY_E = int(os.environ.get("BENCH_PY_E", 2_000_000))
TARGET_ROWS = int(os.environ.get("BENCH_TARGET_ROWS", 2_000))
LAT_N = int(os.environ.get("BENCH_LAT_N", 30))
KERNEL = os.environ.get("BENCH_KERNEL", "auto")

TS_MAX = 1_000_000_000
HBM_PEAK_GBS = 819.0   # v5e HBM bandwidth

_BIAS64 = np.uint64(1 << 63)
_BIAS32 = np.uint32(1 << 31)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def gen_degrees(rng, v, e):
    """Clipped-zipf out-degrees with a floor of 1 (LDBC knows shape)."""
    deg = np.minimum(rng.zipf(1.7, v), 1000).astype(np.float64)
    extra = e - v
    deg = np.round(deg * (extra / deg.sum())).astype(np.int64)
    srcs = np.concatenate([np.arange(v, dtype=np.int64),
                           np.repeat(np.arange(v, dtype=np.int64), deg)])
    if len(srcs) > e:
        srcs = np.concatenate([srcs[:v], rng.permutation(srcs[v:])[:e - v]])
    elif len(srcs) < e:
        srcs = np.concatenate([srcs, rng.integers(0, v, e - len(srcs))])
    return srcs


def _row_template(schema, field, probe_value=0):
    """Fixed-slot row bytes with the int field's 8 LE bytes at the tail
    (single-int-field schemas only — asserted)."""
    from nebula_tpu.codec import RowWriter
    row = RowWriter(schema).set(field, probe_value).encode()
    assert len(row) >= 9
    return row[:-8]


class _Recs:
    """Vectorized [u32 klen][key][u32 vlen][row] record building."""

    def __init__(self, n, key_fields, row_hdr: bytes):
        self.rec_dt = np.dtype(
            [("klen", "<u4")] + key_fields
            + [("vlen", "<u4"), ("hdr", f"V{len(row_hdr)}"), ("pv", "<i8")])
        self.a = np.zeros(n, self.rec_dt)
        klen = sum(np.dtype(t).itemsize for _, t in key_fields)
        self.a["klen"] = klen
        self.a["vlen"] = len(row_hdr) + 8
        self.a["hdr"] = np.frombuffer(row_hdr, dtype=f"V{len(row_hdr)}")[0]

    def tobytes(self):
        return self.a.tobytes()


EDGE_KEY_FIELDS = [("part", ">u4"), ("kind", "u1"), ("src", ">u8"),
                   ("etype", ">u4"), ("rank", ">u8"), ("dst", ">u8"),
                   ("ver", ">u8")]
VERT_KEY_FIELDS = [("part", ">u4"), ("kind", "u1"), ("vid", ">u8"),
                   ("tag", ">u4"), ("ver", ">u8")]


def bulk_load_snb(engine, tag_id, etype, person_schema, knows_schema,
                  v, e, parts, rng):
    """Vectorized sorted bulk ingest of the SNB-shaped person/knows
    graph into one native engine (the SST-ingest path). Returns the
    generated (srcs, dsts) so callers can derive seed sets. Shared by
    bench.py and scripts/concurrency_sweep.py."""
    t0 = time.time()
    srcs = gen_degrees(rng, v, e)
    dsts = rng.integers(0, v, e).astype(np.int64)
    ts = rng.integers(0, TS_MAX, e).astype(np.int64)
    ages = rng.integers(18, 80, v).astype(np.int64)
    ranks = np.arange(e, dtype=np.int64)
    ver = np.uint64((1 << 64) - 1 - time.time_ns() // 1000)
    vhdr = _row_template(person_schema, "age")
    ehdr = _row_template(knows_schema, "ts")
    log(f"  generated in {time.time()-t0:.1f}s; bulk ingest "
        f"({2*e + v} rows, sorted per (part, kind) bucket)...")

    t0 = time.time()
    src_part = (srcs.view(np.uint64) % np.uint64(parts)).astype(np.int64) + 1
    dst_part = (dsts.view(np.uint64) % np.uint64(parts)).astype(np.int64) + 1
    vid_part = (np.arange(v, dtype=np.int64).view(np.uint64)
                % np.uint64(parts)).astype(np.int64) + 1
    # biased etype codes (python-int arithmetic so the intended uint32
    # wraparound never trips numpy's overflow warning)
    et_b = np.uint32(int(etype) + int(_BIAS32))
    et_rev_b = np.uint32((int(_BIAS32) - int(etype)) & 0xFFFFFFFF)
    for p in range(1, parts + 1):
        # vertices of part p (kind 1 sorts before kind 2)
        sel = np.nonzero(vid_part == p)[0]
        vr = _Recs(len(sel), VERT_KEY_FIELDS, vhdr)
        vr.a["part"], vr.a["kind"], vr.a["ver"] = p, 1, ver
        vids = np.sort(sel.astype(np.int64))
        vr.a["vid"] = vids.view(np.uint64) + _BIAS64
        vr.a["tag"] = np.uint32(tag_id) + _BIAS32
        vr.a["pv"] = ages[vids]
        engine.ingest_packed(vr.tobytes(), len(sel))
        # edges of part p: forward rows (src here) + reverse rows
        fwd = np.nonzero(src_part == p)[0]
        rev = np.nonzero(dst_part == p)[0]
        n = len(fwd) + len(rev)
        er = _Recs(n, EDGE_KEY_FIELDS, ehdr)
        er.a["part"], er.a["kind"], er.a["ver"] = p, 2, ver
        row_src = np.concatenate([srcs[fwd], dsts[rev]])
        row_dst = np.concatenate([dsts[fwd], srcs[rev]])
        row_et = np.concatenate([np.full(len(fwd), et_b, np.uint32),
                                 np.full(len(rev), et_rev_b, np.uint32)])
        row_rank = np.concatenate([ranks[fwd], ranks[rev]])
        row_ts = np.concatenate([ts[fwd], ts[rev]])
        order = np.lexsort((row_dst, row_rank, row_et, row_src))
        er.a["src"] = row_src[order].view(np.uint64) + _BIAS64
        er.a["etype"] = row_et[order]
        er.a["rank"] = row_rank[order].view(np.uint64) + _BIAS64
        er.a["dst"] = row_dst[order].view(np.uint64) + _BIAS64
        er.a["pv"] = row_ts[order]
        engine.ingest_packed(er.tobytes(), n)
        log(f"  part {p}: {len(sel)} vertices + {n} edge rows")
    log(f"store loaded in {time.time()-t0:.1f}s "
        f"({engine.total_keys()} keys)")
    return srcs, dsts


def load_cluster():
    """InProcCluster over the native C++ engine, bulk-loaded with the
    vectorized sorted-ingest path."""
    from nebula_tpu import native as native_mod
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.engine_tpu import TpuGraphEngine
    from nebula_tpu.kvstore.nativeengine import NativeEngine

    if not native_mod.available():
        raise SystemExit("bench requires the native engine (make -C native)")

    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu,
                            engine_factory=lambda sid: NativeEngine())
    conn = cluster.connect()
    conn.must(f"CREATE SPACE snb(partition_num={PARTS}, replica_factor=1)")
    conn.must("USE snb")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(ts int)")
    sid = cluster.meta.get_space("snb").value().space_id
    tag_id = cluster.sm.tag_id(sid, "person")
    etype = cluster.sm.edge_type(sid, "knows")
    person_schema = cluster.sm.tag_schema(sid, tag_id).value()
    knows_schema = cluster.sm.edge_schema(sid, etype).value()
    engine = cluster.store.space_engine(sid)

    rng = np.random.default_rng(42)
    log(f"generating SNB-shaped graph V={V} E={E} (x2 stored rows)...")
    bulk_load_snb(engine, tag_id, etype, person_schema, knows_schema,
                  V, E, PARTS, rng)
    seed_sets = [[int(s) for s in rng.choice(V, SEEDS, replace=False)]
                 for _ in range(BATCH)]
    return cluster, tpu, conn, sid, etype, seed_sets


def bench_tpu_batched(cluster, tpu, sid, etype, seed_sets):
    import jax
    import jax.numpy as jnp
    from nebula_tpu.engine_tpu import traverse

    log(f"jax devices: {jax.devices()}")
    t0 = time.time()
    snap = tpu.snapshot(sid)
    # the engine may decline transiently while a background repack
    # folds the bulk load (e.g. a pre-load snapshot whose delta pull
    # exceeded the change ring) — CPU would serve meanwhile; the bench
    # waits for the device snapshot it exists to measure
    while snap is None and time.time() - t0 < 900:
        log("snapshot declined (background repack in flight); waiting...")
        time.sleep(5)
        snap = tpu.snapshot(sid)
    assert snap is not None
    log(f"CSR snapshot built in {time.time()-t0:.1f}s "
        f"({snap.total_edges} stored edges, cap_v={snap.cap_v}, "
        f"cap_e={snap.cap_e}, slots={snap.num_parts*snap.cap_e})")
    t0 = time.time()
    ak, chunk, group = snap.aligned_kernel()
    log(f"aligned layout built in {time.time()-t0:.1f}s "
        f"(E_pad={int(ak.src.shape[0])}, chunk={chunk})")
    f_batch = jnp.asarray(np.stack(
        [snap.frontier_from_vids(s) for s in seed_sets]))
    req = jnp.asarray(traverse.pad_edge_types([etype]))
    args = (f_batch, jnp.int32(STEPS), ak, req)
    kw = dict(chunk=chunk, group=group)
    variants = {"int8": traverse.multi_hop_count_batch,
                "packed": traverse.multi_hop_count_batch_packed}
    if KERNEL in variants:
        picks = [KERNEL]
    else:   # auto: time both, keep the faster for the measured runs
        picks = list(variants)
    timed = {}
    for name in picks:
        fn = variants[name]
        t0 = time.time()
        counts = np.asarray(fn(*args, **kw))
        log(f"kernel[{name}]: compile+1 {time.time()-t0:.1f}s")
        best = float("inf")      # min-of-3: one scheduling hiccup must
        for _ in range(3):       # not mispick the measured kernel
            t0 = time.time()
            out = fn(*args, **kw)
            out.block_until_ready()
            best = min(best, time.time() - t0)
        timed[name] = best
    pick = min(timed, key=timed.get)
    kernel_fn = variants[pick]
    counts = np.asarray(kernel_fn(*args, **kw))
    per_batch = int(counts.sum())
    log(f"kernel pick: {pick} ({ {k: round(v*1e3) for k, v in timed.items()} }"
        f" ms/dispatch), {per_batch} edges per {len(seed_sets)}-query batch "
        f"(q0={int(counts[0])})")
    t0 = time.time()
    for _ in range(ITERS):
        out = kernel_fn(*args, **kw)
    out.block_until_ready()
    dt = time.time() - t0
    eps = per_batch * ITERS / dt
    qps = len(seed_sets) * ITERS / dt
    # modeled HBM traffic, accounting the PACKED edge widths (narrow-
    # width CSR, docs/manual/13-device-speed.md): per hop the kernel
    # reads E_pad frontier rows (128B int8 / 16B packed) + the E_pad
    # int32 src-index stream + ~3 passes over the [NC,128] i32 chunk
    # sums + boundary rows; the per-DISPATCH type-gate pass reads the
    # aligned etype stream once at its packed width (int8 when the
    # space's types fit, else int32 — dtype_widths records which).
    e_pad = int(ak.src.shape[0])
    ns = int(ak.cbound.shape[0]) - 1
    nc = e_pad // chunk
    row_b = 16 if pick == "packed" else 128
    widths = snap.dtype_widths()
    et_b = int(np.dtype(ak.etype.dtype).itemsize)
    src_idx_b = 4                     # aligned src slots are global int32
    bytes_per_hop = (e_pad * (row_b + src_idx_b)
                     + nc * 128 * 4 * 3 + ns * 128 * 4 * 2)
    bytes_per_dispatch = e_pad * et_b     # type gate, once per dispatch
    gbs = ((bytes_per_hop * STEPS + bytes_per_dispatch) * ITERS
           / dt / 1e9)
    hbm_model = {"row_bytes": row_b, "src_index_bytes": src_idx_b,
                 "etype_bytes": et_b, "e_pad": e_pad,
                 "bytes_per_hop": bytes_per_hop,
                 "bytes_per_dispatch": bytes_per_dispatch,
                 "csr_widths": widths}
    log(f"TPU tier1[{pick}]: {ITERS} x {len(seed_sets)}-query batches of "
        f"{STEPS}-hop GO in {dt*1000:.1f}ms -> {eps:,.0f} edges/s, "
        f"{qps:,.1f} QPS, modeled HBM {gbs:,.0f} GB/s "
        f"({100*gbs/HBM_PEAK_GBS:.0f}% of {HBM_PEAK_GBS:.0f} peak); "
        f"packed widths {widths}")
    return eps, qps, gbs, int(counts[0]), snap, pick, hbm_model


def span_breakdown_run(run_queries, n_samples):
    """Force-sample `n_samples` queries through the tracer (the
    X-Trace arm knob) and reduce their span trees to per-stage p50/p95
    — BENCH_*.json tracks WHERE the time goes (dispatcher_wait /
    kernel / materialize / encode), not just end-to-end QPS. The
    forced-sample pass runs OUTSIDE the measured loops so sampling
    overhead never touches the headline numbers.

    The same sampled traces feed the critical-path analyzer (ISSUE
    12): the artifact's `attribution` block must explain where the
    wall time went — per-(span, host) self-time shares plus the mean
    explained fraction (common/critpath.py)."""
    from nebula_tpu.common import critpath
    from nebula_tpu.common.tracing import stage_breakdown, tracer
    # identify NEW traces by id, not ring position: the ring is
    # bounded, so once full its length stops growing and a positional
    # slice would silently drop the traces this pass just sampled
    before = {t["trace_id"] for t in tracer.ring.snapshot()}
    tracer.arm(n_samples)
    run_queries()
    tracer.arm(0)
    traces = [t for t in tracer.ring.snapshot()
              if t["trace_id"] not in before
              and not t.get("remote_fragment")]
    out = stage_breakdown(traces)
    out["sampled_traces"] = len(traces)
    out["attribution"] = critpath.aggregate(traces)
    return out


def bench_full_queries(conn, tpu, snap, etype, seed_sets):
    """Tier 2: the REAL query path — parse, plan, device traversal,
    pushed-down filter compile, columnar YIELD of edge+dst props."""
    import jax.numpy as jnp
    from nebula_tpu.engine_tpu import traverse

    # pick the ts cut so one 3-hop query yields ~TARGET_ROWS rows:
    # final-hop active edges * selectivity = target
    req = jnp.asarray(traverse.pad_edge_types([etype]))
    f0 = jnp.asarray(snap.frontier_from_vids([seed_sets[0][0]]))
    _, active = traverse.multi_hop(f0, jnp.int32(STEPS), snap.kernel, req)
    final_edges = max(int(np.asarray(active).sum()), 1)
    sel = min(TARGET_ROWS / final_edges, 1.0)
    cut = int(TS_MAX * (1 - sel))
    log(f"tier2 filter: final-hop edges ~{final_edges} per query, "
        f"ts > {cut} (selectivity {sel:.2%}, ~{TARGET_ROWS} rows)")

    def q(seed):
        return (f"GO {STEPS} STEPS FROM {seed} OVER knows "
                f"WHERE knows.ts > {cut} "
                f"YIELD knows._dst, knows.ts, $$.person.age")

    seeds = [s[0] for s in seed_sets[:LAT_N]]
    r = conn.must(q(seeds[0]))      # warm/compile
    nrows = len(r.rows)
    served0 = tpu.stats["go_served"]
    fused0 = tpu.stats["fused_launches"]
    h2d0 = tpu.prefetch_stats()["h2d_overlap_us"]
    lats = []
    profiles = []                   # per-query stage breakdown + mode
    t0 = time.time()
    for seed in seeds:
        seq0 = tpu.profile_seq
        t1 = time.time()
        r = conn.must(q(seed))
        lats.append((time.time() - t1) * 1000)
        if tpu.profile_seq != seq0 and tpu.last_profile:
            profiles.append(dict(tpu.last_profile))
    wall = time.time() - t0
    assert tpu.stats["go_served"] - served0 == len(seeds), tpu.stats
    lats = np.sort(np.array(lats))
    p50 = float(np.percentile(lats, 50))
    p99 = float(np.percentile(lats, 99))
    qps1 = len(seeds) / wall
    # where does the time go, and which mode served each query
    # (round-3 verdict: the per-stage profile existed but was never
    # reported per tier-2 query)
    modes: dict = {}
    stage_med = {}
    for pr in profiles:
        modes[pr["mode"]] = modes.get(pr["mode"], 0) + 1
    for k in ("snapshot_us", "kernel_us", "materialize_us"):
        vs = [pr[k] for pr in profiles]
        stage_med[k] = int(np.median(vs)) if vs else 0
    log(f"TPU tier2 (batch=1 FULL query, ~{nrows} rows/query): "
        f"p50={p50:.1f}ms p99={p99:.1f}ms, {qps1:.1f} QPS sequential; "
        f"modes={modes} stage medians(us)={stage_med}; "
        f"native_encode_rows={tpu.stats['native_encode_rows']} "
        f"(fallback={tpu.stats['encode_fallback_rows']})")
    # CPU contrast on the same cluster/queries (a seed subset — the
    # cpp-scan path is ~100x slower per query)
    tpu.enabled = False
    cpu_lats = []
    try:
        for seed in seeds[:max(3, len(seeds) // 4)]:
            t1 = time.time()
            rc = conn.must(q(seed))
            cpu_lats.append((time.time() - t1) * 1000)
    finally:
        tpu.enabled = True
    cpu_ms = float(np.percentile(np.array(cpu_lats), 50))
    rt = conn.must(q(seeds[len(cpu_lats) - 1]))
    ident = sorted(map(str, rt.rows)) == sorted(map(str, rc.rows))
    log(f"CPU tier2 same queries: p50={cpu_ms:.0f}ms over {len(cpu_lats)} "
        f"seeds (cpp-scan storaged path); result identity: {ident}")
    assert ident, "CPU/TPU full-query results diverged"
    # span-level breakdown from a forced-sample pass (off the clock)
    sb_seeds = seeds[:max(3, len(seeds) // 2)]
    spans2 = span_breakdown_run(
        lambda: [conn.must(q(s)) for s in sb_seeds], len(sb_seeds))
    log(f"tier2 span breakdown (us): {spans2}")
    return p50, p99, qps1, cpu_ms, {"modes": modes,
                                    "span_breakdown": spans2,
                                    "stage_median_us": stage_med,
                                    # fused-loop engagement during the
                                    # tier-2 window (batch=1 queries
                                    # fuse only on the agg/window
                                    # paths — tier-3 is the fused
                                    # loop's real showcase)
                                    "fused_launches":
                                        tpu.stats["fused_launches"]
                                        - fused0,
                                    "h2d_overlap_us":
                                        tpu.prefetch_stats()
                                        ["h2d_overlap_us"] - h2d0,
                                    # mesh serving matrix (empty on an
                                    # unmeshed bench run; populated by
                                    # --mesh-dryrun and meshed boxes)
                                    "mesh_served": dict(tpu.mesh_served),
                                    "mesh_declined": {
                                        f: dict(d) for f, d in
                                        tpu.mesh_decline_reasons.items()},
                                    # degradation ladder: breaker state
                                    # + trip/degrade/deadline counters
                                    # (all zero on a healthy run)
                                    "robustness": tpu.robustness_stats(),
                                    # histogram bucket vectors + flight
                                    # trigger counts (ISSUE 10)
                                    **_obs_block()}


def bench_stats_query(conn, tpu, seed_sets):
    """Stats pushdown at SNB scale: GO | YIELD COUNT/SUM/AVG served as
    one masked device reduction (engine_tpu/aggregate.py — the
    bound_stats role, ref storage.thrift StatType) vs the CPU pipe's
    materialize-then-aggregate over the same query."""
    def q(seed):
        return (f"GO {STEPS} STEPS FROM {seed} OVER knows "
                f"YIELD knows.ts AS t | YIELD COUNT(*) AS n, "
                f"SUM($-.t) AS s, AVG($-.t) AS a")
    seeds = [s[0] for s in seed_sets[:max(3, LAT_N // 4)]]
    conn.must(q(seeds[0]))          # warm/compile
    a0 = tpu.stats["agg_served"]
    s0 = tpu.stats["agg_sparse_served"]
    d0 = tpu.stats["agg_declined"]
    lats = []
    for seed in seeds:
        t1 = time.time()
        rt = conn.must(q(seed))
        lats.append((time.time() - t1) * 1000)
    served = tpu.stats["agg_served"] - a0
    p50 = float(np.percentile(np.array(lats), 50))
    tpu.enabled = False
    try:
        t1 = time.time()
        rc = conn.must(q(seeds[-1]))
        cpu_ms = (time.time() - t1) * 1000
    finally:
        tpu.enabled = True
    ident = rt.rows == rc.rows
    log(f"stats query (COUNT/SUM/AVG over {STEPS}-hop edges): device "
        f"p50={p50:.1f}ms ({served}/{len(seeds)} device-served), CPU "
        f"pipe {cpu_ms:.0f}ms; identity: {ident}")
    assert ident, (rt.rows, rc.rows)
    return {"p50_ms": round(p50, 1), "cpu_pipe_ms": round(cpu_ms, 1),
            "device_served": int(served),
            "sparse_served": int(tpu.stats["agg_sparse_served"] - s0),
            "declined": int(tpu.stats["agg_declined"] - d0),
            "decline_reasons": dict(tpu.agg_decline_reasons)}


def bench_concurrent(cluster, tpu, seed_sets, seconds=6.0, sessions=8):
    """Tier 3: concurrent sessions through the cross-session
    dispatcher — N closed-loop threads firing the tier-2 query shape;
    aggregate QPS + window coalescing (PARITY.md Concurrency's
    measurement, in-process at bench scale so it lands in the driver
    artifact)."""
    import threading
    sessions = min(sessions, len(seed_sets))   # BENCH_BATCH can be < 8
    hubs = [s[0] for s in seed_sets[:sessions]]
    conns = []
    for _ in range(sessions):
        c = cluster.connect()
        c.must("USE snb")
        conns.append(c)

    def tier3_q(k):
        return (f"GO {STEPS} STEPS FROM {hubs[k]} OVER knows "
                f"WHERE knows.ts > {TS_MAX - 1} YIELD knows._dst")

    # compile + calibration warmup OFF the clock (tier-1/2 warm their
    # compiles the same way): two concurrent barrages so the batched
    # window shapes compile and the engine's one-shot lane-vs-vmapped
    # kernel calibration runs before measurement starts
    for _ in range(2):
        warm = [threading.Thread(target=lambda k=k: conns[k].must(
            tier3_q(k))) for k in range(sessions)]
        for t in warm:
            t.start()
        for t in warm:
            t.join()
    if tpu.batched_kernel_calibrations:
        log(f"tier3 batched-kernel calibration: "
            f"{tpu.batched_kernel_calibrations}")
    b0 = {k: tpu.stats[k] for k in ("batched_dispatches",
                                    "batched_queries",
                                    "batched_lane_rounds",
                                    "disp_rounds", "disp_group_keys",
                                    "early_releases", "leader_handoffs",
                                    "native_encode_rows",
                                    "group_wait_us_total",
                                    "group_wait_count",
                                    "fused_launches")}
    pf0 = tpu.prefetch_stats()
    errs = []

    def measure(secs):
        """One closed-loop measured window over all sessions."""
        stop = threading.Event()
        counts = [0] * sessions

        def worker(k):
            q = tier3_q(k)
            while not stop.is_set():
                try:
                    conns[k].must(q)
                    counts[k] += 1
                except Exception as ex:   # noqa: BLE001 — recorded,
                    errs.append(repr(ex))  # fails the run
                    return

        threads = [threading.Thread(target=worker, args=(k,),
                                    name=f"bench-t3-{k}")
                   for k in range(sessions)]
        t0 = time.time()
        for t in threads:
            t.start()
        time.sleep(secs)
        stop.set()
        for t in threads:
            # a round in flight at stop must complete; one full-scale
            # dense round on the CPU fallback can take minutes
            t.join(timeout=300)
        w = time.time() - t0
        assert not [t for t in threads if t.is_alive()], \
            "tier3 stragglers would skew the CPU baselines"
        assert not errs, errs[:2]
        return sum(counts), w

    # OVERHEAD PROOF (ISSUE 13 acceptance): the same measured loop
    # runs twice on the same warm engine — sampler OFF (profile_hz=0,
    # no sampler thread) then ON at the default 19 Hz — and the
    # artifact records both QPS numbers plus the sampler's own
    # measured self-time. The hz=19 window also supplies the tier's
    # `profile` block (top self-time frames + top contended locks
    # during the measured loop).
    from nebula_tpu.common import profiler as prof_mod
    prof_mod.ensure_started()
    prof_mod.profiler.set_hz(0)
    total0, wall0 = measure(seconds)
    qps_hz0 = total0 / wall0
    prof_mod.profiler.reset()
    prof_mod.profiler.set_hz(19.0)
    lock0 = {s["name"]: s["wait_us_total"]
             for s in prof_mod.lock_table(50)}
    total, wall = measure(seconds)
    qps_hz19 = total / wall
    # sampler state sampled BEFORE disarming: the artifact must show
    # the hz the profiled window actually ran at, not the cleared 0
    sampler_state = prof_mod.profiler.state()
    prof_mod.profiler.set_hz(0)
    prof_top = prof_mod.profiler.top(window=600, n=20)
    top_share = round(sum(f["share"] for f in prof_top["frames"]), 4)
    locks_delta = sorted(
        ({"name": s["name"], "contended": s["contended"],
          "wait_us": s["wait_us_total"] - lock0.get(s["name"], 0),
          "last_holder": s["last_holder"]}
         for s in prof_mod.lock_table(50)),
        key=lambda r: -r["wait_us"])[:8]
    profile_block = {
        "sampler": sampler_state,
        "qps_hz0": round(qps_hz0, 1),
        "qps_hz19": round(qps_hz19, 1),
        # < 1.0 means the profiled window was slower; the acceptance
        # bound is |1 - ratio| <= 0.03 on a full-scale run
        "qps_ratio": round(qps_hz19 / max(qps_hz0, 1e-9), 4),
        "top_frames": prof_top["frames"][:10],
        # top-N self-time coverage of the sampled wall time
        "top_share": top_share,
        "top_locks": locks_delta,
        "gc": prof_mod.gc_profiler.table(),
        "compiles": prof_mod.compiles.totals(),
    }
    d = {k: tpu.stats[k] - b0[k] for k in b0}

    # span-level breakdown under COALESCED load — a short forced-sample
    # barrage after the measured window (dispatcher_wait is only
    # meaningful when concurrent sessions share a group)
    def barrage():
        ts = [threading.Thread(target=lambda k=k: conns[k].must(
            tier3_q(k))) for k in range(sessions)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    spans3 = span_breakdown_run(
        lambda: [barrage() for _ in range(3)], sessions * 3)
    log(f"tier3 span breakdown (us): {spans3}")
    out = {"sessions": sessions,
           # headline QPS is the UNPROFILED window (the clean number);
           # the profile block records the hz=19 twin + ratio
           "qps": round(qps_hz0, 1),
           "queries": total0 + total,
           "profile": profile_block,
           "span_breakdown": spans3,
           "batched_queries": d["batched_queries"],
           "batched_dispatches": d["batched_dispatches"],
           "lane_rounds": d["batched_lane_rounds"],
           # dispatcher window lifecycle (group-complete scheduling)
           "disp_rounds": d["disp_rounds"],
           "groups_per_round": round(
               d["disp_group_keys"] / max(d["disp_rounds"], 1), 2),
           "early_releases": d["early_releases"],
           "leader_handoffs": d["leader_handoffs"],
           "native_encode_rows": d["native_encode_rows"],
           "group_wait_us_avg": int(
               d["group_wait_us_total"] / max(d["group_wait_count"], 1)),
           "mesh_served": dict(tpu.mesh_served),
           "mesh_declined": {f: dict(dd) for f, dd in
                             tpu.mesh_decline_reasons.items()},
           # device-resident fused loop (docs/manual/13-device-
           # speed.md): one launch per chunk, filters fused in; the
           # prefetch delta shows H2D transfers that overlapped a
           # kernel wait during the measured window
           "fused_launches": d["fused_launches"],
           "fused_programs": tpu.fused_stats(),
           "frontier_prefetch": (pf1 := tpu.prefetch_stats()),
           "h2d_overlap_us": pf1["h2d_overlap_us"]
           - pf0["h2d_overlap_us"],
           "robustness": tpu.robustness_stats(),
           # histogram bucket vectors + flight trigger counts (the
           # tier builds its own richer `profile` block above)
           **_obs_block(profile=False)}
    log(f"tier3 concurrent ({sessions} sessions, "
        f"{wall0 + wall:.1f}s): {out['qps']} QPS aggregate "
        f"(profiled twin {profile_block['qps_hz19']}, ratio "
        f"{profile_block['qps_ratio']}, top-frame share "
        f"{profile_block['top_share']}), {d['batched_queries']} "
        f"queries over {d['batched_dispatches']} shared dispatches "
        f"({d['batched_lane_rounds']} lane rounds, "
        f"{out['groups_per_round']} group keys visible/election, "
        f"{out['early_releases']} early releases, "
        f"wait p_avg={out['group_wait_us_avg']}us)")
    return out


def _obs_block(profile=True):
    """Observability block for the bench JSON artifacts (ISSUE 10 +
    13): native-histogram snapshots — the full bucket vectors plus the
    exemplar trace ids, not just p50/p95 — the flight recorder's
    event/trigger/bundle state at sample time, and (unless the tier
    builds a richer one itself) a compact continuous-profiling block:
    top self-time frames + top contended locks + GC/compile tables."""
    from nebula_tpu.common import profiler as _prof
    from nebula_tpu.common.flight import recorder as _rec
    from nebula_tpu.common.stats import stats as _st
    hists = {}
    for name in _st.histogram_names():
        h = _st.histogram_snapshot(name)
        if h is None:
            continue
        hists[name] = {
            "bounds": h["bounds"],
            "counts": h["counts"],
            "sum": h["sum"],
            "count": h["count"],
            "exemplar_trace_ids": sorted(
                {e["trace_id"] for e in h["exemplars"].values()}),
        }
    d = _rec.describe(limit=1)
    out = {
        "histograms": hists,
        "flight": {
            "event_count": d["event_count"],
            "triggers": {t["name"]: t["fires"] for t in d["triggers"]},
            "bundles": d["bundles"],
        },
    }
    # workload & data observatory (ISSUE 14): per-space skew indices
    # + the hottest parts at sample time (empty when heat disarmed)
    from nebula_tpu.common import heat as _heat
    pr = _heat.accountant.parts_snapshot()
    pr.sort(key=lambda r: r["score_600s"], reverse=True)
    out["heat"] = {
        "enabled": _heat.enabled(),
        "skew": {str(s): v["index"]
                 for s, v in _heat.accountant.skew_indices().items()},
        "parts_tracked": len(pr),
        "top_parts": [{"space": r["space"], "part": r["part"],
                       "score_600s": r["score_600s"]}
                      for r in pr[:4]],
    }
    if profile:
        top = _prof.profiler.top(window=600, n=10)
        out["profile"] = {
            "sampler": _prof.profiler.state(),
            "top_frames": top["frames"],
            "top_share": round(sum(f["share"]
                                   for f in top["frames"]), 4),
            "top_locks": _prof.lock_table(8),
            "gc": _prof.gc_profiler.table(),
            "compiles": _prof.compiles.totals(),
        }
    return out


def _cache_rung_stats(cluster, tpu):
    """One merged cache matrix: engine rungs + the graphd plan cache
    + the storaged rungs (docs/manual/11-caching.md)."""
    out = dict(tpu.cache_stats())
    out["plan"] = cluster.service.engine.plan_cache.stats()
    out["storaged_stats"] = cluster.storage.stats_cache.stats()
    out["storaged_scan"] = cluster.storage.scan_cache.stats()
    return out


def bench_hot_repeat(cluster, tpu, conn, seed_sets,
                     sessions=8, seconds=3.0):
    """Hot-repeat tier: a REPEATED statement mix through the full
    cache ladder (docs/manual/11-caching.md) — the tier the earlier
    tiers deliberately avoid (their seeds are distinct so they measure
    the serve path, not the cache). Reports cold (cache_mode=off) vs
    cached (cache_mode=full) p50/QPS, per-rung hit rates, and a
    concurrent full-mode closed loop in the tier-3 query shape so the
    JSON records that concurrent QPS does not regress with caching on
    (identical per-session statements are exactly where the result
    rung + in-window dedupe bite)."""
    import threading
    from nebula_tpu.common.flags import graph_flags, storage_flags
    hubs = [s[0] for s in seed_sets[:max(3, sessions)]]
    cut = TS_MAX // 2
    mix = [
        f"GO {STEPS} STEPS FROM {hubs[0]} OVER knows "
        f"WHERE knows.ts > {cut} YIELD knows._dst, knows.ts",
        f"GO 2 STEPS FROM {hubs[1]} OVER knows YIELD knows._dst",
        f"GO 2 STEPS FROM {hubs[2]} OVER knows YIELD knows.ts AS t"
        f" | YIELD COUNT(*) AS n, SUM($-.t) AS s",
    ]
    reps = max(5, LAT_N // 3)
    mode0 = graph_flags.get("cache_mode")
    smode0 = storage_flags.get("cache_mode")

    def timed_pass():
        lats = []
        t0 = time.time()
        for _ in range(reps):
            for q in mix:
                t1 = time.time()
                conn.must(q)
                lats.append((time.time() - t1) * 1000)
        wall = time.time() - t0
        lats = np.sort(np.array(lats))
        return (float(np.percentile(lats, 50)),
                float(np.percentile(lats, 95)),
                len(lats) / wall)

    try:
        graph_flags.set("cache_mode", "off")
        storage_flags.set("cache_mode", "off")
        for q in mix:
            conn.must(q)                 # warm compiles off the clock
        cold_p50, cold_p95, cold_qps = timed_pass()
        graph_flags.set("cache_mode", "full")
        storage_flags.set("cache_mode", "full")
        c0 = _cache_rung_stats(cluster, tpu)
        for q in mix:
            conn.must(q)                 # populate pass
        hot_p50, hot_p95, hot_qps = timed_pass()
        c1 = _cache_rung_stats(cluster, tpu)
        rungs = {}
        for rung in ("result", "negative", "plan"):
            h = c1[rung]["hits"] - c0[rung]["hits"]
            m = c1[rung]["misses"] - c0[rung]["misses"]
            rungs[rung] = {"hits": h, "misses": m,
                           "hit_rate": round(h / max(h + m, 1), 3)}
        rungs["filter_plan"] = {
            "hits": c1["filter_plan"]["hits"] - c0["filter_plan"]["hits"],
            "misses": (c1["filter_plan"]["misses"]
                       - c0["filter_plan"]["misses"])}

        # concurrent repeated load, cache_mode=full (tier-3 shape:
        # every session repeats ITS one statement; sessions share the
        # hub pool so in-window duplicates are real)
        conns = []
        for _ in range(sessions):
            c = cluster.connect()
            c.must("USE snb")
            conns.append(c)
        counts = [0] * sessions
        errs = []
        stop = threading.Event()

        def worker(k):
            q = mix[k % len(mix)]
            while not stop.is_set():
                try:
                    conns[k].must(q)
                    counts[k] += 1
                except Exception as ex:  # noqa: BLE001 — fails the tier
                    errs.append(repr(ex))
                    return

        d0 = tpu.stats["dedup_collapsed"]
        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(sessions)]
        t0 = time.time()
        for t in threads:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in threads:
            t.join(timeout=120)
        wall = time.time() - t0
        assert not errs, errs[:2]
        conc_qps = sum(counts) / wall
    finally:
        graph_flags.set("cache_mode", mode0)
        storage_flags.set("cache_mode", smode0)
    out = {
        "mix": len(mix), "reps": reps,
        "cold": {"p50_ms": round(cold_p50, 2), "p95_ms": round(cold_p95, 2),
                 "qps": round(cold_qps, 1)},
        "cached": {"p50_ms": round(hot_p50, 2), "p95_ms": round(hot_p95, 2),
                   "qps": round(hot_qps, 1)},
        "speedup_p50": round(cold_p50 / max(hot_p50, 1e-6), 2),
        "rung_hit_rates": rungs,
        "concurrent_full": {"sessions": sessions,
                            "qps": round(conc_qps, 1),
                            "dedup_collapsed":
                                tpu.stats["dedup_collapsed"] - d0},
    }
    log(f"hot-repeat tier: cold p50={cold_p50:.1f}ms "
        f"{cold_qps:.0f} QPS -> cached p50={hot_p50:.2f}ms "
        f"{hot_qps:.0f} QPS (x{out['speedup_p50']}); rung hits="
        f"{ {k: v.get('hit_rate', v) for k, v in rungs.items()} }; "
        f"concurrent full-mode {out['concurrent_full']['qps']} QPS "
        f"({out['concurrent_full']['dedup_collapsed']} deduped)")
    return out


def bench_cpu_scan(cluster, sid, etype, seeds, label):
    """The CPU storage scatter/gather path (get_neighbors fan-out with
    frontier dedup — what GoExecutor drives), over whatever engine the
    cluster was built with."""
    client = cluster.client
    t0 = time.time()
    edges_traversed = 0
    frontier = list(seeds)
    for _ in range(STEPS):
        resp = client.get_neighbors(sid, frontier, [etype], edge_props=[])
        seen = set()
        nxt = []
        for v in resp.vertices:
            for e in v.edges:
                edges_traversed += 1
                if e.dst not in seen:
                    seen.add(e.dst)
                    nxt.append(e.dst)
        frontier = nxt
    dt = time.time() - t0
    eps = edges_traversed / dt
    log(f"CPU [{label}]: {STEPS}-hop GO from {len(seeds)} seeds: "
        f"{edges_traversed} edges in {dt:.2f}s -> {eps:,.0f} edges/s")
    return eps, edges_traversed


def bench_python_baseline():
    """python-loop storaged at reduced scale (rate is the comparator)."""
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.codec import RowWriter
    from nebula_tpu.storage import NewEdge, NewVertex

    v = max(PY_E // 10, 1000)
    cluster = InProcCluster()
    conn = cluster.connect()
    conn.must(f"CREATE SPACE py(partition_num={PARTS})")
    conn.must("USE py")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(ts int)")
    sid = cluster.meta.get_space("py").value().space_id
    etype = cluster.sm.edge_type(sid, "knows")
    rng = np.random.default_rng(7)
    srcs = gen_degrees(rng, v, PY_E)
    dsts = rng.integers(0, v, PY_E)
    row = RowWriter(cluster.sm.edge_schema(sid, etype).value()) \
        .set("ts", 1).encode()
    vrow = RowWriter(cluster.sm.tag_schema(
        sid, cluster.sm.tag_id(sid, "person")).value()).set("age", 30).encode()
    t0 = time.time()
    tag_id = cluster.sm.tag_id(sid, "person")
    cluster.client.add_vertices(sid, [NewVertex(int(i), [(tag_id, vrow)])
                                      for i in range(v)])
    edges = [NewEdge(int(s), etype, int(i), int(d), row)
             for i, (s, d) in enumerate(zip(srcs, dsts))]
    for i in range(0, PY_E, 200_000):
        cluster.client.add_edges(sid, edges[i:i + 200_000])
    log(f"python-baseline store loaded in {time.time()-t0:.1f}s "
        f"(V={v} E={PY_E})")
    seeds = [int(s) for s in rng.choice(v, SEEDS, replace=False)]
    eps, _ = bench_cpu_scan(cluster, sid, etype, seeds,
                            "python-loop storaged (reduced scale)")
    return eps


def _ensure_backend():
    """Probe accelerator reachability in a SUBPROCESS (shared helper:
    nebula_tpu.common.accel): a dead tunnel makes in-process backend
    init hang forever (and poison the init lock), which would hang the
    driver's round-end bench. On a hung or failed probe, force the CPU
    XLA backend at a reduced graph scale — the bench still reports,
    loudly labeled."""
    from nebula_tpu.common import accel
    plat, _n = accel.probe()
    if plat and plat != "cpu":
        return plat
    import jax
    jax.config.update("jax_platforms", "cpu")
    # shrink each knob individually unless the user pinned it
    for var, small in (("BENCH_V", 50_000), ("BENCH_E", 500_000),
                       ("BENCH_BATCH", 32), ("BENCH_ITERS", 3),
                       ("BENCH_PY_E", 200_000), ("BENCH_LAT_N", 5)):
        if var not in os.environ:
            globals()[var[len("BENCH_"):]] = small
    label = "cpu-fallback(accelerator unreachable)" if not plat else "cpu"
    log(f"WARNING: running on {label} at V={V} E={E} — accelerator "
        f"numbers are NOT represented by this run")
    return label


def zipf_edges(rng, v, e, clip=200):
    """Clipped-zipf edge lists for the small in-proc tiers (mesh
    dryrun, chaos): -> (srcs, dsts, ts)."""
    deg = np.minimum(rng.zipf(1.6, v), clip).astype(np.int64)
    srcs = np.repeat(np.arange(v), deg)
    if len(srcs) < e:
        srcs = np.concatenate([srcs, rng.integers(0, v, e - len(srcs))])
    return srcs[:e], rng.integers(0, v, e), rng.integers(0, TS_MAX, e)


def insert_person_knows(conn, space, parts, v, srcs, dsts, ts,
                        replica_factor=1, settle_s=0.0):
    """Create the person(age)/knows(ts) schema in `space` and batch-
    INSERT the generated graph through real nGQL (shared by the mesh
    dryrun, chaos and cluster tiers). `settle_s` retries the first
    INSERT for that long — a replicated cluster needs its raft
    elections to finish before writes land."""
    conn.must(f"CREATE SPACE {space}(partition_num={parts}, "
              f"replica_factor={replica_factor})")
    conn.must(f"USE {space}")
    conn.must("CREATE TAG person(age int)")
    conn.must("CREATE EDGE knows(ts int)")
    B = 500
    first = True
    for i in range(0, v, B):
        stmt = "INSERT VERTEX person(age) VALUES " + ", ".join(
            f"{j}:({20 + j % 60})" for j in range(i, min(i + B, v)))
        if first and settle_s:
            deadline = time.time() + settle_s
            while True:
                r = conn.execute(stmt)
                if r.ok() or time.time() >= deadline:
                    break
                time.sleep(0.2)
            assert r.ok(), r.error_msg
            first = False
        else:
            conn.must(stmt)
    for i in range(0, len(srcs), B):
        conn.must("INSERT EDGE knows(ts) VALUES " + ", ".join(
            f"{srcs[j]} -> {dsts[j]}@{j}:({ts[j]})"
            for j in range(i, min(i + B, len(srcs)))))


def bench_mesh_dryrun(out_path: str, n_devices: int = 4):
    """Tier-1-safe mesh smoke tier (`bench.py --mesh-dryrun`): boot a
    host-emulated n-device mesh (JAX_PLATFORMS=cpu +
    xla_force_host_platform_device_count — no accelerator, no native
    engine), drive the FULL meshed serving surface through real nGQL —
    concurrent mixed-key dispatcher windows, grouped + ungrouped
    aggregation pushdown, an ALL-path query — identity-checked against
    a plain CPU cluster, and record the mesh serving matrix into a
    MULTICHIP json artifact. The env forcing must run before the first
    jax import, so this tier runs INSTEAD of the accelerator tiers."""
    import threading
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    n_devices = min(n_devices, len(jax.devices()))

    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.common.flags import graph_flags
    from nebula_tpu.engine_tpu import TpuGraphEngine
    from nebula_tpu.engine_tpu import distributed as dist
    mesh = dist.make_mesh(jax.devices()[:n_devices])
    parts = n_devices * 2
    tpu = TpuGraphEngine(mesh=mesh)
    clusters = [InProcCluster(tpu_engine=tpu), InProcCluster()]

    rng = np.random.default_rng(5)
    V, E = 600, 6000
    srcs, dsts, ts = zipf_edges(rng, V, E, clip=200)
    conns = []
    for cl in clusters:
        conn = cl.connect()
        insert_person_knows(conn, "meshdry", parts, V, srcs, dsts, ts)
        conns.append(conn)
    tconn, cconn = conns
    hubs = [int(x) for x in np.argsort(np.bincount(srcs,
                                                   minlength=V))[-4:]]

    queries = [
        f"GO 2 STEPS FROM {hubs[0]} OVER knows YIELD knows._dst",
        f"GO 3 STEPS FROM {hubs[1]} OVER knows YIELD knows._dst",
        f"GO FROM {hubs[2]} OVER knows WHERE knows.ts > {TS_MAX // 2} "
        f"YIELD knows._dst, knows.ts",
        f"GO 2 STEPS FROM {hubs[0]} OVER knows YIELD knows.ts AS t"
        f" | YIELD COUNT(*) AS n, SUM($-.t) AS s, AVG($-.t) AS a",
        f"GO FROM {hubs[1]}, {hubs[2]} OVER knows "
        f"YIELD knows._dst AS d, knows.ts AS t | GROUP BY $-.d "
        f"YIELD $-.d AS d, COUNT(*) AS c, SUM($-.t) AS s",
        f"FIND ALL PATH FROM {hubs[3]} TO {hubs[0]} OVER knows "
        f"UPTO 3 STEPS",
    ]
    checked = 0
    mismatches = []
    for q in queries:
        rt, rc = tconn.must(q), cconn.must(q)
        if sorted(map(str, rt.rows)) != sorted(map(str, rc.rows)):
            mismatches.append(q)
        checked += 1

    # concurrent mixed-key windows through the group-commit dispatcher
    # (two distinct steps keys x several sessions): the windows must
    # coalesce on the MESH (mesh_served.go_batched). Pre-build the
    # per-device window layout so the measurement doesn't race the
    # engine's off-lock lazy build.
    from nebula_tpu.engine_tpu import mesh_exec
    sid = clusters[0].meta.get_space("meshdry").value().space_id
    snap = tpu.snapshot(sid)
    if snap is not None and snap.sharded_kernel is not None:
        mesh_exec.ensure_sharded_aligned(mesh, snap)
    errs = []

    def worker(q, n):
        try:
            c = clusters[0].connect()
            c.must("USE meshdry")
            for _ in range(n):
                c.must(q)
        except Exception as e:   # noqa: BLE001 — recorded, fails run
            errs.append(repr(e))
    threads = []
    for q in (f"GO 2 STEPS FROM {hubs[0]} OVER knows YIELD knows._dst",
              f"GO 3 STEPS FROM {hubs[1]} OVER knows YIELD knows._dst"):
        for _ in range(4):
            t = threading.Thread(target=worker, args=(q, 3))
            t.start()
            threads.append(t)
    for t in threads:
        t.join()

    # cache segment AFTER the meshed window sections (a full-mode
    # result cache would absorb the repeated queries those sections
    # need to form windows): re-run the identity sweep twice under
    # cache_mode=full — hits must occur and rows must still match the
    # plain CPU cluster on a MESHED engine
    mode0 = graph_flags.get("cache_mode")
    graph_flags.set("cache_mode", "full")
    try:
        h0 = tpu.result_cache.stats()["hits"]
        for q in queries:
            r1, r2 = tconn.must(q), tconn.must(q)
            rc = cconn.must(q)
            if not (sorted(map(str, r1.rows)) == sorted(map(str, r2.rows))
                    == sorted(map(str, rc.rows))):
                mismatches.append("cached:" + q)
        cache_hits = tpu.result_cache.stats()["hits"] - h0
    finally:
        graph_flags.set("cache_mode", mode0)

    rec = {
        "n_devices": n_devices,
        "partitions": parts,
        "graph": {"V": V, "E": E},
        "identity_checked": checked,
        "identity_ok": not mismatches and not errs,
        "mismatches": mismatches,
        "errors": errs[:3],
        "mesh_served": dict(tpu.mesh_served),
        "mesh_declined": {f: dict(d) for f, d in
                          tpu.mesh_decline_reasons.items()},
        "sharded_queries": tpu.stats["sharded_queries"],
        "batched_dispatches": tpu.stats["batched_dispatches"],
        "cache": tpu.cache_stats(),
        "cache_hits_meshed": cache_hits,
    }
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    log(f"mesh dryrun: {checked} identity-checked queries on a "
        f"{n_devices}-device host-emulated mesh, mesh_served="
        f"{rec['mesh_served']} -> {out_path}")
    log(f"mesh dryrun cache matrix: {rec['cache']}")
    print(json.dumps({"metric": "mesh_dryrun", **rec}))
    ok = rec["identity_ok"] and \
        all(rec["mesh_served"].get(k, 0) > 0
            for k in ("go_batched", "agg", "path_all"))
    if not ok:
        raise SystemExit(f"mesh dryrun FAILED: {rec}")
    return rec


def _witness_summary() -> dict:
    """Compact lock-order-witness block for a bench record
    (docs/manual/15-static-analysis.md#witness)."""
    from nebula_tpu.common.lockwitness import witness
    return witness.summary()


def bench_skew(out_path: str, trim: bool = False):
    """Workload & data observatory proof tier (`bench.py --skew`;
    docs/manual/10-observability.md, "Workload & data observatory").
    Tier-1-safe on XLA:CPU, no accelerator / native engine. PASSES
    only when

      (a) DISARMED IS FREE: with heat_enabled=false an entire warm
          query loop leaves zero heat slabs, zero nebula_part_heat_*/
          nebula_heat_* families on the metrics surface (byte-
          identical /metrics), and zero sketch state;
      (b) SKETCH RECALL: the space-saving hot-vertex sketch's top-K
          over a Zipf start-vid stream recalls >= 0.9 of the ground-
          truth top-K the bench itself counted;
      (c) SKEW INDEX SEPARATES: the per-space p99/mean part-heat
          index reads ~1 under uniform starts and >= 1.5x that under
          Zipf starts (same graph, same query shape);
      (d) HOT_PART FIRES: with heat_hot_part_pct armed below the
          measured dominant-part share, the flight recorder captures
          a hot_part-triggered bundle embedding the /heat view;
      (e) ADVISOR REDUCES SPREAD: on a deliberately skewed 3-host
          layout fed through REAL heartbeats, the heat-aware BALANCE
          advisor's modeled plan strictly reduces the per-host heat
          spread (and moves leadership toward replica holders);
      (f) OVERHEAD: armed-vs-disarmed interleaved QPS ratio recorded;
          full runs gate it within the PR 13 3% contract.
    """
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.common import heat as heat_mod
    from nebula_tpu.common.flags import graph_flags, storage_flags
    from nebula_tpu.common.flight import recorder as flight_rec
    from nebula_tpu.common.stats import stats as global_stats
    from nebula_tpu.engine_tpu import TpuGraphEngine

    seed = int(os.environ.get("BENCH_SKEW_SEED", 13))
    parts = 8
    v, e = (400, 3000) if trim else (2000, 16000)
    n_uniform, n_zipf = (240, 320) if trim else (1200, 1600)
    rng = np.random.default_rng(seed)
    gates: dict = {}
    art: dict = {"seed": seed,
                 "graph": {"V": v, "E": e, "parts": parts},
                 "trim": trim}

    def heat_metric_lines():
        # every family the observatory would add to /metrics: the
        # accountant's gauge source + any heat.*/staleness stats
        # families (the WebService renders exactly these)
        lines = [ln for ln in global_stats.prometheus_lines()
                 if "nebula_heat_" in ln or "part_heat" in ln
                 or "staleness" in ln]
        return lines, heat_mod.accountant.gauges()

    # ---- phase 0: DISARMED — the whole loop must leave no trace
    heat_mod.accountant.reset()
    flight_rec.reset()
    graph_flags.set("heat_enabled", False)
    storage_flags.set("heat_enabled", False)
    graph_flags.set("heat_vertices_k", 64)   # k armed but heat off:
    storage_flags.set("heat_vertices_k", 64)  # master flag wins
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    srcs, dsts, ts = zipf_edges(rng, v, e, clip=120)
    insert_person_knows(conn, "skew", parts, v, srcs, dsts, ts)
    sid = cluster.meta.get_space("skew").value().space_id
    tpu.prewarm(sid, block=True)

    def go(start, steps=2):
        return conn.must(f"GO {steps} STEPS FROM {int(start)} "
                         f"OVER knows YIELD knows._dst")

    warm = rng.integers(0, v, 32)
    for s in warm:
        go(s)
    lines0, gauges0 = heat_metric_lines()
    gates["disarmed_no_metric_families"] = lines0 == []
    gates["disarmed_no_gauges"] = gauges0 == {}
    gates["disarmed_no_slabs"] = \
        heat_mod.accountant.parts_snapshot() == []
    gates["disarmed_no_sketch"] = \
        heat_mod.accountant.sketch(sid) is None
    art["disarmed"] = {"metric_lines": len(lines0),
                       "gauges": len(gauges0)}

    # ---- overhead: interleaved disarmed/armed passes on the same
    # warm engine (the PR 13 qps_hz0/qps_hz19 idiom)
    per_pass = 40 if trim else 150
    passes_off: list = []
    passes_on: list = []
    starts_oh = rng.integers(0, v, per_pass)
    for _ in range(3 if trim else 5):
        # BOTH registries every toggle: heat._flag takes the first
        # non-default value across them, so a lone graph-side True
        # (== default, skipped) with storage still False would leave
        # the "armed" pass actually disarmed
        graph_flags.set("heat_enabled", False)
        storage_flags.set("heat_enabled", False)
        assert not heat_mod.enabled()
        t0 = time.perf_counter()
        for s in starts_oh:
            go(s)
        passes_off.append(time.perf_counter() - t0)
        graph_flags.set("heat_enabled", True)
        storage_flags.set("heat_enabled", True)
        assert heat_mod.enabled()
        t0 = time.perf_counter()
        for s in starts_oh:
            go(s)
        passes_on.append(time.perf_counter() - t0)
    # the A/B ratio (median of per-pair ratios, drift cancels within a
    # pair) is RECORDED for the artifact — but at ~200ms passes it
    # carries +-5% box noise, far above the ~1% true cost, so the 3%
    # contract is GATED on the deterministic measurement instead: the
    # armed seam's own per-query cost (observe_query + charge_device
    # + restore, exactly what a device-served GO pays) against the
    # workload's measured per-query latency (the PR 13 idiom — the
    # profiler gates its sampler's measured overhead, not an
    # end-to-end QPS ratio it can't measure above the noise floor)
    pair_ratios = sorted(off / on for off, on
                         in zip(passes_off, passes_on))
    ratio = pair_ratios[len(pair_ratios) // 2]
    qps_off = per_pass / min(passes_off)
    qps_on = per_pass / min(passes_on)
    n_seam = 4000

    def seam_cost(starts_shape):
        t0 = time.perf_counter()
        for _ in range(n_seam):
            tok = heat_mod.observe_query(sid, starts_shape, parts)
            heat_mod.charge_device(1500.0)
            heat_mod.restore(tok)
        return (time.perf_counter() - t0) / n_seam * 1e6
    # gate like-for-like: the measured workload is single-start GOs,
    # so the gated seam runs the same shape; the 8-start variant
    # (wide piped frontiers) is recorded as information
    seam_us = seam_cost([int(starts_oh[0])])
    seam_us_8 = seam_cost([int(x) for x in starts_oh[:8]])
    query_us = min(passes_on) / per_pass * 1e6
    seam_frac = seam_us / query_us
    art["overhead"] = {"qps_disarmed": round(qps_off, 1),
                       "qps_armed": round(qps_on, 1),
                       "ratio": round(ratio, 4),
                       "seam_us_per_query": round(seam_us, 2),
                       "seam_us_8start": round(seam_us_8, 2),
                       "query_us": round(query_us, 1),
                       "seam_frac": round(seam_frac, 4)}
    gates["overhead_within_contract"] = seam_frac <= 0.03

    # ---- phase 1: ARMED, uniform starts -> skew index ~ 1
    graph_flags.set("heat_enabled", True)
    storage_flags.set("heat_enabled", True)
    heat_mod.accountant.reset()
    for s in rng.integers(0, v, n_uniform):
        go(s)
    skew_u = heat_mod.accountant.skew_index(sid, window=600)
    art["skew_index"] = {"uniform": skew_u["index"],
                         "uniform_detail": skew_u}

    # ---- phase 2: ARMED, Zipf starts -> sketch recall + skew index
    heat_mod.accountant.reset()
    alpha = 1.25
    draws = rng.zipf(alpha, n_zipf * 4)
    draws = draws[draws <= v][:n_zipf]
    # map rank r -> a scattered vid (rank-1 vids would all be tiny and
    # co-located; the affine map spreads hubs across parts while
    # keeping the draw<->vid mapping deterministic)
    vids = [(int(r) * 131 + 7) % v for r in draws]
    truth: dict = {}
    for x in vids:
        truth[x] = truth.get(x, 0) + 1
    for x in vids:
        go(x)
    skew_z = heat_mod.accountant.skew_index(sid, window=600)
    art["skew_index"]["zipf"] = skew_z["index"]
    art["skew_index"]["zipf_detail"] = skew_z
    sep = skew_z["index"] / max(skew_u["index"], 1e-9)
    art["skew_index"]["separation"] = round(sep, 3)
    gates["skew_separates"] = sep >= 1.5 and skew_z["index"] > 1.2

    K = 10
    true_top = [x for x, _ in sorted(truth.items(),
                                     key=lambda kv: kv[1],
                                     reverse=True)[:K]]
    sk = heat_mod.accountant.sketch(sid)
    gates["sketch_exists"] = sk is not None
    est_top = [int(r["vid"]) for r in (sk.topk(K) if sk else [])]
    recall = len(set(true_top) & set(est_top)) / K
    art["sketch"] = {
        "k": sk.k if sk else 0, "recall": round(recall, 3),
        "tracked": len(sk.counts) if sk else 0,
        "evictions": sk.evictions if sk else 0,
        "true_topk": true_top, "est_topk": est_top,
    }
    gates["sketch_recall"] = recall >= 0.9
    gates["sketch_cardinality_cap"] = \
        sk is not None and len(sk.counts) <= sk.k

    # ---- phase 2b: hot_part flight trigger, armed just under the
    # measured dominant-part share (testing the plumbing, not the
    # threshold choice)
    scores = heat_mod.accountant.space_scores(600).get(sid, {})
    total = sum(scores.values()) or 1.0
    top_share = 100.0 * max(scores.values()) / total
    pct = max(5.0, top_share - 5.0)
    graph_flags.set("heat_hot_part_pct", pct)
    heat_mod.accountant.check_hot_part(sid)
    flight_rec.flush()
    fired = [b for b in flight_rec.bundles
             if b["trigger"] == "hot_part"]
    gates["hot_part_bundle"] = bool(
        fired and fired[-1].get("collectors", {}).get("heat"))
    art["hot_part"] = {"top_share_pct": round(top_share, 1),
                       "armed_pct": round(pct, 1),
                       "bundles": len(fired)}
    graph_flags.set("heat_hot_part_pct", 0)

    # ---- phase 3: the heat-aware BALANCE advisor on a deliberately
    # skewed 3-host layout, fed through REAL heartbeats (the exact
    # storaged -> metad carry path)
    from nebula_tpu.meta.balancer import Balancer
    from nebula_tpu.meta.service import MetaService
    meta2 = MetaService(expired_threshold_secs=3600)
    hosts3 = ["10.0.0.1:1", "10.0.0.2:1", "10.0.0.3:1"]
    for h in hosts3:
        meta2.heartbeat(h, "storage")
    sid2 = meta2.create_space("hot", partition_num=6,
                              replica_factor=2).value()
    alloc = meta2.get_parts_alloc(sid2)
    # every part's first replica leads; host 1 deliberately leads the
    # hot parts (a zipf score ladder)
    leaders = {p: hs[0] for p, hs in alloc.items()}
    ladder = [100.0, 60.0, 8.0, 4.0, 2.0, 1.0]
    hot_host = leaders[sorted(alloc)[0]]
    score_of_part = {}
    hot_rank = 0
    cold_rank = len(ladder) - 1
    for p in sorted(alloc):
        if leaders[p] == hot_host:
            score_of_part[p] = ladder[hot_rank]
            hot_rank += 1
        else:
            score_of_part[p] = ladder[cold_rank]
            cold_rank -= 1
    for h in hosts3:
        led = sorted(p for p, l in leaders.items() if l == h)
        payload = {"parts": {sid2: {
            p: {"score": score_of_part[p], "reads": score_of_part[p]}
            for p in led}}}
        meta2.heartbeat(h, "storage", leader_parts={sid2: led},
                        part_heat=payload)
    bal = Balancer(meta2, admin=None)
    meta2.attach_balancer(bal)
    advise = meta2.balance_advise_heat().value()
    art["advisor"] = advise
    gates["advisor_reduces_spread"] = bool(
        advise["spread_after"] < advise["spread_before"]
        and advise["moves"])
    gates["advisor_moves_wellformed"] = all(
        m["kind"] in ("leader", "move") and m["src"] != m["dst"]
        and m["score"] > 0 for m in advise["moves"])

    # ---- artifact + verdict (_obs_block supplies the compact `heat`
    # block every tier carries; `heat_detail` is this tier's full view)
    art["heat_detail"] = heat_mod.accountant.describe(vertices=False)
    art.update(_obs_block(profile=False))
    art["gates"] = gates
    art["ok"] = all(bool(x) for x in gates.values())
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1, default=str)
    log(f"SKEW tier: {json.dumps(gates)}")
    log(f"skew index uniform={skew_u['index']} zipf={skew_z['index']} "
        f"recall={recall} advisor spread "
        f"{advise['spread_before']} -> {advise['spread_after']} "
        f"overhead ratio={ratio:.4f}")
    log(f"wrote {out_path}")
    if not art["ok"]:
        failed = [k for k, ok in gates.items() if not ok]
        raise SystemExit(f"SKEW tier FAILED gates: {failed}")


def bench_consistency(out_path: str, trim: bool = False):
    """Consistency observatory proof tier (`bench.py --consistency`;
    docs/manual/10-observability.md, "Consistency observatory").
    Tier-1-safe on XLA:CPU. PASSES only when

      (a) DISARMED IS FREE: with consistency_enabled=false a whole
          warm read+write loop leaves ZERO nebula_consistency_*/
          nebula_shadow_* families on the metrics surface (byte-
          identical /metrics), no part digests and no shadow state;
      (b) CLEAN PHASE IS SILENT: armed, a single-host mixed workload
          with shadow-read sampling at 0.5 produces verifications > 0
          with ZERO mismatches (the production-resident identity
          discipline), every part's deep scrub agrees with its
          incremental digest, and the device-snapshot audit checks
          clean — zero false positives anywhere;
      (c) SHOW CONSISTENCY renders per-part digest rows;
      (d) CORRUPTION IS DETECTED: on a REAL 3-replica raft cluster
          (metad + 3 replicated storaged + TPU graphd, localhost TCP)
          an armed `consistency.corrupt:n=1` flips one byte of one
          committed put on one replica — the leader's digest exchange
          must flag the divergence within DETECT_WINDOW_S, the
          `replica_divergence` flight bundle must name the part,
          replica and anchor, the per-part digest_ok gauge must drop
          to 0 on /metrics, and the pre-corruption clean window must
          have had zero divergence (no false positives).
    """
    import shutil
    import tempfile
    import threading
    import urllib.request

    from nebula_tpu.client import GraphClient
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.common import consistency as cons
    from nebula_tpu.common.faults import faults
    from nebula_tpu.common.flags import graph_flags, storage_flags
    from nebula_tpu.common.flight import recorder as flight_rec
    from nebula_tpu.common.stats import stats as global_stats
    from nebula_tpu.daemons import (serve_graphd, serve_metad,
                                    serve_storaged)
    from nebula_tpu.engine_tpu import TpuGraphEngine

    seed = int(os.environ.get("BENCH_CONSISTENCY_SEED", 23))
    DETECT_WINDOW_S = 5.0
    parts = 3
    v, e = (240, 1500) if trim else (1000, 8000)
    n_reads = 60 if trim else 300
    rng = np.random.default_rng(seed)
    gates: dict = {}
    art: dict = {"seed": seed, "trim": trim,
                 "graph": {"V": v, "E": e, "parts": parts},
                 "detect_window_s": DETECT_WINDOW_S}

    def cons_metric_lines():
        return [ln for ln in global_stats.prometheus_lines()
                if "nebula_consistency" in ln or "nebula_shadow" in ln]

    # ---- phase 0: DISARMED — the whole loop must leave no trace
    cons.shadow.reset()
    flight_rec.reset()
    graph_flags.set("consistency_enabled", False)
    storage_flags.set("consistency_enabled", False)
    graph_flags.set("shadow_read_rate", 0.0)
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    srcs, dsts, ts = zipf_edges(rng, v, e, clip=100)
    insert_person_knows(conn, "consb", parts, v, srcs, dsts, ts)
    sid = cluster.meta.get_space("consb").value().space_id
    tpu.prewarm(sid, block=True)

    def go(start, steps=2):
        return conn.must(f"GO {steps} STEPS FROM {int(start)} "
                         f"OVER knows YIELD knows._dst, knows.ts")

    for s in rng.integers(0, v, 24):
        go(s)
    conn.must(f"INSERT EDGE knows(ts) VALUES 1 -> 2:(7)")
    go(1)
    lines0 = cons_metric_lines()
    gates["disarmed_no_metric_families"] = lines0 == []
    gates["disarmed_no_store_digest"] = \
        cluster.store.space_digest(sid) is None
    gates["disarmed_no_shadow"] = \
        cons.shadow.stats()["sampled"] == 0
    art["disarmed"] = {"metric_lines": len(lines0)}

    # ---- phase 1: ARMED single host — clean-phase silence + shadow
    # identity + scrub + snapshot audit + SHOW CONSISTENCY
    graph_flags.set("consistency_enabled", True)
    storage_flags.set("consistency_enabled", True)
    graph_flags.set("shadow_read_rate", 0.5)
    cons.shadow.reset()
    div0 = global_stats.lifetime_total("consistency.divergence")
    writes = 0
    for i, s in enumerate(rng.integers(0, v, n_reads)):
        if i % 10 == 9:      # writes interleaved: stale-skip machinery
            conn.must(f"INSERT EDGE knows(ts) VALUES "
                      f"{int(s)} -> {int((s * 13 + 1) % v)}:"
                      f"({int(s) % 1000})")
            writes += 1
            continue
        if i % 7 == 3:
            conn.must(f"FETCH PROP ON person {int(s)}")
        else:
            go(s, steps=1 + int(s) % 2)
    go(0)                    # settle the snapshot at the final version
    gates["shadow_drained"] = cons.shadow.drain(30)
    sh = cons.shadow.stats()
    art["shadow"] = {k: sh[k] for k in
                     ("sampled", "verified", "mismatches",
                      "skipped_stale", "errors", "dropped")}
    gates["shadow_sampled"] = sh["sampled"] > 0
    gates["shadow_verified"] = sh["verified"] > 0
    gates["shadow_identity_green"] = sh["mismatches"] == 0
    scrubs = [p.digest_scrub() for p in cluster.store.space_parts(sid)]
    art["scrub"] = scrubs
    gates["scrub_green"] = bool(scrubs) and \
        all(r["ok"] is True for r in scrubs)
    audit = None
    for _ in range(50):
        audit = tpu.audit_snapshots()
        if audit["checked"] >= 1 or audit["mismatches"]:
            break
        go(0)
        time.sleep(0.05)
    art["audit"] = audit
    gates["audit_checked"] = audit is not None and \
        audit["checked"] >= 1
    gates["audit_green"] = audit is not None and \
        audit["mismatches"] == 0
    showr = conn.must("SHOW CONSISTENCY")
    art["show_consistency_rows"] = len(showr.rows)
    gates["show_consistency"] = len(showr.rows) >= parts
    gates["clean_phase_no_divergence"] = \
        global_stats.lifetime_total("consistency.divergence") == div0
    graph_flags.set("shadow_read_rate", 0.0)
    log(f"CONSISTENCY phase 1: shadow={art['shadow']} "
        f"scrubs={len(scrubs)} audit={audit}")

    # ---- phase 2: the corruption drill on a REAL replicated cluster
    space = "consrep"
    run_dir = tempfile.mkdtemp(prefix="nebula_tpu_consbench_")
    old_hb = storage_flags.get("heartbeat_interval_secs")
    old_rhb = storage_flags.get("raft_heartbeat_ms")
    old_rel = storage_flags.get("raft_election_timeout_ms")
    storage_flags.set("heartbeat_interval_secs", 0.4)
    storage_flags.set("raft_heartbeat_ms", 60)
    storage_flags.set("raft_election_timeout_ms", 250)
    metad = graphd = None
    storers = {}
    try:
        metad = serve_metad(expired_threshold_secs=5)
        for i in range(3):
            storers[i] = serve_storaged(
                metad.addr, replicated=True, engine="mem",
                data_dir=os.path.join(run_dir, f"s{i}"),
                load_interval=0.15, ws_port=0)
        tpu2 = TpuGraphEngine()
        graphd = serve_graphd(metad.addr, tpu_engine=tpu2)
        gc = GraphClient(graphd.addr).connect()
        v2, e2 = (160, 900) if trim else (400, 3000)
        srcs2, dsts2, ts2 = zipf_edges(rng, v2, e2, clip=60)
        insert_person_knows(gc, space, parts, v2, srcs2, dsts2, ts2,
                            replica_factor=3, settle_s=20.0)
        sid2 = metad.meta.get_space(space).value().space_id
        gc.must(f"GO 2 STEPS FROM 1 OVER knows YIELD knows._dst")
        graph_flags.set("shadow_read_rate", 0.3)
        cons.shadow.reset()

        def divergent() -> list:
            found = []
            for h in storers.values():
                if h.node is None:
                    continue
                for p in h.node.consistency_status():
                    for rep in p.get("digest_divergent") or []:
                        found.append({"node": h.addr,
                                      "space": p["space"],
                                      "part": p["part"],
                                      "replica": rep,
                                      "digest": p.get("digest")})
            return found

        def verified_replicas() -> int:
            n = 0
            for h in storers.values():
                if h.node is None:
                    continue
                for p in h.node.consistency_status():
                    n += sum(1 for m in p["replicas"]
                             if m.get("digest_ok") is True)
            return n

        # clean window: traffic flows, every replica verifies, zero
        # divergence — the no-false-positive half of the drill
        div_clean0 = global_stats.lifetime_total(
            "consistency.divergence")
        clean_end = time.monotonic() + (1.5 if trim else 4.0)
        wseq = 0
        while time.monotonic() < clean_end:
            s = int(rng.integers(0, v2))
            gc.must(f"GO FROM {s} OVER knows YIELD knows._dst")
            gc.must(f"INSERT EDGE knows(ts) VALUES {s} -> "
                    f"{(s * 7 + 3) % v2}:({wseq % 997})")
            wseq += 1
            time.sleep(0.01)
        deadline = time.monotonic() + 5
        while verified_replicas() == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        art["clean"] = {"writes": wseq,
                        "verified_replicas": verified_replicas(),
                        "divergent": divergent()}
        gates["clean_replicas_verified"] = \
            art["clean"]["verified_replicas"] > 0
        gates["clean_no_divergence"] = (
            not art["clean"]["divergent"] and
            global_stats.lifetime_total("consistency.divergence")
            == div_clean0)

        # ARM the corruption: exactly one committed put on exactly one
        # replica gets one byte flipped as it is applied
        flight_rec.reset()
        faults.set_plan("consistency.corrupt:n=1")
        t0 = time.monotonic()
        fired_at = None
        detect_at = None
        for i in range(400):
            s = int(rng.integers(0, v2))
            gc.must(f"INSERT EDGE knows(ts) VALUES {s} -> "
                    f"{(s * 11 + 5) % v2}:({i})")
            if fired_at is None and \
                    faults.counts().get("consistency.corrupt"):
                fired_at = time.monotonic()
            if fired_at is not None:
                if divergent():
                    detect_at = time.monotonic()
                    break
            time.sleep(0.02)
        if fired_at is not None and detect_at is None:
            deadline = fired_at + DETECT_WINDOW_S
            while time.monotonic() < deadline:
                if divergent():
                    detect_at = time.monotonic()
                    break
                time.sleep(0.05)
        div = divergent()
        art["drill"] = {
            "corrupt_fired": faults.counts().get(
                "consistency.corrupt", 0),
            "detect_s": round(detect_at - fired_at, 3)
            if (detect_at and fired_at) else None,
            "divergent": div,
        }
        gates["corrupt_fired"] = bool(fired_at)
        gates["divergence_detected"] = bool(detect_at)
        gates["detected_within_window"] = bool(
            detect_at and fired_at and
            detect_at - fired_at <= DETECT_WINDOW_S)
        # the flight bundle names part / replica / anchor
        flight_rec.flush()
        bundles = [b for b in flight_rec.bundles
                   if b["trigger"] == "replica_divergence"]
        ev = bundles[-1]["event"] if bundles else {}
        art["drill"]["bundle_event"] = {
            k: ev.get(k) for k in ("kind", "space", "part", "replica",
                                   "anchor", "term")}
        gates["divergence_bundle"] = bool(
            bundles and ev.get("part") is not None
            and ev.get("replica") and ev.get("anchor") is not None)
        gates["divergence_counter_moved"] = \
            global_stats.lifetime_total("consistency.divergence") > \
            div_clean0
        # the gauge surface: some leader part scrapes digest_ok 0
        gauge_zero = False
        gauge_lines = 0
        for h in storers.values():
            if not h.ws_port:
                continue
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{h.ws_port}/metrics",
                        timeout=3) as r:
                    text = r.read().decode()
            except Exception:
                continue
            for ln in text.splitlines():
                if "_digest_ok" in ln and "nebula_consistency" in ln:
                    gauge_lines += 1
                    if ln.strip().endswith(" 0"):
                        gauge_zero = True
        art["drill"]["digest_ok_gauge_lines"] = gauge_lines
        gates["divergence_gauge"] = gauge_zero
        # SHOW CONSISTENCY federates the verdicts over the storaged
        # /consistency endpoints (registered via heartbeat ws ports)
        showr2 = gc.must("SHOW CONSISTENCY")
        flat = [" ".join(str(c) for c in row) for row in showr2.rows]
        art["drill"]["show_rows"] = len(flat)
        gates["show_consistency_diverged"] = any(
            "DIVERGED" in ln for ln in flat)
        # shadow reads rode the replicated phase too — still green
        # (divergence on a follower never changes leader-served rows)
        gates["shadow_drained_repl"] = cons.shadow.drain(30)
        sh2 = cons.shadow.stats()
        art["drill"]["shadow"] = {k: sh2[k] for k in
                                 ("sampled", "verified", "mismatches",
                                  "skipped_stale", "errors")}
        gates["shadow_identity_green_repl"] = sh2["mismatches"] == 0
    finally:
        faults.clear()
        graph_flags.set("shadow_read_rate", 0.0)
        try:
            if graphd is not None:
                graphd.stop()
            for h in storers.values():
                h.stop()
            if metad is not None:
                metad.stop()
        except Exception:
            pass
        storage_flags.set("heartbeat_interval_secs", old_hb)
        storage_flags.set("raft_heartbeat_ms", old_rhb)
        storage_flags.set("raft_election_timeout_ms", old_rel)
        shutil.rmtree(run_dir, ignore_errors=True)

    art["gates"] = gates
    art["ok"] = all(bool(x) for x in gates.values())
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1, default=str)
    log(f"CONSISTENCY tier: {json.dumps(gates)}")
    log(f"wrote {out_path}")
    if not art["ok"]:
        failed = [k for k, ok in gates.items() if not ok]
        raise SystemExit(f"CONSISTENCY tier FAILED gates: {failed}")


def bench_writes(out_path: str, trim: bool = False):
    """Write-path observatory proof tier (`bench.py --writes`;
    docs/manual/10-observability.md, "Write-path observatory") — the
    before-numbers baseline for ROADMAP item 2 (group-commit pipelined
    raft writes, on-device delta compaction). Tier-1-safe on XLA:CPU.
    PASSES only when

      (a) DISARMED IS FREE: with write_obs_enabled=false a whole warm
          mixed write+read loop leaves ZERO nebula_write_*/
          nebula_snapshot_*/nebula_wal_fsync* families on /metrics and
          /snapshots reports only {"enabled": false};
      (b) STAGE TIMELINE: armed, a mixed INSERT/UPDATE/GO workload
          populates the per-stage histograms for every in-proc seam
          (execute/fanout/commit_apply/ring_publish/delta_apply) with
          trace exemplars, PROFILE on a mutation renders the
          write_stages cost block, the ack-to-visible watermark
          advances and its histogram records, the PR 15 shadow reads
          ride armed with ZERO mismatches, and EVERY acked write reads
          back (zero acked-write loss);
      (c) OVERRUN CHAIN: a sustained-churn burst past a shrunk change
          ring forces a GENUINE ring overrun — overrun(truncated) ->
          snapshot poison(ring_overrun) -> full host repack is one
          attributed chain in the lifecycle ledger, the ring_overrun
          flight bundle's "writepath" collector carries that ledger,
          the `ring.overrun` fault point fires as the deterministic
          backstop, and no acked write is lost through the repack;
      (d) REPLICATION SEAMS: on a REAL 3-replica raft cluster (metad +
          3 replicated storaged + TPU graphd, localhost TCP,
          wal_sync_every_append) the wal_append/replicate stage
          histograms, the group-commit readiness metrics
          (write.raft.round_us/round_entries/commit_batch_entries) and
          the WAL fsync histogram all populate; an injected slow fsync
          fires the fsync_stall flight trigger and a real
          acked-but-unpulled write fires visibility_stall; /snapshots
          on a storaged serves the lifecycle view;
      (e) SEAM COST: the measured per-write cost of every armed seam
          (seam_cost_probe) stays within 3% of a measured end-to-end
          write (the PR 13/14 deterministic-overhead idiom).
    """
    import shutil
    import tempfile
    import urllib.request

    from nebula_tpu.client import GraphClient
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.common import consistency as cons
    from nebula_tpu.common import writepath as wp
    from nebula_tpu.common.faults import faults
    from nebula_tpu.common.flags import graph_flags, storage_flags
    from nebula_tpu.common.flight import recorder as flight_rec
    from nebula_tpu.common.stats import stats as global_stats
    from nebula_tpu.common.tracing import tracer
    from nebula_tpu.daemons import (serve_graphd, serve_metad,
                                    serve_storaged)
    from nebula_tpu.engine_tpu import TpuGraphEngine

    seed = int(os.environ.get("BENCH_WRITES_SEED", 29))
    parts = 3
    v, e = (240, 1500) if trim else (1000, 8000)
    rng = np.random.default_rng(seed)
    gates: dict = {}
    art: dict = {"seed": seed, "trim": trim,
                 "graph": {"V": v, "E": e, "parts": parts}}

    def wp_metric_lines():
        return [ln for ln in global_stats.prometheus_lines()
                if "nebula_write" in ln or "nebula_snapshot" in ln
                or "nebula_wal_fsync" in ln]

    def hist(name):
        return global_stats.histogram_snapshot(name)

    def hist_count(name) -> int:
        h = hist(name)
        return int(h["count"]) if h else 0

    def verify_edges(connX, space, wantmap):
        """Durability journal check: every acked rank-0 write must
        read back with its LAST acked ts (the zero-acked-write-loss
        gate). One GO per distinct src; (dst, ts) existence — seed
        edges at other ranks ride the same adjacency and never mask a
        missing row."""
        connX.must(f"USE {space}")
        by_src: dict = {}
        for (s, d), t in wantmap.items():
            by_src.setdefault(s, {})[d] = t
        missing = []
        for s, dm in by_src.items():
            r = connX.must(f"GO FROM {s} OVER knows "
                           f"YIELD knows._dst, knows.ts")
            seen = {(int(row[0]), int(row[1])) for row in r.rows}
            for d, t in dm.items():
                if (d, t) not in seen:
                    missing.append([s, d, t])
        return missing

    # ---- phase 0: DISARMED — the whole loop must leave no trace
    wp.reset()
    flight_rec.reset()
    graph_flags.set("write_obs_enabled", False)
    storage_flags.set("write_obs_enabled", False)
    assert not wp.enabled()
    want: dict = {}          # (src, dst) -> last acked rank-0 ts
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    srcs, dsts, ts = zipf_edges(rng, v, e, clip=100)
    insert_person_knows(conn, "wrt", parts, v, srcs, dsts, ts)
    sid = cluster.meta.get_space("wrt").value().space_id
    tpu.prewarm(sid, block=True)

    def go(start, steps=1):
        return conn.must(f"GO {steps} STEPS FROM {int(start)} "
                         f"OVER knows YIELD knows._dst, knows.ts")

    for i in range(24):
        s = int(rng.integers(0, v))
        d = (s * 7 + 1) % v
        conn.must(f"INSERT EDGE knows(ts) VALUES {s} -> {d}:({i})")
        want[(s, d)] = i
        go(s)
    lines0 = wp_metric_lines()
    gates["disarmed_no_metric_families"] = lines0 == []
    gates["disarmed_snapshots_view"] = \
        wp.snapshots_view() == {"enabled": False}
    gates["disarmed_gauges_empty"] = wp.gauges() == {}
    art["disarmed"] = {"metric_lines": len(lines0)}

    # ---- phase 1: ARMED — mixed INSERT/UPDATE/GO with the durability
    # journal, shadow reads riding, stage histograms + watermark
    graph_flags.set("write_obs_enabled", True)
    storage_flags.set("write_obs_enabled", True)
    graph_flags.set("consistency_enabled", True)
    storage_flags.set("consistency_enabled", True)
    graph_flags.set("shadow_read_rate", 0.5)
    cons.shadow.reset()
    wp.reset()
    tracer.arm(64)           # exemplar fuel: sampled traces for the
    n_ops = 150 if trim else 600   # next 64 queries' stage records
    n_ins = n_upd = n_reads = 0
    for i in range(n_ops):
        s = int(rng.integers(0, v))
        r = i % 10
        if r < 5:
            d = int(rng.integers(0, v))
            t = TS_MAX + i
            conn.must(f"INSERT EDGE knows(ts) VALUES {s} -> {d}:({t})")
            want[(s, d)] = t
            n_ins += 1
        elif r < 7 and want:
            pairs = list(want)
            s2, d2 = pairs[int(rng.integers(0, len(pairs)))]
            t = TS_MAX + n_ops + i
            conn.must(f"UPDATE EDGE {s2} -> {d2} OF knows SET ts = {t}")
            want[(s2, d2)] = t
            n_upd += 1
        else:
            go(s, steps=1 + i % 2)
            n_reads += 1
    # PROFILE on a mutation renders the per-stage cost block the way
    # reads already do (the appended write_* ledger fields)
    t_prof = TS_MAX + 10 * n_ops
    rp = conn.must(f"PROFILE INSERT EDGE knows(ts) "
                   f"VALUES 1 -> 2:({t_prof})")
    want[(1, 2)] = t_prof
    ws = (getattr(rp, "profile", None) or {}).get("write_stages") or {}
    art["profile_write_stages"] = ws
    gates["profile_write_stages"] = \
        {"execute", "fanout", "commit_apply"} <= set(ws)
    go(0)                    # settle: pull deltas, advance watermark
    wmv = wp.watermark.stats_view()
    art["watermark"] = {str(k): dict(val) for k, val in wmv.items()}
    gates["acks_recorded"] = any(m["acked"] > 0 for m in wmv.values())
    gates["watermark_advanced"] = \
        any(m["visible"] > 0 for m in wmv.values())
    gates["ack_to_visible_recorded"] = \
        hist_count("write.ack_to_visible_ms") > 0
    art["ack_to_visible_ms"] = {
        "count": hist_count("write.ack_to_visible_ms"),
        "avg_600s": global_stats.read_stats(
            "write.ack_to_visible_ms.avg.600"),
        "p99_600s": global_stats.read_stats(
            "write.ack_to_visible_ms.p99.600")}
    st_counts = {}
    for stg in wp.STAGES:
        h = hist(f"write.stage.{stg}_us")
        st_counts[stg] = {"count": int(h["count"]),
                          "exemplars": len(h["exemplars"]),
                          "p99_600s": global_stats.read_stats(
                              f"write.stage.{stg}_us.p99.600")} \
            if h else None
    art["stages"] = st_counts
    gates["stage_timeline_inproc"] = all(
        st_counts[stg] and st_counts[stg]["count"] > 0
        for stg in ("execute", "fanout", "commit_apply",
                    "ring_publish", "delta_apply"))
    gates["stage_exemplars"] = any(
        (st_counts[stg] or {}).get("exemplars", 0) > 0
        for stg in ("execute", "fanout", "commit_apply"))
    gates["shadow_drained"] = cons.shadow.drain(30)
    sh = cons.shadow.stats()
    art["shadow"] = {k: sh[k] for k in
                     ("sampled", "verified", "mismatches",
                      "skipped_stale", "errors", "dropped")}
    gates["shadow_verified"] = sh["verified"] > 0
    gates["shadow_identity_green"] = sh["mismatches"] == 0
    graph_flags.set("shadow_read_rate", 0.0)
    missing = verify_edges(conn, "wrt", want)
    art["durability"] = {"edges_tracked": len(want),
                         "inserts": n_ins, "updates": n_upd,
                         "reads": n_reads, "missing": missing[:10]}
    gates["zero_acked_write_loss"] = missing == []
    log(f"WRITES phase 1: stages={ {k: (s0 or {}).get('count') for k, s0 in st_counts.items()} } "
        f"shadow={art['shadow']} tracked={len(want)}")

    # ---- seam cost: measured armed-seam cost vs a measured write
    # (PR 13/14 idiom — gate the deterministic seam measurement, not a
    # noisy A/B QPS ratio)
    n_probe = 60 if trim else 200
    t0 = time.perf_counter()
    for i in range(n_probe):
        s = int(rng.integers(0, v))
        d = int(rng.integers(0, v))
        t = 2 * TS_MAX + i
        conn.must(f"INSERT EDGE knows(ts) VALUES {s} -> {d}:({t})")
        want[(s, d)] = t
    write_us = (time.perf_counter() - t0) / n_probe * 1e6
    seam_us = wp.seam_cost_probe()
    seam_frac = seam_us / write_us
    art["overhead"] = {"seam_us_per_write": round(seam_us, 2),
                       "write_us": round(write_us, 1),
                       "seam_frac": round(seam_frac, 4)}
    gates["overhead_within_contract"] = seam_frac <= 0.03

    # ---- phase 2: sustained churn past a shrunk change ring — the
    # GENUINE overrun -> poison -> repack chain, attributed end to end
    old_ring_ops = storage_flags.get("change_ring_ops")
    storage_flags.set("change_ring_ops", 64)   # REBOOT-effective: the
    v2, e2 = (120, 400) if trim else (300, 1200)  # ring is born with
    srcs2, dsts2, ts2 = zipf_edges(rng, v2, e2, clip=40)  # this space
    insert_person_knows(conn, "wchurn", parts, v2, srcs2, dsts2, ts2)
    storage_flags.set("change_ring_ops", old_ring_ops)
    sid2 = cluster.meta.get_space("wchurn").value().space_id
    tpu.prewarm(sid2, block=True)
    conn.must("GO FROM 1 OVER knows YIELD knows._dst")  # anchor cursor
    flight_rec.reset()
    ov0 = global_stats.lifetime_total("write.ring.overrun")
    rp0 = wp.snapshots.view()["counts"].get("repack", 0)
    want2: dict = {}
    n_burst = 200 if trim else 400     # >> the 64-op ring between pulls
    for i in range(n_burst):
        s = int(rng.integers(0, v2))
        d = int(rng.integers(0, v2))
        t = 3 * TS_MAX + i
        conn.must(f"INSERT EDGE knows(ts) VALUES {s} -> {d}:({t})")
        want2[(s, d)] = t
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        conn.must("GO FROM 1 OVER knows YIELD knows._dst")
        if (global_stats.lifetime_total("write.ring.overrun") > ov0
                and wp.snapshots.view()["counts"].get("repack", 0)
                > rp0):
            break
        time.sleep(0.05)
    gates["ring_overrun_fired"] = \
        global_stats.lifetime_total("write.ring.overrun") > ov0
    view = wp.snapshots.view()
    ev2 = view["spaces"].get(sid2, [])
    causes: dict = {}
    for evt in ev2:
        causes.setdefault(evt["event"], []).append(evt.get("cause"))
    art["overrun"] = {"ledger_counts": view["counts"],
                      "space_events": ev2[-12:],
                      "rings": {str(k): val for k, val
                                in wp.ring_status().items()}}
    gates["overrun_cause_chain"] = (
        "truncated" in causes.get("overrun", ())
        and "ring_overrun" in causes.get("poison", ())
        and "ring_overrun" in causes.get("repack", ()))
    flight_rec.flush()
    bundles = [b for b in flight_rec.bundles
               if b["trigger"] == "ring_overrun"]
    wcol = (bundles[-1].get("collectors") or {}).get("writepath") \
        if bundles else None
    gates["overrun_bundle"] = bool(
        bundles and bundles[-1]["event"].get("cause") == "truncated")
    gates["bundle_carries_lifecycle"] = bool(
        wcol and (wcol.get("ledger") or {}).get("counts", {})
        .get("overrun"))
    # deterministic backstop: the `ring.overrun` fault point forces
    # the identical decline shape (cause="injected") on the next pull
    faults.set_plan("ring.overrun:n=1")
    t_inj = 3 * TS_MAX + n_burst + 1
    conn.must(f"INSERT EDGE knows(ts) VALUES 2 -> 3:({t_inj})")
    want2[(2, 3)] = t_inj
    # the fault sits in the provider's delta pull — under load the
    # first GO can land while the post-overrun repack is still
    # installing (no snapshot to pull against), so retry until the
    # engine is back on the incremental feed and the point fires
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        conn.must("GO FROM 2 OVER knows YIELD knows._dst")
        if faults.counts().get("ring.overrun", 0) >= 1:
            break
        time.sleep(0.1)
    gates["overrun_fault_fired"] = \
        faults.counts().get("ring.overrun", 0) >= 1
    faults.clear()
    # zero acked-write loss THROUGH the overrun + repack: retry while
    # the background repack lands
    deadline = time.monotonic() + 20
    missing2 = verify_edges(conn, "wchurn", want2)
    while missing2 and time.monotonic() < deadline:
        time.sleep(0.2)
        missing2 = verify_edges(conn, "wchurn", want2)
    art["overrun"]["edges_tracked"] = len(want2)
    art["overrun"]["missing"] = missing2[:10]
    gates["zero_loss_through_overrun"] = missing2 == []
    log(f"WRITES phase 2: overruns="
        f"{global_stats.lifetime_total('write.ring.overrun') - ov0:g} "
        f"chain={gates['overrun_cause_chain']} "
        f"bundle={gates['overrun_bundle']}")

    # ---- phase 3: the replication seams on a REAL 3-replica cluster
    space = "wrep"
    run_dir = tempfile.mkdtemp(prefix="nebula_tpu_writebench_")
    old_hb = storage_flags.get("heartbeat_interval_secs")
    old_rhb = storage_flags.get("raft_heartbeat_ms")
    old_rel = storage_flags.get("raft_election_timeout_ms")
    old_sync = storage_flags.get("wal_sync_every_append")
    storage_flags.set("heartbeat_interval_secs", 0.4)
    storage_flags.set("raft_heartbeat_ms", 60)
    storage_flags.set("raft_election_timeout_ms", 250)
    storage_flags.set("wal_sync_every_append", True)   # REBOOT: read
    metad = graphd = None                              # at part bind
    storers = {}
    try:
        metad = serve_metad(expired_threshold_secs=5)
        for i in range(3):
            storers[i] = serve_storaged(
                metad.addr, replicated=True, engine="mem",
                data_dir=os.path.join(run_dir, f"s{i}"),
                load_interval=0.15, ws_port=0)
        tpu2 = TpuGraphEngine()
        graphd = serve_graphd(metad.addr, tpu_engine=tpu2)
        gc = GraphClient(graphd.addr).connect()
        v3, e3 = (120, 600) if trim else (300, 2000)
        srcs3, dsts3, ts3 = zipf_edges(rng, v3, e3, clip=60)
        insert_person_knows(gc, space, parts, v3, srcs3, dsts3, ts3,
                            replica_factor=3, settle_s=20.0)
        sid3 = metad.meta.get_space(space).value().space_id
        gc.must("GO 1 STEPS FROM 1 OVER knows YIELD knows._dst")
        wseq = 0
        end = time.monotonic() + (1.5 if trim else 3.0)
        while time.monotonic() < end:
            s = int(rng.integers(0, v3))
            gc.must(f"INSERT EDGE knows(ts) VALUES {s} -> "
                    f"{(s * 7 + 3) % v3}:({wseq})")
            if wseq % 3 == 0:
                gc.must(f"GO FROM {s} OVER knows YIELD knows._dst")
            wseq += 1
        repl = {}
        for name in ("write.stage.wal_append_us",
                     "write.stage.replicate_us",
                     "write.raft.round_us",
                     "write.raft.round_entries",
                     "write.raft.pending_appends",
                     "write.raft.quorum_wait_us",
                     "write.raft.commit_batch_entries",
                     "wal.fsync_us"):
            repl[name] = {"count": hist_count(name),
                          "p99_600s": global_stats.read_stats(
                              f"{name}.p99.600")}
        art["replicated"] = {"writes": wseq, "metrics": repl}
        gates["stage_timeline_replicated"] = (
            hist_count("write.stage.wal_append_us") > 0
            and hist_count("write.stage.replicate_us") > 0)
        gates["group_commit_metrics"] = (
            hist_count("write.raft.round_us") > 0
            and hist_count("write.raft.round_entries") > 0
            and hist_count("write.raft.commit_batch_entries") > 0)
        gates["fsync_histogram"] = hist_count("wal.fsync_us") > 0
        # /snapshots on a storaged serves the lifecycle view
        snap_body = None
        for h in storers.values():
            if not h.ws_port:
                continue
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{h.ws_port}/snapshots",
                        timeout=3) as r:
                    snap_body = json.loads(r.read().decode())
                break
            except Exception:
                continue
        gates["snapshots_endpoint"] = bool(
            snap_body and snap_body.get("enabled") is True
            and "ledger" in snap_body and "watermark" in snap_body)
        # fsync_stall drill: one injected slow fsync on a leader WAL
        # (the fault sleeps INSIDE the measured sync extent)
        storage_flags.set("fsync_stall_ms", 2)
        # n=3: group-commit/compaction syncs race this plan — a budget
        # of 1 can be consumed before the drill's own sync under load.
        # The whole drill retries: under heavy load the leader lookup
        # can catch sid3 mid-election (no LEADER row → nothing to
        # sync), so keep re-resolving until the stall lands.
        faults.set_plan("wal.sync:latency=10,n=3")
        gates["fsync_stall_fired"] = False
        fs_deadline = time.monotonic() + 15
        while time.monotonic() < fs_deadline \
                and not gates["fsync_stall_fired"]:
            target = None
            for h in storers.values():
                if h.node is None:
                    continue
                for st in h.node.raft_status():
                    if st["role"] == "LEADER" and st["space"] == sid3:
                        target = h.node.raft(st["space"], st["part"])
                        break
                if target is not None:
                    break
            if target is None:
                time.sleep(0.3)
                continue
            if faults.counts().get("wal.sync", 0) < 1:
                faults.set_plan("wal.sync:latency=10,n=3")
            target.wal.sync()
            flight_rec.flush()
            gates["fsync_stall_fired"] = (
                faults.counts().get("wal.sync", 0) >= 1
                and any(b["trigger"] == "fsync_stall"
                        for b in flight_rec.bundles))
            if not gates["fsync_stall_fired"]:
                time.sleep(0.3)
        storage_flags.set("fsync_stall_ms", 0)
        faults.clear()
        # visibility_stall drill: a REAL acked write with no read to
        # pull it device-side — the gauge scrape fires the trigger
        graph_flags.set("visibility_stall_ms", 1)
        gc.must(f"INSERT EDGE knows(ts) VALUES 1 -> 5:({4 * TS_MAX})")
        time.sleep(0.05)
        wp.gauges()          # scrape path: stalled spaces fire without
        flight_rec.flush()   # a fresh watermark advance
        gates["visibility_stall_fired"] = any(
            b["trigger"] == "visibility_stall"
            for b in flight_rec.bundles)
        graph_flags.set("visibility_stall_ms", 0)
        art["flight_bundles"] = sorted(
            {b["trigger"] for b in flight_rec.bundles})
        log(f"WRITES phase 3: writes={wseq} repl_metrics="
            f"{ {k: m['count'] for k, m in repl.items()} }")
    finally:
        faults.clear()
        graph_flags.set("shadow_read_rate", 0.0)
        graph_flags.set("consistency_enabled", False)
        storage_flags.set("consistency_enabled", False)
        graph_flags.set("visibility_stall_ms", 0)
        storage_flags.set("fsync_stall_ms", 0)
        storage_flags.set("change_ring_ops", old_ring_ops)
        try:
            if graphd is not None:
                graphd.stop()
            for h in storers.values():
                h.stop()
            if metad is not None:
                metad.stop()
        except Exception:
            pass
        storage_flags.set("heartbeat_interval_secs", old_hb)
        storage_flags.set("raft_heartbeat_ms", old_rhb)
        storage_flags.set("raft_election_timeout_ms", old_rel)
        storage_flags.set("wal_sync_every_append", old_sync)
        shutil.rmtree(run_dir, ignore_errors=True)

    # ---- disarm re-check: the live surfaces empty out the moment the
    # flag drops (the registered stats families are process-lifetime —
    # phase 0 proved none exist before arming)
    graph_flags.set("write_obs_enabled", False)
    storage_flags.set("write_obs_enabled", False)
    gates["disarm_gauges_empty"] = wp.gauges() == {}
    gates["disarm_snapshots_view"] = \
        wp.snapshots_view() == {"enabled": False}
    graph_flags.set("write_obs_enabled", True)
    storage_flags.set("write_obs_enabled", True)

    art["gates"] = gates
    art["ok"] = all(bool(x) for x in gates.values())
    with open(out_path, "w") as f:
        json.dump(art, f, indent=1, default=str)
    log(f"WRITES tier: {json.dumps(gates)}")
    log(f"wrote {out_path}")
    if not art["ok"]:
        failed = [k for k, ok in gates.items() if not ok]
        raise SystemExit(f"WRITES tier FAILED gates: {failed}")


def bench_chaos(out_path: str, trim: bool = False):
    """Chaos tier (`bench.py --chaos`): the 8-session workload under
    injected kernel/mesh/encode faults (common/faults.py; docs/manual/
    9-robustness.md). PASSES only when

      (a) every result observed by a session is byte-identical to the
          CPU pipe's for the same query,
      (b) the error rate seen by clients is ZERO (every device failure
          degraded, none escaped), and
      (c) the degradation ladder actually engaged: breaker trips
          during the fault window, then half-open recovery back to the
          device path once faults stop.

    Tier-1-safe on XLA:CPU — no accelerator, no native engine needed
    (`--trim` shrinks the graph/query counts and trips the breaker on
    the first failure so the smoke test is fast and deterministic)."""
    import threading
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.common.faults import faults
    from nebula_tpu.common.lockwitness import witness
    from nebula_tpu.engine_tpu import TpuGraphEngine

    # the lock-order witness rides every chaos run: the failure/
    # degradation paths exercised here (breaker trips, CPU-pipe
    # retries, half-open probes) are exactly where a lock-order
    # inversion would hide; the run fails on a cycle or a sleep
    # observed under a witnessed lock (common/lockwitness.py; set
    # NEBULA_TPU_LOCK_WITNESS=1 to also wrap import-time locks)
    witness.install()

    seed = int(os.environ.get("BENCH_CHAOS_SEED", 7))
    sessions = 8
    v, e, per_session = (300, 2500, 6) if trim else (1500, 15000, 40)
    # chaos runs with the FULL cache ladder armed (docs/manual/
    # 11-caching.md): byte-identity under injected faults must hold
    # with the result cache, in-window dedupe and negative caches all
    # live — a stale or fault-corrupted cache entry would surface as a
    # mismatch here
    from nebula_tpu.common.flags import graph_flags, storage_flags
    from nebula_tpu.common.status import ErrorCode
    graph_flags.set("cache_mode", "full")
    storage_flags.set("cache_mode", "full")
    # chaos runs with the QoS ladder ARMED (docs/manual/14-qos.md):
    # per-space admission + lane scheduling + a shed watermark must
    # COMPOSE with breakers and CPU-pipe retries — the budgets are
    # generous (this workload is legitimate), so sheds/denials are
    # rare, but every E_OVERLOAD a worker does see is retried per the
    # typed-retryable contract and counted, and any OTHER error still
    # fails the tier
    graph_flags.set("qos_plan", "chaos:rate=500,burst=500")
    graph_flags.set("qos_shed_queue_depth", 64)
    qos_overload_retries = [0]
    # flight recorder armed for the run (ISSUE 10 acceptance): the
    # injected anomalies must AUTO-capture at least one bundle whose
    # events correlate by trace_id with a histogram exemplar on the
    # metrics surface; bundles dump atomically to a scratch dir
    import tempfile
    from nebula_tpu.common.flight import recorder as flight_rec
    flight_rec.reset()
    graph_flags.set("flight_dir", tempfile.mkdtemp(
        prefix="nebula_tpu_flight_"))
    graph_flags.set("flight_arm_samples", 200)
    # continuous-profiling observatory armed for the run (ISSUE 13
    # acceptance): every auto-captured bundle must embed a populated
    # profile capture whose trace-tagged samples correlate with an
    # exemplar trace id — the chaos harness runs headless (no
    # webservice), so it arms the sampler the way a daemon boot would
    from nebula_tpu.common import profiler as prof_mod
    prof_mod.ensure_started()
    prof_mod.profiler.reset()
    prof_mod.profiler.set_hz(19.0)
    tpu = TpuGraphEngine()
    # tight ladder so the run observes the full trip -> half-open ->
    # recover cycle in seconds (production defaults are 3 / 0.5s / 30s)
    tpu.breaker_threshold = 1 if trim else 2
    tpu.breaker_base_s = 0.2
    tpu.breaker_max_s = 2.0
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    rng = np.random.default_rng(seed)
    srcs, dsts, ts = zipf_edges(rng, v, e, clip=120)
    insert_person_knows(conn, "chaos", 4, v, srcs, dsts, ts)
    # the index verbs ride the same chaos mix (ISSUE 17): LOOKUP needs
    # a catalog index, and index.search faults join the plan below so
    # the device index path degrades to the storaged scan under fire
    conn.must("CREATE TAG INDEX chaos_person_age ON person(age)")
    sid = cluster.meta.get_space("chaos").value().space_id
    tpu.prewarm(sid, block=True)
    tpu.sparse_edge_budget = 0   # pin dense: faults land on the
    hubs = [int(x) for x in     # kernel-launch path, not the host pull
            np.argsort(np.bincount(srcs, minlength=v))[-4:]]
    queries = [
        f"GO 2 STEPS FROM {hubs[0]} OVER knows YIELD knows._dst",
        f"GO 3 STEPS FROM {hubs[1]} OVER knows YIELD knows._dst",
        f"GO 2 STEPS FROM {hubs[2]} OVER knows "
        f"WHERE knows.ts > {TS_MAX // 2} YIELD knows._dst, knows.ts",
        f"GO 2 STEPS FROM {hubs[3]} OVER knows YIELD knows.ts AS t"
        f" | YIELD COUNT(*) AS n, SUM($-.t) AS s, AVG($-.t) AS a",
        f"GO FROM {hubs[0]}, {hubs[1]} OVER knows "
        f"YIELD knows._dst, knows.ts",
        # PR 17 verbs under the same identity + zero-client-error bar
        "LOOKUP ON person WHERE person.age > 70 YIELD person.age",
        f"GET SUBGRAPH 2 STEPS FROM {hubs[2]} OVER knows",
        "MATCH (a:person {age: 42})-[e:knows]->(b) RETURN a, b",
    ]
    conn.must(queries[0])   # compile + snapshot warm, OFF the chaos

    # ---- phase 1: the 8-session workload under an armed fault plan
    plan = (f"seed={seed};kernel.launch:p=0.3;mesh.collective:p=0.3;"
            f"encode.rows:p=0.2;index.search:p=0.2")
    faults.set_plan(plan)
    observed: dict = {}
    errs: list = []
    olock = threading.Lock()

    def must_qos(c, q):
        """must() that honors the E_OVERLOAD contract: typed overloads
        retry after a short backoff (counted); anything else raises
        and fails the tier."""
        for _ in range(400):
            r = c.execute(q)
            if r.ok():
                return r
            if r.code != ErrorCode.E_OVERLOAD:
                raise RuntimeError(f"query failed [{r.code.name}]: "
                                   f"{r.error_msg}\n  query: {q}")
            with olock:
                qos_overload_retries[0] += 1
            time.sleep(0.02)
        raise RuntimeError(f"E_OVERLOAD never cleared for: {q}")

    def worker(k):
        try:
            c = cluster.connect()
            c.must("USE chaos")
            for i in range(per_session):
                q = queries[(k + i) % len(queries)]
                if i % 2 == 0:
                    # the full-mode result cache would absorb this
                    # fixed query pool and starve the kernel-launch
                    # fault point (no launches -> no trips -> flaky
                    # run); alternating clears guarantee device serves
                    # under the armed plan while the odd iterations
                    # still exercise cached serves' byte-identity
                    tpu.result_cache.clear()
                r = must_qos(c, q)
                key = tuple(sorted(map(repr, r.rows)))
                with olock:
                    observed.setdefault(q, set()).add(key)
        except Exception as ex:   # noqa: BLE001 — recorded, fails run
            errs.append(repr(ex))

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(sessions)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    chaos_wall = time.time() - t0
    faults.clear()
    fired = faults.counts()
    trips = tpu.stats["breaker_trips"]

    # ---- identity: every observed result must be byte-identical to
    # the CPU pipe's (the graph is static, so one reference per query)
    mismatches = []
    tpu.enabled = False
    try:
        for q in queries:
            ref = tuple(sorted(map(repr, conn.must(q).rows)))
            for obs in observed.get(q, ()):
                if obs != ref:
                    mismatches.append(q)
                    break
    finally:
        tpu.enabled = True

    # ---- phase 2: faults stopped — half-open probes must re-admit the
    # device path (breaker closed + device actually serving again).
    # The result cache is dropped per sweep: on this STATIC graph the
    # warm cache would otherwise serve every repeat before the breaker
    # gate (by design — an open breaker degrades to the cache, and the
    # half-open probe rides the first MISS; here we force misses so
    # the run proves the device itself recovers)
    recovered = False
    deadline = time.time() + 60
    while time.time() < deadline:
        tpu.result_cache.clear()
        g0 = tpu.stats["go_served"] + tpu.stats["agg_served"]
        l0 = tpu.stats["lookup_served"]
        for q in queries:
            conn.must(q)
        states = tpu.breaker_states()
        # the device must serve GO *and* the index path again (the
        # armed index.search faults trip the "index" breaker too)
        served_again = ((tpu.stats["go_served"]
                         + tpu.stats["agg_served"]) > g0
                        and tpu.stats["lookup_served"] > l0)
        if served_again and all(s == "closed" for s in states.values()):
            recovered = True
            break
        time.sleep(0.1)

    # ---- phase 3 (ISSUE 10): an INJECTED OVERLOAD must drive an SLO
    # burn-rate gauge over its threshold, and recovery traffic must
    # bring it back under — the availability objective rides the QoS
    # per-tenant admission slices (common/slo.py). Denials here are
    # deliberate typed E_OVERLOADs, never client errors.
    from nebula_tpu.common import slo as slo_mod
    slo_name = "chaos-avail"
    graph_flags.set("slo_plan",
                    f"{slo_name}:kind=availability,"
                    f"good=graph.qos.admitted.chaos,"
                    f"bad=graph.qos.denied.chaos,target=0.9,burn=2")
    slo_rec = {"denied": 0, "burn_peak": 0.0, "breached": False,
               "burn_recovered": None, "recovered_under": False}
    graph_flags.set("qos_plan", "chaos:rate=0")   # deny-all: overload
    # paced like a real client under deny-all (denials return in
    # ~0.2ms — unpaced, the WHOLE storm fits inside one evaluation
    # cache window and the gauge legitimately never turns over):
    # detection latency is bounded by the 1 Hz evaluator, so the
    # storm keeps burning until the gauge has had a chance to see it
    slo_poll = time.time() + 20
    i = 0
    while time.time() < slo_poll and not slo_rec["breached"]:
        for _ in range(40):
            i += 1
            r = conn.execute("YIELD 1")
            if r.code == ErrorCode.E_OVERLOAD:
                slo_rec["denied"] += 1
            elif not r.ok():
                errs.append(f"slo overload phase: [{r.code.name}] "
                            f"{r.error_msg}")
                break
        if errs and errs[-1].startswith("slo overload phase"):
            break
        time.sleep(0.25)   # let the evaluator tick / the cache age
        g = slo_mod.engine.gauges()
        slo_rec["burn_peak"] = max(slo_rec["burn_peak"],
                                   g[f"slo.{slo_name}.burn_60s"])
        if g[f"slo.{slo_name}.breached"] >= 1:
            slo_rec["breached"] = True
    graph_flags.set("qos_plan", "chaos:rate=500,burst=500")  # recover
    slo_deadline = time.time() + 45
    while slo_rec["breached"] and time.time() < slo_deadline:
        for _ in range(25):
            r = conn.execute("YIELD 1")
            if r.code == ErrorCode.E_OVERLOAD:
                time.sleep(0.01)   # paced: honor the restored budget
            elif not r.ok():
                errs.append(f"slo recovery phase: [{r.code.name}] "
                            f"{r.error_msg}")
                break
        if errs and errs[-1].startswith("slo recovery phase"):
            break   # fail fast with ONE error, not 45s of duplicates
        time.sleep(0.25)   # evaluator cadence, like the breach side
        g = slo_mod.engine.gauges()
        slo_rec["burn_recovered"] = g[f"slo.{slo_name}.burn_60s"]
        if g[f"slo.{slo_name}.breached"] < 1 \
                and g[f"slo.{slo_name}.burn_60s"] < 2:
            slo_rec["recovered_under"] = True
            break
    graph_flags.set("slo_plan", "")

    # ---- flight-recorder acceptance: >= 1 auto-captured bundle with a
    # populated ring whose events correlate (by trace_id) with at
    # least one exemplar exposed on the metrics surface
    flight_rec.flush(10.0)   # capture threads finish enrichment
    from nebula_tpu.common.stats import stats as global_stats
    exemplar_tids = set()
    for hname in global_stats.histogram_names():
        h = global_stats.histogram_snapshot(hname)
        exemplar_tids.update(e["trace_id"]
                             for e in h["exemplars"].values())
    bundle_tids = set()
    for b in flight_rec.bundles:
        for e in list(b["events"]) + list(b["aftermath_events"]):
            if "trace_id" in e:
                bundle_tids.add(e["trace_id"])
    flight_ok = bool(
        flight_rec.bundles
        and all(len(b["events"]) > 0 for b in flight_rec.bundles)
        and (bundle_tids & exemplar_tids))
    # ---- continuous-profiling acceptance (ISSUE 13): the bundles'
    # embedded profile captures are populated (sampled frames) and
    # their trace-TAGGED samples correlate with >= 1 exemplar trace id
    profile_tids = set()
    profile_samples = 0
    for b in flight_rec.bundles:
        pb = (b.get("collectors") or {}).get("profile")
        if not isinstance(pb, dict) or "top" not in pb:
            continue
        profile_samples = max(profile_samples,
                              pb["top"].get("samples", 0))
        profile_tids.update(s["trace_id"]
                            for s in pb.get("tagged_samples", ()))
    profile_ok = bool(profile_samples > 0
                      and (profile_tids & exemplar_tids))
    flight_summary = flight_rec.describe(limit=8)
    graph_flags.set("flight_dir", "")
    graph_flags.set("flight_arm_samples", 25)

    rb = tpu.robustness_stats()
    # sample the dispatcher qos block BEFORE disarming: the artifact
    # must record the watermarks the run actually proved composition
    # under, not the cleared values
    qos_disp = tpu.qos_stats()
    graph_flags.set("qos_plan", "")
    graph_flags.set("qos_shed_queue_depth", 0)
    rec = {
        "trim": trim,
        "cache_mode": "full",
        # QoS ladder armed for the whole run (composition proof):
        # every overload a worker saw was typed + retried successfully
        "qos": {"plan": "chaos:rate=500,burst=500",
                "overload_retries": qos_overload_retries[0],
                "dispatcher": qos_disp},
        "cache": tpu.cache_stats(),
        # device secondary-index lifecycle under fire (ISSUE 17):
        # nonzero lookup/subgraph serves prove the verbs rode the mix
        "index": tpu.index_stats(),
        "seed": seed,
        "sessions": sessions,
        "graph": {"V": v, "E": e},
        "queries_per_session": per_session,
        "chaos_wall_s": round(chaos_wall, 1),
        "fault_plan": plan,
        "faults_injected": fired,
        "client_errors": errs[:3],
        "mismatches": mismatches,
        "breaker_trips": trips,
        "recovered": recovered,
        "robustness": rb,
        "degraded_serves": rb["degraded_serves"],
        "deadline_exceeded": rb["deadline_exceeded"],
        "lock_witness": _witness_summary(),
        # continuous diagnostics (ISSUE 10): auto-captured flight
        # bundles + the metric<->trace exemplar correlation, and the
        # SLO burn round-trip under the injected overload (the
        # "flight" block itself rides in via _obs_block below)
        "flight_correlated_trace_ids": sorted(
            bundle_tids & exemplar_tids)[:8],
        "flight_ok": flight_ok,
        # the bundles' embedded profile captures (ISSUE 13): sampled
        # frames present + tagged samples correlating with exemplars
        "profile_bundle": {
            "ok": profile_ok,
            "samples": profile_samples,
            "correlated_trace_ids": sorted(
                profile_tids & exemplar_tids)[:8],
        },
        "slo": {"plan_objective": slo_name, **slo_rec},
        **_obs_block(),
    }
    # disarm AFTER the artifact's profile block sampled the live
    # sampler state (it must record the hz the run actually ran at)
    prof_mod.profiler.set_hz(0)
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    ok = (not errs and not mismatches and trips > 0 and recovered
          and sum(fired.values()) > 0
          and rb["breaker_recoveries"] > 0
          and rec["lock_witness"]["clean"]
          and flight_ok and profile_ok
          and slo_rec["breached"] and slo_rec["recovered_under"])
    log(f"chaos tier: {sessions} sessions x {per_session} queries under "
        f"{plan!r}: {sum(fired.values())} faults injected, "
        f"{trips} breaker trips, {rb['degraded_serves']} degraded "
        f"serves, errors={len(errs)}, mismatches={len(mismatches)}, "
        f"recovered={recovered}, flight bundles="
        f"{len(flight_summary['bundles'])} (correlated="
        f"{len(bundle_tids & exemplar_tids)}), profile capture "
        f"ok={profile_ok} ({profile_samples} samples, "
        f"{len(profile_tids & exemplar_tids)} correlated), slo burn "
        f"peak={slo_rec['burn_peak']} -> back under="
        f"{slo_rec['recovered_under']} -> {out_path}")
    print(json.dumps({"metric": "chaos", "ok": ok, **{
        k: rec[k] for k in ("faults_injected", "breaker_trips",
                            "degraded_serves", "recovered",
                            "mismatches", "flight_ok")},
        "slo_breached": slo_rec["breached"],
        "slo_recovered": slo_rec["recovered_under"]}))
    if not ok:
        raise SystemExit(f"chaos tier FAILED: {rec}")
    return rec


# multi-tenant QoS tier bounds (docs/manual/14-qos.md): with the
# abuser throttled, every small tenant's p99 must hold within this
# factor of its own no-abuser baseline — with an absolute floor so
# 1-core CPU-XLA timing noise can't flake a passing run
QOS_P99_FACTOR = 8.0
QOS_P99_FLOOR_MS = 250.0


def bench_tenants(out_path: str, trim: bool = False):
    """Multi-tenant QoS tier (`bench.py --tenants`): one ABUSIVE tenant
    firing closed-loop bulk scans against many small tenants running
    interactive point queries, all through one graphd/engine, with the
    QoS ladder armed (per-space admission + priority lanes + shed
    watermarks; docs/manual/14-qos.md). PASSES only when

      (a) the abuser is actually throttled: admission denials > 0 and
          the abuser observed typed E_OVERLOAD errors (with retry-after
          hints) — and still made progress (throttled, not starved);
      (b) every small tenant's p99 under abuse holds within
          QOS_P99_FACTOR of its own no-abuser baseline (floor
          QOS_P99_FLOOR_MS) — the isolation claim;
      (c) the ONLY client-visible errors anywhere are E_OVERLOAD, and
          none of them land on a small tenant;
      (d) TPU-vs-CPU byte identity is green for every tenant's query
          pool after the abuse phase.

    Per-tenant slices (admitted/denied per space, lane rounds, sheds)
    land in the JSON artifact — the same data /tpu_stats serves in its
    "qos" block. Tier-1-safe on XLA:CPU (`--trim` shrinks everything
    for the subprocess smoke test, tests/test_qos_smoke.py)."""
    import random
    import threading
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.common.flags import graph_flags
    from nebula_tpu.common.qos import admission
    from nebula_tpu.common.status import ErrorCode
    from nebula_tpu.engine_tpu import TpuGraphEngine

    seed = int(os.environ.get("BENCH_TENANTS_SEED", 13))
    n_small, sv, se, av, ae, phase_s, abusers = \
        (3, 150, 900, 300, 2500, 2.5, 2) if trim \
        else (5, 400, 3000, 900, 7000, 6.0, 3)
    admission.reset()
    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    rng = np.random.default_rng(seed)

    tenants = [f"tenant{i}" for i in range(n_small)]
    pools: dict = {}
    log(f"tenants tier: loading {n_small} small tenants "
        f"(V={sv} E={se}) + 1 abuser (V={av} E={ae})...")
    for t in tenants:
        srcs, dsts, ts = zipf_edges(rng, sv, se, clip=60)
        insert_person_knows(conn, t, 2, sv, srcs, dsts, ts)
        hubs = [int(x) for x in
                np.argsort(np.bincount(srcs, minlength=sv))[-3:]]
        pools[t] = [
            f"GO FROM {hubs[0]} OVER knows YIELD knows._dst",
            f"GO 2 STEPS FROM {hubs[1]} OVER knows YIELD knows._dst",
            f"GO FROM {hubs[1]}, {hubs[2]} OVER knows "
            f"YIELD knows._dst, knows.ts",
            f"GO 2 STEPS FROM {hubs[2]} OVER knows "
            f"WHERE knows.ts > {TS_MAX // 2} YIELD knows._dst",
        ]
    srcs, dsts, ts = zipf_edges(rng, av, ae, clip=120)
    insert_person_knows(conn, "abuser", 4, av, srcs, dsts, ts)
    ab_hubs = [int(x) for x in
               np.argsort(np.bincount(srcs, minlength=av))[-4:]]
    abuser_pool = [
        f"GO 3 STEPS FROM {ab_hubs[0]} OVER knows YIELD knows._dst",
        f"GO 3 STEPS FROM {ab_hubs[1]} OVER knows "
        f"WHERE knows.ts > {TS_MAX // 3} YIELD knows._dst, knows.ts",
        f"GO 3 STEPS FROM {ab_hubs[2]}, {ab_hubs[3]} OVER knows "
        f"YIELD knows._dst",
    ]
    for t in tenants + ["abuser"]:
        sid = cluster.meta.get_space(t).value().space_id
        tpu.prewarm(sid, block=True)
    # one pass per pool off the clock (kernel compiles + plan cache)
    for space, pool in list(pools.items()) + [("abuser", abuser_pool)]:
        conn.must(f"USE {space}")
        for q in pool:
            conn.must(q)

    errors: list = []             # every non-E_OVERLOAD failure
    overloads = {"abuser": 0, "small": 0}
    served = {"abuser": 0}
    lock = threading.Lock()
    lats = {t: {"baseline": [], "abuse": []} for t in tenants}

    def tenant_worker(t, phase, stop):
        rr = random.Random(seed * 100 + tenants.index(t))
        c = cluster.connect()
        c.must(f"USE {t}")
        pool = pools[t]
        while not stop.is_set():
            q = pool[rr.randrange(len(pool))]
            t0 = time.monotonic()
            r = c.execute(q)
            ms = (time.monotonic() - t0) * 1e3
            with lock:
                if r.ok():
                    lats[t][phase].append(ms)
                elif r.code == ErrorCode.E_OVERLOAD:
                    overloads["small"] += 1
                else:
                    errors.append((t, phase, r.code.name,
                                   r.error_msg))

    def abuser_worker(k, stop):
        rr = random.Random(seed * 999 + k)
        c = cluster.connect()
        c.must("USE abuser")
        while not stop.is_set():
            q = abuser_pool[rr.randrange(len(abuser_pool))]
            r = c.execute(q)
            with lock:
                if r.ok():
                    served["abuser"] += 1
                elif r.code == ErrorCode.E_OVERLOAD:
                    overloads["abuser"] += 1
                else:
                    errors.append(("abuser", "abuse", r.code.name,
                                   r.error_msg))
            if not r.ok():
                # the E_OVERLOAD contract: typed + retryable — back
                # off by (a fraction of) the hint and re-issue
                time.sleep(0.02)

    def run_phase(phase, with_abuser):
        stop = threading.Event()
        ths = [threading.Thread(target=tenant_worker,
                                args=(t, phase, stop))
               for t in tenants]
        if with_abuser:
            ths += [threading.Thread(target=abuser_worker,
                                     args=(k, stop))
                    for k in range(abusers)]
        for th in ths:
            th.start()
        time.sleep(phase_s)
        stop.set()
        for th in ths:
            th.join(timeout=120)
        return [th.name for th in ths if th.is_alive()]

    # ---- phase 1: small tenants alone (their own baseline)
    stragglers = run_phase("baseline", False)

    # ---- phase 2: abuser joins, QoS armed — admission throttles the
    # abusive space, its scans classify onto the bulk lane, and the
    # shed watermark stands behind both (ahead of deadline balks)
    plan = "abuser:rate=8,burst=8,lane=bulk"
    graph_flags.set("qos_plan", plan)
    graph_flags.set("qos_shed_queue_depth", 32)
    try:
        stragglers += run_phase("abuse", True)
    finally:
        # sample the armed-state dispatcher block before disarming —
        # the artifact records the configuration the phase ran under
        qos_disp = tpu.qos_stats()
        graph_flags.set("qos_plan", "")
        graph_flags.set("qos_shed_queue_depth", 0)

    # ---- identity: every tenant's pool TPU-vs-CPU byte-identical
    identity_checked, mismatches = 0, []
    for space, pool in list(pools.items()) + [("abuser", abuser_pool)]:
        conn.must(f"USE {space}")
        for q in pool:
            rt = conn.must(q)
            tpu.enabled = False
            try:
                rc = conn.must(q)
            finally:
                tpu.enabled = True
            if sorted(map(repr, rt.rows)) != sorted(map(repr, rc.rows)):
                mismatches.append(f"{space}: {q}")
            identity_checked += 1

    def pct(xs, p):
        if not xs:
            return None
        return round(float(np.percentile(np.asarray(xs), p)), 2)

    per_tenant: dict = {}
    p99_ok = True
    for t in tenants:
        b, a = lats[t]["baseline"], lats[t]["abuse"]
        bp99, ap99 = pct(b, 99), pct(a, 99)
        bound = round(max((bp99 or 0) * QOS_P99_FACTOR,
                          QOS_P99_FLOOR_MS), 2)
        ok_t = bool(b) and bool(a) and ap99 <= bound
        p99_ok = p99_ok and ok_t
        per_tenant[t] = {
            "baseline": {"n": len(b), "p50_ms": pct(b, 50),
                         "p99_ms": bp99},
            "abuse": {"n": len(a), "p50_ms": pct(a, 50),
                      "p99_ms": ap99},
            "p99_bound_ms": bound,
            "p99_within_bound": ok_t,
        }

    adm = admission.describe()
    ab = adm["spaces"].get("abuser", {})
    rec = {
        "trim": trim,
        "seed": seed,
        "tenants": {"small": n_small, "abusers": abusers},
        "graph": {"small": {"V": sv, "E": se},
                  "abuser": {"V": av, "E": ae}},
        "phase_s": phase_s,
        "qos_plan": plan,
        "p99_factor": QOS_P99_FACTOR,
        "p99_floor_ms": QOS_P99_FLOOR_MS,
        "per_tenant": per_tenant,
        "abuser": {"served": served["abuser"],
                   "overloads": overloads["abuser"],
                   "admitted": ab.get("admitted", 0),
                   "denied": ab.get("denied", 0)},
        "small_tenant_overloads": overloads["small"],
        "client_errors": errors[:5],
        "client_error_count": len(errors),
        "identity": {"checked": identity_checked,
                     "mismatches": mismatches},
        "qos": {"admission": adm, "dispatcher": qos_disp},
        "stragglers": stragglers,
    }
    abuser_throttled = ab.get("denied", 0) > 0 \
        and overloads["abuser"] > 0
    ok = (p99_ok and abuser_throttled and served["abuser"] > 0
          and overloads["small"] == 0 and not errors
          and not mismatches and not stragglers
          and all(per_tenant[t]["abuse"]["n"] > 0 for t in tenants))
    rec["ok"] = ok
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    log(f"tenants tier: per_tenant={ {t: per_tenant[t]['abuse'] for t in tenants} } "
        f"abuser={rec['abuser']} errors={len(errors)} "
        f"mismatches={len(mismatches)} -> {out_path}")
    print(json.dumps({
        "metric": "tenants", "ok": ok,
        "abuser": rec["abuser"],
        "small_tenant_overloads": overloads["small"],
        "client_errors": len(errors),
        "p99_within_bound": {t: per_tenant[t]["p99_within_bound"]
                             for t in tenants},
        "identity_mismatches": len(mismatches)}))
    if not ok:
        raise SystemExit(f"tenants tier FAILED: "
                         f"{json.dumps(rec, indent=1)[:4000]}")
    return rec


def bench_cache_smoke(out_path: str):
    """Cache smoke tier (`bench.py --cache-smoke`): tier-1-safe on
    XLA:CPU, no accelerator / native engine. Proves on one small
    in-proc cluster that the cache ladder (docs/manual/11-caching.md)

      (a) HITS: repeated statements hit the plan + result rungs (and
          the storaged stats/scan rungs, exercised directly),
      (b) INVALIDATES: a write between two identical statements moves
          the freshness token — the second result reflects the write
          and matches the CPU pipe,
      (c) IS BIT-IDENTICAL: every cached serve equals the same
          statement under cache_mode=off, exactly,
      (d) DEDUPES: identical requests inside one dispatcher window
          collapse to one lane and fan out identical rows.

    Writes one JSON artifact and exits nonzero on any failure."""
    import threading
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.common.flags import graph_flags, storage_flags
    from nebula_tpu.engine_tpu import TpuGraphEngine
    from nebula_tpu.storage.types import StatDef

    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    rng = np.random.default_rng(11)
    v, e = 400, 3000
    srcs, dsts, ts = zipf_edges(rng, v, e, clip=80)
    insert_person_knows(conn, "cachesmoke", 4, v, srcs, dsts, ts)
    sid = cluster.meta.get_space("cachesmoke").value().space_id
    etype = cluster.sm.edge_type(sid, "knows")
    tpu.prewarm(sid, block=True)
    hubs = [int(x) for x in np.argsort(np.bincount(srcs,
                                                   minlength=v))[-3:]]
    queries = [
        f"GO 2 STEPS FROM {hubs[0]} OVER knows YIELD knows._dst",
        f"GO 2 STEPS FROM {hubs[1]} OVER knows "
        f"WHERE knows.ts > {TS_MAX // 2} YIELD knows._dst, knows.ts",
        f"GO 2 STEPS FROM {hubs[2]} OVER knows YIELD knows.ts AS t"
        f" | YIELD COUNT(*) AS n, SUM($-.t) AS s, AVG($-.t) AS a",
    ]
    checks: dict = {}

    # ---- (c) baseline: cache_mode=off, run twice (determinism too)
    graph_flags.set("cache_mode", "off")
    storage_flags.set("cache_mode", "off")
    off_rows = {}
    for q in queries:
        r1, r2 = conn.must(q), conn.must(q)
        checks.setdefault("off_deterministic", True)
        if r1.rows != r2.rows:
            checks["off_deterministic"] = False
        off_rows[q] = r1.rows

    # ---- (a) full mode: second pass must HIT, rows bit-identical
    graph_flags.set("cache_mode", "full")
    storage_flags.set("cache_mode", "full")
    h0 = tpu.result_cache.stats()["hits"]
    p0 = cluster.service.engine.plan_cache.stats()["hits"]
    full_rows = {}
    for q in queries:
        conn.must(q)                       # populate
        full_rows[q] = conn.must(q).rows   # must hit
    checks["result_hits"] = tpu.result_cache.stats()["hits"] - h0
    checks["plan_hits"] = cluster.service.engine.plan_cache.stats()[
        "hits"] - p0
    checks["hits_occurred"] = (checks["result_hits"] >= len(queries)
                               and checks["plan_hits"] > 0)
    checks["bit_identical_vs_off"] = all(
        full_rows[q] == off_rows[q] for q in queries)

    # ---- (b) invalidation on write: the token moves, the second
    # identical statement reflects the write and matches the CPU pipe
    qw = f"GO FROM {hubs[0]} OVER knows YIELD knows._dst"
    before = conn.must(qw).rows
    conn.must(qw)                          # cached
    conn.must("INSERT VERTEX person(age) VALUES 999777:(1)")
    conn.must(f"INSERT EDGE knows(ts) VALUES {hubs[0]} -> 999777:(1)")
    after = conn.must(qw).rows
    tpu.enabled = False
    try:
        cpu_after = conn.must(qw).rows
    finally:
        tpu.enabled = True
    checks["write_invalidates"] = (
        (999777,) in after and (999777,) not in before
        and sorted(map(repr, after)) == sorted(map(repr, cpu_after)))

    # ---- (d) in-window dedupe: pace the dispatcher so concurrent
    # identical statements pile into one window, then collapse
    orig = tpu._serve_batch

    def paced(batch, ex):
        time.sleep(0.05)
        orig(batch, ex)

    qd = f"GO 2 STEPS FROM {hubs[1]} OVER knows YIELD knows._dst"
    dedup_rows: list = []
    derrs: list = []

    def worker():
        try:
            c = cluster.connect()
            c.must("USE cachesmoke")
            dedup_rows.append(sorted(map(repr, c.must(qd).rows)))
        except Exception as ex:  # noqa: BLE001 — recorded, fails run
            derrs.append(repr(ex))

    tpu._serve_batch = paced
    try:
        for _ in range(5):                 # scheduling is not ours to
            d0 = tpu.stats["dedup_collapsed"]   # command: retry a few
            dedup_rows.clear()
            # drop any cached result for qd so every attempt reaches
            # the dispatcher (a hit would bypass the window entirely)
            tpu.result_cache.clear()
            threads = [threading.Thread(target=worker)
                       for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            if tpu.stats["dedup_collapsed"] > d0:
                break
    finally:
        tpu._serve_batch = orig
    ref = sorted(map(repr, off_rows[queries[0]])) \
        if qd == queries[0] else sorted(map(repr, conn.must(qd).rows))
    checks["dedup_collapsed"] = tpu.stats["dedup_collapsed"]
    checks["dedup_fanout_identical"] = (not derrs and len(dedup_rows)
                                        and all(r == ref
                                                for r in dedup_rows))
    checks["dedup_occurred"] = tpu.stats["dedup_collapsed"] > 0

    # ---- storaged rungs, exercised directly: bound_stats + scan
    defs = [StatDef("edge", etype, "ts", 1),
            StatDef("edge", etype, "", 2)]
    s1 = cluster.client.bound_stats(sid, hubs, [etype], defs)
    s2 = cluster.client.bound_stats(sid, hubs, [etype], defs)
    checks["stats_cache_hits"] = cluster.storage.stats_cache.stats()[
        "hits"]
    checks["stats_cache_identical"] = (s1.sums == s2.sums
                                       and s1.counts == s2.counts)
    parts = sorted(cluster.store.parts(sid))
    cluster.storage.scan_part_cols(sid, parts[0], 2)
    r_scan = cluster.storage.scan_part_cols(sid, parts[0], 2)
    checks["scan_cache_hits"] = cluster.storage.scan_cache.stats()[
        "hits"]
    checks["storaged_hits_occurred"] = (checks["stats_cache_hits"] > 0
                                        and checks["scan_cache_hits"] > 0
                                        and r_scan.n > 0)

    rec = {"graph": {"V": v, "E": e}, "checks": checks,
           "cache": tpu.cache_stats(),
           "plan_cache": cluster.service.engine.plan_cache.stats(),
           "storaged": {
               "stats_cache": cluster.storage.stats_cache.stats(),
               "scan_cache": cluster.storage.scan_cache.stats()}}
    ok = all(checks[k] for k in
             ("off_deterministic", "hits_occurred",
              "bit_identical_vs_off", "write_invalidates",
              "dedup_occurred", "dedup_fanout_identical",
              "stats_cache_identical", "storaged_hits_occurred"))
    rec["ok"] = ok
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    log(f"cache smoke: checks={checks} -> {out_path}")
    print(json.dumps({"metric": "cache_smoke", "ok": ok, **checks}))
    if not ok:
        raise SystemExit(f"cache smoke FAILED: {rec}")
    return rec


def bench_lookup_smoke(out_path: str):
    """Index-verb smoke tier (`bench.py --lookup-smoke`): tier-1-safe
    on XLA:CPU, no accelerator / native engine. Proves the device
    secondary-index subsystem (docs/manual/16-indexes.md) end to end
    on one small in-proc cluster:

      (a) SERVES: a LOOKUP / GET SUBGRAPH / MATCH mix runs with the
          device index armed and the artifact records NONZERO
          lookup_served / subgraph_served / index-hit counters,
      (b) IS BIT-IDENTICAL: every device-served result equals the
          storaged CPU-scan twin (`tpu.enabled = False`), exactly,
      (c) INVALIDATES: an INSERT between two identical LOOKUPs drops
          the sorted arrays — the second result includes the new
          vertex and matches the CPU pipe,
      (d) DEGRADES: with index.search faults armed every LOOKUP still
          succeeds via the storaged scan — zero client errors — and
          the "index" breaker recovers once the faults stop.

    Records per-verb QPS/p50/p99 plus the engine's index counters in
    the JSON artifact and exits nonzero on any failure."""
    from nebula_tpu.cluster import InProcCluster
    from nebula_tpu.common.faults import faults
    from nebula_tpu.engine_tpu import TpuGraphEngine

    tpu = TpuGraphEngine()
    cluster = InProcCluster(tpu_engine=tpu)
    conn = cluster.connect()
    rng = np.random.default_rng(23)
    v, e = 400, 3000
    srcs, dsts, ts = zipf_edges(rng, v, e, clip=80)
    insert_person_knows(conn, "lookupsmoke", 4, v, srcs, dsts, ts)
    conn.must("CREATE TAG INDEX smoke_age ON person(age)")
    sid = cluster.meta.get_space("lookupsmoke").value().space_id
    tpu.prewarm(sid, block=True)
    hubs = [int(x) for x in np.argsort(np.bincount(srcs,
                                                   minlength=v))[-3:]]
    # MATCH seeds pin to the hubs' ages so the 1-hop expansions are
    # guaranteed nonempty on the zipf graph (ages are 20 + vid % 60)
    mix = {
        "lookup": [
            "LOOKUP ON person WHERE person.age > 70 YIELD person.age",
            "LOOKUP ON person WHERE person.age == 42 "
            "YIELD person.age AS age",
            "LOOKUP ON person WHERE person.age <= 21",
        ],
        "subgraph": [
            f"GET SUBGRAPH FROM {hubs[0]}",
            f"GET SUBGRAPH 2 STEPS FROM {hubs[1]}, {hubs[2]} "
            f"OVER knows",
        ],
        "match": [
            f"MATCH (a:person {{age: {20 + hubs[0] % 60}}})"
            f"-[e:knows]->(b) RETURN a, b",
            f"MATCH (a:person {{age: {20 + hubs[1] % 60}}})"
            f"-[e*1..2]->(b) RETURN a.age, b",
        ],
    }
    checks: dict = {}

    # ---- (b) identity: device rows vs the storaged CPU-scan twin
    dev_rows = {q: conn.must(q).rows
                for qs in mix.values() for q in qs}
    tpu.enabled = False
    try:
        cpu_rows = {q: conn.must(q).rows
                    for qs in mix.values() for q in qs}
    finally:
        tpu.enabled = True
    mismatches = [q for q in dev_rows
                  if sorted(map(repr, dev_rows[q]))
                  != sorted(map(repr, cpu_rows[q]))]
    checks["identity"] = not mismatches
    checks["nonempty_mix"] = all(len(dev_rows[q]) > 0
                                 for qs in mix.values() for q in qs)

    # ---- (a) per-verb QPS/p99, every iteration a genuine device
    # serve (the result cache would absorb the fixed pool otherwise)
    iters = 30
    perf = {}
    for verb, qs in mix.items():
        lat = []
        for i in range(iters):
            q = qs[i % len(qs)]
            tpu.result_cache.clear()
            t0 = time.perf_counter()
            conn.must(q)
            lat.append(time.perf_counter() - t0)
        lat_ms = np.asarray(lat) * 1e3
        perf[verb] = {
            "iters": iters,
            "qps": round(iters / float(np.sum(lat)), 1),
            "p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
            "p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
        }
    idx = tpu.index_stats()
    checks["lookup_served"] = idx["lookup_served"]
    checks["subgraph_served"] = idx["subgraph_served"]
    checks["index_hits"] = idx["hits"]
    checks["device_served"] = (idx["lookup_served"] > 0
                               and idx["subgraph_served"] > 0
                               and idx["builds"] > 0
                               and idx["hits"] > 0)

    # ---- (c) a write between identical LOOKUPs invalidates: ages
    # land in 20..79, so 97 can only match the inserted vertex
    qw = "LOOKUP ON person WHERE person.age == 97 YIELD person.age"
    before = conn.must(qw).rows
    inv0 = tpu.index_stats()["invalidations"]
    conn.must("INSERT VERTEX person(age) VALUES 999888:(97)")
    after = conn.must(qw).rows
    tpu.enabled = False
    try:
        cpu_after = conn.must(qw).rows
    finally:
        tpu.enabled = True
    checks["write_invalidates"] = (
        before == [] and [999888, 97] in after
        and sorted(map(repr, after)) == sorted(map(repr, cpu_after))
        and tpu.index_stats()["invalidations"] > inv0)

    # ---- (d) degradation ladder: index.search faults at p=1 must
    # feed the "index" breaker and degrade every LOOKUP to the
    # storaged scan — identical successes only, never a client error
    tpu.breaker_threshold = 2
    tpu.breaker_base_s = 0.1
    tpu.breaker_max_s = 0.5
    faults.set_plan("seed=23;index.search:p=1")
    degraded_ok = True
    ref = sorted(map(repr, conn.must(mix["lookup"][0]).rows))
    try:
        for _ in range(6):
            tpu.result_cache.clear()
            r = conn.execute(mix["lookup"][0])
            if not r.ok() or sorted(map(repr, r.rows)) != ref:
                degraded_ok = False
    finally:
        faults.clear()
    checks["degrades_to_scan"] = (degraded_ok
                                  and tpu.stats["breaker_trips"] > 0)
    recovered = False
    deadline = time.time() + 30
    l0 = tpu.stats["lookup_served"]
    while time.time() < deadline:
        tpu.result_cache.clear()
        conn.must(mix["lookup"][0])
        if tpu.stats["lookup_served"] > l0 and all(
                s == "closed"
                for s in tpu.breaker_states().values()):
            recovered = True
            break
        time.sleep(0.05)
    checks["breaker_recovered"] = recovered

    rec = {"graph": {"V": v, "E": e}, "perf": perf, "checks": checks,
           "mismatches": mismatches, "index": tpu.index_stats(),
           "robustness": tpu.robustness_stats()}
    ok = all(checks[k] for k in
             ("identity", "nonempty_mix", "device_served",
              "write_invalidates", "degrades_to_scan",
              "breaker_recovered"))
    rec["ok"] = ok
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    log(f"lookup smoke: checks={checks} -> {out_path}")
    print(json.dumps({"metric": "lookup_smoke", "ok": ok, **checks}))
    if not ok:
        raise SystemExit(f"lookup smoke FAILED: {rec}")
    return rec


def bench_cluster(out_path: str, trim: bool = False):
    """Replicated-cluster tier (`bench.py --cluster`): the headline
    proof of the raft serving subsystem (docs/manual/12-replication.md).
    Boots a REAL multi-daemon topology on localhost TCP — metad + 3
    replicated storaged (raft over the rpc/ transport at
    replica_factor=3) + one graphd with the TPU engine — then, under
    continuous reader+writer traffic:

      phase 1 (baseline)  closed-loop sessions measure p50/p99/QPS;
      phase 2 (failover)  the storaged leading the most partitions is
                          KILLED mid-soak — required outcome: ZERO
                          client-visible errors, device serving resumes
                          against the new leaders, and a TPU-vs-CPU
                          byte-identity sweep is green;
      phase 3 (balance)   a replacement storaged joins and
                          `BALANCE DATA` evacuates the dead host while
                          traffic runs — required outcome: every
                          persisted task reaches SUCCEEDED, zero
                          errors, identity green, p99 impact recorded.

    Tier-1-safe on XLA:CPU (`--trim` shrinks the graph and phases for
    the subprocess smoke test, tests/test_cluster_smoke.py)."""
    import random
    import shutil
    import tempfile
    import threading

    from nebula_tpu.client import GraphClient
    from nebula_tpu.common.flags import storage_flags
    from nebula_tpu.common.lockwitness import witness
    from nebula_tpu.common.stats import stats as _gstats
    from nebula_tpu.daemons import (serve_graphd, serve_metad,
                                    serve_storaged)
    from nebula_tpu.engine_tpu import TpuGraphEngine

    # lock-order witness across raft elections, failover and rebalance
    # — the heaviest cross-thread lock traffic in the tree (raft part
    # locks x host locks x wal locks); a cycle or sleep-under-lock
    # fails the tier (common/lockwitness.py)
    witness.install()

    v, e, parts, readers_n, phase_s = \
        (240, 1500, 3, 3, 1.5) if trim else (1200, 9000, 4, 6, 4.0)
    space = "clusterb"
    run_dir = tempfile.mkdtemp(prefix="nebula_tpu_clusterbench_")
    old_hb = storage_flags.get("heartbeat_interval_secs")
    old_rhb = storage_flags.get("raft_heartbeat_ms")
    old_rel = storage_flags.get("raft_election_timeout_ms")
    old_fr = storage_flags.get("follower_read_max_ms")
    # fast heartbeats + elections so failover and liveness expiry fit a
    # bench run (production keeps the defaults)
    storage_flags.set("heartbeat_interval_secs", 0.4)
    storage_flags.set("raft_heartbeat_ms", 60)
    storage_flags.set("raft_election_timeout_ms", 250)
    metad = storers = graphd = None
    try:
        metad = serve_metad(expired_threshold_secs=3)
        storers = {}

        def boot_storaged(i):
            storers[i] = serve_storaged(
                metad.addr, replicated=True, engine="mem",
                data_dir=os.path.join(run_dir, f"s{i}"),
                load_interval=0.15)
            return storers[i]

        for i in range(3):
            boot_storaged(i)
        tpu = TpuGraphEngine()
        graphd = serve_graphd(metad.addr, tpu_engine=tpu)
        gc = GraphClient(graphd.addr).connect()

        rng = np.random.default_rng(int(os.environ.get(
            "BENCH_CLUSTER_SEED", 17)))
        srcs, dsts, ts = zipf_edges(rng, v, e, clip=100)
        log(f"cluster tier: loading V={v} E={e} parts={parts} rf=3 "
            f"over 3 storaged + raft-TCP...")
        insert_person_knows(gc, space, parts, v, srcs, dsts, ts,
                            replica_factor=3, settle_s=20.0)
        sid = metad.meta.get_space(space).value().space_id
        hubs = [int(x) for x in
                np.argsort(np.bincount(srcs, minlength=v))[-3:]]
        queries = [
            f"GO 2 STEPS FROM {hubs[0]} OVER knows YIELD knows._dst",
            f"GO 2 STEPS FROM {hubs[1]} OVER knows "
            f"WHERE knows.ts > {TS_MAX // 2} "
            f"YIELD knows._dst, knows.ts",
            f"GO FROM {hubs[0]}, {hubs[2]} OVER knows "
            f"YIELD knows._dst, knows.ts",
            f"GO 2 STEPS FROM {hubs[2]} OVER knows YIELD knows.ts "
            f"AS t | YIELD COUNT(*) AS n, SUM($-.t) AS s",
        ]
        for q in queries:            # compile + snapshot warm for
            gc.must(q)               # EVERY shape: a cold XLA compile
        # landing inside the short trim baseline window can eat the
        # whole phase and record zero baseline latencies (observed as
        # a load-dependent flake under the full tier-1 suite)

        # ---- traffic harness: closed-loop readers + one paced writer
        stop = threading.Event()
        pause = threading.Event()
        phase_box = {"name": None}
        lock = threading.Lock()
        lats: list = []              # (phase, ms)
        errors: list = []
        n_workers = readers_n + 1
        paused_flags = [threading.Event() for _ in range(n_workers)]

        def reader(k):
            rr = random.Random(1000 + k)
            c = GraphClient(graphd.addr).connect()
            c.must(f"USE {space}")
            while not stop.is_set():
                if pause.is_set():
                    paused_flags[k].set()
                    time.sleep(0.02)
                    continue
                paused_flags[k].clear()
                q = queries[rr.randrange(len(queries))]
                t0 = time.monotonic()
                r = c.execute(q)
                ms = (time.monotonic() - t0) * 1000
                ph = phase_box["name"]
                with lock:
                    if not r.ok():
                        errors.append((ph, q, r.error_msg))
                    elif ph:
                        lats.append((ph, ms))

        def writer(k):
            rr = random.Random(7000 + k)
            c = GraphClient(graphd.addr).connect()
            c.must(f"USE {space}")
            rank = e + 1
            last_ins = None
            while not stop.is_set():
                if pause.is_set():
                    paused_flags[k].set()
                    time.sleep(0.02)
                    continue
                paused_flags[k].clear()
                if last_ins is not None and rr.random() < 0.15:
                    a, b, rk = last_ins
                    q = f"DELETE EDGE knows {a} -> {b}@{rk}"
                    last_ins = None
                else:
                    a, b = rr.randrange(v), rr.randrange(v)
                    q = (f"INSERT EDGE knows(ts) VALUES "
                         f"{a} -> {b}@{rank}:({(a + b) % TS_MAX})")
                    last_ins = (a, b, rank)
                    rank += 1
                r = c.execute(q)
                ph = phase_box["name"]
                if not r.ok():
                    with lock:
                        errors.append((ph, q, r.error_msg))
                time.sleep(0.015)

        threads = [threading.Thread(target=reader, args=(k,),
                                    daemon=True)
                   for k in range(readers_n)]
        threads.append(threading.Thread(target=writer,
                                        args=(readers_n,), daemon=True))
        for t in threads:
            t.start()

        def quiesce():
            pause.set()
            deadline = time.time() + 15
            while time.time() < deadline and \
                    not all(f.is_set() for f in paused_flags):
                time.sleep(0.02)
            deadline = time.time() + 15
            while any(tpu._repacking.values()) and \
                    time.time() < deadline:
                time.sleep(0.05)

        def resume():
            for f in paused_flags:
                f.clear()
            pause.clear()

        def identity_sweep():
            """TPU rows == CPU rows for the whole pool; also reports
            whether the device actually served (vs CPU fallback)."""
            ok_all, device = True, False
            for q in queries:
                g0 = tpu.stats["go_served"] + tpu.stats["agg_served"]
                rt = gc.must(q)
                device |= (tpu.stats["go_served"]
                           + tpu.stats["agg_served"]) > g0
                tpu.enabled = False
                try:
                    rc = gc.must(q)
                finally:
                    tpu.enabled = True
                if sorted(map(repr, rt.rows)) != \
                        sorted(map(repr, rc.rows)):
                    ok_all = False
            return ok_all, device

        phase_dur: dict = {}

        def run_phase(name, end_fn):
            phase_box["name"] = name
            t0 = time.monotonic()
            end_fn()
            phase_dur[name] = time.monotonic() - t0
            phase_box["name"] = None

        # ---- phase 1: baseline (leader-only routing)
        run_phase("baseline", lambda: time.sleep(phase_s))

        # ---- phase 1b: arm bounded-staleness follower reads and
        # measure the same traffic with GO windows spread across
        # follower replicas under the raft read fence (ISSUE 16;
        # docs/manual/12-replication.md "Follower reads")
        fr_bound_ms = int(os.environ.get("BENCH_FOLLOWER_READ_MS", 150))
        # arm through the cluster config registry (UPDATE CONFIGS ->
        # meta -> heartbeat pull), the production path — a bare local
        # flag set would be overwritten by the next meta pull
        gc.must(f"UPDATE CONFIGS STORAGE:follower_read_max_ms = "
                f"{fr_bound_ms}")
        deadline = time.time() + 15
        while storage_flags.get("follower_read_max_ms") != fr_bound_ms \
                and time.time() < deadline:
            time.sleep(0.05)
        assert storage_flags.get("follower_read_max_ms") == fr_bound_ms
        run_phase("follower_reads", lambda: time.sleep(phase_s))
        quiesce()
        identity_follower = follower_device = False
        deadline = time.time() + (60 if trim else 45)
        while time.time() < deadline:
            identity_follower, dev = identity_sweep()
            if identity_follower and dev:
                follower_device = True
                break
            time.sleep(0.4)
        resume()

        def pct(phase):
            xs = sorted(ms for ph, ms in lats if ph == phase)
            if not xs:
                return {"n": 0}
            dur = max(phase_dur.get(phase, phase_s), 1e-3)
            return {"n": len(xs),
                    "p50_ms": round(float(np.percentile(xs, 50)), 2),
                    "p99_ms": round(float(np.percentile(xs, 99)), 2),
                    "qps": round(len(xs) / dur, 1),
                    "wall_s": round(dur, 1)}

        def follower_read_summary():
            """Client + per-host device-serve counters, measured max
            SERVED staleness, and the bound it must respect (fence
            budget + shard-freshness slack)."""
            cdev = dict(graphd.engine.client.device_stats)
            per_host = {}
            stal = [float(cdev.get("max_staleness_ms", 0.0))]
            fr_granted = 0
            for h in storers.values():
                mgr = getattr(h, "device_shards", None)
                if mgr is None:
                    continue
                per_host[h.addr] = dict(mgr.stats)
                stal.append(float(mgr.stats.get("max_staleness_ms", 0)))
                for p in range(1, parts + 1):
                    r = h.node.raft(sid, p)
                    if r is not None:
                        fr_granted += r.follower_read_stats["granted"]
            slack = int(storage_flags.get_or(
                "device_shard_max_ms", 250, int))
            max_stal = round(max(stal), 2)
            return {
                "bound_ms": fr_bound_ms,
                "shard_slack_ms": slack,
                "identity": identity_follower,
                "device_served": follower_device,
                "client": cdev,
                "per_host": per_host,
                "follower_parts_served": sum(
                    s.get("follower_parts_served", 0)
                    for s in per_host.values()),
                "fence_grants": fr_granted,
                "max_served_staleness_ms": max_stal,
                "staleness_bounded": max_stal <= fr_bound_ms + slack,
            }

        if os.environ.get("BENCH_CLUSTER_READS_ONLY") == "1":
            # the follower-read smoke tier
            # (tests/test_cluster_read_smoke.py): stop after the armed
            # phase — failover/balance ride the full cluster tier
            stop.set()
            resume()
            for t in threads:
                t.join(timeout=30)
            fr = follower_read_summary()
            phases = {ph: pct(ph) for ph in ("baseline",
                                             "follower_reads")}
            rec = {
                "trim": trim, "reads_only": True,
                "graph": {"V": v, "E": e, "partition_num": parts,
                          "replica_factor": 3},
                "sessions": {"readers": readers_n, "writers": 1},
                "phases": phases,
                "client_errors": errors[:5],
                "client_error_count": len(errors),
                "follower_reads": fr,
                "lock_witness": _witness_summary(),
            }
            ok = (not errors and identity_follower and follower_device
                  and fr["staleness_bounded"]
                  and fr["follower_parts_served"] > 0
                  and all(phases[ph]["n"] > 0 for ph in phases))
            rec["ok"] = ok
            with open(out_path, "w") as f:
                json.dump(rec, f, indent=1)
            log(f"cluster reads tier: phases={phases} "
                f"errors={len(errors)} follower={fr['client']} "
                f"-> {out_path}")
            print(json.dumps({
                "metric": "cluster_reads", "ok": ok,
                "client_errors": len(errors),
                "follower_parts_served": fr["follower_parts_served"],
                "max_served_staleness_ms":
                    fr["max_served_staleness_ms"]}))
            if not ok:
                raise SystemExit(f"cluster reads tier FAILED: "
                                 f"{json.dumps(rec, indent=1)[:4000]}")
            return rec

        # ---- phase 2: kill the storaged leading the most partitions
        def leader_counts():
            out = {}
            for i, h in storers.items():
                n = 0
                for p in range(1, parts + 1):
                    r = h.node.raft(sid, p)
                    if r is not None and r.is_leader():
                        n += 1
                out[i] = n
            return out

        deadline = time.time() + 15
        counts = leader_counts()
        while sum(counts.values()) < parts and time.time() < deadline:
            time.sleep(0.1)
            counts = leader_counts()
        victim = max(counts, key=counts.get)
        dead_addr = storers[victim].addr
        log(f"cluster tier: killing storaged {victim} ({dead_addr}), "
            f"led {counts[victim]}/{parts} parts")

        def kill_and_soak():
            storers.pop(victim).stop()
            time.sleep(phase_s)

        run_phase("failover", kill_and_soak)

        # device must resume serving against the NEW leaders, with
        # TPU-vs-CPU identity green (writes quiesced for the sweep)
        quiesce()
        post_failover_device = identity_failover = False
        deadline = time.time() + (60 if trim else 45)
        while time.time() < deadline:
            identity_failover, dev = identity_sweep()
            if identity_failover and dev:
                post_failover_device = True
                break
            time.sleep(0.4)
        resume()

        # ---- phase 3: replacement joins; BALANCE DATA evacuates the
        # dead host's replicas while traffic runs
        s3 = boot_storaged(3)
        deadline = time.time() + 30
        while time.time() < deadline:
            hosts = {h.host for h in metad.meta.active_hosts()}
            if s3.addr in hosts and dead_addr not in hosts:
                break
            time.sleep(0.2)
        plan_box = {}

        def balance_under_load():
            r = gc.must("BALANCE DATA")
            plan_box["id"] = r.rows[0][0]
            metad.meta._balancer.wait(120)

        run_phase("balance", balance_under_load)
        plan_id = plan_box["id"]
        balance_rows = metad.meta.balance_show(plan_id)
        tasks_by_status: dict = {}
        for row in balance_rows:
            tasks_by_status[row[-1]] = tasks_by_status.get(row[-1], 0) + 1
        balance_done = bool(balance_rows) and \
            all(row[-1] == "SUCCEEDED" for row in balance_rows)
        alloc = metad.meta.get_parts_alloc(sid)
        evacuated = all(dead_addr not in hosts
                        for hosts in alloc.values())
        fully_replicated = all(len(hosts) == 3
                               for hosts in alloc.values())

        quiesce()
        identity_balance = post_balance_device = False
        deadline = time.time() + (60 if trim else 45)
        while time.time() < deadline:
            identity_balance, dev = identity_sweep()
            if identity_balance and dev:
                post_balance_device = True
                break
            time.sleep(0.4)
        # forced-sample attribution pass (ISSUE 12): where a cluster
        # query's wall time actually goes, per span and host — runs
        # quiesced, off the measured phases, over the warm query pool
        n_attr = len(queries) * (2 if trim else 3)
        spans_cluster = span_breakdown_run(
            lambda: [gc.must(q)
                     for q in queries * (2 if trim else 3)], n_attr)
        stop.set()
        resume()
        for t in threads:
            t.join(timeout=30)

        phases = {ph: pct(ph) for ph in ("baseline", "follower_reads",
                                         "failover", "balance")}
        base_p99 = phases["baseline"].get("p99_ms") or 1.0
        follower_reads = follower_read_summary()
        # leader-only vs follower-armed comparison of the SAME traffic
        follower_reads["leader_only"] = phases["baseline"]
        follower_reads["follower_armed"] = phases["follower_reads"]
        rec = {
            "trim": trim,
            "graph": {"V": v, "E": e, "partition_num": parts,
                      "replica_factor": 3},
            "topology": {"storaged": 3, "killed": dead_addr,
                         "replacement": s3.addr},
            "sessions": {"readers": readers_n, "writers": 1},
            "phases": phases,
            "p99_impact": {
                "failover_vs_baseline": round(
                    (phases["failover"].get("p99_ms") or 0)
                    / base_p99, 2),
                "balance_vs_baseline": round(
                    (phases["balance"].get("p99_ms") or 0)
                    / base_p99, 2),
            },
            "client_errors": errors[:5],
            "client_error_count": len(errors),
            "identity": {"after_failover": identity_failover,
                         "after_balance": identity_balance},
            "device": {"post_failover_served": post_failover_device,
                       "post_balance_served": post_balance_device,
                       "go_served": tpu.stats["go_served"],
                       "agg_served": tpu.stats["agg_served"]},
            "balance": {"plan": plan_id, "tasks": tasks_by_status,
                        "all_succeeded": balance_done,
                        "dead_host_evacuated": evacuated,
                        "fully_replicated": fully_replicated},
            # ISSUE 16: bounded-staleness follower reads — leader-only
            # vs follower-armed QPS/p99, per-host device-partial
            # counters, and the measured max SERVED staleness against
            # its bound (fence budget + shard slack)
            "follower_reads": follower_reads,
            "cluster_stats": {
                "retries": dict(graphd.engine.client.retry_stats),
                # raft elections/deposals observed across the in-proc
                # storageds (the shared StatsManager's lifetime total)
                "leader_changes": _gstats.lifetime_total(
                    "raftex.leader_changes"),
                "membership_reconciled": _gstats.lifetime_total(
                    "raftex.membership_reconciled"),
                "balance_task_rows": len(balance_rows),
            },
            # ISSUE 12: span breakdown + dominant-path attribution of
            # the forced-sample pass — the artifact must EXPLAIN where
            # cluster wall time went, not just report it
            "span_breakdown": spans_cluster,
            "attribution": spans_cluster["attribution"],
            "lock_witness": _witness_summary(),
        }
        # "bounded p99 impact": no phase may starve queries toward the
        # deadline horizon — a generous absolute cap, the exact ratios
        # are recorded above for trend tracking
        p99_bounded = all(
            (phases[ph].get("p99_ms") or 0) < 15000
            for ph in ("failover", "balance"))
        # the attribution must explain >= 80% of sampled wall time
        # (acceptance: a cost story with holes is not a cost story)
        attribution_ok = rec["attribution"]["explained"] >= 0.8 and \
            rec["attribution"]["sampled_traces"] > 0
        ok = (not errors and identity_failover and identity_balance
              and post_failover_device and balance_done and evacuated
              and fully_replicated and p99_bounded and attribution_ok
              and all(phases[ph]["n"] > 0 for ph in phases)
              and identity_follower and follower_device
              and follower_reads["staleness_bounded"]
              and follower_reads["follower_parts_served"] > 0
              and rec["lock_witness"]["clean"])
        rec["ok"] = ok
        with open(out_path, "w") as f:
            json.dump(rec, f, indent=1)
        log(f"cluster tier: phases={phases} errors={len(errors)} "
            f"identity={rec['identity']} balance={rec['balance']} "
            f"-> {out_path}")
        print(json.dumps({
            "metric": "cluster", "ok": ok,
            "client_errors": len(errors),
            "identity": rec["identity"],
            "balance_tasks": tasks_by_status,
            "p99_impact": rec["p99_impact"]}))
        if not ok:
            raise SystemExit(f"cluster tier FAILED: "
                             f"{json.dumps(rec, indent=1)[:4000]}")
        return rec
    finally:
        try:
            if graphd is not None:
                graphd.stop()
            for h in (storers or {}).values():
                try:
                    h.stop()
                except Exception:
                    pass
            if metad is not None:
                metad.stop()
        finally:
            storage_flags.set("heartbeat_interval_secs", old_hb)
            storage_flags.set("raft_heartbeat_ms", old_rhb)
            storage_flags.set("raft_election_timeout_ms", old_rel)
            storage_flags.set("follower_read_max_ms", old_fr)
            shutil.rmtree(run_dir, ignore_errors=True)


def bench_crash(out_path: str, trim: bool = False):
    """Crash-storm tier (`bench.py --crash`): proof that a `kill -9`
    against a storaged is a non-event (docs/manual/12-replication.md,
    "Crash recovery & compaction"). Boots metad + TPU graphd in-process
    and 3 REPLICATED storaged as real SUBPROCESSES (crashstorm harness
    over scripts/services.py + serve_storaged, per-node data dirs,
    aggressive wal compaction flags), then under closed-loop readers +
    ledger-journaling writers runs a SIGKILL storm where every victim
    restarts on its OWN data dir:

      cycle 1  SIGKILL the storaged leading the most parts;
      cycle 2  restart a node with `crashpoint.wal_applied` armed — it
               aborts itself exactly between WAL append and engine
               apply, then restarts clean (the recovery window forced,
               not raced);
      cycle 3  (full runs) SIGKILL a node, overflow wal_compact_lag so
               the survivors' compaction truncates the gap, restart
               with `crashpoint.snapshot_recv` armed — it dies
               mid-snapshot-install, restarts clean, re-requests and
               converges.

    FAILS unless every ACKED write is readable after recovery (the
    client-side durability ledger), zero non-retryable client errors,
    TPU-vs-CPU byte identity green post-recovery with the device
    actually serving, each recovery captured >=1 `wal_replay` flight
    event, replay lengths bounded by wal_compact_lag, and WAL spans
    bounded by compaction."""
    import random
    import shutil
    import tempfile
    import threading

    from nebula_tpu.client import GraphClient
    from nebula_tpu.engine_tpu import TpuGraphEngine
    from nebula_tpu.tools.crashstorm import (RETRYABLE, CrashTopology,
                                             LedgerWriters,
                                             load_person_knows)

    v, e, parts, traffic_s = (240, 1500, 3, 1.5) if trim \
        else (900, 6000, 4, 3.0)
    lag = 300
    space = "crashb"
    run_dir = tempfile.mkdtemp(prefix="nebula_tpu_crashbench_")
    seed = int(os.environ.get("BENCH_CRASH_SEED", 23))
    topo = None
    try:
        tpu = TpuGraphEngine()
        log("crash tier: booting metad + graphd in-proc, 3 storaged "
            "subprocesses...")
        topo = CrashTopology(run_dir, n=3,
                             flag_overrides={"wal_compact_lag": lag},
                             tpu_engine=tpu)
        gc = GraphClient(topo.graphd.addr).connect()
        log(f"crash tier: loading V={v} E={e} parts={parts} rf=3...")
        srcs, _dsts, _ts = load_person_knows(
            gc, space, parts, v, e, seed, replica_factor=3,
            settle_s=30.0)
        sid = topo.metad.meta.get_space(space).value().space_id
        hubs = [int(x) for x in
                np.argsort(np.bincount(srcs, minlength=v))[-3:]]
        queries = [
            f"GO 2 STEPS FROM {hubs[0]} OVER knows YIELD knows._dst",
            f"GO 2 STEPS FROM {hubs[1]} OVER knows "
            f"WHERE knows.ts > 40000 YIELD knows._dst, knows.ts",
            f"GO FROM {hubs[0]}, {hubs[2]} OVER knows "
            f"YIELD knows._dst, knows.ts",
            f"GO 2 STEPS FROM {hubs[2]} OVER knows YIELD knows.ts "
            f"AS t | YIELD COUNT(*) AS n, SUM($-.t) AS s",
        ]
        for q in queries:         # warm every shape (XLA compile)
            gc.must(q)
        topo.wait_leaders(sid, parts)

        # ---- traffic: ledger writers + retry-tolerant readers
        writers = LedgerWriters(topo.graphd.addr, space, v,
                                n_writers=2).start()
        stop = threading.Event()
        pause = threading.Event()
        reader_errors: list = []
        reader_retried = [0]
        rlock = threading.Lock()

        def reader(k):
            rr = random.Random(3100 + k)
            c = GraphClient(topo.graphd.addr).connect()
            c.must(f"USE {space}")
            while not stop.is_set():
                if pause.is_set():
                    time.sleep(0.02)
                    continue
                q = queries[rr.randrange(len(queries))]
                r = c.execute(q)
                if not r.ok():
                    if r.code in RETRYABLE:
                        with rlock:
                            reader_retried[0] += 1
                        time.sleep(0.05)
                    else:
                        with rlock:
                            reader_errors.append(
                                (q, f"{r.code}: {r.error_msg}"))

        rthreads = [threading.Thread(target=reader, args=(k,),
                                     daemon=True) for k in range(2)]
        for t in rthreads:
            t.start()

        recoveries: list = []

        def sample_recovery(i, label, timeout=90.0):
            st = topo.wait_recovered(i, sid, parts, timeout=timeout)
            evs = topo.flight_events(i, "wal_replay")
            snaps = topo.flight_events(i, "snapshot_install")
            rec = {"cycle": label, "node": i,
                   "replay_events": len(evs),
                   "replayed_total": sum(ev.get("n", 0) for ev in evs),
                   "replay_max_n": max([ev.get("n", 0) for ev in evs]
                                       or [0]),
                   "snapshot_installs": len(snaps),
                   "parts": len(st)}
            recoveries.append(rec)
            log(f"crash tier: recovery[{label}] node {i}: {rec}")
            return rec

        # ---- cycle 1: SIGKILL the leader-heaviest storaged
        time.sleep(traffic_s)
        counts = topo.leader_counts(sid)
        victim = max(counts, key=counts.get)
        log(f"crash tier: cycle 1 — SIGKILL storaged{victim} "
            f"(leads {counts[victim]}/{parts}), restart on same dir")
        topo.sigkill(victim)
        time.sleep(traffic_s)
        topo.restart(victim)
        sample_recovery(victim, "sigkill_leader")

        # ---- cycle 2: forced crash between WAL append and engine
        # apply (crashpoint.wal_applied aborts the process at the seam)
        victim2 = next(i for i in range(3) if i != victim)
        log(f"crash tier: cycle 2 — storaged{victim2} restarted with "
            f"crashpoint.wal_applied armed")
        topo.sigkill(victim2)
        topo.restart(victim2, env_extra={
            "NEBULA_TPU_FAULTS": "crashpoint.wal_applied:after=40,n=1"})
        died = topo.wait_exit(victim2, timeout=120.0)
        assert died, "crashpoint.wal_applied never killed the process"
        topo.restart(victim2)
        sample_recovery(victim2, "crashpoint_wal_applied")

        # ---- cycle 3 (full): crash mid-snapshot-install — kill a
        # node, overflow the compaction lag so survivors truncate the
        # gap, restart with crashpoint.snapshot_recv armed
        snapshot_cycle = None
        if not trim:
            victim3 = next(i for i in range(3)
                           if i not in (victim, victim2))
            pre = {p["part"]: p["committed"]
                   for p in topo.raft_parts(victim3)
                   if p["space"] == sid}
            log(f"crash tier: cycle 3 — SIGKILL storaged{victim3}, "
                f"overflow wal_compact_lag={lag} while it is down")
            topo.sigkill(victim3)
            wc = GraphClient(topo.graphd.addr).connect()
            wc.must(f"USE {space}")
            burst = 0
            deadline = time.time() + 120
            while time.time() < deadline:
                # singles (not batches): each INSERT is one raft log
                # entry, which is what must overflow the lag
                for _ in range(200):
                    a = random.randrange(v)
                    b = random.randrange(v)
                    wc.execute(f"INSERT EDGE knows(ts) VALUES "
                               f"{a} -> {b}@{5_000_000 + burst}:"
                               f"({90000 + (burst % 1000)})")
                    burst += 1
                # compaction must have truncated past the dead node's
                # tail on every part it needs to catch up
                firsts: dict = {}
                for j in range(3):
                    if topo.nodes[j].pid is None:
                        continue
                    for p in topo.raft_parts(j):
                        if p["space"] == sid and \
                                p["role"] == "LEADER":
                            firsts[p["part"]] = \
                                p["wal_first_log_id"]
                if firsts and all(
                        firsts.get(pt, 0) > pre.get(pt, 0) + 1
                        for pt in pre):
                    break
            gap_truncated = bool(firsts) and all(
                firsts.get(pt, 0) > pre.get(pt, 0) + 1 for pt in pre)
            topo.restart(victim3, env_extra={
                "NEBULA_TPU_FAULTS": "crashpoint.snapshot_recv:n=1"})
            died3 = topo.wait_exit(victim3, timeout=120.0)
            topo.restart(victim3)
            rec3 = sample_recovery(victim3, "crashpoint_snapshot_recv",
                                   timeout=150.0)
            snapshot_cycle = {"gap_truncated": gap_truncated,
                              "burst_writes": burst,
                              "crashpoint_fired": died3,
                              "snapshot_installs":
                                  rec3["snapshot_installs"]}
            log(f"crash tier: cycle 3 — {snapshot_cycle}")

        # ---- settle: stop traffic, verify
        time.sleep(traffic_s)
        writers.pause()
        pause.set()
        time.sleep(0.3)
        deadline = time.time() + 20
        while any(tpu._repacking.values()) and time.time() < deadline:
            time.sleep(0.05)

        def identity_sweep():
            ok_all, device = True, False
            for q in queries:
                g0 = tpu.stats["go_served"] + tpu.stats["agg_served"]
                rt = gc.must(q)
                device |= (tpu.stats["go_served"]
                           + tpu.stats["agg_served"]) > g0
                tpu.enabled = False
                try:
                    rc = gc.must(q)
                finally:
                    tpu.enabled = True
                if sorted(map(repr, rt.rows)) != \
                        sorted(map(repr, rc.rows)):
                    ok_all = False
            return ok_all, device

        identity_ok = device_served = False
        deadline = time.time() + (90 if trim else 60)
        while time.time() < deadline:
            identity_ok, dev = identity_sweep()
            if identity_ok and dev:
                device_served = True
                break
            time.sleep(0.4)

        missing = writers.verify_ledger(gc)
        wsum = writers.summary()
        stop.set()
        writers.stop()
        pause.clear()
        for t in rthreads:
            t.join(timeout=20)

        spans = topo.wal_spans(sid)
        # replay bounded by the compaction lag (+ slack for entries
        # landed since the last 1s flush); wal span bounded by lag +
        # whole-segment granularity
        replay_bound = lag + 1024
        span_bound = lag + 4096
        replay_bounded = all(r["replay_max_n"] <= replay_bound
                             for r in recoveries)
        # every recovery must leave flight-recorder evidence: a
        # wal_replay event per SIGKILL recovery; the forced
        # mid-snapshot-crash cycle recovers parts whose gap was
        # compacted away, where snapshot_install IS the recovery event
        replay_events_per_recovery = all(
            (r["replay_events"] >= 1
             if r["cycle"] != "crashpoint_snapshot_recv"
             else r["replay_events"] + r["snapshot_installs"] >= 1)
            for r in recoveries) and any(
            r["replay_events"] >= 1 for r in recoveries)
        rec = {
            "trim": trim,
            "graph": {"V": v, "E": e, "partition_num": parts,
                      "replica_factor": 3},
            "flags": topo.flags,
            "cycles": len(recoveries),
            "recoveries": recoveries,
            "snapshot_cycle": snapshot_cycle,
            "ledger": {**wsum, "missing": len(missing),
                       "missing_samples": missing[:5]},
            "readers": {"errors": len(reader_errors),
                        "error_samples": reader_errors[:5],
                        "retried": reader_retried[0]},
            "identity_post_recovery": identity_ok,
            "device_served_post_recovery": device_served,
            "wal_spans": {"max": max(spans) if spans else 0,
                          "bound": span_bound},
            "replay": {"bound": replay_bound,
                       "bounded": replay_bounded,
                       "events_per_recovery":
                           replay_events_per_recovery},
            "restarts": {n.name: n.restarts for n in topo.nodes},
        }
        ok = (len(missing) == 0 and wsum["errors"] == 0
              and wsum["acked"] > 0
              and len(reader_errors) == 0
              and identity_ok and device_served
              and replay_events_per_recovery and replay_bounded
              and len(recoveries) >= (2 if trim else 3)
              and (trim or (snapshot_cycle or {}).get("gap_truncated"))
              and (trim or (snapshot_cycle or {}).get(
                  "snapshot_installs", 0) >= 1)
              and (spans and max(spans) <= span_bound))
        rec["ok"] = bool(ok)
        with open(out_path, "w") as f:
            json.dump(rec, f, indent=1)
        log(f"crash tier: ledger={rec['ledger']} "
            f"recoveries={recoveries} identity={identity_ok} "
            f"-> {out_path}")
        print(json.dumps({
            "metric": "crash", "ok": rec["ok"],
            "acked": wsum["acked"], "missing": len(missing),
            "client_errors": wsum["errors"] + len(reader_errors),
            "recoveries": len(recoveries),
            "replay_events": sum(r["replay_events"]
                                 for r in recoveries),
            "identity": identity_ok}))
        if not ok:
            raise SystemExit(f"crash tier FAILED: "
                             f"{json.dumps(rec, indent=1)[:4000]}")
        return rec
    finally:
        try:
            if topo is not None:
                topo.stop()
        finally:
            if os.environ.get("BENCH_CRASH_KEEP"):
                log(f"crash tier: keeping run dir {run_dir}")
            else:
                shutil.rmtree(run_dir, ignore_errors=True)


def bench_partition(out_path: str, trim: bool = False):
    """Partition & gray-failure tier (`bench.py --partition`, ISSUE 18;
    docs/manual/9-robustness.md "Network nemesis"): the same real
    multi-daemon topology as `--cluster` (metad + 3 replicated storaged
    + TPU graphd over localhost TCP), but the failures are NETWORK
    shapes injected by the nemesis into the live transport, not process
    kills:

      baseline        closed-loop readers + durability-ledger writers;
      follower_reads  bounded-staleness reads armed (the staleness
                      bound under test);
      sym_split       the leader-heaviest storaged fully partitioned
                      (raft both directions + graphd data inbound) —
                      failover + peer-health ejection + hedged reads
                      carry the traffic;
      follower_fenced a FOLLOWER raft-isolated while its data plane
                      stays open: the raft read fence must DECLINE its
                      follower reads (never serve staler than the
                      bound), observable as fence rejections;
      gray            one storaged slowed 250ms±100 (data plane only):
                      hedged reads must win and keep phase p99 within
                      BENCH_GRAY_FACTOR x baseline;
      flap            the symmetric split toggled on/off repeatedly;
      converge        heal everything, then prove: zero acked-write
                      loss (ledger re-read), zero non-retryable client
                      errors, zero replica divergence (observatory
                      armed the whole run), committed ids converged,
                      served staleness within bound + slack, and the
                      TPU-vs-CPU identity sweep green with device
                      serving back on.

    Tier-1-safe on XLA:CPU (`--trim` shrinks the graph and phases for
    tests/test_partition_smoke.py)."""
    import random
    import shutil
    import tempfile
    import threading

    from nebula_tpu.client import GraphClient
    from nebula_tpu.common import consistency as cons
    from nebula_tpu.common.faults import Nemesis, faults
    from nebula_tpu.common.flags import graph_flags, storage_flags
    from nebula_tpu.common.flight import recorder as flight_rec
    from nebula_tpu.common.lockwitness import witness
    from nebula_tpu.common.stats import stats as _gstats
    from nebula_tpu.daemons import (serve_graphd, serve_metad,
                                    serve_storaged)
    from nebula_tpu.engine_tpu import TpuGraphEngine
    from nebula_tpu.meta.net_admin import raft_addr_of
    from nebula_tpu.tools.crashstorm import RETRYABLE, LedgerWriters

    witness.install()

    v, e, parts, readers_n, phase_s = \
        (240, 1500, 3, 3, 1.5) if trim else (1200, 9000, 4, 6, 3.0)
    space = "partb"
    run_dir = tempfile.mkdtemp(prefix="nebula_tpu_partbench_")
    gray_factor = float(os.environ.get("BENCH_GRAY_FACTOR", 10.0))
    fr_bound_ms = int(os.environ.get("BENCH_FOLLOWER_READ_MS", 150))
    saved = {f: storage_flags.get(f) for f in
             ("heartbeat_interval_secs", "raft_heartbeat_ms",
              "raft_election_timeout_ms", "follower_read_max_ms",
              "consistency_enabled")}
    saved_g = {f: graph_flags.get(f) for f in
               ("consistency_enabled", "shadow_read_rate",
                "storage_client_timeout_ms")}
    storage_flags.set("heartbeat_interval_secs", 0.4)
    storage_flags.set("raft_heartbeat_ms", 60)
    storage_flags.set("raft_election_timeout_ms", 250)
    # consistency observatory armed for the WHOLE run: every injected
    # partition must leave replica digests convergent
    storage_flags.set("consistency_enabled", True)
    graph_flags.set("consistency_enabled", True)
    # bounded data-plane timeout so blackholed peers cost ~2s per
    # attempt, not the 30s default — the gray-hygiene knob under test
    graph_flags.set("storage_client_timeout_ms", 2000)
    cons.shadow.reset()
    metad = storers = graphd = lw = None
    stop = threading.Event()
    try:
        metad = serve_metad(expired_threshold_secs=5)
        storers = {}
        for i in range(3):
            storers[i] = serve_storaged(
                metad.addr, replicated=True, engine="mem",
                data_dir=os.path.join(run_dir, f"s{i}"),
                load_interval=0.15)
        tpu = TpuGraphEngine()
        graphd = serve_graphd(metad.addr, tpu_engine=tpu)
        gc = GraphClient(graphd.addr).connect()
        client = graphd.engine.client

        rng = np.random.default_rng(int(os.environ.get(
            "BENCH_PARTITION_SEED", 23)))
        srcs, dsts, ts = zipf_edges(rng, v, e, clip=100)
        log(f"partition tier: loading V={v} E={e} parts={parts} rf=3 "
            f"over 3 storaged + raft-TCP, observatory armed...")
        insert_person_knows(gc, space, parts, v, srcs, dsts, ts,
                            replica_factor=3, settle_s=20.0)
        sid = metad.meta.get_space(space).value().space_id
        div0 = _gstats.lifetime_total("consistency.divergence")
        # shadow-read verification sampled throughout: partitions must
        # never make the serve path LIE, only decline/fail retryably
        graph_flags.set("shadow_read_rate", 0.05)
        hubs = [int(x) for x in
                np.argsort(np.bincount(srcs, minlength=v))[-3:]]
        queries = [
            f"GO 2 STEPS FROM {hubs[0]} OVER knows YIELD knows._dst",
            f"GO 2 STEPS FROM {hubs[1]} OVER knows "
            f"WHERE knows.ts > {TS_MAX // 2} "
            f"YIELD knows._dst, knows.ts",
            f"GO FROM {hubs[0]}, {hubs[2]} OVER knows "
            f"YIELD knows._dst, knows.ts",
        ]
        for q in queries:
            gc.must(q)               # compile + snapshot warm

        # ---- traffic: closed-loop readers (RETRYABLE-tolerant — the
        # contract is zero NON-retryable errors) + ledger writers
        pause = threading.Event()
        phase_box = {"name": None}
        lock = threading.Lock()
        lats: list = []
        errors: list = []            # non-retryable / budget-exhausted
        read_retries = [0]
        paused_flags = [threading.Event() for _ in range(readers_n)]

        def reader(k):
            rr = random.Random(1000 + k)
            c = GraphClient(graphd.addr).connect()
            c.must(f"USE {space}")
            while not stop.is_set():
                if pause.is_set():
                    paused_flags[k].set()
                    time.sleep(0.02)
                    continue
                paused_flags[k].clear()
                q = queries[rr.randrange(len(queries))]
                t0 = time.monotonic()
                r = c.execute(q)
                n_retry = 0
                while (not r.ok() and r.code in RETRYABLE
                       and n_retry < 8 and not stop.is_set()):
                    n_retry += 1
                    time.sleep(min(0.05 * n_retry, 0.4))
                    r = c.execute(q)
                ms = (time.monotonic() - t0) * 1000
                ph = phase_box["name"]
                with lock:
                    read_retries[0] += n_retry
                    if not r.ok():
                        errors.append((ph, q, f"{r.code}: {r.error_msg}"))
                    elif ph:
                        lats.append((ph, ms))

        lw = LedgerWriters(graphd.addr, space, v, n_writers=2,
                           pace_s=0.012).start()
        threads = [threading.Thread(target=reader, args=(k,),
                                    daemon=True)
                   for k in range(readers_n)]
        for t in threads:
            t.start()

        def quiesce():
            pause.set()
            lw.quiesce()
            deadline = time.time() + 15
            while time.time() < deadline and \
                    not all(f.is_set() for f in paused_flags):
                time.sleep(0.02)
            deadline = time.time() + 15
            while any(tpu._repacking.values()) and \
                    time.time() < deadline:
                time.sleep(0.05)

        def resume():
            for f in paused_flags:
                f.clear()
            pause.clear()
            lw.resume()

        def identity_sweep():
            ok_all, device = True, False
            for q in queries:
                g0 = tpu.stats["go_served"] + tpu.stats["agg_served"]
                rt = gc.must(q)
                device |= (tpu.stats["go_served"]
                           + tpu.stats["agg_served"]) > g0
                tpu.enabled = False
                try:
                    rc = gc.must(q)
                finally:
                    tpu.enabled = True
                if sorted(map(repr, rt.rows)) != \
                        sorted(map(repr, rc.rows)):
                    ok_all = False
            return ok_all, device

        phase_dur: dict = {}

        def run_phase(name, end_fn):
            phase_box["name"] = name
            t0 = time.monotonic()
            end_fn()
            phase_dur[name] = time.monotonic() - t0
            phase_box["name"] = None

        def pct(phase):
            xs = sorted(ms for ph, ms in lats if ph == phase)
            if not xs:
                return {"n": 0}
            dur = max(phase_dur.get(phase, phase_s), 1e-3)
            return {"n": len(xs),
                    "p50_ms": round(float(np.percentile(xs, 50)), 2),
                    "p99_ms": round(float(np.percentile(xs, 99)), 2),
                    "qps": round(len(xs) / dur, 1),
                    "wall_s": round(dur, 1)}

        def leader_counts():
            out = {}
            for i, h in storers.items():
                n = 0
                for p in range(1, parts + 1):
                    r = h.node.raft(sid, p)
                    if r is not None and r.is_leader():
                        n += 1
                out[i] = n
            return out

        def fence_rejections():
            n = 0
            for h in storers.values():
                for p in range(1, parts + 1):
                    r = h.node.raft(sid, p)
                    if r is not None:
                        n += (r.follower_read_stats["rejected_stale"]
                              + r.follower_read_stats["rejected_commit"])
            return n

        def wait_converged(timeout=30.0):
            """All three replicas of every part report the same
            committed id (post-heal catch-up proof)."""
            deadline = time.time() + timeout
            while time.time() < deadline:
                ok = True
                for p in range(1, parts + 1):
                    ids = {h.node.raft(sid, p).committed_id
                           for h in storers.values()
                           if h.node.raft(sid, p) is not None}
                    if len(ids) != 1:
                        ok = False
                        break
                if ok:
                    return True
                time.sleep(0.1)
            return False

        nemesis = Nemesis()

        def heal_and_settle(settle_s=1.5):
            nemesis.heal()
            deadline = time.time() + 20
            while sum(leader_counts().values()) < parts and \
                    time.time() < deadline:
                time.sleep(0.1)
            time.sleep(settle_s)

        # ---- phase 1: baseline (leader-only routing)
        run_phase("baseline", lambda: time.sleep(phase_s))

        # ---- phase 2: arm bounded-staleness follower reads via the
        # production config path (UPDATE CONFIGS -> meta -> heartbeat)
        gc.must(f"UPDATE CONFIGS STORAGE:follower_read_max_ms = "
                f"{fr_bound_ms}")
        deadline = time.time() + 15
        while storage_flags.get("follower_read_max_ms") != fr_bound_ms \
                and time.time() < deadline:
            time.sleep(0.05)
        assert storage_flags.get("follower_read_max_ms") == fr_bound_ms
        run_phase("follower_reads", lambda: time.sleep(phase_s))

        # ---- phase 3: symmetric split — the leader-heaviest storaged
        # partitioned raft-and-data; failover + ejection + hedges
        deadline = time.time() + 15
        counts = leader_counts()
        while sum(counts.values()) < parts and time.time() < deadline:
            time.sleep(0.1)
            counts = leader_counts()
        victim = max(counts, key=counts.get)
        v_store = storers[victim].addr
        v_raft = raft_addr_of(v_store)
        o_rafts = [raft_addr_of(storers[i].addr)
                   for i in storers if i != victim]
        log(f"partition tier: sym-splitting storaged {victim} "
            f"({v_store}), led {counts[victim]}/{parts} parts")
        sym_plan = ";".join([
            Nemesis.symmetric_split([v_raft], o_rafts),
            f"symdata:peer=*>{v_store},hang=1",
        ])

        def sym_split():
            nemesis.apply(sym_plan)
            time.sleep(phase_s * 2)

        run_phase("sym_split", sym_split)
        sym_fired = dict(faults.counts())
        heal_and_settle()

        # ---- phase 4: raft-isolate a FOLLOWER, data plane open — its
        # fence must decline follower reads rather than serve stale
        counts = leader_counts()
        fenced = min(counts, key=counts.get)
        if fenced == victim and len(storers) > 2:
            others = sorted(i for i in storers if i != victim)
            fenced = min(others, key=lambda i: counts[i])
        f_raft = raft_addr_of(storers[fenced].addr)
        rej0 = fence_rejections()
        log(f"partition tier: raft-isolating follower {fenced} "
            f"({storers[fenced].addr}), data plane open")

        def follower_fence():
            nemesis.apply(f"fence:peer=*>{f_raft},hang=1;"
                          f"fence:peer={f_raft}>*,hang=1")
            time.sleep(phase_s * 2)

        run_phase("follower_fenced", follower_fence)
        fence_rej = fence_rejections() - rej0
        heal_and_settle()

        # ---- phase 5: gray node — slow, never erroring; hedged reads
        # must win and contain p99
        counts = leader_counts()
        gray = min(counts, key=counts.get)
        g_store = storers[gray].addr
        wins0 = client.hedge_stats["won"]
        log(f"partition tier: graying storaged {gray} ({g_store}) "
            f"+250ms±100 data-plane latency")

        def gray_phase():
            nemesis.apply(Nemesis.slow_node(
                [g_store], latency_ms=250.0, jitter_ms=100.0))
            time.sleep(phase_s * 2)

        run_phase("gray", gray_phase)
        hedge_wins_gray = client.hedge_stats["won"] - wins0
        heal_and_settle()

        # ---- phase 6: flapping link — the split toggled on/off
        def flap_phase():
            nemesis.flap(sym_plan, cycles=3 if trim else 5,
                         on_s=0.3, off_s=0.3)

        run_phase("flap", flap_phase)
        heal_and_settle()

        # ---- converge: ledger re-read, divergence, staleness bound,
        # identity + device serving
        converged = wait_converged()
        quiesce()
        graph_flags.set("shadow_read_rate", 0.0)
        cons.shadow.drain(20)
        missing = lw.verify_ledger(gc)
        identity_ok = device_ok = False
        deadline = time.time() + (60 if trim else 45)
        while time.time() < deadline:
            identity_ok, dev = identity_sweep()
            if identity_ok and dev:
                device_ok = True
                break
            time.sleep(0.4)
        resume()
        stop.set()
        lw.stop()
        for t in threads:
            t.join(timeout=30)

        # follower-read staleness bound: measured max SERVED staleness
        # across client + hosts vs fence budget + shard slack
        cdev = dict(client.device_stats)
        stal = [float(cdev.get("max_staleness_ms", 0.0))]
        per_host = {}
        for h in storers.values():
            mgr = getattr(h, "device_shards", None)
            if mgr is None:
                continue
            per_host[h.addr] = dict(mgr.stats)
            stal.append(float(mgr.stats.get("max_staleness_ms", 0)))
        slack = int(storage_flags.get_or("device_shard_max_ms", 250,
                                         int))
        max_stal = round(max(stal), 2)
        divergence = _gstats.lifetime_total(
            "consistency.divergence") - div0
        cons_rows = []
        for h in storers.values():
            for row in h.node.consistency_status():
                if row.get("digest_divergent"):
                    cons_rows.append(row)
        sh = cons.shadow.stats()
        flight_triggers = {r["name"]: r["fires"]
                           for r in flight_rec.describe()["triggers"]
                           if r["fires"]}

        phases = {ph: pct(ph) for ph in (
            "baseline", "follower_reads", "sym_split",
            "follower_fenced", "gray", "flap")}
        base_p99 = max(phases["baseline"].get("p99_ms") or 1.0, 25.0)
        gray_p99 = phases["gray"].get("p99_ms") or 0.0
        rec = {
            "trim": trim,
            "graph": {"V": v, "E": e, "partition_num": parts,
                      "replica_factor": 3},
            "sessions": {"readers": readers_n, "writers": 2},
            "phases": phases,
            "nemesis": {
                "sym_split_victim": v_store,
                "fenced_follower": storers[fenced].addr,
                "gray_node": g_store,
                "sym_fired": sym_fired,
                "fired_total": dict(faults.counts()),
            },
            "ledger": {**lw.summary(), "missing": len(missing),
                       "missing_samples": missing[:5]},
            "client": {
                "read_errors": errors[:5],
                "read_error_count": len(errors),
                "read_retries": read_retries[0],
                "retry_stats": dict(client.retry_stats),
                "peer_health": client.peer_health.snapshot(),
                "hedge": dict(client.hedge_stats),
            },
            "gray_slo": {
                "baseline_p99_ms_floored": base_p99,
                "gray_p99_ms": gray_p99,
                "factor": round(gray_p99 / base_p99, 2),
                "declared_factor": gray_factor,
                "hedge_wins_in_phase": hedge_wins_gray,
            },
            "follower_reads": {
                "bound_ms": fr_bound_ms,
                "shard_slack_ms": slack,
                "max_served_staleness_ms": max_stal,
                "staleness_bounded": max_stal <= fr_bound_ms + slack,
                "fence_rejections_while_fenced": fence_rej,
                "client": cdev,
                "per_host": per_host,
            },
            "consistency": {
                "divergence": divergence,
                "divergent_rows": cons_rows[:5],
                "shadow": {k: sh[k] for k in
                           ("sampled", "verified", "mismatches")},
            },
            "convergence": {"committed_ids_converged": converged,
                            "identity": identity_ok,
                            "device_served": device_ok},
            "flight_triggers": flight_triggers,
            "lock_witness": _witness_summary(),
        }
        ok = (len(missing) == 0                    # no acked-write loss
              and not errors and not lw.errors     # no non-retryable
              and divergence == 0 and not cons_rows
              and sh["sampled"] > 0
              and sh["mismatches"] == 0            # no replica lies
              and rec["follower_reads"]["staleness_bounded"]
              and fence_rej > 0                    # fenced != served
              and hedge_wins_gray > 0
              and gray_p99 <= gray_factor * base_p99
              and converged and identity_ok and device_ok
              and all(phases[ph]["n"] > 0 for ph in phases)
              and rec["lock_witness"]["clean"])
        rec["ok"] = ok
        with open(out_path, "w") as f:
            json.dump(rec, f, indent=1)
        log(f"partition tier: phases={ {p: phases[p].get('p99_ms') for p in phases} } "
            f"errors={len(errors)} missing={len(missing)} "
            f"fence_rej={fence_rej} hedge_wins={hedge_wins_gray} "
            f"-> {out_path}")
        print(json.dumps({
            "metric": "partition", "ok": ok,
            "acked_missing": len(missing),
            "read_errors": len(errors),
            "divergence": divergence,
            "fence_rejections": fence_rej,
            "gray_p99_factor": rec["gray_slo"]["factor"],
            "hedge_wins": hedge_wins_gray}))
        if not ok:
            raise SystemExit(f"partition tier FAILED: "
                             f"{json.dumps(rec, indent=1)[:4000]}")
        return rec
    finally:
        stop.set()
        faults.reset()
        try:
            if lw is not None:
                lw.stop(timeout=10)
            if graphd is not None:
                graphd.stop()
            for h in (storers or {}).values():
                try:
                    h.stop()
                except Exception:
                    pass
            if metad is not None:
                metad.stop()
        finally:
            for k, val in saved.items():
                storage_flags.set(k, val)
            for k, val in saved_g.items():
                graph_flags.set(k, val)
            shutil.rmtree(run_dir, ignore_errors=True)


def main():
    if "--tenants" in sys.argv:
        out = os.environ.get("BENCH_TENANTS_OUT", "TENANTS_bench.json")
        for a in sys.argv:
            if a.startswith("--out="):
                out = a.split("=", 1)[1]
        bench_tenants(out, trim="--trim" in sys.argv)
        return
    if "--cluster" in sys.argv:
        out = os.environ.get("BENCH_CLUSTER_OUT", "CLUSTER_bench.json")
        for a in sys.argv:
            if a.startswith("--out="):
                out = a.split("=", 1)[1]
        bench_cluster(out, trim="--trim" in sys.argv)
        return
    if "--partition" in sys.argv:
        out = os.environ.get("BENCH_PARTITION_OUT",
                             "PARTITION_bench.json")
        for a in sys.argv:
            if a.startswith("--out="):
                out = a.split("=", 1)[1]
        bench_partition(out, trim="--trim" in sys.argv)
        return
    if "--crash" in sys.argv:
        out = os.environ.get("BENCH_CRASH_OUT", "CRASH_bench.json")
        for a in sys.argv:
            if a.startswith("--out="):
                out = a.split("=", 1)[1]
        bench_crash(out, trim="--trim" in sys.argv)
        return
    if "--skew" in sys.argv:
        out = os.environ.get("BENCH_SKEW_OUT", "SKEW_bench.json")
        for a in sys.argv:
            if a.startswith("--out="):
                out = a.split("=", 1)[1]
        bench_skew(out, trim="--trim" in sys.argv)
        return
    if "--consistency" in sys.argv:
        out = os.environ.get("BENCH_CONSISTENCY_OUT",
                             "CONSISTENCY_bench.json")
        for a in sys.argv:
            if a.startswith("--out="):
                out = a.split("=", 1)[1]
        bench_consistency(out, trim="--trim" in sys.argv)
        return
    if "--writes" in sys.argv:
        out = os.environ.get("BENCH_WRITES_OUT", "WRITE_bench.json")
        for a in sys.argv:
            if a.startswith("--out="):
                out = a.split("=", 1)[1]
        bench_writes(out, trim="--trim" in sys.argv)
        return
    if "--cache-smoke" in sys.argv:
        out = os.environ.get("BENCH_CACHE_OUT", "CACHE_smoke.json")
        for a in sys.argv:
            if a.startswith("--out="):
                out = a.split("=", 1)[1]
        bench_cache_smoke(out)
        return
    if "--lookup-smoke" in sys.argv:
        out = os.environ.get("BENCH_LOOKUP_OUT", "LOOKUP_smoke.json")
        for a in sys.argv:
            if a.startswith("--out="):
                out = a.split("=", 1)[1]
        bench_lookup_smoke(out)
        return
    if "--chaos" in sys.argv:
        out = os.environ.get("BENCH_CHAOS_OUT", "CHAOS_bench.json")
        for a in sys.argv:
            if a.startswith("--out="):
                out = a.split("=", 1)[1]
        bench_chaos(out, trim="--trim" in sys.argv)
        return
    if "--mesh-dryrun" in sys.argv:
        out = os.environ.get("BENCH_MESH_OUT",
                             "MULTICHIP_mesh_dryrun.json")
        for a in sys.argv:
            if a.startswith("--out="):
                out = a.split("=", 1)[1]
        bench_mesh_dryrun(out,
                          int(os.environ.get("BENCH_MESH_DEVICES", 4)))
        return
    platform = _ensure_backend()
    cluster, tpu, conn, sid, etype, seed_sets = load_cluster()
    (tpu_eps, tpu_qps, gbs, q0_edges, snap, kernel_pick,
     hbm_model) = bench_tpu_batched(cluster, tpu, sid, etype, seed_sets)
    # measured pull-vs-push crossover replaces the modeled constant
    # BEFORE tier-2 runs, so the latency numbers reflect the fitted
    # routing (round-3 verdict item 8)
    cal = tpu.calibrate_sparse_budget(sid, [s[0] for s in seed_sets[:16]],
                                      [etype], STEPS)
    log(f"sparse/dense breakeven calibrated: {cal}")
    p50, p99, qps1, cpu_q_ms, tier2_profile = bench_full_queries(
        conn, tpu, snap, etype, seed_sets)
    stats_extra = bench_stats_query(conn, tpu, seed_sets)
    saved_budget = tpu.sparse_edge_budget
    tpu.sparse_edge_budget = 0       # pin dense: dispatcher rounds
    try:
        tier3 = bench_concurrent(cluster, tpu, seed_sets)
    finally:
        tpu.sparse_edge_budget = saved_budget
    # hot-repeat tier (docs/manual/11-caching.md): repeated statement
    # mix, cold vs cached + per-rung hit rates + concurrent full-mode
    # QPS; runs AFTER the serve-path tiers so their numbers stay
    # cache-free (the default cache_mode=plan never caches results)
    hot_repeat = bench_hot_repeat(cluster, tpu, conn, seed_sets)
    tier3["cache"] = _cache_rung_stats(cluster, tpu)
    # CPU baselines measure a RATE — a seed subset keeps the python
    # materialization of the scan bounded at SNB scale
    cpu_seeds = seed_sets[0][:8]
    cpp_eps, cpp_edges = bench_cpu_scan(cluster, sid, etype, cpu_seeds,
                                        "cpp-scan storaged")
    import jax.numpy as jnp
    from nebula_tpu.engine_tpu import traverse
    tpu_same = int(traverse.multi_hop_count(
        jnp.asarray(snap.frontier_from_vids(cpu_seeds)), jnp.int32(STEPS),
        snap.kernel, jnp.asarray(traverse.pad_edge_types([etype]))))
    if cpp_edges != tpu_same:
        log(f"WARNING: CPU/TPU edge count mismatch "
            f"({cpp_edges} vs {tpu_same})")
    py_eps = bench_python_baseline()
    print(json.dumps({
        "metric": "3hop_go_edges_traversed_per_sec_per_chip",
        "value": round(tpu_eps, 1),
        "unit": "edges/s",
        "platform": platform,
        "vs_baseline": round(tpu_eps / cpp_eps, 2),
        "baseline": "cpp-scan storaged (this framework's native-engine "
                    "CPU hot loop)",
        "vs_python_storaged": round(tpu_eps / py_eps, 2),
        "graph": {"V": V, "E_forward": E, "stored_rows": 2 * E,
                  "shape": "LDBC-SNB person/knows, clipped zipf(1.7)"},
        "batch": BATCH,
        "tier1_kernel": kernel_pick,
        "tier1_qps": round(tpu_qps, 1),
        "tier1_modeled_hbm_gbs": round(gbs, 1),
        "tier1_hbm_util_vs_peak": round(gbs / HBM_PEAK_GBS, 3),
        # packed-width HBM model (docs/manual/13-device-speed.md): the
        # per-stream byte widths behind tier1_modeled_hbm_gbs, so the
        # utilization claim is measured against what the kernels read
        "tier1_hbm_model": hbm_model,
        # device-resident fused serve loop: launches + H2D transfers
        # that overlapped a kernel wait, across the whole bench run —
        # the scalar twins derive from the SAME snapshot as the
        # structured blocks, so the two copies can never disagree
        "fused_launches": (fp_end := tpu.fused_stats())["launches"],
        "h2d_overlap_us": (pf_end :=
                           tpu.prefetch_stats())["h2d_overlap_us"],
        "fused_programs": fp_end,
        "frontier_prefetch": pf_end,
        "tier2_full_query_ms": {"p50": round(p50, 1), "p99": round(p99, 1),
                                "qps_batch1": round(qps1, 1),
                                "cpu_same_query_p50_ms": round(cpu_q_ms, 1)},
        "tier2_profile": tier2_profile,
        "sparse_budget_calibration": cal,
        "stats_query": stats_extra,
        "tier3_concurrent": tier3,
        "hot_repeat": hot_repeat,
    }))


if __name__ == "__main__":
    main()
