"""Benchmark: 3-hop GO traversal rate, TPU engine vs CPU storage path.

Prints ONE JSON line:
  {"metric": "3hop_go_edges_traversed_per_sec_per_chip",
   "value": <TPU edges/sec>, "unit": "edges/s",
   "vs_baseline": <TPU rate / CPU-storage-path rate>}

The graph is a synthetic LDBC-SNB-like social graph (power-law
out-degree "knows" edges). Both paths run the same semantics over the
same store: the CPU baseline is this framework's storage-processor
scatter/gather loop (the role of the reference's CPU storaged,
QueryBoundProcessor); the TPU path is the CSR snapshot + compiled
multi-hop kernel. "Edges traversed" counts every hop's expansions.

Env knobs: BENCH_V, BENCH_E, BENCH_PARTS, BENCH_SEEDS, BENCH_STEPS,
BENCH_ITERS.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

V = int(os.environ.get("BENCH_V", 50_000))
E = int(os.environ.get("BENCH_E", 500_000))
PARTS = int(os.environ.get("BENCH_PARTS", 8))
SEEDS = int(os.environ.get("BENCH_SEEDS", 64))
STEPS = int(os.environ.get("BENCH_STEPS", 3))
ITERS = int(os.environ.get("BENCH_ITERS", 20))
CPU_SEEDS = int(os.environ.get("BENCH_CPU_SEEDS", 2))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def build_store():
    from nebula_tpu.kvstore import GraphStore
    from nebula_tpu.meta.schema_manager import AdHocSchemaManager
    from nebula_tpu.codec import PropType, Schema, SchemaField, RowWriter
    from nebula_tpu.storage import StorageService, StorageClient, NewVertex, NewEdge

    sm = AdHocSchemaManager()
    sm.set_num_parts(1, PARTS)
    person = Schema([])           # prop-free: bench isolates traversal
    knows = Schema([])
    sm.add_tag(1, 1, "person", person)
    sm.add_edge(1, 1, "knows", knows)
    store = GraphStore()
    for p in range(1, PARTS + 1):
        store.add_part(1, p)
    svc = StorageService(store, sm)
    client = StorageClient(sm, local_service=svc)

    rng = np.random.default_rng(42)
    log(f"generating power-law graph V={V} E={E} ...")
    # power-law out-degrees (LDBC-knows-like): zipf exponent 1.7
    srcs = (rng.zipf(1.7, E) - 1) % V
    dsts = rng.integers(0, V, E)
    empty_row = RowWriter(person).encode()
    t0 = time.time()
    vertices = [NewVertex(int(v), [(1, empty_row)]) for v in range(V)]
    client.add_vertices(1, vertices)
    edge_row = RowWriter(knows).encode()
    edges = [NewEdge(int(s), 1, int(i), int(d), edge_row)
             for i, (s, d) in enumerate(zip(srcs, dsts))]
    B = 100_000
    for i in range(0, E, B):
        client.add_edges(1, edges[i:i + B])
    log(f"store loaded in {time.time()-t0:.1f}s")
    seeds = [int(s) for s in rng.choice(V, SEEDS, replace=False)]
    return store, sm, client, seeds


def bench_tpu(store, sm, seeds):
    import jax
    import jax.numpy as jnp
    from nebula_tpu.engine_tpu import traverse
    from nebula_tpu.engine_tpu.csr import build_snapshot

    log(f"jax devices: {jax.devices()}")
    t0 = time.time()
    snap = build_snapshot(store, sm, 1, PARTS)
    log(f"CSR snapshot built in {time.time()-t0:.1f}s "
        f"({snap.total_edges} stored edges, cap_v={snap.cap_v}, cap_e={snap.cap_e})")
    f0 = jnp.asarray(snap.frontier_from_vids(seeds))
    req = jnp.asarray(traverse.pad_edge_types([1]))
    args = (f0, jnp.int32(STEPS), snap.d_edge_src, snap.d_edge_gidx,
            snap.d_edge_etype, snap.d_edge_valid, req)
    t0 = time.time()
    total = int(traverse.multi_hop_count(*args))
    log(f"first run (compile): {time.time()-t0:.1f}s, "
        f"{total} edges traversed per query")
    # timed iterations
    t0 = time.time()
    for _ in range(ITERS):
        out = traverse.multi_hop_count(*args)
    out.block_until_ready()
    dt = time.time() - t0
    eps = total * ITERS / dt
    log(f"TPU: {ITERS} x {STEPS}-hop GO in {dt*1000:.1f}ms "
        f"-> {eps:,.0f} edges/s")
    return eps, total


def bench_cpu(client, seeds, expected_total):
    """The CPU storage scatter/gather path: per-hop get_neighbors fan-out
    with frontier dedup, exactly what GoExecutor drives. Same seed set as
    the TPU measurement (one pass — the rate is what's compared)."""
    t0 = time.time()
    edges_traversed = 0
    frontier = seeds
    for _ in range(STEPS):
        resp = client.get_neighbors(1, frontier, [1], edge_props=[])
        seen = set()
        nxt = []
        for v in resp.vertices:
            for e in v.edges:
                edges_traversed += 1
                if e.dst not in seen:
                    seen.add(e.dst)
                    nxt.append(e.dst)
        frontier = nxt
    dt = time.time() - t0
    eps = edges_traversed / dt
    log(f"CPU: {STEPS}-hop GO from {len(seeds)} seeds: "
        f"{edges_traversed} edges in {dt:.2f}s -> {eps:,.0f} edges/s")
    if edges_traversed != expected_total:
        log(f"WARNING: CPU/TPU edge count mismatch "
            f"({edges_traversed} vs {expected_total})")
    return eps


def main():
    store, sm, client, seeds = build_store()
    tpu_eps, per_query = bench_tpu(store, sm, seeds)
    cpu_eps = bench_cpu(client, seeds, per_query)
    print(json.dumps({
        "metric": "3hop_go_edges_traversed_per_sec_per_chip",
        "value": round(tpu_eps, 1),
        "unit": "edges/s",
        "vs_baseline": round(tpu_eps / cpu_eps, 2),
    }))


if __name__ == "__main__":
    main()
