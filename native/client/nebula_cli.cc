// nebula-tpu C++ graph client + CLI.
//
// A SECOND-LANGUAGE implementation of the frozen v1 wire protocol
// (docs/manual/6-wire-protocol.md; conformance vectors
// docs/manual/wire-vectors.json) — the role the reference's Java
// client fills (ref src/client/java): proof that graphd's wire is
// language-neutral, and a usable CLI:
//
//   nebula_cli --addr 127.0.0.1:3699 [--user root] [--password ""]
//              [--space nba] "GO FROM 100 OVER like"
//
// prints the response as one JSON object {code, columns, rows, ...}.
// `--selftest <wire-vectors.json>` round-trips every conformance
// vector through this codec instead (exit 0 = conformant).
//
// No dependencies beyond POSIX sockets + C++17.

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace wire {

// ---- value model ----------------------------------------------------
struct Value;
using ValuePtr = std::shared_ptr<Value>;

struct Value {
  enum Kind { NUL, BOOL, INT, FLOAT, STR, BYTES, LIST, TUPLE, MAP, ENUM,
              STRUCT };
  Kind kind = NUL;
  bool b = false;
  long long i = 0;              // INT; ENUM member value
  double d = 0;
  std::string s;                // STR/BYTES payload
  std::vector<ValuePtr> items;  // LIST/TUPLE; STRUCT field values
  std::vector<std::pair<ValuePtr, ValuePtr>> kv;  // MAP
  uint32_t reg_id = 0;          // ENUM/STRUCT registry id
};

inline ValuePtr mk(Value::Kind k) {
  auto v = std::make_shared<Value>();
  v->kind = k;
  return v;
}
inline ValuePtr mk_int(long long n) { auto v = mk(Value::INT); v->i = n; return v; }
inline ValuePtr mk_str(const std::string &s) { auto v = mk(Value::STR); v->s = s; return v; }

// ---- encoding (spec §3) ---------------------------------------------
inline void put_u32(std::string &out, uint32_t n) {
  char b[4];
  memcpy(b, &n, 4);             // little-endian hosts only (x86/arm64)
  out.append(b, 4);
}

inline void put_varint(std::string &out, long long n) {
  // (n << 1) ^ (n >> 63) — overflow-free zigzag incl. INT64_MIN
  unsigned long long z =
      (static_cast<unsigned long long>(n) << 1) ^
      static_cast<unsigned long long>(n >> 63);
  while (true) {
    unsigned char byte = z & 0x7F;
    z >>= 7;
    if (z) {
      out.push_back(static_cast<char>(byte | 0x80));
    } else {
      out.push_back(static_cast<char>(byte));
      return;
    }
  }
}

void encode(std::string &out, const Value &v) {
  switch (v.kind) {
    case Value::NUL: out.push_back('N'); return;
    case Value::BOOL: out.push_back(v.b ? 'T' : 'F'); return;
    case Value::INT: out.push_back('i'); put_varint(out, v.i); return;
    case Value::FLOAT: {
      out.push_back('d');
      char b[8];
      memcpy(b, &v.d, 8);
      out.append(b, 8);
      return;
    }
    case Value::STR:
    case Value::BYTES:
      out.push_back(v.kind == Value::STR ? 's' : 'b');
      put_u32(out, static_cast<uint32_t>(v.s.size()));
      out += v.s;
      return;
    case Value::LIST:
    case Value::TUPLE:
      out.push_back(v.kind == Value::LIST ? 'l' : 't');
      put_u32(out, static_cast<uint32_t>(v.items.size()));
      for (const auto &x : v.items) encode(out, *x);
      return;
    case Value::MAP:
      out.push_back('m');
      put_u32(out, static_cast<uint32_t>(v.kv.size()));
      for (const auto &p : v.kv) {
        encode(out, *p.first);
        encode(out, *p.second);
      }
      return;
    case Value::ENUM:
      out.push_back('e');
      put_u32(out, v.reg_id);
      put_varint(out, v.i);
      return;
    case Value::STRUCT:
      out.push_back('c');
      put_u32(out, v.reg_id);
      for (const auto &x : v.items) encode(out, *x);
      return;
  }
}

// ---- decoding -------------------------------------------------------
struct DecodeError {
  std::string msg;
};

// struct field counts by registry id — the wire carries no count, so a
// decoder must know the frozen registry (spec §4; regenerated from
// wire-vectors.json's registry table when types append)
struct Registry {
  // id -> field count (structs) or -1 (enums)
  std::map<uint32_t, int> fields;
  std::map<uint32_t, std::string> names;
};

struct Decoder {
  const unsigned char *p;
  size_t n, off = 0;
  const Registry &reg;

  Decoder(const std::string &buf, const Registry &r)
      : p(reinterpret_cast<const unsigned char *>(buf.data())),
        n(buf.size()), reg(r) {}

  unsigned char byte() {
    if (off >= n) throw DecodeError{"truncated"};
    return p[off++];
  }
  uint32_t u32() {
    if (off + 4 > n) throw DecodeError{"truncated u32"};
    uint32_t v;
    memcpy(&v, p + off, 4);
    off += 4;
    return v;
  }
  long long varint() {
    unsigned long long z = 0;
    int shift = 0;
    while (true) {
      unsigned char b = byte();
      z |= static_cast<unsigned long long>(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 70) throw DecodeError{"varint too long"};
    }
    // (z >> 1) ^ -(z & 1) — exact at the INT64_MIN boundary
    return static_cast<long long>((z >> 1) ^
                                  (~(z & 1) + 1));
  }
  std::string raw(size_t len) {
    if (off + len > n) throw DecodeError{"truncated payload"};
    std::string s(reinterpret_cast<const char *>(p + off), len);
    off += len;
    return s;
  }

  ValuePtr value() {
    unsigned char tag = byte();
    switch (tag) {
      case 'N': return mk(Value::NUL);
      case 'T': { auto v = mk(Value::BOOL); v->b = true; return v; }
      case 'F': { auto v = mk(Value::BOOL); v->b = false; return v; }
      case 'i': return mk_int(varint());
      case 'd': {
        auto v = mk(Value::FLOAT);
        std::string b = raw(8);
        memcpy(&v->d, b.data(), 8);
        return v;
      }
      case 's': case 'b': {
        auto v = mk(tag == 's' ? Value::STR : Value::BYTES);
        uint32_t len = u32();
        v->s = raw(len);
        return v;
      }
      case 'l': case 't': {
        auto v = mk(tag == 'l' ? Value::LIST : Value::TUPLE);
        uint32_t cnt = u32();
        v->items.reserve(cnt);
        for (uint32_t i = 0; i < cnt; i++) v->items.push_back(value());
        return v;
      }
      case 'm': {
        auto v = mk(Value::MAP);
        uint32_t cnt = u32();
        for (uint32_t i = 0; i < cnt; i++) {
          auto k = value();
          auto val = value();
          v->kv.emplace_back(k, val);
        }
        return v;
      }
      case 'e': {
        auto v = mk(Value::ENUM);
        v->reg_id = u32();
        v->i = varint();
        return v;
      }
      case 'c': {
        auto v = mk(Value::STRUCT);
        v->reg_id = u32();
        auto it = reg.fields.find(v->reg_id);
        if (it == reg.fields.end() || it->second < 0)
          throw DecodeError{"unknown struct registry id " +
                            std::to_string(v->reg_id)};
        v->items.reserve(it->second);
        for (int i = 0; i < it->second; i++) v->items.push_back(value());
        return v;
      }
      default:
        throw DecodeError{std::string("unknown tag '") +
                          static_cast<char>(tag) + "'"};
    }
  }
};

// ---- JSON rendering -------------------------------------------------
void json_escape(std::string &out, const std::string &s) {
  out.push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char b[8];
          snprintf(b, sizeof b, "\\u%04x", c);
          out += b;
        } else {
          out.push_back(static_cast<char>(c));
        }
    }
  }
  out.push_back('"');
}

void to_json(std::string &out, const Value &v, const Registry &reg) {
  switch (v.kind) {
    case Value::NUL: out += "null"; return;
    case Value::BOOL: out += v.b ? "true" : "false"; return;
    case Value::INT: out += std::to_string(v.i); return;
    case Value::FLOAT: {
      char b[32];
      snprintf(b, sizeof b, "%.17g", v.d);
      out += b;
      return;
    }
    case Value::STR: json_escape(out, v.s); return;
    case Value::BYTES: {
      static const char *hex = "0123456789abcdef";
      std::string h;
      for (unsigned char c : v.s) {
        h.push_back(hex[c >> 4]);
        h.push_back(hex[c & 15]);
      }
      out += "{\"$bytes\": ";
      json_escape(out, h);
      out += "}";
      return;
    }
    case Value::LIST:
    case Value::TUPLE: {
      out.push_back('[');
      for (size_t i = 0; i < v.items.size(); i++) {
        if (i) out += ", ";
        to_json(out, *v.items[i], reg);
      }
      out.push_back(']');
      return;
    }
    case Value::MAP: {
      out.push_back('{');
      for (size_t i = 0; i < v.kv.size(); i++) {
        if (i) out += ", ";
        if (v.kv[i].first->kind == Value::STR) {
          json_escape(out, v.kv[i].first->s);
        } else {
          // JSON keys must be strings: render the key and quote it
          std::string k;
          to_json(k, *v.kv[i].first, reg);
          json_escape(out, k);
        }
        out += ": ";
        to_json(out, *v.kv[i].second, reg);
      }
      out.push_back('}');
      return;
    }
    case Value::ENUM: {
      auto it = reg.names.find(v.reg_id);
      out += "{\"$enum\": ";
      json_escape(out, it == reg.names.end() ? "?" : it->second);
      out += ", \"value\": " + std::to_string(v.i) + "}";
      return;
    }
    case Value::STRUCT: {
      auto it = reg.names.find(v.reg_id);
      out += "{\"$struct\": ";
      json_escape(out, it == reg.names.end() ? "?" : it->second);
      out += ", \"fields\": [";
      for (size_t i = 0; i < v.items.size(); i++) {
        if (i) out += ", ";
        to_json(out, *v.items[i], reg);
      }
      out += "]}";
      return;
    }
  }
}

}  // namespace wire

// ---- v1 registry (docs/manual/wire-vectors.json `registry`) ---------
// Positional and append-only (spec §4). Struct entries carry their
// field count; enums -1.
static wire::Registry v1_registry() {
  wire::Registry r;
  struct E { const char *name; int fields; };
  static const E table[] = {
      // generated from wire-vectors.json / rpc.wire._register_defaults
      {"ErrorCode", -1},        {"Status", 2},       {"StatusOr", 2},
      {"PropType", -1},         {"SchemaField", 4},  {"Schema", 4},
      {"ExecutionResponse", 8}, {"SpaceDesc", 4},    {"HostInfo", 3},
      {"PartResult", 2},        {"EdgeData", 5},     {"VertexData", 3},
      {"BoundRequest", 7},      {"BoundResponse", 3},
      {"PropsResponse", 4},     {"ExecResponse", 2}, {"NewVertex", 2},
      {"NewEdge", 5},           {"EdgeKey", 4},      {"UpdateItemReq", 2},
      {"UpdateResponse", 4},    {"StatDef", 4},      {"StatsResponse", 4},
      {"RaftCode", -1},         {"LogType", -1},     {"LogRecord", 2},
      {"AskForVoteRequest", 6}, {"AskForVoteResponse", 2},
      {"AppendLogRequest", 9},  {"AppendLogResponse", 6},
      {"SendSnapshotRequest", 10}, {"SendSnapshotResponse", 2},
      {"ScanPartResponse", 7},
  };
  uint32_t id = 0;
  for (const auto &e : table) {
    r.fields[id] = e.fields;
    r.names[id] = e.name;
    id++;
  }
  return r;
}

// ---- framing + RPC (spec §1, §2) ------------------------------------
struct Conn {
  int fd = -1;

  bool dial(const std::string &host, const std::string &port) {
    addrinfo hints{};
    hints.ai_socktype = SOCK_STREAM;
    addrinfo *res = nullptr;
    if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0)
      return false;
    for (addrinfo *a = res; a; a = a->ai_next) {
      fd = socket(a->ai_family, a->ai_socktype, a->ai_protocol);
      if (fd < 0) continue;
      if (connect(fd, a->ai_addr, a->ai_addrlen) == 0) break;
      close(fd);
      fd = -1;
    }
    freeaddrinfo(res);
    return fd >= 0;
  }

  bool send_frame(const std::string &payload) {
    uint32_t len = static_cast<uint32_t>(payload.size());
    char hdr[4];
    memcpy(hdr, &len, 4);
    std::string buf(hdr, 4);
    buf += payload;
    size_t off = 0;
    while (off < buf.size()) {
      ssize_t k = write(fd, buf.data() + off, buf.size() - off);
      if (k <= 0) return false;
      off += static_cast<size_t>(k);
    }
    return true;
  }

  bool recv_exact(std::string &out, size_t len) {
    out.resize(len);
    size_t off = 0;
    while (off < len) {
      ssize_t k = read(fd, &out[off], len - off);
      if (k <= 0) return false;
      off += static_cast<size_t>(k);
    }
    return true;
  }

  bool recv_frame(std::string &payload) {
    std::string hdr;
    if (!recv_exact(hdr, 4)) return false;
    uint32_t len;
    memcpy(&len, hdr.data(), 4);
    if (len > (1u << 30)) return false;
    return recv_exact(payload, len);
  }

  // call "graph".<method>(args...) -> result value; throws DecodeError
  wire::ValuePtr call(const wire::Registry &reg, const std::string &method,
                      std::vector<wire::ValuePtr> args) {
    auto req = wire::mk(wire::Value::TUPLE);
    req->items.push_back(wire::mk_str("graph"));
    req->items.push_back(wire::mk_str(method));
    auto arglist = wire::mk(wire::Value::LIST);
    arglist->items = std::move(args);
    req->items.push_back(arglist);
    req->items.push_back(wire::mk(wire::Value::MAP));
    std::string payload;
    wire::encode(payload, *req);
    std::string resp;
    if (!send_frame(payload) || !recv_frame(resp))
      throw wire::DecodeError{"transport failure"};
    wire::Decoder dec(resp, reg);
    auto v = dec.value();
    if (v->kind != wire::Value::TUPLE || v->items.size() != 2)
      throw wire::DecodeError{"bad response envelope"};
    if (v->items[0]->kind != wire::Value::BOOL || !v->items[0]->b)
      throw wire::DecodeError{"server error: " + v->items[1]->s};
    return v->items[1];
  }
};

// ---- self-test against the conformance vectors ----------------------
// Minimal JSON scanner: pulls every {"name":..,"hex":..} vector and
// round-trips decode(hex) -> encode == hex. Value comparison is via
// byte equality of the re-encoding (encoding is canonical, spec §6).
static int selftest(const std::string &path) {
  FILE *f = fopen(path.c_str(), "rb");
  if (!f) {
    fprintf(stderr, "cannot open %s\n", path.c_str());
    return 2;
  }
  std::string js;
  char buf[1 << 16];
  size_t k;
  while ((k = fread(buf, 1, sizeof buf, f)) > 0) js.append(buf, k);
  fclose(f);
  auto reg = v1_registry();
  int count = 0;
  size_t pos = 0;
  while ((pos = js.find("\"hex\": \"", pos)) != std::string::npos) {
    pos += 8;
    size_t end = js.find('"', pos);
    std::string hex = js.substr(pos, end - pos);
    std::string raw;
    for (size_t i = 0; i + 1 < hex.size(); i += 2)
      raw.push_back(static_cast<char>(
          std::stoi(hex.substr(i, 2), nullptr, 16)));
    try {
      wire::Decoder dec(raw, reg);
      auto v = dec.value();
      if (dec.off != raw.size()) {
        fprintf(stderr, "vector %d: trailing bytes\n", count);
        return 1;
      }
      std::string re;
      wire::encode(re, *v);
      if (re != raw) {
        fprintf(stderr, "vector %d: re-encode mismatch\n", count);
        return 1;
      }
    } catch (const wire::DecodeError &e) {
      fprintf(stderr, "vector %d: %s\n", count, e.msg.c_str());
      return 1;
    }
    count++;
  }
  printf("{\"selftest\": \"ok\", \"vectors\": %d}\n", count);
  return count > 0 ? 0 : 1;
}

// Validated nested response access: a malformed or non-conforming
// server reply must fail as a protocol error, never index out of
// bounds or misread a union member (review finding, round 4). `kind`
// of -1 accepts any member kind (callers reading sub-structs).
static const wire::Value &field(const wire::Value &v, size_t i,
                                int kind = -1) {
  if (v.kind != wire::Value::STRUCT || i >= v.items.size() ||
      !v.items[i])
    throw wire::DecodeError{"bad response shape"};
  const wire::Value &f = *v.items[i];
  if (kind >= 0 && f.kind != kind)
    throw wire::DecodeError{"bad response shape"};
  return f;
}

// Status/response codes ride as INT or ENUM depending on the type's
// registry entry — both store the payload in .i; anything else is a
// protocol error, never a misread of an inactive union member.
static long long code_field(const wire::Value &v, size_t i) {
  const wire::Value &f = field(v, i);
  if (f.kind != wire::Value::INT && f.kind != wire::Value::ENUM)
    throw wire::DecodeError{"bad response shape"};
  return f.i;
}

int main(int argc, char **argv) {
  std::string addr = "127.0.0.1:3699", user = "root", password = "",
              space, query, selftest_path;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      return (i + 1 < argc) ? argv[++i] : "";
    };
    if (a == "--addr") addr = next();
    else if (a == "--user") user = next();
    else if (a == "--password") password = next();
    else if (a == "--space") space = next();
    else if (a == "--selftest") selftest_path = next();
    else if (a == "--help") {
      printf("usage: nebula_cli [--addr H:P] [--user U] [--password P] "
             "[--space S] \"<nGQL>\" | --selftest wire-vectors.json\n");
      return 0;
    } else query = a;
  }
  if (!selftest_path.empty()) return selftest(selftest_path);
  if (query.empty()) {
    fprintf(stderr, "no query given (--help for usage)\n");
    return 2;
  }
  size_t colon = addr.rfind(':');
  if (colon == std::string::npos) {
    fprintf(stderr, "bad --addr %s\n", addr.c_str());
    return 2;
  }
  auto reg = v1_registry();
  Conn c;
  if (!c.dial(addr.substr(0, colon), addr.substr(colon + 1))) {
    fprintf(stderr, "cannot connect to %s\n", addr.c_str());
    return 2;
  }
  try {
    // authenticate -> StatusOr{Status{code, msg}, session_id}
    auto r = c.call(reg, "authenticate",
                    {wire::mk_str(user), wire::mk_str(password)});
    const auto &auth_st = field(*r, 0);
    if (code_field(auth_st, 0) != 0) {
      fprintf(stderr, "auth failed: %s\n",
              field(auth_st, 1, wire::Value::STR).s.c_str());
      return 1;
    }
    long long session = field(*r, 1, wire::Value::INT).i;
    if (!space.empty()) {
      auto u = c.call(reg, "execute",
                      {wire::mk_int(session), wire::mk_str("USE " + space)});
      if (code_field(*u, 0) != 0) {
        fprintf(stderr, "USE %s failed: %s\n", space.c_str(),
                field(*u, 1, wire::Value::STR).s.c_str());
        return 1;
      }
    }
    auto resp = c.call(reg, "execute",
                       {wire::mk_int(session), wire::mk_str(query)});
    // ExecutionResponse: code, error_msg, columns, rows, latency_us,
    // space_name, warning, profile
    long long code = code_field(*resp, 0);
    std::string out = "{\"code\": " + std::to_string(code);
    out += ", \"error_msg\": ";
    wire::json_escape(out, field(*resp, 1, wire::Value::STR).s);
    out += ", \"columns\": ";
    wire::to_json(out, field(*resp, 2), reg);
    out += ", \"rows\": ";
    wire::to_json(out, field(*resp, 3), reg);
    out += ", \"latency_us\": " +
           std::to_string(field(*resp, 4, wire::Value::INT).i);
    out += "}";
    printf("%s\n", out.c_str());
    c.call(reg, "signout", {wire::mk_int(session)});
    return code == 0 ? 0 : 1;
  } catch (const wire::DecodeError &e) {
    fprintf(stderr, "protocol error: %s\n", e.msg.c_str());
    return 1;
  }
}
