/* C ABI for the native runtime components.
 *
 * Role parity with the reference's native (C++) layer: the write-ahead
 * log (ref kvstore/wal/FileBasedWal.{h,cpp}), and — in later additions —
 * the KV engine and codec hot paths. Python binds via ctypes; everything
 * crossing this boundary is plain C types.
 */
#ifndef NEBULA_NATIVE_H
#define NEBULA_NATIVE_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---------------------------------------------------------------- WAL */

typedef struct nwal nwal;
typedef struct nwal_iter nwal_iter;

/* Open (creating dir if needed) a segmented WAL.
 * ttl_secs: sealed segments older than this are eligible for clean_ttl.
 * max_file_size: segment roll threshold in bytes.
 * sync_every_append: fsync after each append (slow, durable). */
nwal *nwal_open(const char *dir, int64_t ttl_secs, int64_t max_file_size,
                int32_t sync_every_append);
void nwal_close(nwal *w);

int64_t nwal_first_log_id(nwal *w);
int64_t nwal_last_log_id(nwal *w);
int64_t nwal_last_log_term(nwal *w);
/* Term of an arbitrary retained log id; -1 if unknown/evicted. */
int64_t nwal_log_term(nwal *w, int64_t log_id);

/* Append one record. log_id must be last_log_id+1 (or anything when
 * empty). Returns 0 on success, negative error code otherwise. */
int32_t nwal_append(nwal *w, int64_t log_id, int64_t term, int64_t cluster,
                    const uint8_t *data, int64_t len);

/* Drop every log with id > keep_to (term-conflict rollback,
 * ref FileBasedWal rollbackToLog). Returns 0 on success. */
int32_t nwal_rollback(nwal *w, int64_t keep_to);

/* Delete all segments and reset to empty. */
int32_t nwal_reset(nwal *w);

/* Delete sealed segments whose newest record is older than ttl
 * (never the active segment). Returns number of files removed. */
int32_t nwal_clean_ttl(nwal *w);

/* TTL sweep bounded by id: an aged segment goes only if its every
 * record id is < id — age alone never truncates unapplied entries. */
int32_t nwal_clean_ttl_before(nwal *w, int64_t id);

/* Delete sealed prefix segments whose every record id is < id (whole
 * segments only; never the active segment) — snapshot-anchored
 * compaction. Returns number of files removed. */
int32_t nwal_clean_before(nwal *w, int64_t id);

/* Force an fsync of the active segment. */
int32_t nwal_sync(nwal *w);

/* Iterator over [from, to] inclusive; to < 0 means "through last". */
nwal_iter *nwal_iter_new(nwal *w, int64_t from, int64_t to);
int32_t nwal_iter_valid(nwal_iter *it);
int64_t nwal_iter_log_id(nwal_iter *it);
int64_t nwal_iter_term(nwal_iter *it);
int64_t nwal_iter_cluster(nwal_iter *it);
/* Returns payload length and sets *out to an internal buffer valid until
 * the next iterator call. */
int64_t nwal_iter_data(nwal_iter *it, const uint8_t **out);
void nwal_iter_next(nwal_iter *it);
void nwal_iter_free(nwal_iter *it);

/* ---------------------------------------------------------- KV engine */

typedef struct nkv nkv;

/* Open an engine. checkpoint_path may be NULL (pure in-memory) — if the
 * file exists its contents are loaded. */
nkv *nkv_open(const char *checkpoint_path);
void nkv_close(nkv *e);

int64_t nkv_count(nkv *e);
int64_t nkv_version(nkv *e);        /* monotonic write counter */
int64_t nkv_approx_size(nkv *e);    /* total key+value bytes */
int32_t nkv_run_count(nkv *e);      /* frozen runs currently held */

/* Runtime tuning (config-registry hook; ref role: hot-applied rocksdb
 * option maps, RocksEngineConfig.cpp). Options: "flush_bytes"
 * (memtable freeze threshold, >= 4096), "max_runs" (background merge
 * trigger, >= 1). set: 0 ok, -1 unknown, -2 invalid; get: value or -1. */
int32_t nkv_set_option(nkv *e, const char *name, int64_t value);
int64_t nkv_get_option(nkv *e, const char *name);

int32_t nkv_put(nkv *e, const uint8_t *k, int64_t klen,
                const uint8_t *v, int64_t vlen);
/* Returns value length and sets *out (valid until the next mutation),
 * or -1 when the key is absent. */
int64_t nkv_get(nkv *e, const uint8_t *k, int64_t klen,
                const uint8_t **out);
int32_t nkv_remove(nkv *e, const uint8_t *k, int64_t klen);
int32_t nkv_remove_range(nkv *e, const uint8_t *s, int64_t slen,
                         const uint8_t *x, int64_t xlen);
int32_t nkv_remove_prefix(nkv *e, const uint8_t *p, int64_t plen);

/* buf = n repetitions of [u32 klen][k][u32 vlen][v] */
int32_t nkv_multi_put(nkv *e, const uint8_t *buf, int64_t len, int32_t n);
/* same buf layout, keys pre-sorted ascending: O(1)/key bulk load */
int64_t nkv_ingest_sorted(nkv *e, const uint8_t *buf, int64_t len,
                          int64_t n);
/* buf = n repetitions of [u32 klen][k] */
int32_t nkv_multi_remove(nkv *e, const uint8_t *buf, int64_t len, int32_t n);

/* Scans materialize matches into a malloc'd packed buffer
 * ([u32 klen][k][u32 vlen][v])*; caller frees with nkv_buf_free.
 * Returns buffer byte length (0 when empty), sets *out and *n_out. */
int64_t nkv_scan_prefix(nkv *e, const uint8_t *p, int64_t plen,
                        uint8_t **out, int64_t *n_out);
int64_t nkv_scan_range(nkv *e, const uint8_t *s, int64_t slen,
                       const uint8_t *x, int64_t xlen,
                       uint8_t **out, int64_t *n_out);
/* Newest-version dedup scan — the getBound hot-loop primitive: keys
 * sharing key[:-group_suffix] form one logical record whose first
 * (= newest, big-endian inverted-timestamp version) row wins. */
int64_t nkv_scan_prefix_dedup(nkv *e, const uint8_t *p, int64_t plen,
                              int32_t group_suffix,
                              uint8_t **out, int64_t *n_out);
/* Columnar scan: all keys in one blob + all values in another, with
 * per-item u32 length arrays (n entries each). Returns item count (or
 * -1 on alloc failure); caller frees all four buffers via nkv_buf_free
 * (klens/vlens cast to uint8_t*). Empty scans return 0 with NULL
 * buffers. The CSR snapshot builder's scan path. */
int64_t nkv_scan_prefix_cols(nkv *e, const uint8_t *p, int64_t plen,
                             uint8_t **keys_out, int64_t *keys_len,
                             uint8_t **vals_out, int64_t *vals_len,
                             uint32_t **klens_out, uint32_t **vlens_out);
void nkv_buf_free(uint8_t *buf);

/* Persist a point-in-time checkpoint (atomic rename). */
int32_t nkv_checkpoint(nkv *e, const char *path);

/* ----------------------------------------------------- CSR extraction
 * One-call pass-1 CSR snapshot build over the engine's graph keys
 * (layout: nebula_tpu/common/keys.py): per part 1..num_parts, scans
 * vertex and edge ranges with newest-version dedup + tombstone skip,
 * parses key fields, assembles sorted-unique per-part vid sets
 * (vertex rows + edge srcs + incoming dsts) and resolves local
 * indices. want_values != 0 retains row values for property decode.
 * Accessor pointers stay valid until ncsr_free; part0 is 0-based. */
typedef struct ncsr ncsr;

ncsr *ncsr_build(nkv *e, int32_t num_parts, int32_t want_values);
void ncsr_free(ncsr *b);
int64_t ncsr_vids(ncsr *b, int32_t part0, const int64_t **vids);
int64_t ncsr_edges(ncsr *b, int32_t part0, const int32_t **src_local,
                   const int32_t **etype, const int64_t **rank,
                   const int64_t **dst_vid, const int32_t **dst_part,
                   const int32_t **dst_local);
int64_t ncsr_edge_vals(ncsr *b, int32_t part0, const uint8_t **blob,
                       int64_t *blob_len, const int64_t **offs,
                       const int32_t **lens);
int64_t ncsr_vert_rows(ncsr *b, int32_t part0, const int32_t **local,
                       const int32_t **tag);
int64_t ncsr_vert_vals(ncsr *b, int32_t part0, const uint8_t **blob,
                       int64_t *blob_len, const int64_t **offs,
                       const int32_t **lens);

/* ------------------------------------------------------------- codec */

/* Field type tags: match nebula_tpu/codec/schema.py PropType values. */
#define NBC_TYPE_BOOL 1
#define NBC_TYPE_INT 2
#define NBC_TYPE_VID 3
#define NBC_TYPE_DOUBLE 5
#define NBC_TYPE_STRING 6
#define NBC_TYPE_TIMESTAMP 7

/* Decode n_rows fixed-slot rows of ONE schema into column buffers.
 * rows_blob: concatenated encoded rows; row_off/row_len per row;
 * row_idx: destination slot per row (0..cap-1, out-of-range skipped).
 * Outputs are caller-allocated flat [n_fields * cap] arrays, indexed
 * f*cap + idx; `nulls` must be pre-filled with 1 (a decoded non-null
 * value clears it). INT/VID/TIMESTAMP and BOOL(0/1) land in vals_i64,
 * DOUBLE in vals_f64, STRING as (absolute offset, length) into
 * rows_blob via str_off/str_len. Returns rows decoded (>=0) or a
 * negative error. */
int64_t nbc_decode_batch(const uint8_t *field_types, int32_t n_fields,
                         const uint8_t *rows_blob, int64_t blob_len,
                         const int64_t *row_off, const int32_t *row_len,
                         const int32_t *row_idx, int64_t n_rows, int64_t cap,
                         int64_t *vals_i64, double *vals_f64,
                         uint32_t *str_off, uint32_t *str_len,
                         uint8_t *nulls);

/* Inverse of nbc_decode_batch: encode [n_fields, n_rows] column-major
 * values into the fixed-slot row layout (byte-identical to
 * codec/row.py RowWriter), writing one contiguous blob plus per-row
 * (row_off, row_len). STRING cells reference (str_off, str_len)
 * slices of str_blob. ver_len (0..8) and schema_ver form each row's
 * version header. Returns total bytes written, or negative: -1 bad
 * args, -2 out_cap too small, -3 a string slice out of str_blob. */
int64_t nbc_encode_rows(const uint8_t *field_types, int32_t n_fields,
                        const int64_t *vals_i64, const double *vals_f64,
                        const uint8_t *nulls, const uint8_t *str_blob,
                        int64_t str_blob_len, const int64_t *str_off,
                        const uint32_t *str_len, int64_t n_rows,
                        int32_t ver_len, int64_t schema_ver, uint8_t *out,
                        int64_t out_cap, int64_t *row_off, int32_t *row_len);

#ifdef __cplusplus
}
#endif

#endif /* NEBULA_NATIVE_H */
