// Batch row decoder — the codec hot path in C++ (role parity with the
// reference's dataman/RowReader C++ codec; ref dataman/RowReader.cpp:
// 221-300). Decodes many fixed-slot rows of one schema straight into
// column buffers, so snapshot builds and scans pay one FFI call per
// batch instead of one Python decode per row.
//
// Row layout (must match nebula_tpu/codec/row.py):
//   [u8 ver_len][schema_ver LE (ver_len bytes)]
//   [null bitmap: ceil(n/8) bytes]
//   [slot region: BOOL=1 byte; INT/VID/TIMESTAMP/DOUBLE=8 LE;
//                 STRING=u32 offset + u32 length into var region]
//   [var region: string payloads]
#include <cstring>

#include "nebula_native.h"

namespace {

inline int64_t rd_i64(const uint8_t *p) {
  int64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86/arm64)
}

inline uint32_t rd_u32(const uint8_t *p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline double rd_f64(const uint8_t *p) {
  double v;
  std::memcpy(&v, p, 8);
  return v;
}

}  // namespace

// Inverse of nbc_decode_batch: encode column-major values into the
// fixed-slot row layout, one contiguous blob + per-row offsets. The
// serving hot path uses it to emit an entire dispatcher window's
// result rows in one GIL-released call (ctypes drops the GIL for the
// duration); byte output is identical to codec/row.py RowWriter so a
// pure-Python fallback can produce the same blob.
//
// Inputs are [n_fields, n_rows] column-major: vals_i64 for
// BOOL/INT/VID/TIMESTAMP, vals_f64 for DOUBLE, (str_off into
// str_blob, str_len) for STRING, nulls (1 = null). schema_ver/ver_len
// form the version header each row carries (ver_len may be 0).
// Returns total bytes written, or negative: -1 bad args, -2 out_cap
// too small, -3 a string slice exceeds str_blob.
extern "C" int64_t nbc_encode_rows(
    const uint8_t *field_types, int32_t n_fields, const int64_t *vals_i64,
    const double *vals_f64, const uint8_t *nulls, const uint8_t *str_blob,
    int64_t str_blob_len, const int64_t *str_off, const uint32_t *str_len,
    int64_t n_rows, int32_t ver_len, int64_t schema_ver, uint8_t *out,
    int64_t out_cap, int64_t *row_off, int32_t *row_len) {
  int32_t slot_offs[256];
  if (n_fields <= 0 || n_fields > 256 || ver_len < 0 || ver_len > 8)
    return -1;
  int32_t off = 0;
  for (int32_t f = 0; f < n_fields; ++f) {
    slot_offs[f] = off;
    off += (field_types[f] == NBC_TYPE_BOOL) ? 1 : 8;
  }
  const int32_t slot_total = off;
  const int32_t null_bytes = (n_fields + 7) / 8;
  const int32_t fixed = 1 + ver_len + null_bytes + slot_total;

  int64_t pos = 0;
  for (int64_t r = 0; r < n_rows; ++r) {
    if (pos + fixed > out_cap) return -2;
    uint8_t *row = out + pos;
    row[0] = static_cast<uint8_t>(ver_len);
    for (int32_t k = 0; k < ver_len; ++k)
      row[1 + k] = static_cast<uint8_t>((schema_ver >> (8 * k)) & 0xFF);
    uint8_t *nullmap = row + 1 + ver_len;
    std::memset(nullmap, 0, null_bytes);
    uint8_t *slots = nullmap + null_bytes;
    std::memset(slots, 0, slot_total);
    int64_t var_len = 0;  // var region filled in a second field pass
    for (int32_t f = 0; f < n_fields; ++f) {
      const int64_t in = static_cast<int64_t>(f) * n_rows + r;
      if (nulls[in]) {
        nullmap[f >> 3] |= static_cast<uint8_t>(1u << (f & 7));
        continue;
      }
      uint8_t *slot = slots + slot_offs[f];
      switch (field_types[f]) {
        case NBC_TYPE_BOOL:
          slot[0] = vals_i64[in] ? 1 : 0;
          break;
        case NBC_TYPE_DOUBLE:
          std::memcpy(slot, &vals_f64[in], 8);
          break;
        case NBC_TYPE_STRING: {
          const int64_t so = str_off[in];
          const uint32_t sl = str_len[in];
          if (so < 0 || so + sl > str_blob_len) return -3;
          const uint32_t vo = static_cast<uint32_t>(var_len);
          std::memcpy(slot, &vo, 4);
          std::memcpy(slot + 4, &sl, 4);
          if (pos + fixed + var_len + sl > out_cap) return -2;
          std::memcpy(row + fixed + var_len, str_blob + so, sl);
          var_len += sl;
          break;
        }
        default:  // INT / VID / TIMESTAMP
          std::memcpy(slot, &vals_i64[in], 8);
          break;
      }
    }
    row_off[r] = pos;
    row_len[r] = static_cast<int32_t>(fixed + var_len);
    pos += fixed + var_len;
  }
  return pos;
}

extern "C" int64_t nbc_decode_batch(
    const uint8_t *field_types, int32_t n_fields, const uint8_t *rows_blob,
    int64_t blob_len, const int64_t *row_off, const int32_t *row_len,
    const int32_t *row_idx, int64_t n_rows, int64_t cap, int64_t *vals_i64,
    double *vals_f64, uint32_t *str_off, uint32_t *str_len, uint8_t *nulls) {
  // slot offsets are schema-constant
  int32_t slot_offs[256];
  if (n_fields <= 0 || n_fields > 256) return -1;
  // str_off is u32: refuse blobs it can't address (caller falls back)
  if (blob_len > static_cast<int64_t>(UINT32_MAX)) return -2;
  int32_t off = 0;
  for (int32_t f = 0; f < n_fields; ++f) {
    slot_offs[f] = off;
    off += (field_types[f] == NBC_TYPE_BOOL) ? 1 : 8;
  }
  const int32_t slot_total = off;
  const int32_t null_bytes = (n_fields + 7) / 8;

  int64_t ok_rows = 0;
  for (int64_t r = 0; r < n_rows; ++r) {
    const int64_t ro = row_off[r];
    const int32_t rl = row_len[r];
    const int64_t idx = row_idx[r];
    if (idx < 0 || idx >= cap) continue;
    if (ro < 0 || rl < 1 || ro + rl > blob_len) continue;
    const uint8_t *row = rows_blob + ro;
    const int32_t ver_len = row[0];
    const int32_t null_off = 1 + ver_len;
    const int32_t slot_off = null_off + null_bytes;
    const int32_t var_off = slot_off + slot_total;
    if (var_off > rl) continue;  // truncated row: leave fields null
    ++ok_rows;
    for (int32_t f = 0; f < n_fields; ++f) {
      const int64_t out = static_cast<int64_t>(f) * cap + idx;
      if (row[null_off + (f >> 3)] & (1u << (f & 7))) continue;  // null
      const uint8_t *slot = row + slot_off + slot_offs[f];
      switch (field_types[f]) {
        case NBC_TYPE_BOOL:
          vals_i64[out] = slot[0] ? 1 : 0;
          break;
        case NBC_TYPE_INT:
        case NBC_TYPE_VID:
        case NBC_TYPE_TIMESTAMP:
          vals_i64[out] = rd_i64(slot);
          break;
        case NBC_TYPE_DOUBLE:
          vals_f64[out] = rd_f64(slot);
          break;
        case NBC_TYPE_STRING: {
          const uint32_t so = rd_u32(slot);
          const uint32_t sl = rd_u32(slot + 4);
          if (static_cast<int64_t>(var_off) + so + sl > rl) continue;
          str_off[out] = static_cast<uint32_t>(ro + var_off + so);
          str_len[out] = sl;
          break;
        }
        default:
          continue;  // unknown type: stays null
      }
      nulls[out] = 0;
    }
  }
  return ok_rows;
}
