// Batch row decoder — the codec hot path in C++ (role parity with the
// reference's dataman/RowReader C++ codec; ref dataman/RowReader.cpp:
// 221-300). Decodes many fixed-slot rows of one schema straight into
// column buffers, so snapshot builds and scans pay one FFI call per
// batch instead of one Python decode per row.
//
// Row layout (must match nebula_tpu/codec/row.py):
//   [u8 ver_len][schema_ver LE (ver_len bytes)]
//   [null bitmap: ceil(n/8) bytes]
//   [slot region: BOOL=1 byte; INT/VID/TIMESTAMP/DOUBLE=8 LE;
//                 STRING=u32 offset + u32 length into var region]
//   [var region: string payloads]
#include <cstring>

#include "nebula_native.h"

namespace {

inline int64_t rd_i64(const uint8_t *p) {
  int64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86/arm64)
}

inline uint32_t rd_u32(const uint8_t *p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline double rd_f64(const uint8_t *p) {
  double v;
  std::memcpy(&v, p, 8);
  return v;
}

}  // namespace

extern "C" int64_t nbc_decode_batch(
    const uint8_t *field_types, int32_t n_fields, const uint8_t *rows_blob,
    int64_t blob_len, const int64_t *row_off, const int32_t *row_len,
    const int32_t *row_idx, int64_t n_rows, int64_t cap, int64_t *vals_i64,
    double *vals_f64, uint32_t *str_off, uint32_t *str_len, uint8_t *nulls) {
  // slot offsets are schema-constant
  int32_t slot_offs[256];
  if (n_fields <= 0 || n_fields > 256) return -1;
  // str_off is u32: refuse blobs it can't address (caller falls back)
  if (blob_len > static_cast<int64_t>(UINT32_MAX)) return -2;
  int32_t off = 0;
  for (int32_t f = 0; f < n_fields; ++f) {
    slot_offs[f] = off;
    off += (field_types[f] == NBC_TYPE_BOOL) ? 1 : 8;
  }
  const int32_t slot_total = off;
  const int32_t null_bytes = (n_fields + 7) / 8;

  int64_t ok_rows = 0;
  for (int64_t r = 0; r < n_rows; ++r) {
    const int64_t ro = row_off[r];
    const int32_t rl = row_len[r];
    const int64_t idx = row_idx[r];
    if (idx < 0 || idx >= cap) continue;
    if (ro < 0 || rl < 1 || ro + rl > blob_len) continue;
    const uint8_t *row = rows_blob + ro;
    const int32_t ver_len = row[0];
    const int32_t null_off = 1 + ver_len;
    const int32_t slot_off = null_off + null_bytes;
    const int32_t var_off = slot_off + slot_total;
    if (var_off > rl) continue;  // truncated row: leave fields null
    ++ok_rows;
    for (int32_t f = 0; f < n_fields; ++f) {
      const int64_t out = static_cast<int64_t>(f) * cap + idx;
      if (row[null_off + (f >> 3)] & (1u << (f & 7))) continue;  // null
      const uint8_t *slot = row + slot_off + slot_offs[f];
      switch (field_types[f]) {
        case NBC_TYPE_BOOL:
          vals_i64[out] = slot[0] ? 1 : 0;
          break;
        case NBC_TYPE_INT:
        case NBC_TYPE_VID:
        case NBC_TYPE_TIMESTAMP:
          vals_i64[out] = rd_i64(slot);
          break;
        case NBC_TYPE_DOUBLE:
          vals_f64[out] = rd_f64(slot);
          break;
        case NBC_TYPE_STRING: {
          const uint32_t so = rd_u32(slot);
          const uint32_t sl = rd_u32(slot + 4);
          if (static_cast<int64_t>(var_off) + so + sl > rl) continue;
          str_off[out] = static_cast<uint32_t>(ro + var_off + so);
          str_len[out] = sl;
          break;
        }
        default:
          continue;  // unknown type: stays null
      }
      nulls[out] = 0;
    }
  }
  return ok_rows;
}
