// Ordered KV engine.
//
// Role parity with the reference's RocksEngine (ref
// kvstore/RocksEngine.{h,cpp}): one ordered namespace per (space,
// data-path) with prefix/range scans, batched writes, bulk ingest and a
// point-in-time checkpoint. The newest-version dedup scan implements
// the QueryBoundProcessor hot-loop primitive (ref
// storage/QueryBaseProcessor.inl:380-458: iterate prefix, keep the
// first — newest, because versions are stored inverted big-endian —
// row of every (rank,dst) group) so the Python processor loop stays out
// of the O(edges) path.
//
// Checkpoint format: "NKVC" | u32 version | u64 count |
//                    ([u32 klen][k][u32 vlen][v])* | u64 count (trailer)

#include "nebula_native.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

constexpr char kMagic[4] = {'N', 'K', 'V', 'C'};
constexpr uint32_t kVersion = 1;

std::string next_prefix(const std::string &p) {
  // smallest string greater than every key starting with p
  std::string q = p;
  while (!q.empty()) {
    unsigned char c = static_cast<unsigned char>(q.back());
    if (c != 0xFF) {
      q.back() = static_cast<char>(c + 1);
      return q;
    }
    q.pop_back();
  }
  return q;  // empty => no upper bound
}

}  // namespace

struct nkv {
  std::map<std::string, std::string> data;
  std::mutex mu;
  int64_t version = 0;
  int64_t bytes = 0;
  std::string get_scratch;
  std::string ckpt_path;

  bool load(const std::string &path) {
    FILE *f = fopen(path.c_str(), "rb");
    if (!f) return true;  // absent: fresh engine
    char magic[4];
    uint32_t ver;
    uint64_t count;
    if (fread(magic, 1, 4, f) != 4 || memcmp(magic, kMagic, 4) != 0 ||
        fread(&ver, 4, 1, f) != 1 || ver != kVersion ||
        fread(&count, 8, 1, f) != 1) {
      fclose(f);
      return false;
    }
    std::string k, v;
    for (uint64_t i = 0; i < count; i++) {
      uint32_t klen, vlen;
      if (fread(&klen, 4, 1, f) != 1) { fclose(f); return false; }
      k.resize(klen);
      if (klen && fread(&k[0], 1, klen, f) != klen) { fclose(f); return false; }
      if (fread(&vlen, 4, 1, f) != 1) { fclose(f); return false; }
      v.resize(vlen);
      if (vlen && fread(&v[0], 1, vlen, f) != vlen) { fclose(f); return false; }
      bytes += static_cast<int64_t>(k.size() + v.size());
      data.emplace_hint(data.end(), k, v);
    }
    uint64_t trailer = 0;
    bool ok = fread(&trailer, 8, 1, f) == 1 && trailer == count;
    fclose(f);
    if (!ok) { data.clear(); bytes = 0; }
    return ok;
  }

  int32_t checkpoint(const std::string &path) {
    std::lock_guard<std::mutex> g(mu);
    std::string tmp = path + ".tmp";
    FILE *f = fopen(tmp.c_str(), "wb");
    if (!f) return -1;
    uint64_t count = data.size();
    fwrite(kMagic, 1, 4, f);
    fwrite(&kVersion, 4, 1, f);
    fwrite(&count, 8, 1, f);
    for (const auto &kv : data) {
      uint32_t klen = static_cast<uint32_t>(kv.first.size());
      uint32_t vlen = static_cast<uint32_t>(kv.second.size());
      fwrite(&klen, 4, 1, f);
      fwrite(kv.first.data(), 1, klen, f);
      fwrite(&vlen, 4, 1, f);
      fwrite(kv.second.data(), 1, vlen, f);
    }
    fwrite(&count, 8, 1, f);
    if (fflush(f) != 0) { fclose(f); return -2; }
    fclose(f);
    return rename(tmp.c_str(), path.c_str()) == 0 ? 0 : -3;
  }

  void put_one(const std::string &k, const std::string &v) {
    auto it = data.find(k);
    if (it != data.end()) {
      bytes += static_cast<int64_t>(v.size()) -
               static_cast<int64_t>(it->second.size());
      it->second = v;
    } else {
      bytes += static_cast<int64_t>(k.size() + v.size());
      data.emplace(k, v);
    }
  }

  void erase_range(const std::string &start, const std::string &end_excl) {
    auto lo = data.lower_bound(start);
    auto hi = end_excl.empty() ? data.end() : data.lower_bound(end_excl);
    for (auto it = lo; it != hi; ++it)
      bytes -= static_cast<int64_t>(it->first.size() + it->second.size());
    data.erase(lo, hi);
  }
};

extern "C" {

nkv *nkv_open(const char *checkpoint_path) {
  nkv *e = new nkv();
  if (checkpoint_path && *checkpoint_path) {
    e->ckpt_path = checkpoint_path;
    if (!e->load(e->ckpt_path)) {
      delete e;
      return nullptr;
    }
  }
  return e;
}

void nkv_close(nkv *e) { delete e; }

int64_t nkv_count(nkv *e) {
  std::lock_guard<std::mutex> g(e->mu);
  return static_cast<int64_t>(e->data.size());
}

int64_t nkv_version(nkv *e) {
  std::lock_guard<std::mutex> g(e->mu);
  return e->version;
}

int64_t nkv_approx_size(nkv *e) {
  std::lock_guard<std::mutex> g(e->mu);
  return e->bytes;
}

int32_t nkv_put(nkv *e, const uint8_t *k, int64_t klen,
                const uint8_t *v, int64_t vlen) {
  std::lock_guard<std::mutex> g(e->mu);
  e->put_one(std::string(reinterpret_cast<const char *>(k), klen),
             std::string(reinterpret_cast<const char *>(v), vlen));
  e->version++;
  return 0;
}

int64_t nkv_get(nkv *e, const uint8_t *k, int64_t klen,
                const uint8_t **out) {
  std::lock_guard<std::mutex> g(e->mu);
  auto it = e->data.find(std::string(reinterpret_cast<const char *>(k), klen));
  if (it == e->data.end()) return -1;
  e->get_scratch = it->second;
  *out = reinterpret_cast<const uint8_t *>(e->get_scratch.data());
  return static_cast<int64_t>(e->get_scratch.size());
}

int32_t nkv_remove(nkv *e, const uint8_t *k, int64_t klen) {
  std::lock_guard<std::mutex> g(e->mu);
  auto it = e->data.find(std::string(reinterpret_cast<const char *>(k), klen));
  if (it != e->data.end()) {
    e->bytes -= static_cast<int64_t>(it->first.size() + it->second.size());
    e->data.erase(it);
  }
  e->version++;
  return 0;
}

int32_t nkv_remove_range(nkv *e, const uint8_t *s, int64_t slen,
                         const uint8_t *x, int64_t xlen) {
  std::lock_guard<std::mutex> g(e->mu);
  e->erase_range(std::string(reinterpret_cast<const char *>(s), slen),
                 std::string(reinterpret_cast<const char *>(x), xlen));
  e->version++;
  return 0;
}

int32_t nkv_remove_prefix(nkv *e, const uint8_t *p, int64_t plen) {
  std::lock_guard<std::mutex> g(e->mu);
  std::string prefix(reinterpret_cast<const char *>(p), plen);
  e->erase_range(prefix, next_prefix(prefix));
  e->version++;
  return 0;
}

int32_t nkv_multi_put(nkv *e, const uint8_t *buf, int64_t len, int32_t n) {
  std::lock_guard<std::mutex> g(e->mu);
  int64_t off = 0;
  for (int32_t i = 0; i < n; i++) {
    if (off + 4 > len) return -1;
    uint32_t klen;
    memcpy(&klen, buf + off, 4);
    off += 4;
    if (off + klen + 4 > len) return -1;
    std::string k(reinterpret_cast<const char *>(buf + off), klen);
    off += klen;
    uint32_t vlen;
    memcpy(&vlen, buf + off, 4);
    off += 4;
    if (off + vlen > len) return -1;
    std::string v(reinterpret_cast<const char *>(buf + off), vlen);
    off += vlen;
    e->put_one(k, v);
  }
  e->version++;
  return 0;
}

int32_t nkv_multi_remove(nkv *e, const uint8_t *buf, int64_t len, int32_t n) {
  std::lock_guard<std::mutex> g(e->mu);
  int64_t off = 0;
  for (int32_t i = 0; i < n; i++) {
    if (off + 4 > len) return -1;
    uint32_t klen;
    memcpy(&klen, buf + off, 4);
    off += 4;
    if (off + klen > len) return -1;
    auto it = e->data.find(
        std::string(reinterpret_cast<const char *>(buf + off), klen));
    off += klen;
    if (it != e->data.end()) {
      e->bytes -= static_cast<int64_t>(it->first.size() + it->second.size());
      e->data.erase(it);
    }
  }
  e->version++;
  return 0;
}

static int64_t pack_out(const std::vector<std::pair<const std::string *,
                                                    const std::string *>> &hits,
                        uint8_t **out, int64_t *n_out) {
  int64_t total = 0;
  for (const auto &kv : hits)
    total += 8 + static_cast<int64_t>(kv.first->size() + kv.second->size());
  if (total == 0) {
    *out = nullptr;
    *n_out = 0;
    return 0;
  }
  uint8_t *buf = static_cast<uint8_t *>(malloc(static_cast<size_t>(total)));
  int64_t off = 0;
  for (const auto &kv : hits) {
    uint32_t klen = static_cast<uint32_t>(kv.first->size());
    uint32_t vlen = static_cast<uint32_t>(kv.second->size());
    memcpy(buf + off, &klen, 4);
    off += 4;
    memcpy(buf + off, kv.first->data(), klen);
    off += klen;
    memcpy(buf + off, &vlen, 4);
    off += 4;
    memcpy(buf + off, kv.second->data(), vlen);
    off += vlen;
  }
  *out = buf;
  *n_out = static_cast<int64_t>(hits.size());
  return total;
}

int64_t nkv_scan_range(nkv *e, const uint8_t *s, int64_t slen,
                       const uint8_t *x, int64_t xlen,
                       uint8_t **out, int64_t *n_out) {
  std::lock_guard<std::mutex> g(e->mu);
  std::string start(reinterpret_cast<const char *>(s), slen);
  std::string end(reinterpret_cast<const char *>(x), xlen);
  auto lo = e->data.lower_bound(start);
  auto hi = end.empty() ? e->data.end() : e->data.lower_bound(end);
  std::vector<std::pair<const std::string *, const std::string *>> hits;
  for (auto it = lo; it != hi; ++it)
    hits.emplace_back(&it->first, &it->second);
  return pack_out(hits, out, n_out);
}

int64_t nkv_scan_prefix(nkv *e, const uint8_t *p, int64_t plen,
                        uint8_t **out, int64_t *n_out) {
  std::string prefix(reinterpret_cast<const char *>(p), plen);
  std::string end = next_prefix(prefix);
  return nkv_scan_range(e, p, plen,
                        reinterpret_cast<const uint8_t *>(end.data()),
                        static_cast<int64_t>(end.size()), out, n_out);
}

int64_t nkv_scan_prefix_dedup(nkv *e, const uint8_t *p, int64_t plen,
                              int32_t group_suffix,
                              uint8_t **out, int64_t *n_out) {
  std::lock_guard<std::mutex> g(e->mu);
  std::string prefix(reinterpret_cast<const char *>(p), plen);
  std::string end = next_prefix(prefix);
  auto lo = e->data.lower_bound(prefix);
  auto hi = end.empty() ? e->data.end() : e->data.lower_bound(end);
  std::vector<std::pair<const std::string *, const std::string *>> hits;
  const std::string *prev_key = nullptr;
  for (auto it = lo; it != hi; ++it) {
    const std::string &k = it->first;
    size_t glen = k.size() >= static_cast<size_t>(group_suffix)
                      ? k.size() - static_cast<size_t>(group_suffix)
                      : k.size();
    if (prev_key != nullptr && prev_key->size() >= static_cast<size_t>(group_suffix)) {
      size_t pglen = prev_key->size() - static_cast<size_t>(group_suffix);
      if (pglen == glen && memcmp(prev_key->data(), k.data(), glen) == 0)
        continue;  // same group: an older version, skip
    }
    hits.emplace_back(&it->first, &it->second);
    prev_key = &it->first;
  }
  return pack_out(hits, out, n_out);
}

void nkv_buf_free(uint8_t *buf) { free(buf); }

int32_t nkv_checkpoint(nkv *e, const char *path) {
  return e->checkpoint(path ? path : e->ckpt_path);
}

}  // extern "C"
