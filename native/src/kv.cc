// Ordered KV engine.
//
// Role parity with the reference's RocksEngine (ref
// kvstore/RocksEngine.{h,cpp}): one ordered namespace per (space,
// data-path) with prefix/range scans, batched writes, bulk ingest and a
// point-in-time checkpoint. The newest-version dedup scan implements
// the QueryBoundProcessor hot-loop primitive (ref
// storage/QueryBaseProcessor.inl:380-458: iterate prefix, keep the
// first — newest, because versions are stored inverted big-endian —
// row of every (rank,dst) group) so the Python processor loop stays out
// of the O(edges) path.
//
// Checkpoint format: "NKVC" | u32 version | u64 count |
//                    ([u32 klen][k][u32 vlen][v])* | u64 count (trailer)

#include "nebula_native.h"

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr char kMagic[4] = {'N', 'K', 'V', 'C'};
constexpr uint32_t kVersion = 1;

std::string next_prefix(const std::string &p) {
  // smallest string greater than every key starting with p
  std::string q = p;
  while (!q.empty()) {
    unsigned char c = static_cast<unsigned char>(q.back());
    if (c != 0xFF) {
      q.back() = static_cast<char>(c + 1);
      return q;
    }
    q.pop_back();
  }
  return q;  // empty => no upper bound
}

}  // namespace

struct nkv {
  std::map<std::string, std::string> data;
  std::mutex mu;
  int64_t version = 0;
  int64_t bytes = 0;
  std::string get_scratch;
  std::string ckpt_path;

  bool load(const std::string &path) {
    FILE *f = fopen(path.c_str(), "rb");
    if (!f) return true;  // absent: fresh engine
    char magic[4];
    uint32_t ver;
    uint64_t count;
    if (fread(magic, 1, 4, f) != 4 || memcmp(magic, kMagic, 4) != 0 ||
        fread(&ver, 4, 1, f) != 1 || ver != kVersion ||
        fread(&count, 8, 1, f) != 1) {
      fclose(f);
      return false;
    }
    std::string k, v;
    for (uint64_t i = 0; i < count; i++) {
      uint32_t klen, vlen;
      if (fread(&klen, 4, 1, f) != 1) { fclose(f); return false; }
      k.resize(klen);
      if (klen && fread(&k[0], 1, klen, f) != klen) { fclose(f); return false; }
      if (fread(&vlen, 4, 1, f) != 1) { fclose(f); return false; }
      v.resize(vlen);
      if (vlen && fread(&v[0], 1, vlen, f) != vlen) { fclose(f); return false; }
      bytes += static_cast<int64_t>(k.size() + v.size());
      data.emplace_hint(data.end(), k, v);
    }
    uint64_t trailer = 0;
    bool ok = fread(&trailer, 8, 1, f) == 1 && trailer == count;
    fclose(f);
    if (!ok) { data.clear(); bytes = 0; }
    return ok;
  }

  int32_t checkpoint(const std::string &path) {
    std::lock_guard<std::mutex> g(mu);
    std::string tmp = path + ".tmp";
    FILE *f = fopen(tmp.c_str(), "wb");
    if (!f) return -1;
    uint64_t count = data.size();
    fwrite(kMagic, 1, 4, f);
    fwrite(&kVersion, 4, 1, f);
    fwrite(&count, 8, 1, f);
    for (const auto &kv : data) {
      uint32_t klen = static_cast<uint32_t>(kv.first.size());
      uint32_t vlen = static_cast<uint32_t>(kv.second.size());
      fwrite(&klen, 4, 1, f);
      fwrite(kv.first.data(), 1, klen, f);
      fwrite(&vlen, 4, 1, f);
      fwrite(kv.second.data(), 1, vlen, f);
    }
    fwrite(&count, 8, 1, f);
    if (fflush(f) != 0) { fclose(f); return -2; }
    fclose(f);
    return rename(tmp.c_str(), path.c_str()) == 0 ? 0 : -3;
  }

  void put_one(const std::string &k, const std::string &v) {
    auto it = data.find(k);
    if (it != data.end()) {
      bytes += static_cast<int64_t>(v.size()) -
               static_cast<int64_t>(it->second.size());
      it->second = v;
    } else {
      bytes += static_cast<int64_t>(k.size() + v.size());
      data.emplace(k, v);
    }
  }

  void erase_range(const std::string &start, const std::string &end_excl) {
    auto lo = data.lower_bound(start);
    auto hi = end_excl.empty() ? data.end() : data.lower_bound(end_excl);
    for (auto it = lo; it != hi; ++it)
      bytes -= static_cast<int64_t>(it->first.size() + it->second.size());
    data.erase(lo, hi);
  }
};

extern "C" {

nkv *nkv_open(const char *checkpoint_path) {
  nkv *e = new nkv();
  if (checkpoint_path && *checkpoint_path) {
    e->ckpt_path = checkpoint_path;
    if (!e->load(e->ckpt_path)) {
      delete e;
      return nullptr;
    }
  }
  return e;
}

void nkv_close(nkv *e) { delete e; }

int64_t nkv_count(nkv *e) {
  std::lock_guard<std::mutex> g(e->mu);
  return static_cast<int64_t>(e->data.size());
}

int64_t nkv_version(nkv *e) {
  std::lock_guard<std::mutex> g(e->mu);
  return e->version;
}

int64_t nkv_approx_size(nkv *e) {
  std::lock_guard<std::mutex> g(e->mu);
  return e->bytes;
}

int32_t nkv_put(nkv *e, const uint8_t *k, int64_t klen,
                const uint8_t *v, int64_t vlen) {
  std::lock_guard<std::mutex> g(e->mu);
  e->put_one(std::string(reinterpret_cast<const char *>(k), klen),
             std::string(reinterpret_cast<const char *>(v), vlen));
  e->version++;
  return 0;
}

int64_t nkv_get(nkv *e, const uint8_t *k, int64_t klen,
                const uint8_t **out) {
  std::lock_guard<std::mutex> g(e->mu);
  auto it = e->data.find(std::string(reinterpret_cast<const char *>(k), klen));
  if (it == e->data.end()) return -1;
  e->get_scratch = it->second;
  *out = reinterpret_cast<const uint8_t *>(e->get_scratch.data());
  return static_cast<int64_t>(e->get_scratch.size());
}

int32_t nkv_remove(nkv *e, const uint8_t *k, int64_t klen) {
  std::lock_guard<std::mutex> g(e->mu);
  auto it = e->data.find(std::string(reinterpret_cast<const char *>(k), klen));
  if (it != e->data.end()) {
    e->bytes -= static_cast<int64_t>(it->first.size() + it->second.size());
    e->data.erase(it);
  }
  e->version++;
  return 0;
}

int32_t nkv_remove_range(nkv *e, const uint8_t *s, int64_t slen,
                         const uint8_t *x, int64_t xlen) {
  std::lock_guard<std::mutex> g(e->mu);
  e->erase_range(std::string(reinterpret_cast<const char *>(s), slen),
                 std::string(reinterpret_cast<const char *>(x), xlen));
  e->version++;
  return 0;
}

int32_t nkv_remove_prefix(nkv *e, const uint8_t *p, int64_t plen) {
  std::lock_guard<std::mutex> g(e->mu);
  std::string prefix(reinterpret_cast<const char *>(p), plen);
  e->erase_range(prefix, next_prefix(prefix));
  e->version++;
  return 0;
}

int32_t nkv_multi_put(nkv *e, const uint8_t *buf, int64_t len, int32_t n) {
  std::lock_guard<std::mutex> g(e->mu);
  int64_t off = 0;
  for (int32_t i = 0; i < n; i++) {
    if (off + 4 > len) return -1;
    uint32_t klen;
    memcpy(&klen, buf + off, 4);
    off += 4;
    if (off + klen + 4 > len) return -1;
    std::string k(reinterpret_cast<const char *>(buf + off), klen);
    off += klen;
    uint32_t vlen;
    memcpy(&vlen, buf + off, 4);
    off += 4;
    if (off + vlen > len) return -1;
    std::string v(reinterpret_cast<const char *>(buf + off), vlen);
    off += vlen;
    e->put_one(k, v);
  }
  e->version++;
  return 0;
}

int64_t nkv_ingest_sorted(nkv *e, const uint8_t *buf, int64_t len,
                          int64_t n) {
  // Bulk load of ASCENDING pre-sorted rows (the SST-ingest fast path,
  // role parity with RocksEngine::ingest of sorted SSTs): each insert
  // hints at its predecessor's successor, making a fresh or
  // append-at-tail load amortized O(1) per key instead of the
  // put_one find+emplace O(log n) x2. Unsorted input stays correct
  // (emplace_hint falls back to a normal insert), just slower;
  // duplicate keys OVERWRITE like every other write path.
  std::lock_guard<std::mutex> g(e->mu);
  int64_t off = 0;
  auto hint = e->data.end();
  for (int64_t i = 0; i < n; i++) {
    if (off + 4 > len) return -1;
    uint32_t klen;
    memcpy(&klen, buf + off, 4);
    off += 4;
    if (off + klen + 4 > len) return -1;
    std::string k(reinterpret_cast<const char *>(buf + off), klen);
    off += klen;
    uint32_t vlen;
    memcpy(&vlen, buf + off, 4);
    off += 4;
    if (off + vlen > len) return -1;
    std::string v(reinterpret_cast<const char *>(buf + off), vlen);
    off += vlen;
    size_t before = e->data.size();
    auto it = e->data.emplace_hint(hint, k, v);
    if (e->data.size() == before) {   // duplicate: overwrite (put_one)
      e->bytes += static_cast<int64_t>(v.size()) -
                  static_cast<int64_t>(it->second.size());
      it->second = std::move(v);
    } else {
      e->bytes += static_cast<int64_t>(k.size() + v.size());
    }
    hint = ++it;
  }
  e->version++;
  return n;
}

int32_t nkv_multi_remove(nkv *e, const uint8_t *buf, int64_t len, int32_t n) {
  std::lock_guard<std::mutex> g(e->mu);
  int64_t off = 0;
  for (int32_t i = 0; i < n; i++) {
    if (off + 4 > len) return -1;
    uint32_t klen;
    memcpy(&klen, buf + off, 4);
    off += 4;
    if (off + klen > len) return -1;
    auto it = e->data.find(
        std::string(reinterpret_cast<const char *>(buf + off), klen));
    off += klen;
    if (it != e->data.end()) {
      e->bytes -= static_cast<int64_t>(it->first.size() + it->second.size());
      e->data.erase(it);
    }
  }
  e->version++;
  return 0;
}

static int64_t pack_out(const std::vector<std::pair<const std::string *,
                                                    const std::string *>> &hits,
                        uint8_t **out, int64_t *n_out) {
  int64_t total = 0;
  for (const auto &kv : hits)
    total += 8 + static_cast<int64_t>(kv.first->size() + kv.second->size());
  if (total == 0) {
    *out = nullptr;
    *n_out = 0;
    return 0;
  }
  uint8_t *buf = static_cast<uint8_t *>(malloc(static_cast<size_t>(total)));
  int64_t off = 0;
  for (const auto &kv : hits) {
    uint32_t klen = static_cast<uint32_t>(kv.first->size());
    uint32_t vlen = static_cast<uint32_t>(kv.second->size());
    memcpy(buf + off, &klen, 4);
    off += 4;
    memcpy(buf + off, kv.first->data(), klen);
    off += klen;
    memcpy(buf + off, &vlen, 4);
    off += 4;
    memcpy(buf + off, kv.second->data(), vlen);
    off += vlen;
  }
  *out = buf;
  *n_out = static_cast<int64_t>(hits.size());
  return total;
}

int64_t nkv_scan_range(nkv *e, const uint8_t *s, int64_t slen,
                       const uint8_t *x, int64_t xlen,
                       uint8_t **out, int64_t *n_out) {
  std::lock_guard<std::mutex> g(e->mu);
  std::string start(reinterpret_cast<const char *>(s), slen);
  std::string end(reinterpret_cast<const char *>(x), xlen);
  auto lo = e->data.lower_bound(start);
  auto hi = end.empty() ? e->data.end() : e->data.lower_bound(end);
  std::vector<std::pair<const std::string *, const std::string *>> hits;
  for (auto it = lo; it != hi; ++it)
    hits.emplace_back(&it->first, &it->second);
  return pack_out(hits, out, n_out);
}

int64_t nkv_scan_prefix(nkv *e, const uint8_t *p, int64_t plen,
                        uint8_t **out, int64_t *n_out) {
  std::string prefix(reinterpret_cast<const char *>(p), plen);
  std::string end = next_prefix(prefix);
  return nkv_scan_range(e, p, plen,
                        reinterpret_cast<const uint8_t *>(end.data()),
                        static_cast<int64_t>(end.size()), out, n_out);
}

int64_t nkv_scan_prefix_dedup(nkv *e, const uint8_t *p, int64_t plen,
                              int32_t group_suffix,
                              uint8_t **out, int64_t *n_out) {
  std::lock_guard<std::mutex> g(e->mu);
  std::string prefix(reinterpret_cast<const char *>(p), plen);
  std::string end = next_prefix(prefix);
  auto lo = e->data.lower_bound(prefix);
  auto hi = end.empty() ? e->data.end() : e->data.lower_bound(end);
  std::vector<std::pair<const std::string *, const std::string *>> hits;
  const std::string *prev_key = nullptr;
  for (auto it = lo; it != hi; ++it) {
    const std::string &k = it->first;
    size_t glen = k.size() >= static_cast<size_t>(group_suffix)
                      ? k.size() - static_cast<size_t>(group_suffix)
                      : k.size();
    if (prev_key != nullptr && prev_key->size() >= static_cast<size_t>(group_suffix)) {
      size_t pglen = prev_key->size() - static_cast<size_t>(group_suffix);
      if (pglen == glen && memcmp(prev_key->data(), k.data(), glen) == 0)
        continue;  // same group: an older version, skip
    }
    hits.emplace_back(&it->first, &it->second);
    prev_key = &it->first;
  }
  return pack_out(hits, out, n_out);
}

int64_t nkv_scan_prefix_cols(nkv *e, const uint8_t *p, int64_t plen,
                             uint8_t **keys_out, int64_t *keys_len,
                             uint8_t **vals_out, int64_t *vals_len,
                             uint32_t **klens_out, uint32_t **vlens_out) {
  // Columnar scan for the CSR snapshot builder: keys and values land in
  // two contiguous blobs plus per-item length arrays, so Python sees
  // exactly four buffers (numpy-viewable) instead of 2N bytes objects.
  std::lock_guard<std::mutex> g(e->mu);
  std::string prefix(reinterpret_cast<const char *>(p), plen);
  std::string end = next_prefix(prefix);
  auto lo = e->data.lower_bound(prefix);
  auto hi = end.empty() ? e->data.end() : e->data.lower_bound(end);
  int64_t n = 0, kbytes = 0, vbytes = 0;
  for (auto it = lo; it != hi; ++it) {
    ++n;
    kbytes += static_cast<int64_t>(it->first.size());
    vbytes += static_cast<int64_t>(it->second.size());
  }
  *keys_len = kbytes;
  *vals_len = vbytes;
  if (n == 0) {
    *keys_out = *vals_out = nullptr;
    *klens_out = *vlens_out = nullptr;
    return 0;
  }
  uint8_t *kb = static_cast<uint8_t *>(malloc(kbytes ? kbytes : 1));
  uint8_t *vb = static_cast<uint8_t *>(malloc(vbytes ? vbytes : 1));
  uint32_t *kl = static_cast<uint32_t *>(malloc(n * sizeof(uint32_t)));
  uint32_t *vl = static_cast<uint32_t *>(malloc(n * sizeof(uint32_t)));
  if (!kb || !vb || !kl || !vl) {
    free(kb); free(vb); free(kl); free(vl);
    return -1;
  }
  int64_t ko = 0, vo = 0, i = 0;
  for (auto it = lo; it != hi; ++it, ++i) {
    memcpy(kb + ko, it->first.data(), it->first.size());
    kl[i] = static_cast<uint32_t>(it->first.size());
    ko += static_cast<int64_t>(it->first.size());
    memcpy(vb + vo, it->second.data(), it->second.size());
    vl[i] = static_cast<uint32_t>(it->second.size());
    vo += static_cast<int64_t>(it->second.size());
  }
  *keys_out = kb;
  *vals_out = vb;
  *klens_out = kl;
  *vlens_out = vl;
  return n;
}

void nkv_buf_free(uint8_t *buf) { free(buf); }

int32_t nkv_checkpoint(nkv *e, const char *path) {
  return e->checkpoint(path ? path : e->ckpt_path);
}

}  // extern "C"

/* ------------------------------------------------------------------ CSR
 * Pass-1 CSR snapshot extraction (the reference's "storage engine feeds
 * the traversal layout" role — here the whole scan→dedup→parse→
 * local-index loop runs in C++, one call per space; ref role:
 * storage/QueryBaseProcessor.inl:380-458 is the equivalent per-RPC scan).
 * Key layout: common/keys.py — part u32be | kind u8 | biased big-endian
 * fields | version u64be. Vertex keys 25 bytes, edge keys 41.
 */

namespace {

inline uint64_t be64_at(const char *p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return __builtin_bswap64(v);
}

inline uint32_t be32_at(const char *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return __builtin_bswap32(v);
}

inline int64_t unbias64(uint64_t u) {
  return static_cast<int64_t>(u ^ 0x8000000000000000ull);
}

inline int32_t unbias32(uint32_t u) {
  return static_cast<int32_t>(u ^ 0x80000000u);
}

std::string part_kind_prefix(int32_t part, uint8_t kind) {
  std::string p(5, '\0');
  uint32_t be = __builtin_bswap32(static_cast<uint32_t>(part));
  memcpy(&p[0], &be, 4);
  p[4] = static_cast<char>(kind);
  return p;
}

constexpr size_t kVertKeyLen = 25;
constexpr size_t kEdgeKeyLen = 41;
constexpr size_t kVertGroupLen = 17;  // part+kind+vid+tag
constexpr size_t kEdgeGroupLen = 33;  // part+kind+src+etype+rank+dst

struct DstRef {
  int64_t dst;
  int32_t src_part;
  int32_t idx;
};

struct ncsr_part_data {
  std::vector<int64_t> vids;            // sorted unique after build
  // edges, canonical (scan) order
  std::vector<int64_t> src_vid, rank, dst_vid;
  std::vector<int32_t> src_local, etype, dst_part, dst_local;
  std::string evals;
  std::vector<int64_t> evoffs;
  std::vector<int32_t> evlens;
  // visible vertex rows
  std::vector<int64_t> vert_vid;
  std::vector<int32_t> vert_local, vert_tag;
  std::string vvals;
  std::vector<int64_t> vvoffs;
  std::vector<int32_t> vvlens;
  // this part's edge dsts bucketed by OWNER part (resolution phase)
  std::vector<std::vector<DstRef>> dst_by_target;
};

// Parallel loop over partitions (scan and resolution phases are
// per-part independent; the map is read-only while e->mu is held).
// Returns false if any worker threw (e.g. bad_alloc on an
// out-of-memory graph) — exceptions never escape a thread (that would
// std::terminate the daemon) and never cross the C ABI.
bool parallel_parts(int32_t num_parts,
                    const std::function<void(int32_t)> &fn) {
  unsigned hw = std::thread::hardware_concurrency();
  unsigned n = std::min<unsigned>(hw ? hw : 1,
                                  static_cast<unsigned>(num_parts));
  std::atomic<bool> failed{false};
  auto safe = [&](int32_t p) {
    try {
      fn(p);
    } catch (...) {
      failed.store(true);
    }
  };
  if (n <= 1) {
    for (int32_t p = 0; p < num_parts && !failed.load(); ++p) safe(p);
    return !failed.load();
  }
  std::atomic<int32_t> next{0};
  std::vector<std::thread> ts;
  for (unsigned t = 0; t < n; ++t)
    ts.emplace_back([&] {
      int32_t p;
      while (!failed.load() && (p = next.fetch_add(1)) < num_parts)
        safe(p);
    });
  for (auto &t : ts) t.join();
  return !failed.load();
}

}  // namespace

struct ncsr {
  std::vector<ncsr_part_data> parts;
};

extern "C" {

ncsr *ncsr_build(nkv *e, int32_t num_parts, int32_t want_values) {
  std::lock_guard<std::mutex> g(e->mu);
  ncsr *b;
  try {
    b = new ncsr();
    b->parts.resize(static_cast<size_t>(num_parts));
  } catch (...) {
    return nullptr;
  }
  // ---- phase 1: scan + parse + visibility, parallel per part --------
  bool ok = parallel_parts(num_parts, [&](int32_t p0) {
    int32_t p = p0 + 1;
    ncsr_part_data &P = b->parts[static_cast<size_t>(p0)];
    P.dst_by_target.resize(static_cast<size_t>(num_parts));
    {  // vertices: newest (vid, tag) row wins, tombstones invisible
      std::string pre = part_kind_prefix(p, 0x01);
      std::string end = next_prefix(pre);
      auto lo = e->data.lower_bound(pre);
      auto hi = end.empty() ? e->data.end() : e->data.lower_bound(end);
      const std::string *prev = nullptr;
      for (auto it = lo; it != hi; ++it) {
        const std::string &k = it->first;
        if (k.size() != kVertKeyLen) continue;
        if (prev && memcmp(prev->data(), k.data(), kVertGroupLen) == 0)
          continue;
        prev = &k;
        if (it->second.empty()) continue;
        int64_t vid = unbias64(be64_at(k.data() + 5));
        P.vert_vid.push_back(vid);
        P.vert_tag.push_back(unbias32(be32_at(k.data() + 13)));
        if (P.vids.empty() || P.vids.back() != vid)  // scan is vid-sorted
          P.vids.push_back(vid);
        if (want_values) {
          P.vvoffs.push_back(static_cast<int64_t>(P.vvals.size()));
          P.vvlens.push_back(static_cast<int32_t>(it->second.size()));
          P.vvals += it->second;
        }
      }
    }
    {  // edges: newest (src, etype, rank, dst) row wins
      std::string pre = part_kind_prefix(p, 0x02);
      std::string end = next_prefix(pre);
      auto lo = e->data.lower_bound(pre);
      auto hi = end.empty() ? e->data.end() : e->data.lower_bound(end);
      const std::string *prev = nullptr;
      for (auto it = lo; it != hi; ++it) {
        const std::string &k = it->first;
        if (k.size() != kEdgeKeyLen) continue;
        if (prev && memcmp(prev->data(), k.data(), kEdgeGroupLen) == 0)
          continue;
        prev = &k;
        if (it->second.empty()) continue;
        int64_t src = unbias64(be64_at(k.data() + 5));
        int64_t dst = unbias64(be64_at(k.data() + 25));
        int32_t dp = static_cast<int32_t>(
            static_cast<uint64_t>(dst) % static_cast<uint64_t>(num_parts));
        P.dst_by_target[static_cast<size_t>(dp)].push_back(
            {dst, p0, static_cast<int32_t>(P.dst_vid.size())});
        P.src_vid.push_back(src);
        P.etype.push_back(unbias32(be32_at(k.data() + 13)));
        P.rank.push_back(unbias64(be64_at(k.data() + 17)));
        P.dst_vid.push_back(dst);
        P.dst_part.push_back(dp);
        if (P.vids.empty() || P.vids.back() != src)  // scan is src-sorted
          P.vids.push_back(src);
        if (want_values) {
          P.evoffs.push_back(static_cast<int64_t>(P.evals.size()));
          P.evlens.push_back(static_cast<int32_t>(it->second.size()));
          P.evals += it->second;
        }
      }
    }
    P.dst_local.resize(P.dst_vid.size());
  });
  if (!ok) {
    delete b;
    return nullptr;
  }
  // ---- phase 2: vid sets + local resolution, parallel per OWNER part.
  // Each worker q merges incoming dsts from every part into q's vid
  // set, then resolves q's own src/vert locals and every edge whose
  // dst q owns (disjoint dst_local slots — data-race free).
  ok = parallel_parts(num_parts, [&](int32_t q) {
    ncsr_part_data &Q = b->parts[static_cast<size_t>(q)];
    std::vector<DstRef> incoming;
    size_t total = 0;
    for (auto &P : b->parts)
      total += P.dst_by_target[static_cast<size_t>(q)].size();
    incoming.reserve(total);
    for (auto &P : b->parts) {
      auto &bk = P.dst_by_target[static_cast<size_t>(q)];
      incoming.insert(incoming.end(), bk.begin(), bk.end());
    }
    std::sort(incoming.begin(), incoming.end(),
              [](const DstRef &a, const DstRef &x) { return a.dst < x.dst; });
    // destinations get a local slot in their owning partition
    for (const auto &r : incoming)
      if (Q.vids.empty() || Q.vids.back() != r.dst) Q.vids.push_back(r.dst);
    std::sort(Q.vids.begin(), Q.vids.end());
    Q.vids.erase(std::unique(Q.vids.begin(), Q.vids.end()), Q.vids.end());
    // src/vert locals: scan order is src-ascending, one merge walk
    Q.src_local.resize(Q.src_vid.size());
    size_t vi = 0;
    for (size_t i = 0; i < Q.src_vid.size(); ++i) {
      while (Q.vids[vi] < Q.src_vid[i]) ++vi;
      Q.src_local[i] = static_cast<int32_t>(vi);
    }
    Q.vert_local.resize(Q.vert_vid.size());
    vi = 0;
    for (size_t i = 0; i < Q.vert_vid.size(); ++i) {
      while (Q.vids[vi] < Q.vert_vid[i]) ++vi;
      Q.vert_local[i] = static_cast<int32_t>(vi);
    }
    // dst locals for edges landing here (sorted merge)
    vi = 0;
    for (const auto &r : incoming) {
      while (Q.vids[vi] < r.dst) ++vi;
      b->parts[static_cast<size_t>(r.src_part)]
          .dst_local[static_cast<size_t>(r.idx)] = static_cast<int32_t>(vi);
    }
  });
  if (!ok) {
    delete b;
    return nullptr;
  }
  for (auto &P : b->parts) {
    P.dst_by_target.clear();
    P.dst_by_target.shrink_to_fit();
  }
  return b;
}

void ncsr_free(ncsr *b) { delete b; }

int64_t ncsr_vids(ncsr *b, int32_t part0, const int64_t **vids) {
  const auto &P = b->parts[static_cast<size_t>(part0)];
  *vids = P.vids.data();
  return static_cast<int64_t>(P.vids.size());
}

int64_t ncsr_edges(ncsr *b, int32_t part0, const int32_t **src_local,
                   const int32_t **etype, const int64_t **rank,
                   const int64_t **dst_vid, const int32_t **dst_part,
                   const int32_t **dst_local) {
  const auto &P = b->parts[static_cast<size_t>(part0)];
  *src_local = P.src_local.data();
  *etype = P.etype.data();
  *rank = P.rank.data();
  *dst_vid = P.dst_vid.data();
  *dst_part = P.dst_part.data();
  *dst_local = P.dst_local.data();
  return static_cast<int64_t>(P.etype.size());
}

int64_t ncsr_edge_vals(ncsr *b, int32_t part0, const uint8_t **blob,
                       int64_t *blob_len, const int64_t **offs,
                       const int32_t **lens) {
  const auto &P = b->parts[static_cast<size_t>(part0)];
  *blob = reinterpret_cast<const uint8_t *>(P.evals.data());
  *blob_len = static_cast<int64_t>(P.evals.size());
  *offs = P.evoffs.data();
  *lens = P.evlens.data();
  return static_cast<int64_t>(P.evlens.size());
}

int64_t ncsr_vert_rows(ncsr *b, int32_t part0, const int32_t **local,
                       const int32_t **tag) {
  const auto &P = b->parts[static_cast<size_t>(part0)];
  *local = P.vert_local.data();
  *tag = P.vert_tag.data();
  return static_cast<int64_t>(P.vert_tag.size());
}

int64_t ncsr_vert_vals(ncsr *b, int32_t part0, const uint8_t **blob,
                       int64_t *blob_len, const int64_t **offs,
                       const int32_t **lens) {
  const auto &P = b->parts[static_cast<size_t>(part0)];
  *blob = reinterpret_cast<const uint8_t *>(P.vvals.data());
  *blob_len = static_cast<int64_t>(P.vvals.size());
  *offs = P.vvoffs.data();
  *lens = P.vvlens.data();
  return static_cast<int64_t>(P.vvlens.size());
}

}  // extern "C"
