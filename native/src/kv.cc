// Ordered KV engine — mini-LSM.
//
// Role parity with the reference's RocksEngine (ref
// kvstore/RocksEngine.{h,cpp}): one ordered namespace per (space,
// data-path) with prefix/range scans, batched writes, bulk ingest and
// checkpoints. Structure mirrors an LSM tree the way RocksDB does:
//
//   memtable   mutable std::map, tombstones as null values; bounded —
//              at kFlushBytes it freezes into a run (and persists
//              incrementally when a data path is configured)
//   runs       immutable sorted arrays, newest first; `ingest_sorted`
//              lands a pre-sorted bulk load directly as a run (the
//              SST-ingest path, ref RocksEngine.cpp:360)
//   merge      a background thread folds runs together once more than
//              kMaxRuns accumulate, dropping tombstones (the
//              compaction role, ref CompactionFilter)
//
// Reads (gets, scans, the CSR extraction) take a SHARED lock and walk
// a k-way merged, newest-wins view — readers never serialize on each
// other (the round-2 verdict's single-mutex finding); writers take the
// exclusive lock. Durability above the engine is the raft WAL exactly
// as the reference layers it: a crash loses only the memtable, which
// WAL replay regenerates; flushed runs reload from disk.
//
// On-disk formats:
//   base/checkpoint  "NKVC" | u32 ver | u64 n | ([u32 klen][k][u32 vlen][v])* | u64 n
//   run file         "NKVR" | u32 ver | u64 n | ([u32 klen][k][u32 vlen][v])* | u64 n
//                    vlen = 0xFFFFFFFF marks a tombstone
//   manifest         text: "<next_run_id> <base_gen>" then run ids
//                    newest-first. The manifest RENAME is the atomic
//                    commit point for checkpoint collapse: the new base
//                    is written under a fresh generation name first, so
//                    a crash on either side of the rename recovers a
//                    consistent (old or new) state — stale runs can
//                    never shadow a newer base. base_gen 0 = the legacy
//                    single-file image at ckpt_path itself.

#include "nebula_native.h"

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr char kMagic[4] = {'N', 'K', 'V', 'C'};
constexpr char kRunMagic[4] = {'N', 'K', 'V', 'R'};
constexpr uint32_t kVersion = 1;
constexpr uint32_t kTombLen = 0xFFFFFFFFu;
// defaults for the per-instance tunables (see nkv_set_option: the
// config registry hot-updates these at runtime, the role of the
// reference's nested rocksdb option maps, RocksEngineConfig.cpp /
// MetaClient.cpp:1294-1429)
constexpr int64_t kDefaultFlushBytes = 64ll << 20;  // memtable freeze
constexpr size_t kDefaultMaxRuns = 8;               // merge trigger

std::string next_prefix(const std::string &p) {
  // smallest string greater than every key starting with p
  std::string q = p;
  while (!q.empty()) {
    unsigned char c = static_cast<unsigned char>(q.back());
    if (c != 0xFF) {
      q.back() = static_cast<char>(c + 1);
      return q;
    }
    q.pop_back();
  }
  return q;  // empty => no upper bound
}

// value + tombstone flag; memtable uses the same encoding
struct Cell {
  std::string val;
  bool tomb = false;
};

using MemTable = std::map<std::string, Cell>;

struct Run {
  std::vector<std::string> keys;  // ascending, unique
  std::vector<Cell> cells;
  int64_t bytes = 0;
  uint64_t id = 0;  // manifest id; 0 = memory-only

  size_t lower_bound(const std::string &k) const {
    return static_cast<size_t>(
        std::lower_bound(keys.begin(), keys.end(), k) - keys.begin());
  }

  void push(std::string k, std::string v, bool tomb) {
    bytes += static_cast<int64_t>(k.size() + v.size());
    keys.push_back(std::move(k));
    cells.push_back(Cell{std::move(v), tomb});
  }

  bool write_file(const std::string &path) const {
    std::string tmp = path + ".tmp";
    FILE *f = fopen(tmp.c_str(), "wb");
    if (!f) return false;
    uint64_t n = keys.size();
    fwrite(kRunMagic, 1, 4, f);
    fwrite(&kVersion, 4, 1, f);
    fwrite(&n, 8, 1, f);
    for (size_t i = 0; i < keys.size(); ++i) {
      uint32_t klen = static_cast<uint32_t>(keys[i].size());
      uint32_t vlen = cells[i].tomb
                          ? kTombLen
                          : static_cast<uint32_t>(cells[i].val.size());
      fwrite(&klen, 4, 1, f);
      fwrite(keys[i].data(), 1, klen, f);
      fwrite(&vlen, 4, 1, f);
      if (!cells[i].tomb) fwrite(cells[i].val.data(), 1, cells[i].val.size(), f);
    }
    fwrite(&n, 8, 1, f);
    bool ok = fflush(f) == 0;
    fclose(f);
    return ok && rename(tmp.c_str(), path.c_str()) == 0;
  }

  bool load_file(const std::string &path) {
    FILE *f = fopen(path.c_str(), "rb");
    if (!f) return false;
    char magic[4];
    uint32_t ver;
    uint64_t n;
    if (fread(magic, 1, 4, f) != 4 || memcmp(magic, kRunMagic, 4) != 0 ||
        fread(&ver, 4, 1, f) != 1 || ver != kVersion ||
        fread(&n, 8, 1, f) != 1) {
      fclose(f);
      return false;
    }
    std::string k, v;
    for (uint64_t i = 0; i < n; i++) {
      uint32_t klen, vlen;
      if (fread(&klen, 4, 1, f) != 1) { fclose(f); return false; }
      k.resize(klen);
      if (klen && fread(&k[0], 1, klen, f) != klen) { fclose(f); return false; }
      if (fread(&vlen, 4, 1, f) != 1) { fclose(f); return false; }
      bool tomb = vlen == kTombLen;
      v.clear();
      if (!tomb) {
        v.resize(vlen);
        if (vlen && fread(&v[0], 1, vlen, f) != vlen) { fclose(f); return false; }
      }
      push(k, v, tomb);
    }
    uint64_t trailer = 0;
    bool ok = fread(&trailer, 8, 1, f) == 1 && trailer == n;
    fclose(f);
    if (!ok) { keys.clear(); cells.clear(); bytes = 0; }
    return ok;
  }
};

using RunPtr = std::shared_ptr<const Run>;

// k-way merged, newest-wins cursor over memtable + runs for [lo, hi)
// (hi empty = unbounded). Precedence: memtable, then runs[0] (newest)
// .. runs[k-1] (oldest). Tombstoned keys are skipped.
struct MergeCursor {
  MemTable::const_iterator mit, mend;
  struct RC {
    const Run *run;
    size_t i, end;
  };
  std::vector<RC> rcs;

  MergeCursor(const MemTable &mem, const std::vector<RunPtr> &runs,
              const std::string &lo, const std::string &hi) {
    mit = mem.lower_bound(lo);
    mend = hi.empty() ? mem.end() : mem.lower_bound(hi);
    rcs.reserve(runs.size());
    for (const auto &r : runs) {
      size_t i = r->lower_bound(lo);
      size_t end = hi.empty() ? r->keys.size() : r->lower_bound(hi);
      rcs.push_back(RC{r.get(), i, end});
    }
  }

  // -> false when exhausted; else k/v point at the winning entry
  bool next(const std::string *&k, const std::string *&v) {
    while (true) {
      const std::string *best = nullptr;
      int src = -1;  // -1 none, 0 memtable, 1+j run j
      if (mit != mend) {
        best = &mit->first;
        src = 0;
      }
      for (size_t j = 0; j < rcs.size(); ++j) {
        auto &rc = rcs[j];
        if (rc.i < rc.end) {
          const std::string &rk = rc.run->keys[rc.i];
          if (best == nullptr || rk < *best) {
            best = &rk;
            src = static_cast<int>(j) + 1;
          }
        }
      }
      if (best == nullptr) return false;
      const Cell *cell;
      if (src == 0) {
        cell = &mit->second;
      } else {
        auto &rc = rcs[static_cast<size_t>(src - 1)];
        cell = &rc.run->cells[rc.i];
      }
      k = best;
      // advance EVERY source sitting on this key (shadowed copies)
      if (mit != mend && mit->first == *best) ++mit;
      for (auto &rc : rcs)
        while (rc.i < rc.end && rc.run->keys[rc.i] == *best) ++rc.i;
      if (cell->tomb) continue;
      v = &cell->val;
      return true;
    }
  }
};

}  // namespace

struct nkv {
  MemTable mem;
  int64_t mem_bytes = 0;
  // runtime-tunable (nkv_set_option, under the exclusive lock)
  int64_t flush_bytes = kDefaultFlushBytes;
  size_t max_runs = kDefaultMaxRuns;
  std::vector<RunPtr> runs;  // newest first
  mutable std::shared_mutex mu;
  std::atomic<int64_t> version{0};
  std::string ckpt_path;
  uint64_t next_run_id = 1;
  uint64_t base_gen = 0;      // 0 = legacy image at ckpt_path itself
  std::thread merge_thread;   // object guarded by merge_mu (join/assign)
  std::mutex merge_mu;        // lock order: mu THEN merge_mu
  std::atomic<bool> merging{false};

  std::string run_path(uint64_t id) const {
    return ckpt_path + ".run" + std::to_string(id);
  }
  std::string base_path(uint64_t gen) const {
    return gen ? ckpt_path + ".base" + std::to_string(gen) : ckpt_path;
  }
  std::string manifest_path() const { return ckpt_path + ".manifest"; }

  // ---- load ---------------------------------------------------------
  bool load() {
    if (ckpt_path.empty()) return true;
    // manifest runs (newest first), then the NKVC base as oldest
    FILE *mf = fopen(manifest_path().c_str(), "r");
    std::vector<uint64_t> ids;
    if (mf) {
      unsigned long long nid = 1, gen = 0, id;
      if (fscanf(mf, "%llu %llu", &nid, &gen) == 2) {
        next_run_id = nid;
        base_gen = gen;
      }
      while (fscanf(mf, "%llu", &id) == 1) ids.push_back(id);
      fclose(mf);
    }
    for (uint64_t id : ids) {
      auto r = std::make_shared<Run>();
      if (!r->load_file(run_path(id))) return false;
      r->id = id;
      runs.push_back(std::move(r));
    }
    return load_base(base_path(base_gen));
  }

  bool load_base(const std::string &path) {
    FILE *f = fopen(path.c_str(), "rb");
    if (!f) return true;  // absent: fresh engine
    char magic[4];
    uint32_t ver;
    uint64_t count;
    if (fread(magic, 1, 4, f) != 4 || memcmp(magic, kMagic, 4) != 0 ||
        fread(&ver, 4, 1, f) != 1 || ver != kVersion ||
        fread(&count, 8, 1, f) != 1) {
      fclose(f);
      return false;
    }
    auto base = std::make_shared<Run>();
    std::string k, v;
    for (uint64_t i = 0; i < count; i++) {
      uint32_t klen, vlen;
      if (fread(&klen, 4, 1, f) != 1) { fclose(f); return false; }
      k.resize(klen);
      if (klen && fread(&k[0], 1, klen, f) != klen) { fclose(f); return false; }
      if (fread(&vlen, 4, 1, f) != 1) { fclose(f); return false; }
      v.resize(vlen);
      if (vlen && fread(&v[0], 1, vlen, f) != vlen) { fclose(f); return false; }
      base->push(k, v, false);
    }
    uint64_t trailer = 0;
    bool ok = fread(&trailer, 8, 1, f) == 1 && trailer == count;
    fclose(f);
    if (!ok) return false;
    if (!base->keys.empty()) runs.push_back(std::move(base));
    return true;
  }

  bool write_manifest_locked() {
    std::string tmp = manifest_path() + ".tmp";
    FILE *f = fopen(tmp.c_str(), "w");
    if (!f) return false;
    fprintf(f, "%llu %llu\n", static_cast<unsigned long long>(next_run_id),
            static_cast<unsigned long long>(base_gen));
    for (const auto &r : runs)
      if (r->id) fprintf(f, "%llu\n", static_cast<unsigned long long>(r->id));
    bool ok = fflush(f) == 0;
    fclose(f);
    return ok && rename(tmp.c_str(), manifest_path().c_str()) == 0;
  }

  // ---- writes (exclusive lock held by caller) -----------------------
  void put_locked(std::string k, std::string v, bool tomb) {
    auto it = mem.find(k);
    if (it == mem.end()) {
      mem_bytes += static_cast<int64_t>(k.size() + v.size());
      mem.emplace(std::move(k), Cell{std::move(v), tomb});
    } else {
      mem_bytes += static_cast<int64_t>(v.size()) -
                   static_cast<int64_t>(it->second.val.size());
      it->second.val = std::move(v);
      it->second.tomb = tomb;
    }
  }

  // freeze the memtable into a run; persists it when a path is set
  // (this is the INCREMENTAL durability path — no full rewrite).
  // Returns false when the run file could NOT be written: the data
  // stays served from memory but is not crash-durable — callers
  // surface the I/O error instead of reporting a silent OK.
  bool flush_mem_locked() {
    if (mem.empty()) return true;
    auto r = std::make_shared<Run>();
    r->keys.reserve(mem.size());
    r->cells.reserve(mem.size());
    for (auto &kv : mem) r->push(kv.first, std::move(kv.second.val),
                                 kv.second.tomb);
    bool durable = true;
    if (!ckpt_path.empty()) {
      r->id = next_run_id++;
      if (!r->write_file(run_path(r->id))) {
        r->id = 0;  // keep serving from memory
        durable = false;
      }
    }
    runs.insert(runs.begin(), std::move(r));
    mem.clear();
    mem_bytes = 0;
    if (!ckpt_path.empty() && durable) durable = write_manifest_locked();
    return durable;
  }

  bool maybe_flush_locked() {
    if (mem_bytes > flush_bytes) {
      bool ok = flush_mem_locked();
      maybe_merge();
      return ok;
    }
    return true;
  }

  // ---- background merge (compaction role) ---------------------------
  void maybe_merge() {
    // caller holds the exclusive data lock
    if (runs.size() <= max_runs || merging.exchange(true)) return;
    std::lock_guard<std::mutex> tg(merge_mu);
    if (merge_thread.joinable()) merge_thread.join();  // finished thread
    std::vector<RunPtr> snapshot = runs;
    merge_thread = std::thread([this, snapshot] {
      // exceptions must not escape a std::thread (std::terminate);
      // on any failure the merge is simply abandoned
      try {
        auto merged = std::make_shared<Run>();
        {
          MemTable empty;
          MergeCursor cur(empty, snapshot, std::string(), std::string());
          const std::string *k;
          const std::string *v;
          // tombstones drop: the merge covers every older source
          while (cur.next(k, v)) merged->push(*k, *v, false);
        }
        std::unique_lock<std::shared_mutex> g(mu);
        // swap by IDENTITY: drop exactly the snapshot runs still
        // present; if any vanished (a checkpoint collapsed state
        // concurrently), the merge is stale — abandon it
        bool all_present = true;
        for (const auto &s : snapshot) {
          bool found = false;
          for (const auto &r : runs)
            if (r.get() == s.get()) { found = true; break; }
          if (!found) { all_present = false; break; }
        }
        if (all_present) {
          if (!ckpt_path.empty()) {
            merged->id = next_run_id++;
            if (!merged->write_file(run_path(merged->id))) merged->id = 0;
          }
          std::vector<RunPtr> next;
          std::vector<uint64_t> dead;
          for (const auto &r : runs) {
            bool in_snap = false;
            for (const auto &s : snapshot)
              if (r.get() == s.get()) { in_snap = true; break; }
            if (in_snap) {
              if (r->id) dead.push_back(r->id);
            } else {
              next.push_back(r);   // newer runs, still newest-first
            }
          }
          next.push_back(std::move(merged));
          runs = std::move(next);
          if (!ckpt_path.empty()) {
            write_manifest_locked();
            for (uint64_t id : dead) remove(run_path(id).c_str());
          }
        }
      } catch (...) {
        // e.g. bad_alloc building the merged run: state unchanged
      }
      merging.store(false);
    });
  }

  void join_merge() {
    std::lock_guard<std::mutex> tg(merge_mu);
    if (merge_thread.joinable()) merge_thread.join();
  }

  // ---- checkpoint: full merged single-file image --------------------
  int32_t checkpoint(const std::string &path) {
    if (path.empty()) return -1;
    join_merge();
    std::unique_lock<std::shared_mutex> g(mu);
    bool collapse = path == ckpt_path && !ckpt_path.empty();
    uint64_t new_gen = base_gen + 1;
    std::string target = collapse ? base_path(new_gen) : path;
    std::string tmp = target + ".tmp";
    FILE *f = fopen(tmp.c_str(), "wb");
    if (!f) return -1;
    uint64_t count = 0;
    fwrite(kMagic, 1, 4, f);
    fwrite(&kVersion, 4, 1, f);
    fwrite(&count, 8, 1, f);  // backpatched
    auto fresh = std::make_shared<Run>();
    {
      MergeCursor cur(mem, runs, std::string(), std::string());
      const std::string *k;
      const std::string *v;
      while (cur.next(k, v)) {
        uint32_t klen = static_cast<uint32_t>(k->size());
        uint32_t vlen = static_cast<uint32_t>(v->size());
        fwrite(&klen, 4, 1, f);
        fwrite(k->data(), 1, klen, f);
        fwrite(&vlen, 4, 1, f);
        fwrite(v->data(), 1, vlen, f);
        fresh->push(*k, *v, false);
        ++count;
      }
    }
    fwrite(&count, 8, 1, f);
    if (fseek(f, 8, SEEK_SET) != 0 || fwrite(&count, 8, 1, f) != 1 ||
        fflush(f) != 0) {
      fclose(f);
      return -2;
    }
    fclose(f);
    if (rename(tmp.c_str(), target.c_str()) != 0) return -3;
    if (collapse) {
      // commit point: the manifest rename atomically switches to the
      // new generation with zero runs; crash before it -> the old
      // manifest (old base + runs) still loads consistently
      uint64_t old_gen = base_gen;
      std::vector<uint64_t> old_runs;
      for (const auto &r : runs)
        if (r->id) old_runs.push_back(r->id);
      base_gen = new_gen;
      std::vector<RunPtr> none;
      runs.swap(none);
      if (!write_manifest_locked()) {   // commit failed: keep old state
        base_gen = old_gen;
        runs.swap(none);
        remove(target.c_str());
        return -4;
      }
      if (!fresh->keys.empty()) runs.push_back(std::move(fresh));
      mem.clear();
      mem_bytes = 0;
      for (uint64_t id : old_runs) remove(run_path(id).c_str());
      if (old_gen != new_gen) remove(base_path(old_gen).c_str());
    }
    return 0;
  }

  int64_t approx_bytes_locked() const {
    int64_t b = mem_bytes;
    for (const auto &r : runs) b += r->bytes;
    return b;  // shadowed copies double-count: approximate by contract
  }
};

extern "C" {

nkv *nkv_open(const char *checkpoint_path) {
  nkv *e = new nkv();
  if (checkpoint_path) e->ckpt_path = checkpoint_path;
  if (!e->load()) {
    delete e;
    return nullptr;
  }
  return e;
}

void nkv_close(nkv *e) {
  if (!e) return;
  e->join_merge();
  if (!e->ckpt_path.empty()) {
    // clean-shutdown durability: persist the memtable as a final run
    // (the RocksEngine role closes through RocksDB's WAL; without
    // this, an orderly stop would drop everything since the last
    // threshold flush)
    std::unique_lock<std::shared_mutex> g(e->mu);
    e->flush_mem_locked();
  }
  delete e;
}

int64_t nkv_version(nkv *e) { return e->version.load(); }

int64_t nkv_count(nkv *e) {
  // exact live count: merged walk (the engine's callers use this for
  // diagnostics, not hot paths)
  std::shared_lock<std::shared_mutex> g(e->mu);
  MergeCursor cur(e->mem, e->runs, std::string(), std::string());
  const std::string *k;
  const std::string *v;
  int64_t n = 0;
  while (cur.next(k, v)) ++n;
  return n;
}

int64_t nkv_approx_size(nkv *e) {
  std::shared_lock<std::shared_mutex> g(e->mu);
  return e->approx_bytes_locked();
}

int32_t nkv_run_count(nkv *e) {
  std::shared_lock<std::shared_mutex> g(e->mu);
  return static_cast<int32_t>(e->runs.size());
}

// Runtime engine tuning (the config-registry hook). Applying a smaller
// flush threshold also flushes an over-threshold memtable immediately,
// so a hot-set takes effect without waiting for the next write.
// Returns 0 ok, -1 unknown option, -2 invalid value.
int32_t nkv_set_option(nkv *e, const char *name, int64_t value) {
  std::unique_lock<std::shared_mutex> g(e->mu);
  if (std::strcmp(name, "flush_bytes") == 0) {
    if (value < 4096) return -2;
    e->flush_bytes = value;
    e->maybe_flush_locked();
    return 0;
  }
  if (std::strcmp(name, "max_runs") == 0) {
    if (value < 1) return -2;
    e->max_runs = static_cast<size_t>(value);
    e->maybe_merge();
    return 0;
  }
  return -1;
}

int64_t nkv_get_option(nkv *e, const char *name) {
  std::shared_lock<std::shared_mutex> g(e->mu);
  if (std::strcmp(name, "flush_bytes") == 0) return e->flush_bytes;
  if (std::strcmp(name, "max_runs") == 0)
    return static_cast<int64_t>(e->max_runs);
  return -1;
}

// Point lookup under the CALLER's shared lock: memtable first, then
// runs newest-first; returns the value or nullptr (missing/tombstone).
// Shared by nkv_get and nkv_multi_get so lookup precedence has one
// definition.
static const std::string *lookup_locked(nkv *e, const std::string &key) {
  auto mit = e->mem.find(key);
  if (mit != e->mem.end())
    return mit->second.tomb ? nullptr : &mit->second.val;
  for (const auto &r : e->runs) {
    size_t i = r->lower_bound(key);
    if (i < r->keys.size() && r->keys[i] == key)
      return r->cells[i].tomb ? nullptr : &r->cells[i].val;
  }
  return nullptr;
}

int64_t nkv_get(nkv *e, const uint8_t *k, int64_t klen,
                const uint8_t **out) {
  // per-thread scratch: the pointer stays valid until this thread's
  // next get, independent of concurrent readers and merges
  thread_local std::string scratch;
  std::string key(reinterpret_cast<const char *>(k), klen);
  std::shared_lock<std::shared_mutex> g(e->mu);
  const std::string *val = lookup_locked(e, key);
  if (!val) return -1;
  scratch = *val;
  *out = reinterpret_cast<const uint8_t *>(scratch.data());
  return static_cast<int64_t>(scratch.size());
}

int32_t nkv_put(nkv *e, const uint8_t *k, int64_t klen, const uint8_t *v,
                int64_t vlen) {
  std::unique_lock<std::shared_mutex> g(e->mu);
  e->put_locked(std::string(reinterpret_cast<const char *>(k), klen),
                std::string(reinterpret_cast<const char *>(v), vlen), false);
  bool ok = e->maybe_flush_locked();
  e->version.fetch_add(1);
  return ok ? 0 : -2;
}

int32_t nkv_remove(nkv *e, const uint8_t *k, int64_t klen) {
  std::unique_lock<std::shared_mutex> g(e->mu);
  e->put_locked(std::string(reinterpret_cast<const char *>(k), klen),
                std::string(), true);
  bool ok = e->maybe_flush_locked();
  e->version.fetch_add(1);
  return ok ? 0 : -2;
}

int32_t nkv_remove_range(nkv *e, const uint8_t *s, int64_t slen,
                         const uint8_t *x, int64_t xlen) {
  std::unique_lock<std::shared_mutex> g(e->mu);
  std::string start(reinterpret_cast<const char *>(s), slen);
  std::string end(reinterpret_cast<const char *>(x), xlen);
  // tombstone every live key in range (per-key tombstones; ranges in
  // this system are part-sized admin ops, not hot-path writes)
  std::vector<std::string> dead;
  {
    MergeCursor cur(e->mem, e->runs, start, end);
    const std::string *k;
    const std::string *v;
    while (cur.next(k, v)) dead.push_back(*k);
  }
  for (auto &k : dead) e->put_locked(std::move(k), std::string(), true);
  bool ok = e->maybe_flush_locked();
  e->version.fetch_add(1);
  return ok ? 0 : -2;
}

int32_t nkv_remove_prefix(nkv *e, const uint8_t *p, int64_t plen) {
  std::string prefix(reinterpret_cast<const char *>(p), plen);
  std::string end = next_prefix(prefix);
  return nkv_remove_range(e, p, plen,
                          reinterpret_cast<const uint8_t *>(end.data()),
                          static_cast<int64_t>(end.size()));
}

int32_t nkv_multi_put(nkv *e, const uint8_t *buf, int64_t len, int32_t n) {
  std::unique_lock<std::shared_mutex> g(e->mu);
  int64_t off = 0;
  for (int32_t i = 0; i < n; i++) {
    if (off + 4 > len) return -1;
    uint32_t klen;
    memcpy(&klen, buf + off, 4);
    off += 4;
    if (off + klen + 4 > len) return -1;
    std::string k(reinterpret_cast<const char *>(buf + off), klen);
    off += klen;
    uint32_t vlen;
    memcpy(&vlen, buf + off, 4);
    off += 4;
    if (off + vlen > len) return -1;
    std::string v(reinterpret_cast<const char *>(buf + off), vlen);
    off += vlen;
    e->put_locked(std::move(k), std::move(v), false);
  }
  bool ok = e->maybe_flush_locked();
  e->version.fetch_add(1);
  return ok ? 0 : -2;
}

int64_t nkv_ingest_sorted(nkv *e, const uint8_t *buf, int64_t len,
                          int64_t n) {
  // Pre-sorted bulk load lands DIRECTLY as an immutable run — the
  // LSM's native SST-ingest shape (ref RocksEngine::ingest): no
  // per-key tree inserts at all. Unsorted input falls back to the
  // memtable path (still correct).
  auto r = std::make_shared<Run>();
  r->keys.reserve(static_cast<size_t>(n));
  r->cells.reserve(static_cast<size_t>(n));
  int64_t off = 0;
  bool sorted = true;
  for (int64_t i = 0; i < n; i++) {
    if (off + 4 > len) return -1;
    uint32_t klen;
    memcpy(&klen, buf + off, 4);
    off += 4;
    if (off + klen + 4 > len) return -1;
    std::string k(reinterpret_cast<const char *>(buf + off), klen);
    off += klen;
    uint32_t vlen;
    memcpy(&vlen, buf + off, 4);
    off += 4;
    if (off + vlen > len) return -1;
    std::string v(reinterpret_cast<const char *>(buf + off), vlen);
    off += vlen;
    if (!r->keys.empty() && !(r->keys.back() < k)) sorted = false;
    r->push(std::move(k), std::move(v), false);
  }
  std::unique_lock<std::shared_mutex> g(e->mu);
  if (sorted) {
    // older memtable entries must not shadow the ingested rows:
    // freeze them into a run first, then the ingest lands newest
    e->flush_mem_locked();
    if (!e->ckpt_path.empty()) {
      r->id = e->next_run_id++;
      if (!r->write_file(e->run_path(r->id))) r->id = 0;
    }
    e->runs.insert(e->runs.begin(), std::move(r));
    if (!e->ckpt_path.empty()) e->write_manifest_locked();
    e->maybe_merge();
  } else {
    for (size_t i = 0; i < r->keys.size(); ++i)
      e->put_locked(std::move(r->keys[i]), std::move(r->cells[i].val),
                    false);
    e->maybe_flush_locked();
  }
  e->version.fetch_add(1);
  return n;
}

int32_t nkv_multi_remove(nkv *e, const uint8_t *buf, int64_t len, int32_t n) {
  std::unique_lock<std::shared_mutex> g(e->mu);
  int64_t off = 0;
  for (int32_t i = 0; i < n; i++) {
    if (off + 4 > len) return -1;
    uint32_t klen;
    memcpy(&klen, buf + off, 4);
    off += 4;
    if (off + klen > len) return -1;
    e->put_locked(std::string(reinterpret_cast<const char *>(buf + off),
                              klen),
                  std::string(), true);
    off += klen;
  }
  bool ok = e->maybe_flush_locked();
  e->version.fetch_add(1);
  return ok ? 0 : -2;
}

static int64_t pack_out(const std::vector<std::pair<const std::string *,
                                                    const std::string *>> &hits,
                        uint8_t **out, int64_t *n_out) {
  int64_t total = 0;
  for (const auto &kv : hits)
    total += 8 + static_cast<int64_t>(kv.first->size() + kv.second->size());
  if (total == 0) {
    *out = nullptr;
    *n_out = 0;
    return 0;
  }
  uint8_t *buf = static_cast<uint8_t *>(malloc(static_cast<size_t>(total)));
  int64_t off = 0;
  for (const auto &kv : hits) {
    uint32_t klen = static_cast<uint32_t>(kv.first->size());
    uint32_t vlen = static_cast<uint32_t>(kv.second->size());
    memcpy(buf + off, &klen, 4);
    off += 4;
    memcpy(buf + off, kv.first->data(), klen);
    off += klen;
    memcpy(buf + off, &vlen, 4);
    off += 4;
    memcpy(buf + off, kv.second->data(), vlen);
    off += vlen;
  }
  *out = buf;
  *n_out = static_cast<int64_t>(hits.size());
  return total;
}

int64_t nkv_scan_range(nkv *e, const uint8_t *s, int64_t slen,
                       const uint8_t *x, int64_t xlen,
                       uint8_t **out, int64_t *n_out) {
  std::shared_lock<std::shared_mutex> g(e->mu);
  std::string start(reinterpret_cast<const char *>(s), slen);
  std::string end(reinterpret_cast<const char *>(x), xlen);
  std::vector<std::pair<const std::string *, const std::string *>> hits;
  MergeCursor cur(e->mem, e->runs, start, end);
  const std::string *k;
  const std::string *v;
  while (cur.next(k, v)) hits.emplace_back(k, v);
  return pack_out(hits, out, n_out);
}

int64_t nkv_scan_prefix(nkv *e, const uint8_t *p, int64_t plen,
                        uint8_t **out, int64_t *n_out) {
  std::string prefix(reinterpret_cast<const char *>(p), plen);
  std::string end = next_prefix(prefix);
  return nkv_scan_range(e, p, plen,
                        reinterpret_cast<const uint8_t *>(end.data()),
                        static_cast<int64_t>(end.size()), out, n_out);
}

int64_t nkv_scan_prefix_dedup(nkv *e, const uint8_t *p, int64_t plen,
                              int32_t group_suffix,
                              uint8_t **out, int64_t *n_out) {
  std::shared_lock<std::shared_mutex> g(e->mu);
  std::string prefix(reinterpret_cast<const char *>(p), plen);
  std::string end = next_prefix(prefix);
  std::vector<std::pair<const std::string *, const std::string *>> hits;
  // MergeCursor keys point into the memtable or an immutable run, both
  // stable while the shared lock is held — no per-row copy
  const std::string *prev_key = nullptr;
  MergeCursor cur(e->mem, e->runs, prefix, end);
  const std::string *k;
  const std::string *v;
  while (cur.next(k, v)) {
    size_t glen = k->size() >= static_cast<size_t>(group_suffix)
                      ? k->size() - static_cast<size_t>(group_suffix)
                      : k->size();
    if (prev_key && prev_key->size() >= static_cast<size_t>(group_suffix)) {
      size_t pglen = prev_key->size() - static_cast<size_t>(group_suffix);
      if (pglen == glen && memcmp(prev_key->data(), k->data(), glen) == 0)
        continue;  // same group: an older version, skip
    }
    hits.emplace_back(k, v);
    prev_key = k;
  }
  return pack_out(hits, out, n_out);
}

int64_t nkv_scan_prefix_cols(nkv *e, const uint8_t *p, int64_t plen,
                             uint8_t **keys_out, int64_t *keys_len,
                             uint8_t **vals_out, int64_t *vals_len,
                             uint32_t **klens_out, uint32_t **vlens_out) {
  // Columnar scan for the CSR snapshot builder: keys and values land in
  // two contiguous blobs plus per-item length arrays, so Python sees
  // exactly four buffers (numpy-viewable) instead of 2N bytes objects.
  std::shared_lock<std::shared_mutex> g(e->mu);
  std::string prefix(reinterpret_cast<const char *>(p), plen);
  std::string end = next_prefix(prefix);
  std::vector<std::pair<const std::string *, const std::string *>> hits;
  int64_t kbytes = 0, vbytes = 0;
  {
    MergeCursor cur(e->mem, e->runs, prefix, end);
    const std::string *k;
    const std::string *v;
    while (cur.next(k, v)) {
      hits.emplace_back(k, v);
      kbytes += static_cast<int64_t>(k->size());
      vbytes += static_cast<int64_t>(v->size());
    }
  }
  int64_t n = static_cast<int64_t>(hits.size());
  *keys_len = kbytes;
  *vals_len = vbytes;
  if (n == 0) {
    *keys_out = *vals_out = nullptr;
    *klens_out = *vlens_out = nullptr;
    return 0;
  }
  uint8_t *kb = static_cast<uint8_t *>(malloc(kbytes ? kbytes : 1));
  uint8_t *vb = static_cast<uint8_t *>(malloc(vbytes ? vbytes : 1));
  uint32_t *kl = static_cast<uint32_t *>(malloc(n * sizeof(uint32_t)));
  uint32_t *vl = static_cast<uint32_t *>(malloc(n * sizeof(uint32_t)));
  if (!kb || !vb || !kl || !vl) {
    free(kb); free(vb); free(kl); free(vl);
    return -1;
  }
  int64_t ko = 0, vo = 0;
  for (int64_t i = 0; i < n; ++i) {
    const auto &kv = hits[static_cast<size_t>(i)];
    memcpy(kb + ko, kv.first->data(), kv.first->size());
    kl[i] = static_cast<uint32_t>(kv.first->size());
    ko += static_cast<int64_t>(kv.first->size());
    memcpy(vb + vo, kv.second->data(), kv.second->size());
    vl[i] = static_cast<uint32_t>(kv.second->size());
    vo += static_cast<int64_t>(kv.second->size());
  }
  *keys_out = kb;
  *vals_out = vb;
  *klens_out = kl;
  *vlens_out = vl;
  return n;
}

// Batched point lookups: keys packed as [u32 klen][key]...; the result
// buffer packs [i32 vlen|-1][val]... in key order (one shared-lock
// acquisition and one FFI crossing for the whole batch — the
// KVStore::multiGet role, and what lets Python reader threads overlap
// inside the engine instead of serializing on per-call overhead).
int64_t nkv_multi_get(nkv *e, const uint8_t *buf, int64_t len, int32_t n,
                      uint8_t **out, int64_t *out_len) {
  std::string res;
  std::shared_lock<std::shared_mutex> g(e->mu);
  int64_t off = 0;
  for (int32_t i = 0; i < n; i++) {
    if (off + 4 > len) return -1;
    uint32_t klen;
    memcpy(&klen, buf + off, 4);
    off += 4;
    if (off + klen > len) return -1;
    std::string key(reinterpret_cast<const char *>(buf + off), klen);
    off += klen;
    const std::string *val = lookup_locked(e, key);
    int32_t vlen = val ? static_cast<int32_t>(val->size()) : -1;
    res.append(reinterpret_cast<const char *>(&vlen), 4);
    if (val) res.append(*val);
  }
  uint8_t *o = static_cast<uint8_t *>(malloc(res.size() ? res.size() : 1));
  if (!o) return -1;
  memcpy(o, res.data(), res.size());
  *out = o;
  *out_len = static_cast<int64_t>(res.size());
  return n;
}

void nkv_buf_free(uint8_t *buf) { free(buf); }

int32_t nkv_checkpoint(nkv *e, const char *path) {
  return e->checkpoint(path ? path : e->ckpt_path);
}

}  // extern "C"

/* ------------------------------------------------------------------ CSR
 * Pass-1 CSR snapshot extraction (the reference's "storage engine feeds
 * the traversal layout" role — here the whole scan→dedup→parse→
 * local-index loop runs in C++, one call per space; ref role:
 * storage/QueryBaseProcessor.inl:380-458 is the equivalent per-RPC scan).
 * Key layout: common/keys.py — part u32be | kind u8 | biased big-endian
 * fields | version u64be. Vertex keys 25 bytes, edge keys 41.
 */

namespace {

inline uint64_t be64_at(const char *p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return __builtin_bswap64(v);
}

inline uint32_t be32_at(const char *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return __builtin_bswap32(v);
}

inline int64_t unbias64(uint64_t u) {
  return static_cast<int64_t>(u ^ 0x8000000000000000ull);
}

inline int32_t unbias32(uint32_t u) {
  return static_cast<int32_t>(u ^ 0x80000000u);
}

std::string part_kind_prefix(int32_t part, uint8_t kind) {
  std::string p(5, '\0');
  uint32_t be = __builtin_bswap32(static_cast<uint32_t>(part));
  memcpy(&p[0], &be, 4);
  p[4] = static_cast<char>(kind);
  return p;
}

constexpr size_t kVertKeyLen = 25;
constexpr size_t kEdgeKeyLen = 41;
constexpr size_t kVertGroupLen = 17;  // part+kind+vid+tag
constexpr size_t kEdgeGroupLen = 33;  // part+kind+src+etype+rank+dst

struct DstRef {
  int64_t dst;
  int32_t src_part;
  int32_t idx;
};

struct ncsr_part_data {
  std::vector<int64_t> vids;            // sorted unique after build
  // edges, canonical (scan) order
  std::vector<int64_t> src_vid, rank, dst_vid;
  std::vector<int32_t> src_local, etype, dst_part, dst_local;
  std::string evals;
  std::vector<int64_t> evoffs;
  std::vector<int32_t> evlens;
  // visible vertex rows
  std::vector<int64_t> vert_vid;
  std::vector<int32_t> vert_local, vert_tag;
  std::string vvals;
  std::vector<int64_t> vvoffs;
  std::vector<int32_t> vvlens;
  // this part's edge dsts bucketed by OWNER part (resolution phase)
  std::vector<std::vector<DstRef>> dst_by_target;
};

// Parallel loop over partitions (scan and resolution phases are
// per-part independent; the LSM state is read-only under the caller's
// shared lock). Returns false if any worker threw (e.g. bad_alloc) —
// exceptions never escape a thread (that would std::terminate the
// daemon) and never cross the C ABI.
bool parallel_parts(int32_t num_parts,
                    const std::function<void(int32_t)> &fn) {
  unsigned hw = std::thread::hardware_concurrency();
  unsigned n = std::min<unsigned>(hw ? hw : 1,
                                  static_cast<unsigned>(num_parts));
  std::atomic<bool> failed{false};
  auto safe = [&](int32_t p) {
    try {
      fn(p);
    } catch (...) {
      failed.store(true);
    }
  };
  if (n <= 1) {
    for (int32_t p = 0; p < num_parts && !failed.load(); ++p) safe(p);
    return !failed.load();
  }
  std::atomic<int32_t> next{0};
  std::vector<std::thread> ts;
  for (unsigned t = 0; t < n; ++t)
    ts.emplace_back([&] {
      int32_t p;
      while (!failed.load() && (p = next.fetch_add(1)) < num_parts)
        safe(p);
    });
  for (auto &t : ts) t.join();
  return !failed.load();
}

}  // namespace

struct ncsr {
  std::vector<ncsr_part_data> parts;
};

extern "C" {

ncsr *ncsr_build(nkv *e, int32_t num_parts, int32_t want_values) {
  std::shared_lock<std::shared_mutex> g(e->mu);
  ncsr *b;
  try {
    b = new ncsr();
    b->parts.resize(static_cast<size_t>(num_parts));
  } catch (...) {
    return nullptr;
  }
  // ---- phase 1: scan + parse + visibility, parallel per part --------
  bool ok = parallel_parts(num_parts, [&](int32_t p0) {
    int32_t p = p0 + 1;
    ncsr_part_data &P = b->parts[static_cast<size_t>(p0)];
    P.dst_by_target.resize(static_cast<size_t>(num_parts));
    {  // vertices: newest (vid, tag) row wins, tombstones invisible
      std::string pre = part_kind_prefix(p, 0x01);
      std::string end = next_prefix(pre);
      MergeCursor cur(e->mem, e->runs, pre, end);
      const std::string *kp;
      const std::string *vp;
      const std::string *prev = nullptr;   // stable under shared lock
      while (cur.next(kp, vp)) {
        const std::string &k = *kp;
        if (k.size() != kVertKeyLen) continue;
        if (prev && memcmp(prev->data(), k.data(), kVertGroupLen) == 0)
          continue;
        prev = kp;
        if (vp->empty()) continue;
        int64_t vid = unbias64(be64_at(k.data() + 5));
        P.vert_vid.push_back(vid);
        P.vert_tag.push_back(unbias32(be32_at(k.data() + 13)));
        if (P.vids.empty() || P.vids.back() != vid)  // scan is vid-sorted
          P.vids.push_back(vid);
        if (want_values) {
          P.vvoffs.push_back(static_cast<int64_t>(P.vvals.size()));
          P.vvlens.push_back(static_cast<int32_t>(vp->size()));
          P.vvals += *vp;
        }
      }
    }
    {  // edges: newest (src, etype, rank, dst) row wins
      std::string pre = part_kind_prefix(p, 0x02);
      std::string end = next_prefix(pre);
      MergeCursor cur(e->mem, e->runs, pre, end);
      const std::string *kp;
      const std::string *vp;
      const std::string *prev = nullptr;   // stable under shared lock
      while (cur.next(kp, vp)) {
        const std::string &k = *kp;
        if (k.size() != kEdgeKeyLen) continue;
        if (prev && memcmp(prev->data(), k.data(), kEdgeGroupLen) == 0)
          continue;
        prev = kp;
        if (vp->empty()) continue;
        int64_t src = unbias64(be64_at(k.data() + 5));
        int64_t dst = unbias64(be64_at(k.data() + 25));
        int32_t dp = static_cast<int32_t>(
            static_cast<uint64_t>(dst) % static_cast<uint64_t>(num_parts));
        P.dst_by_target[static_cast<size_t>(dp)].push_back(
            {dst, p0, static_cast<int32_t>(P.dst_vid.size())});
        P.src_vid.push_back(src);
        P.etype.push_back(unbias32(be32_at(k.data() + 13)));
        P.rank.push_back(unbias64(be64_at(k.data() + 17)));
        P.dst_vid.push_back(dst);
        P.dst_part.push_back(dp);
        if (P.vids.empty() || P.vids.back() != src)  // scan is src-sorted
          P.vids.push_back(src);
        if (want_values) {
          P.evoffs.push_back(static_cast<int64_t>(P.evals.size()));
          P.evlens.push_back(static_cast<int32_t>(vp->size()));
          P.evals += *vp;
        }
      }
    }
    P.dst_local.resize(P.dst_vid.size());
  });
  if (!ok) {
    delete b;
    return nullptr;
  }
  // ---- phase 2: vid sets + local resolution, parallel per OWNER part.
  // Each worker q merges incoming dsts from every part into q's vid
  // set, then resolves q's own src/vert locals and every edge whose
  // dst q owns (disjoint dst_local slots — data-race free).
  ok = parallel_parts(num_parts, [&](int32_t q) {
    ncsr_part_data &Q = b->parts[static_cast<size_t>(q)];
    std::vector<DstRef> incoming;
    size_t total = 0;
    for (auto &P : b->parts)
      total += P.dst_by_target[static_cast<size_t>(q)].size();
    incoming.reserve(total);
    for (auto &P : b->parts) {
      auto &bk = P.dst_by_target[static_cast<size_t>(q)];
      incoming.insert(incoming.end(), bk.begin(), bk.end());
    }
    std::sort(incoming.begin(), incoming.end(),
              [](const DstRef &a, const DstRef &x) { return a.dst < x.dst; });
    // destinations get a local slot in their owning partition
    for (const auto &r : incoming)
      if (Q.vids.empty() || Q.vids.back() != r.dst) Q.vids.push_back(r.dst);
    std::sort(Q.vids.begin(), Q.vids.end());
    Q.vids.erase(std::unique(Q.vids.begin(), Q.vids.end()), Q.vids.end());
    // src/vert locals: scan order is src-ascending, one merge walk
    Q.src_local.resize(Q.src_vid.size());
    size_t vi = 0;
    for (size_t i = 0; i < Q.src_vid.size(); ++i) {
      while (Q.vids[vi] < Q.src_vid[i]) ++vi;
      Q.src_local[i] = static_cast<int32_t>(vi);
    }
    Q.vert_local.resize(Q.vert_vid.size());
    vi = 0;
    for (size_t i = 0; i < Q.vert_vid.size(); ++i) {
      while (Q.vids[vi] < Q.vert_vid[i]) ++vi;
      Q.vert_local[i] = static_cast<int32_t>(vi);
    }
    // dst locals for edges landing here (sorted merge)
    vi = 0;
    for (const auto &r : incoming) {
      while (Q.vids[vi] < r.dst) ++vi;
      b->parts[static_cast<size_t>(r.src_part)]
          .dst_local[static_cast<size_t>(r.idx)] = static_cast<int32_t>(vi);
    }
  });
  if (!ok) {
    delete b;
    return nullptr;
  }
  for (auto &P : b->parts) {
    P.dst_by_target.clear();
    P.dst_by_target.shrink_to_fit();
  }
  return b;
}

void ncsr_free(ncsr *b) { delete b; }

int64_t ncsr_vids(ncsr *b, int32_t part0, const int64_t **vids) {
  const auto &P = b->parts[static_cast<size_t>(part0)];
  *vids = P.vids.data();
  return static_cast<int64_t>(P.vids.size());
}

int64_t ncsr_edges(ncsr *b, int32_t part0, const int32_t **src_local,
                   const int32_t **etype, const int64_t **rank,
                   const int64_t **dst_vid, const int32_t **dst_part,
                   const int32_t **dst_local) {
  const auto &P = b->parts[static_cast<size_t>(part0)];
  *src_local = P.src_local.data();
  *etype = P.etype.data();
  *rank = P.rank.data();
  *dst_vid = P.dst_vid.data();
  *dst_part = P.dst_part.data();
  *dst_local = P.dst_local.data();
  return static_cast<int64_t>(P.etype.size());
}

int64_t ncsr_edge_vals(ncsr *b, int32_t part0, const uint8_t **blob,
                       int64_t *blob_len, const int64_t **offs,
                       const int32_t **lens) {
  const auto &P = b->parts[static_cast<size_t>(part0)];
  *blob = reinterpret_cast<const uint8_t *>(P.evals.data());
  *blob_len = static_cast<int64_t>(P.evals.size());
  *offs = P.evoffs.data();
  *lens = P.evlens.data();
  return static_cast<int64_t>(P.evlens.size());
}

int64_t ncsr_vert_rows(ncsr *b, int32_t part0, const int32_t **local,
                       const int32_t **tag) {
  const auto &P = b->parts[static_cast<size_t>(part0)];
  *local = P.vert_local.data();
  *tag = P.vert_tag.data();
  return static_cast<int64_t>(P.vert_tag.size());
}

int64_t ncsr_vert_vals(ncsr *b, int32_t part0, const uint8_t **blob,
                       int64_t *blob_len, const int64_t **offs,
                       const int32_t **lens) {
  const auto &P = b->parts[static_cast<size_t>(part0)];
  *blob = reinterpret_cast<const uint8_t *>(P.vvals.data());
  *blob_len = static_cast<int64_t>(P.vvals.size());
  *offs = P.vvoffs.data();
  *lens = P.vvlens.data();
  return static_cast<int64_t>(P.vvlens.size());
}

}  // extern "C"
