// Parallel stable counting sort for small-range uint32 keys.
//
// The device kernel layouts (engine_tpu/traverse.py build_kernel /
// build_aligned) need a stable sort of ~10^8 edges by destination
// slot, where the key range is only ~10^6 (n_slots+1). numpy's stable
// argsort is a comparison sort (~100s at SNB scale); key-range
// counting sort is O(E) and embarrassingly parallel: each thread
// histograms its slice, a (thread, key) prefix pass assigns exact
// placement offsets, and each thread scatters its slice in order —
// stability follows from threads owning contiguous, ordered slices.
// Role parity: the reference leans on RocksDB's native sorted storage
// for this ordering; here the sort feeds the TPU edge layout instead.
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Stable sort permutation of keys (values in [0, n_keys)): fills
// order_out[n] with indices such that keys[order_out] is
// non-decreasing and equal keys keep input order. Returns 0, or -1 on
// bad arguments (a key >= n_keys).
int nsort_counting_u32(const uint32_t* keys, int64_t n, int64_t n_keys,
                       int64_t* order_out, int threads) {
  if (n <= 0) return 0;
  if (threads < 1) threads = 1;
  if (threads > 64) threads = 64;
  int64_t chunk = (n + threads - 1) / threads;
  std::vector<std::vector<int64_t>> hist(
      threads, std::vector<int64_t>(n_keys, 0));
  std::vector<int> bad(threads, 0);
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < threads; ++t) {
      ts.emplace_back([&, t]() {
        int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
        auto& h = hist[t];
        for (int64_t i = lo; i < hi; ++i) {
          uint32_t k = keys[i];
          if (k >= n_keys) { bad[t] = 1; return; }
          ++h[k];
        }
      });
    }
    for (auto& th : ts) th.join();
  }
  for (int t = 0; t < threads; ++t)
    if (bad[t]) return -1;
  // exclusive running offset in (key-major, thread-minor) order
  int64_t run = 0;
  for (int64_t k = 0; k < n_keys; ++k) {
    for (int t = 0; t < threads; ++t) {
      int64_t c = hist[t][k];
      hist[t][k] = run;
      run += c;
    }
  }
  {
    std::vector<std::thread> ts;
    for (int t = 0; t < threads; ++t) {
      ts.emplace_back([&, t]() {
        int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
        auto& off = hist[t];
        for (int64_t i = lo; i < hi; ++i)
          order_out[off[keys[i]]++] = i;
      });
    }
    for (auto& th : ts) th.join();
  }
  return 0;
}

}  // extern "C"
