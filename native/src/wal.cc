// Segmented write-ahead log.
//
// Role parity with the reference's FileBasedWal (ref
// kvstore/wal/FileBasedWal.{h,cpp}): append-only segment files that roll
// at a size threshold, an in-memory index for fast seek/term lookup
// (standing in for the reference's InMemoryLogBuffer hot path), rollback
// for raft term conflicts, TTL-based cleanup of sealed segments, and
// torn-tail truncation on open so a crash mid-append never poisons
// recovery.
//
// On-disk layout, per segment file "<first-log-id, 19 digits>.wal":
//   header : magic "NWAL" | u32 version | i64 firstLogId
//   record : i64 logId | i64 term | i64 cluster | u32 len |
//            bytes data[len] | u32 crc32(data) | u32 len (trailer)
// The trailing len mirrors the reference's format trick enabling
// backward walks and cheap torn-tail detection.

#include "nebula_native.h"

#include <algorithm>
#include <cerrno>
#include <cinttypes>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <string>
#include <vector>

#include <dirent.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr char kMagic[4] = {'N', 'W', 'A', 'L'};
constexpr uint32_t kVersion = 1;
constexpr int64_t kHeaderSize = 4 + 4 + 8;
constexpr int64_t kRecordOverhead = 8 + 8 + 8 + 4 + 4 + 4;

uint32_t crc32_table[256];
bool crc32_init_done = false;

void crc32_init() {
  if (crc32_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_table[i] = c;
  }
  crc32_init_done = true;
}

uint32_t crc32(const uint8_t *buf, size_t len) {
  crc32_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc32_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct RecordMeta {
  int64_t log_id;
  int64_t term;
  int64_t cluster;
  int64_t offset;   // file offset of the record start
  int32_t seg;      // index into segments_
  uint32_t len;     // payload length
};

struct Segment {
  int64_t first_id;
  int64_t last_id;     // -1 when empty
  std::string path;
  int64_t size;        // valid byte length (post torn-tail truncation)
  time_t mtime;
};

std::string seg_path(const std::string &dir, int64_t first_id) {
  char buf[64];
  snprintf(buf, sizeof(buf), "%019" PRId64 ".wal", first_id);
  return dir + "/" + buf;
}

bool read_exact(FILE *f, void *dst, size_t n) {
  return fread(dst, 1, n, f) == n;
}

}  // namespace

struct nwal {
  std::string dir;
  int64_t ttl_secs;
  int64_t max_file_size;
  bool sync_every;

  std::vector<Segment> segments;     // sorted by first_id; last is active
  std::vector<RecordMeta> index;     // sorted by log_id, contiguous
  FILE *active = nullptr;            // append handle for last segment

  int64_t first_log_id() const { return index.empty() ? 0 : index.front().log_id; }
  int64_t last_log_id() const { return index.empty() ? 0 : index.back().log_id; }
  int64_t last_log_term() const { return index.empty() ? 0 : index.back().term; }

  ~nwal() {
    if (active) fclose(active);
  }

  bool open_dir() {
    struct stat st;
    if (stat(dir.c_str(), &st) != 0) {
      if (mkdir(dir.c_str(), 0755) != 0 && errno != EEXIST) return false;
    }
    DIR *d = opendir(dir.c_str());
    if (!d) return false;
    std::vector<std::string> files;
    while (dirent *e = readdir(d)) {
      std::string name = e->d_name;
      if (name.size() > 4 && name.substr(name.size() - 4) == ".wal")
        files.push_back(name);
    }
    closedir(d);
    std::sort(files.begin(), files.end());
    for (const auto &name : files) {
      if (!load_segment(dir + "/" + name)) return false;
    }
    // Reopen the last segment for append.
    if (!segments.empty()) {
      Segment &s = segments.back();
      active = fopen(s.path.c_str(), "r+b");
      if (!active) return false;
      // Truncate any torn tail discovered during load.
      if (ftruncate(fileno(active), s.size) != 0) return false;
      fseeko(active, s.size, SEEK_SET);
    }
    return true;
  }

  bool load_segment(const std::string &path) {
    FILE *f = fopen(path.c_str(), "rb");
    if (!f) return false;
    char magic[4];
    uint32_t ver = 0;
    int64_t first = 0;
    if (!read_exact(f, magic, 4) || memcmp(magic, kMagic, 4) != 0 ||
        !read_exact(f, &ver, 4) || ver != kVersion ||
        !read_exact(f, &first, 8)) {
      fclose(f);
      // Unreadable header: treat as an empty/corrupt stray; drop it.
      remove(path.c_str());
      return true;
    }
    Segment seg;
    seg.first_id = first;
    seg.last_id = -1;
    seg.path = path;
    seg.size = kHeaderSize;
    struct stat st;
    stat(path.c_str(), &st);
    seg.mtime = st.st_mtime;
    int32_t seg_idx = static_cast<int32_t>(segments.size());

    // Scan records until EOF or a torn/corrupt tail.
    for (;;) {
      int64_t off = ftello(f);
      int64_t log_id, term, cluster;
      uint32_t len;
      if (!read_exact(f, &log_id, 8) || !read_exact(f, &term, 8) ||
          !read_exact(f, &cluster, 8) || !read_exact(f, &len, 4))
        break;
      if (len > (1u << 30)) break;  // absurd: corrupt
      std::vector<uint8_t> data(len);
      uint32_t crc = 0, len2 = 0;
      if (len && !read_exact(f, data.data(), len)) break;
      if (!read_exact(f, &crc, 4) || !read_exact(f, &len2, 4)) break;
      if (len2 != len || crc != crc32(data.data(), len)) break;
      // Record is sound; must chain onto the index.
      if (!index.empty() && log_id != index.back().log_id + 1) break;
      index.push_back({log_id, term, cluster, off, seg_idx, len});
      seg.last_id = log_id;
      seg.size = off + kRecordOverhead + static_cast<int64_t>(len);
    }
    fclose(f);
    if (seg.last_id < 0 && seg_idx + 1 < static_cast<int32_t>(segments.size())) {
      // fully-empty non-final segment — drop the file
      remove(path.c_str());
      return true;
    }
    segments.push_back(seg);
    return true;
  }

  bool roll_segment(int64_t first_id) {
    if (active) {
      fflush(active);
      fsync(fileno(active));
      fclose(active);
      active = nullptr;
    }
    Segment seg;
    seg.first_id = first_id;
    seg.last_id = -1;
    seg.path = seg_path(dir, first_id);
    seg.size = kHeaderSize;
    seg.mtime = time(nullptr);
    active = fopen(seg.path.c_str(), "w+b");
    if (!active) return false;
    fwrite(kMagic, 1, 4, active);
    fwrite(&kVersion, 4, 1, active);
    fwrite(&first_id, 8, 1, active);
    fflush(active);
    segments.push_back(seg);
    return true;
  }

  int32_t append(int64_t log_id, int64_t term, int64_t cluster,
                 const uint8_t *data, int64_t len) {
    if (!index.empty() && log_id != last_log_id() + 1) return -2;
    if (segments.empty() || segments.back().size >= max_file_size) {
      if (!roll_segment(log_id)) return -3;
    }
    Segment &seg = segments.back();
    int64_t off = seg.size;
    fseeko(active, off, SEEK_SET);
    uint32_t len32 = static_cast<uint32_t>(len);
    uint32_t crc = crc32(data, static_cast<size_t>(len));
    fwrite(&log_id, 8, 1, active);
    fwrite(&term, 8, 1, active);
    fwrite(&cluster, 8, 1, active);
    fwrite(&len32, 4, 1, active);
    if (len) fwrite(data, 1, static_cast<size_t>(len), active);
    fwrite(&crc, 4, 1, active);
    fwrite(&len32, 4, 1, active);
    if (fflush(active) != 0) return -4;
    if (sync_every) fsync(fileno(active));
    index.push_back({log_id, term, cluster, off,
                     static_cast<int32_t>(segments.size() - 1), len32});
    seg.last_id = log_id;
    seg.size = off + kRecordOverhead + len;
    seg.mtime = time(nullptr);
    return 0;
  }

  int32_t rollback(int64_t keep_to) {
    if (index.empty() || keep_to >= last_log_id()) return 0;
    // Binary search for the first record with log_id > keep_to.
    auto it = std::upper_bound(
        index.begin(), index.end(), keep_to,
        [](int64_t v, const RecordMeta &r) { return v < r.log_id; });
    if (it == index.begin()) {
      return reset();
    }
    size_t keep_n = static_cast<size_t>(it - index.begin());
    const RecordMeta &last_kept = index[keep_n - 1];
    // Drop segments entirely past the kept record.
    if (active) { fclose(active); active = nullptr; }
    while (static_cast<int32_t>(segments.size()) - 1 > last_kept.seg) {
      remove(segments.back().path.c_str());
      segments.pop_back();
    }
    Segment &seg = segments.back();
    seg.last_id = last_kept.log_id;
    seg.size = last_kept.offset + kRecordOverhead +
               static_cast<int64_t>(last_kept.len);
    active = fopen(seg.path.c_str(), "r+b");
    if (!active) return -5;
    if (ftruncate(fileno(active), seg.size) != 0) return -6;
    fseeko(active, seg.size, SEEK_SET);
    fsync(fileno(active));
    index.resize(keep_n);
    return 0;
  }

  int32_t reset() {
    if (active) { fclose(active); active = nullptr; }
    for (auto &s : segments) remove(s.path.c_str());
    segments.clear();
    index.clear();
    return 0;
  }

  // Drop the front segment: erase its records from the index, fix up
  // the surviving records' segment slots, and unlink the file.
  void drop_front_segment() {
    const Segment &s = segments.front();
    auto it = std::upper_bound(
        index.begin(), index.end(), s.last_id,
        [](int64_t v, const RecordMeta &r) { return v < r.log_id; });
    index.erase(index.begin(), it);
    for (auto &r : index) r.seg -= 1;
    remove(s.path.c_str());
    segments.erase(segments.begin());
  }

  // TTL sweep, optionally bounded: only segments whose every record
  // id is < bound may go (bound < 0 = unbounded). Callers pass the
  // applied anchor so age alone can never truncate unapplied entries.
  int32_t clean_ttl(int64_t bound = -1) {
    time_t now = time(nullptr);
    int32_t removed = 0;
    // Never touch the active (last) segment.
    while (segments.size() > 1 &&
           now - segments.front().mtime >= ttl_secs &&
           (bound < 0 || segments.front().last_id < bound)) {
      drop_front_segment();
      removed++;
    }
    return removed;
  }

  // Snapshot-anchored compaction: drop sealed prefix segments whose
  // every record id is below `id`. Whole segments only (the record
  // layout is append-only), never the active segment, so the WAL
  // keeps at least every record >= id — the caller passes
  // applied_anchor - lag, which bounds both disk and restart replay.
  int32_t clean_before(int64_t id) {
    int32_t removed = 0;
    while (segments.size() > 1 && segments.front().last_id < id) {
      drop_front_segment();
      removed++;
    }
    return removed;
  }

  const RecordMeta *find(int64_t log_id) const {
    if (index.empty() || log_id < index.front().log_id ||
        log_id > index.back().log_id)
      return nullptr;
    return &index[static_cast<size_t>(log_id - index.front().log_id)];
  }
};

struct nwal_iter {
  nwal *w;
  int64_t cur;
  int64_t to;
  FILE *f = nullptr;
  int32_t f_seg = -1;
  std::vector<uint8_t> buf;
  int64_t term = 0, cluster = 0;
  bool valid = false;

  ~nwal_iter() {
    if (f) fclose(f);
  }

  void load() {
    valid = false;
    if (cur > to) return;
    const RecordMeta *r = w->find(cur);
    if (!r) return;
    if (f_seg != r->seg) {
      if (f) fclose(f);
      f = fopen(w->segments[r->seg].path.c_str(), "rb");
      f_seg = r->seg;
      if (!f) return;
    }
    fseeko(f, r->offset + 8 + 8 + 8 + 4, SEEK_SET);
    buf.resize(r->len);
    if (r->len && !read_exact(f, buf.data(), r->len)) return;
    term = r->term;
    cluster = r->cluster;
    valid = true;
  }
};

extern "C" {

nwal *nwal_open(const char *dir, int64_t ttl_secs, int64_t max_file_size,
                int32_t sync_every_append) {
  nwal *w = new nwal();
  w->dir = dir;
  w->ttl_secs = ttl_secs >= 0 ? ttl_secs : 86400;
  w->max_file_size = max_file_size > kHeaderSize + kRecordOverhead
                         ? max_file_size
                         : 16 * 1024 * 1024;
  w->sync_every = sync_every_append != 0;
  if (!w->open_dir()) {
    delete w;
    return nullptr;
  }
  return w;
}

void nwal_close(nwal *w) { delete w; }

int64_t nwal_first_log_id(nwal *w) { return w->first_log_id(); }
int64_t nwal_last_log_id(nwal *w) { return w->last_log_id(); }
int64_t nwal_last_log_term(nwal *w) { return w->last_log_term(); }

int64_t nwal_log_term(nwal *w, int64_t log_id) {
  const RecordMeta *r = w->find(log_id);
  return r ? r->term : -1;
}

int32_t nwal_append(nwal *w, int64_t log_id, int64_t term, int64_t cluster,
                    const uint8_t *data, int64_t len) {
  return w->append(log_id, term, cluster, data, len);
}

int32_t nwal_rollback(nwal *w, int64_t keep_to) { return w->rollback(keep_to); }
int32_t nwal_reset(nwal *w) { return w->reset(); }
int32_t nwal_clean_ttl(nwal *w) { return w->clean_ttl(); }
int32_t nwal_clean_ttl_before(nwal *w, int64_t id) {
  return w->clean_ttl(id);
}
int32_t nwal_clean_before(nwal *w, int64_t id) { return w->clean_before(id); }

int32_t nwal_sync(nwal *w) {
  if (w->active) {
    fflush(w->active);
    fsync(fileno(w->active));
  }
  return 0;
}

nwal_iter *nwal_iter_new(nwal *w, int64_t from, int64_t to) {
  nwal_iter *it = new nwal_iter();
  it->w = w;
  it->cur = from;
  it->to = to < 0 ? w->last_log_id() : to;
  it->load();
  return it;
}

int32_t nwal_iter_valid(nwal_iter *it) { return it->valid ? 1 : 0; }
int64_t nwal_iter_log_id(nwal_iter *it) { return it->cur; }
int64_t nwal_iter_term(nwal_iter *it) { return it->term; }
int64_t nwal_iter_cluster(nwal_iter *it) { return it->cluster; }

int64_t nwal_iter_data(nwal_iter *it, const uint8_t **out) {
  *out = it->buf.data();
  return static_cast<int64_t>(it->buf.size());
}

void nwal_iter_next(nwal_iter *it) {
  it->cur += 1;
  it->load();
}

void nwal_iter_free(nwal_iter *it) { delete it; }

}  // extern "C"
