"""nebula-tpu: a TPU-native distributed property-graph database framework.

Brand-new implementation with the capabilities of NebulaGraph v1.x
(reference: shunpeizhang/nebula): a partitioned, Raft-replicated
property-graph store with an nGQL-style query language, a three-service
topology (stateless query engine / meta catalog / partitioned storage),
and a pluggable storage-engine seam.

The query hot path — multi-hop neighbor expansion (GO) and path search
(FIND SHORTEST PATH) — is offloaded to TPU via JAX/XLA: partition edge
lists are laid out as CSR arrays in device memory, BFS frontiers are
advanced with dense-mask scatter/gather under `lax.fori_loop`, and
cross-partition frontier exchange maps to `lax.all_to_all` over the ICI
mesh (see `nebula_tpu.engine_tpu`).

Layer map (mirrors reference layers, re-designed TPU-first; see SURVEY.md §1):
  common/     Status codes, key codec, stats, config   (ref: src/common/)
  codec/      row/schema codec                         (ref: src/dataman/)
  parser/     nGQL lexer + recursive-descent parser    (ref: src/parser/)
  filter/     expression trees, eval + device compile  (ref: src/common/filter/)
  kvstore/    KV engines, WAL, Raft consensus          (ref: src/kvstore/)
  storage/    storage processors + client              (ref: src/storage/)
  meta/       catalog, schemas, balancer, heartbeats   (ref: src/meta/)
  graph/      session, execution engine, executors     (ref: src/graph/)
  engine_tpu/ CSR shards + device traversal kernels    (new: the TPU engine)
  rpc/        wire transport for multi-process deploy  (ref: fbthrift seam)
"""

__version__ = "0.10.0"

# Opt-in runtime lock-order witness (docs/manual/15-static-analysis.md):
# with NEBULA_TPU_LOCK_WITNESS set, importing the package installs the
# witness BEFORE any submodule creates a lock, so module-level locks
# (native encode lock, rpc stats lock, mesh build lock, tracer rings)
# are wrapped too. The import itself performs the install.
import os as _os

if _os.environ.get("NEBULA_TPU_LOCK_WITNESS"):
    from .common import lockwitness as _lockwitness  # noqa: F401
