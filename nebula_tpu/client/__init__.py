"""Client library: connect to a graphd over the rpc/ transport.

Role parity with the reference's `client/cpp/GraphClient` (ref
client/cpp/GraphClient.{h,cpp}): connect → authenticate → execute nGQL →
ExecutionResponse with columns/rows/latency; plus a context-manager
convenience. The console REPL and tools drive this same class.
"""
from __future__ import annotations

from typing import Optional

from ..common.status import ErrorCode, NebulaError
from ..graph.context import ExecutionResponse
from ..rpc import proxy


class GraphClient:
    def __init__(self, addr: str):
        # dedicated socket per client (the reference client's model):
        # N concurrent clients must mean N concurrent queries, not
        # contention on the process-wide 4-socket RPC pool
        self._rpc = proxy(addr, "graph", dedicated=True)
        self.addr = addr
        self._session_id: Optional[int] = None

    # ------------------------------------------------------------------
    def connect(self, user: str = "root", password: str = "") -> "GraphClient":
        r = self._rpc.authenticate(user, password)
        if not r.ok():
            raise NebulaError(r.status)
        self._session_id = r.value()
        return self

    def execute(self, stmt: str) -> ExecutionResponse:
        if self._session_id is None:
            resp = ExecutionResponse()
            resp.code = ErrorCode.E_SESSION_INVALID
            resp.error_msg = "not connected (call connect() first)"
            return resp
        return self._rpc.execute(self._session_id, stmt)

    def must(self, stmt: str) -> ExecutionResponse:
        """Execute and raise on a server-side error (parity with the
        in-proc Connection.must test/bench helper)."""
        resp = self.execute(stmt)
        if not resp.ok():
            from ..common.status import Status
            raise NebulaError(Status.error(
                resp.code, f"{resp.error_msg}  query: {stmt}"))
        return resp

    def disconnect(self) -> None:
        if self._session_id is not None:
            try:
                self._rpc.signout(self._session_id)
            finally:
                self._session_id = None
                self._rpc.close()   # dedicated socket: release the fd

    # ------------------------------------------------------------------
    def __enter__(self) -> "GraphClient":
        return self

    def __exit__(self, *exc) -> None:
        self.disconnect()


__all__ = ["GraphClient"]
