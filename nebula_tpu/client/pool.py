"""Pooled client sessions over one or more graphd endpoints.

Role parity with the reference's richer client surface (the Java
client's connection-pool + session model, ref client/java/; the C++
GraphClient stays the thin single-connection form in __init__.py):

- `ConnectionPool([addr, ...])` — round-robin endpoint selection with
  per-endpoint health state; a failed endpoint is quarantined and
  retried after `retry_after` seconds.
- `pool.session(user, password)` — authenticated Session handle.
  Sessions auto-reconnect: on a transport error (graphd restart,
  network blip) the next execute() re-authenticates — possibly on a
  different healthy endpoint — and retries the statement once.
- Sessions are context managers and sign out on close.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from ..common.status import ErrorCode, NebulaError
from ..graph.context import ExecutionResponse
from ..rpc import proxy


class NoHealthyGraphd(RuntimeError):
    def __init__(self, detail: str):
        super().__init__(f"no healthy graphd endpoint: {detail}")


class _Endpoint:
    __slots__ = ("addr", "down_until")

    def __init__(self, addr: str):
        self.addr = addr
        self.down_until = 0.0


class ConnectionPool:
    """Round-robin over graphd endpoints with failure quarantine."""

    def __init__(self, addrs: List[str], timeout: Optional[float] = 30.0,
                 retry_after: float = 3.0):
        if not addrs:
            raise ValueError("ConnectionPool needs at least one address")
        self._eps = [_Endpoint(a) for a in addrs]
        self._timeout = timeout
        self._retry_after = retry_after
        self._next = 0
        self._lock = threading.Lock()

    # -- endpoint selection -------------------------------------------
    def _pick(self) -> _Endpoint:
        now = time.monotonic()
        with self._lock:
            n = len(self._eps)
            for _ in range(n):
                ep = self._eps[self._next % n]
                self._next += 1
                if ep.down_until <= now:
                    return ep
            # all quarantined: least-recently-failed gets the probe
            return min(self._eps, key=lambda e: e.down_until)

    def _mark_down(self, ep: _Endpoint) -> None:
        with self._lock:
            ep.down_until = time.monotonic() + self._retry_after

    # -- public -------------------------------------------------------
    def session(self, user: str = "root", password: str = "") -> "Session":
        """Authenticate against a healthy endpoint -> Session."""
        s = Session(self, user, password)
        s._ensure_connected()
        return s

    def _connect_once(self, user: str, password: str):
        """-> (rpc client, endpoint, session_id); raises on total
        failure (every endpoint tried once)."""
        last = None
        for _ in range(len(self._eps)):
            ep = self._pick()
            try:
                # each pooled session owns its socket (see GraphClient)
                rpc = proxy(ep.addr, "graph", timeout=self._timeout,
                            dedicated=True)
                r = rpc.authenticate(user, password)
            except Exception as e:           # transport-level failure
                self._mark_down(ep)
                last = e
                continue
            if not r.ok():
                raise NebulaError(r.status)  # bad credentials: no retry
            return rpc, ep, r.value()
        raise NoHealthyGraphd(repr(last))


class Session:
    """One authenticated session; survives graphd restarts by
    re-authenticating on the next call (the session id itself is NOT
    preserved across reconnects — server-side session state such as
    USE <space> must be re-established, matching the reference client's
    reconnect contract)."""

    def __init__(self, pool: ConnectionPool, user: str, password: str):
        self._pool = pool
        self._user = user
        self._password = password
        self._rpc = None
        self._ep = None
        self._session_id: Optional[int] = None
        self._space: Optional[str] = None

    # -- connection management ----------------------------------------
    def _ensure_connected(self) -> None:
        if self._session_id is not None:
            return
        self._rpc, self._ep, self._session_id = \
            self._pool._connect_once(self._user, self._password)
        if self._space:
            r = self._rpc.execute(self._session_id, f"USE {self._space}")
            if r.code != ErrorCode.SUCCEEDED:
                self._space = None

    def _drop_connection(self) -> None:
        if self._ep is not None:
            self._pool._mark_down(self._ep)
        if self._rpc is not None:
            try:
                self._rpc.close()   # dead socket: still release the fd
            except Exception:
                pass
        self._rpc = None
        self._ep = None
        self._session_id = None

    # -- public -------------------------------------------------------
    # statements safe to retry after a mid-flight transport error: the
    # server may have applied the statement before the connection died,
    # so only reads (and `$var =` result assignments, which only write
    # session-local state) are retried automatically. A mutation that
    # dies in flight surfaces the transport error to the caller, who
    # alone knows whether re-applying is safe (at-least-once).
    _READ_ONLY = ("GO", "FETCH", "FIND", "YIELD", "USE", "SHOW",
                  "DESC", "DESCRIBE", "MATCH", "LOOKUP")

    @classmethod
    def _retry_safe(cls, stmt: str) -> bool:
        """One execute() can carry `;`-compound statements (the
        parser's SequentialSentences): EVERY segment must be read-only
        for the whole to be retried, else `USE x; INSERT …` would be
        re-applied after a mid-flight error — exactly the
        at-least-once hazard this gate exists to prevent. `$var =`
        assignments are classified by their right-hand sentence."""
        first = True
        for seg in cls._split_statements(stmt):
            s = seg.strip()
            if not s:
                continue
            # the PROFILE prefix (first statement only, matching the
            # parser) changes observability, not semantics: classify
            # by the profiled statement (shared rule: tracing.py)
            if first:
                from ..common.tracing import split_profile_prefix
                s = split_profile_prefix(s)[1]
                first = False
            if s.startswith("$"):
                eq = s.find("=")
                if eq < 0:
                    return False   # not an assignment: fail closed
                s = s[eq + 1:].strip()
            head = s.split(None, 1)[0].upper() if s else ""
            if head not in cls._READ_ONLY:
                return False
        return True

    @staticmethod
    def _split_statements(stmt: str):
        """Split on top-level `;` only — quote- and escape-aware,
        matching the lexer's string rules, so a `;` inside a string
        literal never splits."""
        out, buf, quote, esc = [], [], None, False
        for ch in stmt:
            if esc:
                buf.append(ch)
                esc = False
                continue
            if quote is not None:
                if ch == "\\":
                    esc = True
                elif ch == quote:
                    quote = None
                buf.append(ch)
                continue
            if ch in ("'", '"'):
                quote = ch
                buf.append(ch)
                continue
            if ch == ";":
                out.append("".join(buf))
                buf = []
                continue
            buf.append(ch)
        out.append("".join(buf))
        return out

    def execute(self, stmt: str) -> ExecutionResponse:
        """Run one statement; on a transport error, reconnect (possibly
        to another endpoint) and retry once — automatically only for
        read-only statements (see _retry_safe)."""
        for attempt in (0, 1):
            try:
                self._ensure_connected()
            except Exception:
                # nothing was sent yet — reconnecting and retrying is
                # always safe, mutation or not
                self._drop_connection()
                if attempt:
                    raise
                continue
            try:
                resp = self._rpc.execute(self._session_id, stmt)
            except Exception:
                self._drop_connection()
                if attempt or not self._retry_safe(stmt):
                    raise
                continue
            if resp.code == ErrorCode.E_SESSION_INVALID and not attempt:
                # graphd restarted but the transport survived: new session
                self._session_id = None
                continue
            # track USE so a reconnect can restore the working space
            if resp.code == ErrorCode.SUCCEEDED:
                s = stmt.strip()
                if s.upper().startswith("USE "):
                    self._space = s[4:].strip().rstrip(";").strip()
            return resp
        raise AssertionError("unreachable")

    def must(self, stmt: str) -> ExecutionResponse:
        resp = self.execute(stmt)
        if resp.code != ErrorCode.SUCCEEDED:
            raise RuntimeError(
                f"query failed [{resp.code.name}]: {resp.error_msg}\n"
                f"  query: {stmt}")
        return resp

    def ping(self) -> bool:
        try:
            return self.execute("SHOW SPACES").code == ErrorCode.SUCCEEDED
        except Exception:
            return False

    def release(self) -> None:
        if self._session_id is not None and self._rpc is not None:
            try:
                self._rpc.signout(self._session_id)
            except Exception:
                pass
        if self._rpc is not None:
            self._rpc.close()   # dedicated socket: release the fd
        self._rpc = None
        self._session_id = None

    def __enter__(self) -> "Session":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


__all__ = ["ConnectionPool", "Session", "NoHealthyGraphd"]
