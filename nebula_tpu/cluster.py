"""Single-process cluster wiring.

Role parity with the reference's test/deployment bootstrap
(`graph/test/TestEnv.cpp:29-71` boots metad + storaged + graphd in one
process; `storage/StorageServer.cpp:88-144` wires MetaClient →
SchemaManager → store → handlers). This is both the unit-test fixture
and the single-node deployment entry point; the daemons/ package runs
the same components behind the rpc/ transport for multi-process.
"""
from __future__ import annotations

from typing import Dict, Optional

from .graph.engine import ExecutionEngine, GraphService
from .graph.session import SessionManager
from .kvstore.store import GraphStore
from .meta.schema_manager import SchemaManager
from .meta.service import MetaService
from .storage.client import StorageClient
from .storage.processors import StorageService


class InProcCluster:
    """metad + storaged + graphd in one process."""

    def __init__(self, tpu_engine=None, balancer_factory=None,
                 engine_factory=None):
        """engine_factory: space_id -> KVEngine (default MemEngine);
        pass a NativeEngine factory for performance-grade storage."""
        self.meta = MetaService()
        self.sm = SchemaManager(self.meta)
        self.store = GraphStore(engine_factory=engine_factory)
        self.storage = StorageService(self.store, self.sm)
        self.client = StorageClient(self.sm, local_service=self.storage)
        # meta-driven topology: new space -> local parts appear (the
        # MetaChangedListener push, ref meta/client/MetaClient.h:87-96)
        self.meta.add_listener(self._on_meta_change)
        self.balancer = balancer_factory(self) if balancer_factory else None
        self.engine = ExecutionEngine(self.meta, self.sm, self.client,
                                      tpu_engine=tpu_engine,
                                      balancer=self.balancer)
        self.service = GraphService(self.engine)
        if tpu_engine is not None:
            tpu_engine.attach(self)

    def _on_meta_change(self, event: str, **kw) -> None:
        if event == "space_added":
            desc = kw["desc"]
            for part in range(1, desc.partition_num + 1):
                self.store.add_part(desc.space_id, part)
        elif event == "space_removed":
            self.store.remove_space(kw["space_id"])

    # ------------------------------------------------------------------
    # convenience API
    # ------------------------------------------------------------------
    def connect(self, user: str = "root", password: str = "") -> "Connection":
        sid = self.service.authenticate(user, password).value()
        return Connection(self.service, sid)


class Connection:
    def __init__(self, service: GraphService, session_id: int):
        self._service = service
        self.session_id = session_id

    def execute(self, text: str):
        return self._service.execute(self.session_id, text)

    def must(self, text: str):
        """Execute and raise on error (test helper)."""
        resp = self._service.execute(self.session_id, text)
        if not resp.ok():
            raise RuntimeError(f"query failed [{resp.code.name}]: "
                               f"{resp.error_msg}\n  query: {text}")
        return resp

    def close(self) -> None:
        self._service.signout(self.session_id)
