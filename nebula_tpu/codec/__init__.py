from .schema import PropType, SchemaField, Schema  # noqa: F401
from .row import RowWriter, RowReader, RowUpdater, RowSetWriter, RowSetReader  # noqa: F401
