"""Row codec: fixed-slot binary rows with O(1) random field access.

Role parity with the reference's `dataman/RowWriter` / `RowReader` /
`RowUpdater` / `RowSetWriter` / `RowSetReader` (ref: dataman/RowWriter
.h:23-80, dataman/RowReader.cpp:221-300). The reference uses varint
fields with block-offset skip lists (O(field) seek within a 16-field
block); we instead use a *fixed-slot* layout so any field is O(1):

  [u8 ver_len][schema_ver LE (ver_len bytes)]
  [null bitmap: ceil(n/8) bytes]
  [slot region: one fixed-width slot per schema field]
  [var region: string payloads]

Slots: BOOL = 1 byte; INT/VID/TIMESTAMP = 8 bytes LE; DOUBLE = 8 bytes
LE IEEE754; STRING = u32 offset + u32 length into the var region. Null
fields still occupy their slot (zeroed) — trading a few bytes for
branch-free decode, which also matches how the TPU engine's columnar
prop arrays are filled (every slot materialized).

Rows embed only the schema *version*; readers resolve the full schema
through a SchemaProvider, exactly like the reference's
`getTagPropReader/getEdgePropReader` factories.
"""
from __future__ import annotations

import struct
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from .schema import PropType, Schema, default_for

_U32LE = struct.Struct("<I")
_I64LE = struct.Struct("<q")
_F64LE = struct.Struct("<d")


def _slot_size(t: PropType) -> int:
    return 1 if t == PropType.BOOL else 8


def _slot_offsets(schema: Schema) -> Tuple[List[int], int]:
    """Per-field slot offsets (relative to slot region start) and total size."""
    offs, off = [], 0
    for f in schema.fields:
        offs.append(off)
        off += _slot_size(f.type)
    return offs, off


class RowWriter:
    """Encode one row against a schema. Unset fields take their default."""

    def __init__(self, schema: Schema):
        self._schema = schema
        self._values: List[Any] = [None] * schema.num_fields()
        self._set: List[bool] = [False] * schema.num_fields()

    def set(self, name: str, value: Any) -> "RowWriter":
        i = self._schema.field_index(name)
        if i < 0:
            raise KeyError(f"no field {name!r} in schema")
        self._values[i] = _coerce(self._schema.fields[i].type, value)
        self._set[i] = True
        return self

    def set_index(self, i: int, value: Any) -> "RowWriter":
        self._values[i] = _coerce(self._schema.fields[i].type, value)
        self._set[i] = True
        return self

    def encode(self) -> bytes:
        s = self._schema
        n = s.num_fields()
        ver = s.version
        ver_bytes = b""
        while ver > 0:
            ver_bytes += bytes([ver & 0xFF])
            ver >>= 8
        nullmap = bytearray((n + 7) // 8)
        offs, slot_total = _slot_offsets(s)
        slots = bytearray(slot_total)
        var = bytearray()
        for i, f in enumerate(s.fields):
            v = self._values[i] if self._set[i] else (
                f.default if f.default is not None else
                (None if f.nullable else default_for(f.type)))
            if v is None:
                nullmap[i >> 3] |= 1 << (i & 7)
                continue
            o = offs[i]
            t = f.type
            if t == PropType.BOOL:
                slots[o] = 1 if v else 0
            elif t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
                slots[o:o + 8] = _I64LE.pack(int(v))
            elif t == PropType.DOUBLE:
                slots[o:o + 8] = _F64LE.pack(float(v))
            elif t == PropType.STRING:
                b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
                slots[o:o + 4] = _U32LE.pack(len(var))
                slots[o + 4:o + 8] = _U32LE.pack(len(b))
                var += b
            else:
                raise ValueError(f"unsupported type {t}")
        return bytes([len(ver_bytes)]) + ver_bytes + bytes(nullmap) + bytes(slots) + bytes(var)


def peek_schema_version(data: bytes) -> int:
    ver_len = data[0]
    ver = 0
    for k in range(ver_len):
        ver |= data[1 + k] << (8 * k)
    return ver


class RowReader:
    """Decode fields of an encoded row. O(1) per field."""

    def __init__(self, schema: Schema, data: bytes):
        self._schema = schema
        self._data = data
        ver_len = data[0]
        n = schema.num_fields()
        self._null_off = 1 + ver_len
        self._slot_off = self._null_off + (n + 7) // 8
        self._offs, slot_total = _slot_offsets(schema)
        self._var_off = self._slot_off + slot_total

    @staticmethod
    def schema_version(data: bytes) -> int:
        return peek_schema_version(data)

    @property
    def schema(self) -> Schema:
        return self._schema

    def is_null(self, i: int) -> bool:
        return bool(self._data[self._null_off + (i >> 3)] & (1 << (i & 7)))

    def get_index(self, i: int) -> Any:
        if self.is_null(i):
            return None
        f = self._schema.fields[i]
        o = self._slot_off + self._offs[i]
        d = self._data
        t = f.type
        if t == PropType.BOOL:
            return d[o] != 0
        if t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
            return _I64LE.unpack_from(d, o)[0]
        if t == PropType.DOUBLE:
            return _F64LE.unpack_from(d, o)[0]
        if t == PropType.STRING:
            so = _U32LE.unpack_from(d, o)[0]
            sl = _U32LE.unpack_from(d, o + 4)[0]
            b = d[self._var_off + so:self._var_off + so + sl]
            return b.decode("utf-8")
        raise ValueError(f"unsupported type {t}")

    def get(self, name: str) -> Any:
        i = self._schema.field_index(name)
        if i < 0:
            raise KeyError(f"no field {name!r}")
        return self.get_index(i)

    def to_dict(self) -> Dict[str, Any]:
        return {f.name: self.get_index(i) for i, f in enumerate(self._schema.fields)}


class RowUpdater:
    """Partial-row update: overlay new values on an existing encoded row
    (ref: dataman/RowUpdater — used by the UPDATE read-modify-write CAS)."""

    def __init__(self, schema: Schema, data: Optional[bytes] = None):
        self._schema = schema
        self._writer = RowWriter(schema)
        if data is not None:
            reader = RowReader(schema, data)
            for i in range(schema.num_fields()):
                v = reader.get_index(i)
                if v is not None:
                    self._writer.set_index(i, v)

    def set(self, name: str, value: Any) -> "RowUpdater":
        self._writer.set(name, value)
        return self

    def get(self, name: str) -> Any:
        i = self._schema.field_index(name)
        if i < 0:
            raise KeyError(name)
        if self._writer._set[i]:
            return self._writer._values[i]
        f = self._schema.fields[i]
        return f.default if f.default is not None else default_for(f.type)

    def encode(self) -> bytes:
        return self._writer.encode()


class RowSetWriter:
    """Length-prefixed row concatenation — the RPC payload format
    (ref: dataman/RowSetWriter, payload of EdgeData.data/TagData.data)."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def add_row(self, row: bytes) -> None:
        self._buf += _U32LE.pack(len(row)) + row

    def data(self) -> bytes:
        return bytes(self._buf)


class RowSetReader:
    def __init__(self, data: bytes):
        self._data = data

    def __iter__(self) -> Iterator[bytes]:
        d, off = self._data, 0
        while off < len(d):
            ln = _U32LE.unpack_from(d, off)[0]
            off += 4
            yield d[off:off + ln]
            off += ln


def _coerce(t: PropType, v: Any) -> Any:
    if v is None:
        return None
    if t == PropType.BOOL:
        if isinstance(v, bool):
            return v
        if isinstance(v, (int, float)):
            return bool(v)
        raise TypeError(f"cannot coerce {v!r} to BOOL")
    if t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise TypeError(f"cannot coerce {v!r} to INT")
        return int(v)
    if t == PropType.DOUBLE:
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise TypeError(f"cannot coerce {v!r} to DOUBLE")
        return float(v)
    if t == PropType.STRING:
        if isinstance(v, (bytes, bytearray)):
            return bytes(v)
        if isinstance(v, str):
            return v
        raise TypeError(f"cannot coerce {v!r} to STRING")
    raise ValueError(f"unsupported type {t}")
