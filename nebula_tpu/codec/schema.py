"""Property schemas.

Role parity with the reference's thrift `Schema`/`ColumnDef` types and
`dataman/ResultSchemaProvider` / `meta/NebulaSchemaProvider`: a schema
is an ordered list of typed, optionally-defaulted fields; tag/edge
schemas are multi-versioned (monotonic SchemaVer) and may carry a TTL
column (ref: meta/processors/schemaMan/, common.thrift:14-92).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class PropType(enum.IntEnum):
    UNKNOWN = 0
    BOOL = 1
    INT = 2        # int64
    VID = 3        # int64 vertex id
    DOUBLE = 5
    STRING = 6
    TIMESTAMP = 7  # int64 seconds

    @staticmethod
    def from_name(name: str) -> "PropType":
        name = name.strip().upper()
        aliases = {
            "BOOL": PropType.BOOL,
            "INT": PropType.INT,
            "INT64": PropType.INT,
            "VID": PropType.VID,
            "DOUBLE": PropType.DOUBLE,
            "FLOAT": PropType.DOUBLE,
            "STRING": PropType.STRING,
            "TIMESTAMP": PropType.TIMESTAMP,
        }
        if name not in aliases:
            raise ValueError(f"unknown property type {name!r}")
        return aliases[name]

    def is_fixed64(self) -> bool:
        return self in (PropType.INT, PropType.VID, PropType.DOUBLE, PropType.TIMESTAMP)


def default_for(t: PropType) -> Any:
    if t == PropType.BOOL:
        return False
    if t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
        return 0
    if t == PropType.DOUBLE:
        return 0.0
    if t == PropType.STRING:
        return ""
    return None


@dataclass
class SchemaField:
    name: str
    type: PropType
    nullable: bool = False
    default: Optional[Any] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "type": int(self.type),
                "nullable": self.nullable, "default": self.default}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SchemaField":
        return SchemaField(d["name"], PropType(d["type"]), d.get("nullable", False),
                           d.get("default"))


@dataclass
class Schema:
    """An ordered field list with a version, plus optional TTL config."""

    fields: List[SchemaField] = field(default_factory=list)
    version: int = 0
    ttl_col: Optional[str] = None
    ttl_duration: int = 0  # seconds; 0 = disabled

    def __post_init__(self) -> None:
        self._index: Dict[str, int] = {f.name: i for i, f in enumerate(self.fields)}

    # -- lookups -------------------------------------------------------
    def num_fields(self) -> int:
        return len(self.fields)

    def field_index(self, name: str) -> int:
        return self._index.get(name, -1)

    def field_type(self, name: str) -> Optional[PropType]:
        i = self.field_index(name)
        return self.fields[i].type if i >= 0 else None

    def field(self, name: str) -> Optional["SchemaField"]:
        i = self.field_index(name)
        return self.fields[i] if i >= 0 else None

    def default_value(self, name: str):
        """Schema default of a field (explicit default, else the type
        default) — what a vertex missing the tag yields for the prop
        (ref: RowReader::getDefaultProp, dataman/RowReader.h:91, used
        by GoExecutor::VertexHolder::get, GoExecutor.cpp:1009-1018).
        None when the field doesn't exist."""
        f = self.field(name)
        if f is None:
            return None
        return f.default if f.default is not None else default_for(f.type)

    def has_field(self, name: str) -> bool:
        return name in self._index

    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    # -- evolution -----------------------------------------------------
    def with_added(self, new_fields: List[SchemaField]) -> "Schema":
        for f in new_fields:
            if self.has_field(f.name):
                raise ValueError(f"field {f.name!r} already exists")
        return Schema(self.fields + new_fields, self.version + 1,
                      self.ttl_col, self.ttl_duration)

    def with_dropped(self, names: List[str]) -> "Schema":
        drop = set(names)
        for n in drop:
            if not self.has_field(n):
                raise ValueError(f"field {n!r} not found")
        return Schema([f for f in self.fields if f.name not in drop],
                      self.version + 1, self.ttl_col, self.ttl_duration)

    def with_changed(self, changed: List[SchemaField]) -> "Schema":
        out = list(self.fields)
        for c in changed:
            i = self.field_index(c.name)
            if i < 0:
                raise ValueError(f"field {c.name!r} not found")
            out[i] = c
        return Schema(out, self.version + 1, self.ttl_col, self.ttl_duration)

    # -- serialization (for meta catalog + RPC-shipped schemas) --------
    def to_dict(self) -> Dict[str, Any]:
        return {"version": self.version,
                "fields": [f.to_dict() for f in self.fields],
                "ttl_col": self.ttl_col, "ttl_duration": self.ttl_duration}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Schema":
        return Schema([SchemaField.from_dict(f) for f in d["fields"]],
                      d.get("version", 0), d.get("ttl_col"), d.get("ttl_duration", 0))
