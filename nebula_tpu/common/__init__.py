from .status import Status, StatusOr, ErrorCode, NebulaError  # noqa: F401
