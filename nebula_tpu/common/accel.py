"""Accelerator reachability probing.

A dead or flaky accelerator relay makes in-process JAX backend init
hang forever AND poison the init lock, so reachability must be decided
in a SUBPROCESS with a deadline. This is the single shared
implementation of that pattern — bench.py's `_ensure_backend` and
`__graft_entry__.dryrun_multichip` both consume it (they briefly had
separate copies which diverged on the platform check).

No reference analogue: the reference assumes local CUDA/CPU devices; a
tunneled TPU needs a liveness check before anything touches the
backend.  This module must stay importable without initializing JAX
(stdlib imports only).
"""
from __future__ import annotations

import subprocess
import sys
from typing import Tuple


def backend_initialized() -> bool:
    """True iff a JAX backend is already live in THIS process.

    When it is, probing in a subprocess is pointless and actively
    harmful: on an exclusive-access accelerator the child blocks on the
    parent's device lock until the probe deadline, then falsely reports
    the accelerator as unreachable. Callers should inspect
    `jax.devices()` directly instead — init already happened, so that
    call cannot hang.

    The check reads the private `jax._src.xla_bridge._backends` (there
    is no public "is the backend up" API). If that attribute ever moves,
    this returns False and callers take the subprocess probe: worst case
    a bounded `timeout`-long stall and a false "unreachable" — chosen
    over the in-process alternative, whose failure mode is an unbounded
    hang on a dead relay.
    """
    mod = sys.modules.get("jax")
    if mod is None:
        return False
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:
        return False


def probe(timeout: float = 180.0) -> Tuple[str, int]:
    """-> (platform, device_count) of the default JAX backend as seen
    by a fresh subprocess, or ("", 0) if the probe hangs or fails.

    `platform == "cpu"` means JAX fell back to host devices — callers
    wanting a *real* accelerator must treat that the same as
    unreachable (virtual host devices can satisfy any count via
    --xla_force_host_platform_device_count).
    """
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(d[0].platform, len(d))"],
            capture_output=True, timeout=timeout, text=True)
        if out.returncode == 0 and out.stdout.strip():
            plat, n = out.stdout.strip().splitlines()[-1].split()
            return plat, int(n)
    except (subprocess.TimeoutExpired, OSError, ValueError):
        pass
    return "", 0
