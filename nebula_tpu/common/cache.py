"""Snapshot-versioned multi-level cache rungs across the serve path.

The serve path pays parse + filter planning + dispatcher wait + device
kernel + materialize + encode for EVERY statement, even when the text
is identical and the snapshot version has not moved. The rungs here
make repetition cheap while keeping correctness STRUCTURAL, not
probabilistic: every rung's key embeds the version token that governs
its inputs, so a stale entry is simply unreachable — there is no TTL
and no heuristic invalidation on the read path (the reference leans on
the same discipline: MetaClient's cached topology pull is keyed by the
pulled version, RocksDB's block cache by immutable block identity).

Rungs (docs/manual/11-caching.md):

  plan        graphd statement text -> parsed AST (graph/engine.py);
              PROFILE-prefix-aware via split_profile_prefix so
              `PROFILE GO ...` and `GO ...` share one entry
  filter_plan per-snapshot compiled WHERE plans, keyed by
              (write_version, filter bytes, edge types, aliases)
              (engine_tpu/engine.py:_plan_filter)
  result      encoded device results keyed by (space, snapshot
              write_version token, catalog version, statement shape)
              + in-window request dedupe in the dispatcher + negative
              caching of structural decline decisions
  storaged    bound-stats responses and (part, version) columnar scan
              blobs server-side (storage/processors.py)

`cache_mode` (a MUTABLE flag on both graph_flags and storage_flags)
ladders the rungs for bisection:

  off   no caching anywhere — the pre-cache serve path, bit-identical
  plan  plan + filter_plan rungs only (pure wins: no observable
        semantics change beyond latency) — the DEFAULT
  full  everything: result cache, in-window dedupe, negative caches,
        storaged stats/scan caches
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Hashable, Optional

from . import ledger as _ledger
from .stats import stats as global_stats

MODE_OFF = "off"
MODE_PLAN = "plan"
MODE_FULL = "full"
_MODES = (MODE_OFF, MODE_PLAN, MODE_FULL)


def mode_of(flags) -> str:
    """Resolve the registry's cache_mode to one of off|plan|full
    (unknown values fall back to the safe default, plan)."""
    v = str(flags.get("cache_mode", MODE_PLAN)).strip().lower()
    return v if v in _MODES else MODE_PLAN


def plan_stage_enabled(flags) -> bool:
    return mode_of(flags) != MODE_OFF


def result_stage_enabled(flags) -> bool:
    return mode_of(flags) == MODE_FULL


class CacheRung:
    """One bounded LRU rung with the hit/miss/evict/invalidate counter
    quartet every rung must expose (/tpu_stats, StatsManager counter
    kinds -> Prometheus /metrics). Values must be treated as immutable
    by callers — hand out copies of anything a caller might mutate.

    `stats_prefix` mirrors the counters into the global StatsManager
    as `<prefix>.hit` / `.miss` / `.evict` / `.invalidate` counters.
    `weigher` + `byte_cap` add a byte budget on top of the entry cap
    (the storaged scan rung holds whole columnar part scans);
    `byte_cap` may be a CALLABLE, resolved per store, so a MUTABLE
    flag like scan_cache_mb keeps working after construction."""

    _MISS = object()

    def __init__(self, name: str, capacity: int = 256,
                 stats_prefix: Optional[str] = None,
                 weigher: Optional[Callable[[Any], int]] = None,
                 byte_cap=None):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.name = name
        self._cap = capacity
        self._weigher = weigher
        self._byte_cap = byte_cap
        self._bytes = 0
        self._map: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self._prefix = stats_prefix
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self.stores = 0

    # ------------------------------------------------------------------
    def _count(self, event: str, n: int = 1) -> None:
        if self._prefix is not None and n:
            global_stats.add_value(f"{self._prefix}.{event}", n,
                                   kind="counter")

    def _cap_bytes(self) -> Optional[int]:
        c = self._byte_cap
        return c() if callable(c) else c

    def get(self, key: Hashable, default: Any = None) -> Any:
        with self._lock:
            v = self._map.get(key, self._MISS)
            if v is self._MISS:
                self.misses += 1
                miss = True
            else:
                self._map.move_to_end(key)
                self.hits += 1
                miss = False
        self._count("miss" if miss else "hit")
        # per-query cost ledger: rung hits/misses on this query's path
        # (one ContextVar read when no ledger is live)
        led = _ledger.current()
        if led is not None:
            if miss:
                led.cache_misses += 1
            else:
                led.cache_hits += 1
        return default if miss else v

    def put(self, key: Hashable, value: Any) -> None:
        w = self._weigher(value) if self._weigher is not None else 0
        cap_b = self._cap_bytes()
        if cap_b is not None and w > cap_b:
            return    # one oversized entry must not wipe the rung
        evicted = 0
        with self._lock:
            old = self._map.pop(key, self._MISS)
            if old is not self._MISS and self._weigher is not None:
                self._bytes -= self._weigher(old)
            self._map[key] = value
            self._bytes += w
            self.stores += 1
            while len(self._map) > self._cap or (
                    cap_b is not None and self._bytes > cap_b
                    and len(self._map) > 1):
                _, ev = self._map.popitem(last=False)
                if self._weigher is not None:
                    self._bytes -= self._weigher(ev)
                self.evictions += 1
                evicted += 1
        self._count("evict", evicted)

    def invalidate_where(self, pred: Callable[[Hashable], bool]) -> int:
        """Drop every entry whose KEY matches; returns the count.
        Poison/purge hygiene — version-keyed entries are already
        unreachable once their token moves, this frees the memory and
        makes the purge observable."""
        with self._lock:
            dead = [k for k in self._map if pred(k)]
            for k in dead:
                v = self._map.pop(k)
                if self._weigher is not None:
                    self._bytes -= self._weigher(v)
            self.invalidations += len(dead)
        self._count("invalidate", len(dead))
        return len(dead)

    def clear(self) -> int:
        with self._lock:
            n = len(self._map)
            self._map.clear()
            self._bytes = 0
            self.invalidations += n
        self._count("invalidate", n)
        return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = {"entries": len(self._map), "hits": self.hits,
                   "misses": self.misses, "evictions": self.evictions,
                   "invalidations": self.invalidations,
                   "stores": self.stores}
            if self._byte_cap is not None:
                out["bytes"] = self._bytes
            return out
