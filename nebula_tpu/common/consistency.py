"""Consistency observatory: online replica content digests, shadow-read
verification and device-snapshot audit (docs/manual/10-observability.md,
"Consistency observatory").

Every correctness guarantee this stack makes used to be proven only in
offline harnesses: TPU-vs-CPU byte identity in bench/soak loops,
durability in the ``--crash`` ledgers, replica convergence in raft
fixture tests. This module makes correctness a first-class, always-on
observable next to heat and profiling:

PART CONTENT DIGESTS — every storage ``Part`` maintains an
order-independent rolling digest over its live data keys (sum mod
2**128 of per-KV hashes, so inserts fold in and removes fold out
incrementally), anchored to ``(term, applied_log_id)`` at every commit
batch. Two replicas at the same applied index MUST agree; leaders
compare each follower's digest (carried on the existing append/
heartbeat round as an additive wire element) against their own anchor
history and flag `digest_ok` per replica. A mismatch records a
``replica_divergence`` flight event naming the part, replica and
anchor. THE hashing implementation lives here — the offline checkers
(tools/integrity_check.py, tools/kv_verify.py), the online digests,
shadow-read comparison and the snapshot audit all share ``item_hash``/
``kv_hash``/``fold_add`` (one authority, no divergable twins).

SHADOW-READ VERIFICATION — a MUTABLE ``shadow_read_rate`` flag samples
a fraction of production GO/FETCH serves at the graph layer; a
background worker re-executes each sampled statement through the CPU
pipe (the ``shadow_serve`` ContextVar makes the device engine decline)
and compares the encoded row multisets byte-for-byte via the shared
digest. The queue is bounded and budgeted (``shadow_read_budget``
re-executions per second, drop-oldest beyond ``SHADOW_QUEUE_CAP``) so
verification can never become load; a write landing between the
original serve and the shadow run moves the space's freshness token
and the comparison is SKIPPED (counted), never a false positive.
Mismatches count per verb/space, annotate the sampled trace, and fire
a ``shadow_mismatch`` flight trigger.

DEVICE-SNAPSHOT AUDIT — CSR builds/delta applies record the store
digest they were built from (engine_tpu/engine.py); auditors
registered here cross-check live snapshot lineage against the current
engine digest on a background cadence (``consistency_audit_interval_s``)
and record ``snapshot_audit_mismatch`` — catching the delta-overrun /
silent-store-mutation class where content moved without the version
token.

Disarm contract (the heat_enabled / profile_hz=0 idiom): with
``consistency_enabled=false`` every charge site is one flag read, no
``consistency.*``/``shadow.*`` stats family is ever created, and
``gauges()`` is empty — /metrics stays byte-identical to a
consistency-free build. Re-arming rebuilds part digests lazily from an
engine scan on first probe.
"""
from __future__ import annotations

import contextvars
import hashlib
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .flags import MUTABLE, graph_flags, meta_flags, storage_flags
from .stats import stats as global_stats

# ---------------------------------------------------------------------------
# flags (every daemon serves /consistency knobs via its OWN registry —
# the flight/heat/profiler multi-registry idiom)
# ---------------------------------------------------------------------------
_REGISTRIES = (graph_flags, storage_flags, meta_flags)
for _reg in _REGISTRIES:
    _reg.declare(
        "consistency_enabled", True, MUTABLE,
        "consistency observatory master switch: per-part content "
        "digests (anchored to (term, applied_log_id)), leader-side "
        "replica digest checks, snapshot audit and the "
        "nebula_consistency_* metric families; off = every charge "
        "site is one flag read and /metrics is byte-identical to a "
        "consistency-free build")
    _reg.declare(
        "shadow_read_rate", 0.0, MUTABLE,
        "fraction of production GO/FETCH serves re-executed through "
        "the CPU pipe off the serve path and compared byte-for-byte "
        "(0 disarms — one flag read per query); mismatches fire the "
        "shadow_mismatch flight trigger")
    _reg.declare(
        "shadow_read_budget", 20, MUTABLE,
        "max shadow-read re-executions per second; samples beyond the "
        "budget (or the bounded queue) are dropped, counted — shadow "
        "verification can never become load")
    _reg.declare(
        "consistency_audit_interval_s", 0.0, MUTABLE,
        "device-snapshot audit cadence: cross-check live CSR snapshot "
        "lineage digests against the current engine digest every this "
        "many seconds (0 = on-demand only via /consistency?audit=1)")


def _flag(name: str, default):
    """First non-default value across the registries (graph first) —
    a daemon process sets only its own registry over HTTP, in-process
    harnesses use graph_flags."""
    for reg in _REGISTRIES:
        v = reg.get(name, default)
        if v is not None and v != default:
            return v
    return default


def enabled() -> bool:
    return bool(_flag("consistency_enabled", True))


def shadow_rate() -> float:
    try:
        return float(_flag("shadow_read_rate", 0.0) or 0.0)
    except (TypeError, ValueError):
        return 0.0


# ---------------------------------------------------------------------------
# the hashing authority (shared by part digests, shadow compare, the
# snapshot audit and the offline tools — ONE implementation)
# ---------------------------------------------------------------------------
DIGEST_BITS = 128
_MASK = (1 << DIGEST_BITS) - 1


def item_hash(data: bytes) -> int:
    """128-bit hash of one opaque item (a row image, a blob)."""
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=16).digest(), "big")


def kv_hash(key: bytes, value: bytes) -> int:
    """128-bit hash of one KV pair. The key length prefixes the
    concatenation so (k, v) pairs can never alias across the
    boundary."""
    h = hashlib.blake2b(digest_size=16)
    h.update(len(key).to_bytes(4, "big"))
    h.update(key)
    h.update(value)
    return int.from_bytes(h.digest(), "big")


def fold_add(digest: int, h: int) -> int:
    """Fold one item INTO an order-independent multiset digest
    (sum mod 2**128 — duplicate-safe, unlike XOR)."""
    return (digest + h) & _MASK


def fold_sub(digest: int, h: int) -> int:
    """Fold one item OUT of the digest (the remove/overwrite path)."""
    return (digest - h) & _MASK


def digest_items(items) -> int:
    """Digest of an iterable of (key, value) pairs — the full-scan /
    offline-tool form of the same authority the incremental path
    folds."""
    d = 0
    for k, v in items:
        d = fold_add(d, kv_hash(k, v))
    return d


def digest_rows(rows) -> Tuple[int, int]:
    """(digest, count) over an iterable of result rows — the shadow
    comparison form: each row's repr bytes hashed, folded
    order-independently (sorting-free, duplicate-safe)."""
    d = 0
    n = 0
    for r in rows:
        d = fold_add(d, item_hash(repr(r).encode()))
        n += 1
    return d, n


def hex_digest(d: Optional[int]) -> Optional[str]:
    return None if d is None else format(d, "032x")


# ---------------------------------------------------------------------------
# per-part incremental digest (owned by kvstore/part.py)
# ---------------------------------------------------------------------------
# the kind byte that marks system keys (commit marker, balance key) —
# excluded from content digests: they encode per-replica bookkeeping
# that is covered by the ANCHOR, not the content
_KIND_SYSTEM = 0x00

HISTORY_ANCHORS = 256


def is_digestable_key(key: bytes) -> bool:
    return len(key) >= 5 and key[4] != _KIND_SYSTEM


class PartDigest:
    """One part's rolling content digest + its (term, applied_log_id)
    anchor history. All mutation happens under the owning Part's lock
    (the apply serialization point); reads take the small local lock
    so monitors never race an apply."""

    __slots__ = ("_lock", "value", "anchor_term", "anchor_id", "valid",
                 "mid_install", "history")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0
        self.anchor_term = 0
        self.anchor_id = 0
        self.valid = False
        self.mid_install = False
        # deque of (log_id, term, digest) — the leader's comparison
        # base for follower-reported anchors (batch boundaries align
        # in the steady state; unknown anchors are skipped, counted)
        self.history: "deque[Tuple[int, int, int]]" = deque(
            maxlen=HISTORY_ANCHORS)

    # -- mutation (caller holds the Part lock) --------------------------
    def add(self, key: bytes, value: bytes) -> None:
        self.value = fold_add(self.value, kv_hash(key, value))

    def remove(self, key: bytes, value: bytes) -> None:
        self.value = fold_sub(self.value, kv_hash(key, value))

    def anchor_to(self, term: int, log_id: int) -> None:
        with self._lock:
            self.anchor_term = int(term)
            self.anchor_id = int(log_id)
            self.mid_install = False
            self.history.append((self.anchor_id, self.anchor_term,
                                 self.value))

    def begin_install(self) -> None:
        """Snapshot install START: history is being replaced wholesale
        (the part prefix was just cleared) — the digest restarts from
        empty and stays unreportable until the final chunk anchors."""
        with self._lock:
            self.value = 0
            self.valid = True
            self.mid_install = True
            self.history.clear()

    def invalidate(self) -> None:
        with self._lock:
            self.valid = False
            self.mid_install = False
            self.history.clear()

    def rebuild(self, engine, part_prefix: bytes) -> None:
        """Full recompute from an engine scan (boot, re-arm after a
        disarm window, post-ingest). Caller holds the Part lock."""
        d = 0
        for k, v in engine.prefix(part_prefix):
            if is_digestable_key(k):
                d = fold_add(d, kv_hash(k, v))
        with self._lock:
            self.value = d
            self.valid = True
            self.mid_install = False
            self.history.clear()

    # -- reads ----------------------------------------------------------
    def anchor(self) -> Optional[Tuple[int, int, int]]:
        """(term, log_id, digest) — None while invalid/mid-install."""
        with self._lock:
            if not self.valid or self.mid_install:
                return None
            return (self.anchor_term, self.anchor_id, self.value)

    def at(self, log_id: int) -> Optional[int]:
        """The digest this part held when its applied index was
        exactly `log_id` — None when the anchor is unknown (rolled off
        the bounded history, or batch boundaries didn't align)."""
        with self._lock:
            if not self.valid:
                return None
            for lid, _term, dig in reversed(self.history):
                if lid == log_id:
                    return dig
                if lid < log_id:
                    break
            return None


# ---------------------------------------------------------------------------
# shadow-read verification (graph layer)
# ---------------------------------------------------------------------------
# set while the shadow worker re-executes a sampled statement: the
# device engine declines (can_serve/can_serve_path) so the run takes
# the CPU pipe, admission is bypassed (off-path internal work must not
# spend a tenant's tokens) and re-sampling is suppressed
_shadow_ctx: "contextvars.ContextVar[bool]" = contextvars.ContextVar(
    "nebula_shadow_serve", default=False)


def is_shadow() -> bool:
    return _shadow_ctx.get()


SHADOW_QUEUE_CAP = 128
# at most this many row reprs kept per sample as mismatch evidence
SHADOW_EVIDENCE_ROWS = 8

# per-space write sequence (graph layer): part of the shadow freshness
# token so a write landing between the sampled serve and the shadow
# re-execution SKIPS the comparison instead of false-positiving. Bumped
# by the graph engine on every successful mutation statement while
# shadow sampling is armed (disarmed: one flag read per write).
_write_seq: Dict[str, int] = {}
_write_seq_lock = threading.Lock()


def note_space_write(space: str) -> None:
    if shadow_rate() <= 0.0:
        return
    with _write_seq_lock:
        _write_seq[space] = _write_seq.get(space, 0) + 1


def space_write_seq(space: str) -> int:
    return _write_seq.get(space, 0)


class ShadowVerifier:
    """Process-global sampled re-execution verifier. ``install`` wires
    the runner (execute one statement through the CPU pipe, return its
    rows) and the per-space freshness probe; ``maybe_sample`` is the
    serve-path seam — one flag read disarmed, one RNG draw + bounded
    deque append armed. The worker thread is lazy and never blocks a
    serve."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q: "deque[dict]" = deque()
        self._runner: Optional[Callable[[str, str], list]] = None
        self._version_fn: Optional[Callable[[str], Any]] = None
        self._worker: Optional[threading.Thread] = None
        self._in_flight = False     # worker holds a popped sample
        self._budget_sec = 0
        self._budget_used = 0
        import random as _random
        self._rng = _random.Random()
        self.counts: Dict[str, int] = {
            "sampled": 0, "verified": 0, "mismatches": 0,
            "skipped_stale": 0, "dropped": 0, "errors": 0}
        self.mismatch_by_verb: Dict[str, int] = {}
        self.mismatch_by_space: Dict[str, int] = {}
        self.last_mismatch: Optional[dict] = None

    # -- wiring ---------------------------------------------------------
    def install(self, runner: Callable[[str, str], list],
                version_fn: Optional[Callable[[str], Any]] = None
                ) -> None:
        """Idempotent by replacement (the flight-collector idiom): the
        newest graph service in the process owns the runner."""
        with self._lock:
            self._runner = runner
            self._version_fn = version_fn

    # -- serve-path seam -------------------------------------------------
    def armed(self) -> bool:
        return enabled() and shadow_rate() > 0.0

    def current_version(self, space: str):
        """The installed freshness probe, for callers that must pin
        the token BEFORE computing the rows they later sample (the
        graph engine captures it at execute start — a write landing
        between row computation and sampling must SKIP the
        comparison, never false-positive)."""
        return self._version(space)

    def maybe_sample(self, space: str, verb: str, text: str,
                     rows, trace_id: Optional[str] = None,
                     version=None) -> bool:
        """Sample one successful serve. Never blocks: digesting the
        rows + a deque append under a leaf lock. `version` is the
        freshness token captured BEFORE the rows were computed
        (current_version); left None it is probed now — safe only
        when no write can have landed since the rows were read.
        Returns True when the sample was enqueued (tests)."""
        r = shadow_rate()
        if r <= 0.0 or not enabled() or _shadow_ctx.get():
            return False
        if self._rng.random() >= r:
            return False
        digest, n = digest_rows(rows)
        evidence = [repr(x) for x in rows[:SHADOW_EVIDENCE_ROWS]]
        item = {
            "space": space or "", "verb": verb, "text": text,
            "digest": digest, "rows": n, "evidence": evidence,
            "trace_id": trace_id,
            "version": version if version is not None
            else self._version(space),
        }
        with self._cv:
            self.counts["sampled"] += 1
            self._q.append(item)
            if len(self._q) > SHADOW_QUEUE_CAP:
                self._q.popleft()
                self.counts["dropped"] += 1
            self._ensure_worker_locked()
            self._cv.notify()
        global_stats.add_value("shadow.sampled", kind="counter")
        return True

    def _version(self, space: str):
        fn = self._version_fn
        if fn is None:
            return None
        try:
            return fn(space or "")
        except Exception:
            return None

    # -- worker ----------------------------------------------------------
    def _ensure_worker_locked(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        # nlint: disable=NL002 -- process-lifetime verification worker;
        # it serves samples from every session and owes none a trace
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="shadow-verify")
        self._worker.start()

    def _budget_ok(self) -> bool:
        budget = int(_flag("shadow_read_budget", 20) or 0)
        if budget <= 0:
            return False
        sec = int(self._clock())
        if sec != self._budget_sec:
            self._budget_sec = sec
            self._budget_used = 0
        if self._budget_used >= budget:
            return False
        self._budget_used += 1
        return True

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q:
                    self._cv.wait(timeout=5.0)
                item = self._q.popleft()
                runner = self._runner
                # visible to drain(): the popped sample's verdict has
                # not landed yet — gates must not read stats early
                self._in_flight = True
            try:
                if runner is None:
                    with self._lock:
                        self.counts["dropped"] += 1
                    continue
                if not self._budget_ok():
                    with self._lock:
                        self.counts["dropped"] += 1
                    global_stats.add_value("shadow.dropped",
                                           kind="counter")
                    continue
                try:
                    self._verify(runner, item)
                except Exception:
                    with self._lock:
                        self.counts["errors"] += 1
                    if enabled():
                        global_stats.add_value("shadow.errors",
                                               kind="counter")
            finally:
                with self._lock:
                    self._in_flight = False

    def _verify(self, runner, item: dict) -> None:
        # a write between the original serve and now moves the token:
        # the comparison would be apples-to-oranges — skip, counted
        if item["version"] != self._version(item["space"]):
            with self._lock:
                self.counts["skipped_stale"] += 1
            global_stats.add_value("shadow.skipped_stale",
                                   kind="counter")
            return
        tok = _shadow_ctx.set(True)
        try:
            rows = runner(item["space"], item["text"])
        except Exception:
            with self._lock:
                self.counts["errors"] += 1
            global_stats.add_value("shadow.errors", kind="counter")
            return
        finally:
            _shadow_ctx.reset(tok)
        # re-check: a write may have landed DURING the shadow run
        if item["version"] != self._version(item["space"]):
            with self._lock:
                self.counts["skipped_stale"] += 1
            global_stats.add_value("shadow.skipped_stale",
                                   kind="counter")
            return
        digest, n = digest_rows(rows)
        if digest == item["digest"] and n == item["rows"]:
            with self._lock:
                self.counts["verified"] += 1
            global_stats.add_value("shadow.verified", kind="counter")
            return
        detail = {
            "space": item["space"], "verb": item["verb"],
            "text": item["text"][:200],
            "served_rows": item["rows"], "shadow_rows": n,
            "served_digest": hex_digest(item["digest"]),
            "shadow_digest": hex_digest(digest),
            "served_sample": item["evidence"],
            "shadow_sample": [repr(x) for x in
                              rows[:SHADOW_EVIDENCE_ROWS]],
        }
        with self._lock:
            self.counts["mismatches"] += 1
            self.mismatch_by_verb[item["verb"]] = \
                self.mismatch_by_verb.get(item["verb"], 0) + 1
            sp = item["space"] or "_"
            self.mismatch_by_space[sp] = \
                self.mismatch_by_space.get(sp, 0) + 1
            self.last_mismatch = detail
        global_stats.add_value("shadow.mismatch." + item["verb"],
                               kind="counter")
        self._tag_trace(item.get("trace_id"))
        from .flight import recorder
        recorder.record("shadow_mismatch", trace_id=item.get("trace_id"),
                        **{k: v for k, v in detail.items()
                           if k not in ("served_sample",
                                        "shadow_sample")})

    @staticmethod
    def _tag_trace(trace_id: Optional[str]) -> None:
        """Best-effort: annotate the (already finished) sampled trace
        in the ring so the /traces view shows the query was later
        proven divergent."""
        if not trace_id:
            return
        try:
            from . import tracing
            t = tracing.tracer.ring.get(trace_id)
            if t is not None and t.get("spans"):
                t["spans"][0].setdefault("tags", {})[
                    "shadow_mismatch"] = True
        except Exception:
            pass

    # -- observation ------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "rate": shadow_rate(),
                "queue": len(self._q),
                "queue_cap": SHADOW_QUEUE_CAP,
                "budget_per_s": int(_flag("shadow_read_budget", 20)
                                    or 0),
                **dict(self.counts),
                "mismatch_by_verb": dict(self.mismatch_by_verb),
                "mismatch_by_space": dict(self.mismatch_by_space),
                "last_mismatch": self.last_mismatch,
            }

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until the queue is empty AND no popped sample is
        still being verified (harness/test seam — gates read stats
        right after, so the last verdict must have landed). True when
        drained within the timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._q and not self._in_flight:
                    return True
            time.sleep(0.02)
        return False

    def reset(self) -> None:
        """Test/bench isolation: drop queued samples and counters."""
        with self._lock:
            self._q.clear()
            for k in self.counts:
                self.counts[k] = 0
            self.mismatch_by_verb.clear()
            self.mismatch_by_space.clear()
            self.last_mismatch = None


# ---------------------------------------------------------------------------
# device-snapshot audit registry: one process-global cadence thread
# driving every registered engine auditor (weakly held)
# ---------------------------------------------------------------------------
_audit_lock = threading.Lock()
_audit_fns: List["weakref.WeakMethod"] = []
_audit_thread: Optional[threading.Thread] = None


def register_audit(bound_method) -> None:
    """Register an engine's ``audit_snapshots`` bound method. Weakly
    held (a test engine must be collectable); the single background
    thread starts on first registration and runs each live auditor
    every ``consistency_audit_interval_s`` seconds while armed."""
    global _audit_thread
    with _audit_lock:
        _audit_fns.append(weakref.WeakMethod(bound_method))
        if _audit_thread is None or not _audit_thread.is_alive():
            # nlint: disable=NL002 -- process-lifetime audit cadence;
            # background maintenance owes no request a trace
            _audit_thread = threading.Thread(
                target=_audit_loop, daemon=True,
                name="consistency-audit")
            _audit_thread.start()


def run_audits() -> int:
    """Run every live registered auditor once (the on-demand seam:
    /consistency?audit=1, benches). Returns how many ran."""
    with _audit_lock:
        refs = list(_audit_fns)
    n = 0
    for ref in refs:
        fn = ref()
        if fn is None:
            continue
        try:
            fn()
            n += 1
        except Exception:
            pass
    with _audit_lock:
        _audit_fns[:] = [r for r in _audit_fns if r() is not None]
    return n


def _audit_loop() -> None:
    while True:
        try:
            interval = float(_flag("consistency_audit_interval_s", 0.0)
                             or 0.0)
        except (TypeError, ValueError):
            interval = 0.0
        time.sleep(min(max(interval, 0.5), 5.0) if interval > 0
                   else 5.0)
        if interval <= 0 or not enabled():
            continue
        run_audits()


# ---------------------------------------------------------------------------
# /consistency surface helpers
# ---------------------------------------------------------------------------
def store_rows(store) -> List[Dict[str, Any]]:
    """Per-part digest rows of a local GraphStore (the unreplicated /
    in-process form the storaged endpoint and SHOW CONSISTENCY fall
    back to). Empty when disarmed."""
    if not enabled():
        return []
    out: List[Dict[str, Any]] = []
    for sid in store.spaces():
        for part in store.space_parts(sid):
            anc = part.digest_anchor()
            row: Dict[str, Any] = {
                "space": sid, "part": part.part_id,
                "role": "LEADER" if part.is_leader() else "FOLLOWER",
                "anchor_term": anc[0] if anc else None,
                "anchor_id": anc[1] if anc else None,
                "digest": hex_digest(anc[2]) if anc else None,
                "replicas": [],
            }
            out.append(row)
    return out


def record_divergence(space: int, part: int, replica: str,
                      anchor_id: int, anchor_term: int,
                      leader_digest: int, replica_digest: int) -> None:
    """One replica-divergence observation (leader side, kvstore/
    raftex): counted + flight-recorded. Caller gates on transition so
    a persistent divergence records one event per episode, not one
    per heartbeat round."""
    global_stats.add_value("consistency.divergence", kind="counter")
    from .flight import recorder
    recorder.record("replica_divergence", space=space, part=part,
                    replica=replica, anchor=anchor_id,
                    term=anchor_term,
                    leader_digest=hex_digest(leader_digest),
                    replica_digest=hex_digest(replica_digest))


# process-global instance (the stats/flight/heat singleton idiom)
shadow = ShadowVerifier()


def capture() -> Dict[str, Any]:
    """Flight-bundle collector body: the shadow verifier's state (the
    per-daemon digest views ride the daemons' own collectors)."""
    return {"enabled": enabled(), "shadow": shadow.stats()}


from .flight import recorder as _flight_recorder  # noqa: E402

_flight_recorder.add_collector("consistency", capture)
