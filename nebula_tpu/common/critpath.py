"""Critical-path analysis over trace span trees: fold a finished trace
— including cross-host storaged fragments — into a dominant-path
attribution: "73% proc.scan_part on host B, 11% dispatcher.wait"
(docs/manual/10-observability.md, "Cost ledger & critical path").

A span tree answers "what happened"; this module answers "what should
the next optimization attack". Two reductions over one trace dict
(the common/tracing.py ring shape):

1. SELF-TIME ATTRIBUTION — every span's self time (its duration minus
   the time covered by its children, interval-merged so concurrent
   children are not double-subtracted) is aggregated by (name, host)
   and expressed as a fraction of the root's wall time. Spans whose
   parent is missing from the tree (a remote fragment whose graft
   raced the trace finish, a dropped span) are treated as extra roots:
   their time still attributes, nothing silently disappears.

2. CRITICAL PATH — from the root, repeatedly descend into the child
   covering the largest share of its parent's duration; the resulting
   chain is the path a latency optimization must shorten. Remote
   fragments participate naturally: storaged's fragment root is a
   child of the caller's rpc.call span (the PR 4 graft contract).

`explained` is the fraction of the root's wall time attributed to
spans OTHER than the root's own self time — the root's self time is
precisely the wall time no instrumented seam covered, so a low
`explained` means the span set has a hole, not that the query was
fast. (Capped at 1.0: attributed time on concurrent spans can exceed
wall time.) Bench tier-2/3 and CLUSTER_bench run this over
their forced-sample pass (bench.py) and publish the aggregate as the
artifact's `attribution` block; `/traces?critpath=<id>` serves the
single-trace form.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

# span names that identify where work ran remotely: fragment roots are
# "<service>.<method>" (tracing.RemoteTrace); processor spans carry an
# explicit host tag (storage/processors.py)
_HOST_TAG = "host"


def _merged_coverage(intervals: List[Tuple[int, int]]) -> int:
    """Total microseconds covered by a set of [start, end) intervals
    (children overlap when they ran concurrently)."""
    if not intervals:
        return 0
    intervals.sort()
    total = 0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    total += cur_e - cur_s
    return total


def _span_host(span: Dict[str, Any],
               inherited: Optional[str]) -> Optional[str]:
    """The host a span's work ran on: its own `host` tag wins, else the
    nearest ancestor's (fragment roots rarely tag themselves but their
    processor children do — and vice versa)."""
    h = span.get("tags", {}).get(_HOST_TAG)
    return str(h) if h is not None else inherited


def analyze(trace: Dict[str, Any], top: int = 8) -> Dict[str, Any]:
    """Fold one finished trace into its attribution. Returns:

      {"trace_id", "wall_us",
       "attribution": [{"name", "host", "self_us", "pct"}...],
       "critical_path": [{"name", "host", "dur_us", "pct"}...],
       "explained": float}       # capped at 1.0

    Degenerate inputs (no spans, a single span, orphaned subtrees) are
    handled, never raised on — this runs inside /traces handlers and
    bench artifact assembly."""
    spans = list(trace.get("spans", ()))
    if not spans:
        return {"trace_id": trace.get("trace_id", ""), "wall_us": 0,
                "attribution": [], "critical_path": [],
                "explained": 0.0}
    by_id = {s["span_id"]: s for s in spans}
    children: Dict[str, List[Dict[str, Any]]] = {}
    roots: List[Dict[str, Any]] = []
    for s in spans:
        if s.get("parent_id") and s["parent_id"] in by_id \
                and s["parent_id"] != s["span_id"]:
            children.setdefault(s["parent_id"], []).append(s)
        else:
            roots.append(s)
    # the trace root is the longest root span (the tracing.TraceHandle
    # root for a normal trace; for a bare fragment, its own root)
    root = max(roots, key=lambda s: int(s.get("dur_us", 0)))
    wall_us = max(int(root.get("dur_us", 0)), 1)

    # ---- 1. self-time aggregation by (name, host) ------------------
    agg: Dict[Tuple[str, Optional[str]], int] = {}
    root_self = 0
    visited: set = set()        # malformed parent cycles terminate
    stack: List[Tuple[Dict[str, Any], Optional[str]]] = \
        [(r, None) for r in roots]
    while stack:
        s, inh_host = stack.pop()
        if id(s) in visited:
            continue
        visited.add(id(s))
        host = _span_host(s, inh_host)
        dur = int(s.get("dur_us", 0))
        kids = children.get(s["span_id"], ())
        ivals = []
        for c in kids:
            t0 = int(c.get("t0_us", 0))
            ivals.append((t0, t0 + int(c.get("dur_us", 0))))
            stack.append((c, host))
        self_us = max(dur - _merged_coverage(ivals), 0)
        if self_us:
            if s is root:
                root_self = self_us
            key = (s["name"], host)
            agg[key] = agg.get(key, 0) + self_us
    attribution = [
        {"name": name, "host": host, "self_us": us,
         "pct": round(100.0 * us / wall_us, 1)}
        for (name, host), us in
        sorted(agg.items(), key=lambda kv: -kv[1])]
    explained = min(max(
        sum(a["self_us"] for a in attribution) - root_self, 0)
        / wall_us, 1.0)

    # ---- 2. dominant path ------------------------------------------
    path: List[Dict[str, Any]] = []
    cur, host = root, _span_host(root, None)
    seen = set()
    while cur is not None and cur["span_id"] not in seen:
        seen.add(cur["span_id"])
        host = _span_host(cur, host)
        path.append({"name": cur["name"], "host": host,
                     "dur_us": int(cur.get("dur_us", 0)),
                     "pct": round(100.0 * int(cur.get("dur_us", 0))
                                  / wall_us, 1)})
        kids = children.get(cur["span_id"], ())
        cur = max(kids, key=lambda c: int(c.get("dur_us", 0))) \
            if kids else None

    return {"trace_id": trace.get("trace_id", ""), "wall_us": wall_us,
            "attribution": attribution[:max(int(top), 1)],
            "critical_path": path,
            "explained": round(explained, 4)}


def aggregate(traces: List[Dict[str, Any]], top: int = 8
              ) -> Dict[str, Any]:
    """Attribution across a SET of traces (the bench forced-sample
    pass): per-(name, host) self time summed over all traces as a
    fraction of their total wall time, plus the mean explained
    fraction — the artifact's `attribution` block."""
    total_wall = 0
    agg: Dict[Tuple[str, Optional[str]], int] = {}
    explained: List[float] = []
    for t in traces:
        a = analyze(t, top=64)
        if not a["wall_us"]:
            continue
        total_wall += a["wall_us"]
        explained.append(a["explained"])
        for row in a["attribution"]:
            key = (row["name"], row["host"])
            agg[key] = agg.get(key, 0) + row["self_us"]
    rows = [
        {"name": name, "host": host, "self_us": us,
         "pct": round(100.0 * us / max(total_wall, 1), 1)}
        for (name, host), us in
        sorted(agg.items(), key=lambda kv: -kv[1])]
    return {"sampled_traces": len(explained),
            "wall_us_total": total_wall,
            "explained": round(sum(explained) / len(explained), 4)
            if explained else 0.0,
            "attribution": rows[:max(int(top), 1)]}
