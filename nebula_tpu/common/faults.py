"""Named fault-point registry + circuit breaker for the device serve
path (docs/manual/9-robustness.md).

The reference survives partial failure by design (Raft-replicated
parts, leader-stale retry in the storage client, WAL restart
recovery); the TPU serve path needs the same discipline PROVABLE: a
fault point is a named site in load-bearing code (`faults.fire(name)`)
that is a no-op in production and, under an activated plan, injects a
failure — raise, added latency, probabilistically, or a bounded number
of times. Every injected fire is counted, so chaos runs (`bench.py
--chaos`, `tools/soak.py --faults`) can assert both that faults
actually landed and that no client ever saw one.

Activation, in priority order (all feed the same process registry):

- env var `NEBULA_TPU_FAULTS="kernel.launch:p=0.3;encode.rows:n=2"`
  read at import;
- the MUTABLE graphd flag `fault_plan` (hot-settable through /flags);
- the graphd admin endpoint `/faults` (GET = state, PUT plan=...).

Plan grammar: `point:arg[,arg]...` joined by `;`. Args:

    p=<0..1>      fire with this probability per evaluation (default 1)
    n=<int>       fire at most N times, then disarm
    latency=<ms>  sleep instead of raising (latency injection)
    after=<int>   skip the first K evaluations before arming

A bare `seed=<int>` entry reseeds the plan RNG so probabilistic plans
replay deterministically (the chaos smoke test pins one).

NETWORK NEMESIS (docs/manual/9-robustness.md "Nemesis catalog"): a
plan entry carrying a `peer=` arg is a LINK RULE, not a point spec —
it targets the real framed-TCP transport per (src, dst) peer pair
instead of a named code site. The entry name becomes the rule label
(free-form, may repeat). Link args:

    peer=<dst>          match calls TO <dst> from anyone ("*" wildcard)
    peer=<src>><dst>    directional: only calls <src> -> <dst>
                        (either side may be "*")
    drop=<0..1>         drop the frame pre-send with this probability
                        (surfaces as a retryable connection error)
    hang=<0..1>         blackhole: the connection stays open but no
                        reply ever comes (accept-then-hang, the
                        gray-failure shape) — the caller burns its
                        socket timeout
    latency=<ms>        sleep before send (slow link / slow node)
    jitter=<ms>         add uniform [0, jitter) on top of latency=
    dup=<0..1>          duplicate delivery: send the frame twice
    p=<0..1>, n=<int>   the usual gate / bounded-count args

One-way partitions fall out of directional `peer=` + `hang=1`;
symmetric splits arm both directions. `Nemesis` (below) builds these
plan strings for the canonical scenarios. `set_link_plan` installs
link rules WITHOUT disturbing armed point specs (so a crash plan and
a nemesis can coexist); `set_plan` replaces both stores wholesale.
Every daemon serves the plan surface at `/nemesis` (webservice.py).

The module also hosts `CircuitBreaker` — the degradation ladder's
state machine (closed -> open on N consecutive failures -> half-open
probes after exponential backoff -> closed on a probe success), used
per-feature by `TpuGraphEngine`.
"""
from __future__ import annotations

import contextvars
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .stats import stats as global_stats


class InjectedFault(Exception):
    """Raised by an armed fault point (mode: raise)."""


class InjectedConnectionFault(InjectedFault, ConnectionError):
    """Transport-shaped injected fault: registered points whose real
    failure mode is a broken socket raise this, so the production
    retry machinery (reconnect loops, leader rotation) engages exactly
    as it would for the genuine failure."""


class _FaultSpec:
    __slots__ = ("p", "remaining", "latency_ms", "skip")

    def __init__(self, p: float = 1.0, n: Optional[int] = None,
                 latency_ms: Optional[float] = None, after: int = 0):
        self.p = p
        self.remaining = n          # None = unbounded
        self.latency_ms = latency_ms
        self.skip = after

    def describe(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"p": self.p}
        if self.remaining is not None:
            out["remaining"] = self.remaining
        if self.latency_ms is not None:
            out["latency_ms"] = self.latency_ms
        if self.skip:
            out["after"] = self.skip
        return out


class _LinkRule:
    """One armed nemesis rule on a (src, dst) peer link (module doc:
    NETWORK NEMESIS). Matching is first-rule-wins; "*" wildcards either
    side; a caller with no declared src identity (src=None) matches
    only "*" src patterns."""

    __slots__ = ("label", "src", "dst", "drop_p", "hang_p",
                 "latency_ms", "jitter_ms", "dup_p", "p", "remaining")

    def __init__(self, label: str, peer: str, drop: float = 0.0,
                 hang: float = 0.0, latency_ms: float = 0.0,
                 jitter_ms: float = 0.0, dup: float = 0.0,
                 p: float = 1.0, n: Optional[int] = None):
        self.label = label
        if ">" in peer:
            src, _, dst = peer.partition(">")
        else:
            src, dst = "*", peer
        self.src = src.strip() or "*"
        self.dst = dst.strip() or "*"
        self.drop_p = drop
        self.hang_p = hang
        self.latency_ms = latency_ms
        self.jitter_ms = jitter_ms
        self.dup_p = dup
        self.p = p
        self.remaining = n          # None = unbounded

    def matches(self, src: Optional[str], dst: str) -> bool:
        if self.src != "*" and self.src != src:
            return False
        return self.dst == "*" or self.dst == dst

    def describe(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"label": self.label,
                               "peer": f"{self.src}>{self.dst}"}
        for k, v in (("drop", self.drop_p), ("hang", self.hang_p),
                     ("latency_ms", self.latency_ms),
                     ("jitter_ms", self.jitter_ms), ("dup", self.dup_p)):
            if v:
                out[k] = v
        if self.p < 1.0:
            out["p"] = self.p
        if self.remaining is not None:
            out["remaining"] = self.remaining
        return out


class FaultRegistry:
    """Process-global named fault points. `fire(name)` costs one dict
    probe when no plan is active — cheap enough for the hot path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active: Dict[str, _FaultSpec] = {}
        self._links: List[_LinkRule] = []
        self._points: Dict[str, Dict[str, Any]] = {}   # name -> catalog
        self.fired: Dict[str, int] = {}
        self._rng = random.Random()

    # -------------------------------------------------------- catalog
    def register(self, name: str, exc: type = InjectedFault,
                 doc: str = "", crash: bool = False) -> None:
        """Declare a fault point (idempotent): names the site in the
        /faults catalog and fixes the exception type a raise-mode fire
        uses (transport points raise InjectedConnectionFault).
        `crash=True` makes the point a CRASHPOINT: an armed fire
        hard-aborts the process (`os._exit`) instead of raising — the
        seam dies exactly where a `kill -9` would leave it, with no
        Python cleanup, atexit hooks or buffered-stream flushes. Only
        subprocess harnesses (`bench --crash`) arm these."""
        with self._lock:
            self._points.setdefault(name, {"exc": exc, "doc": doc,
                                           "crash": bool(crash)})

    # ----------------------------------------------------------- fire
    def fire(self, name: str) -> None:
        """Evaluate the fault point: no-op unless an active plan arms
        `name`; otherwise sleep (latency mode) or raise the point's
        exception type. Every injected fire is counted."""
        if not self._active:            # fast path: nothing armed
            return
        with self._lock:
            spec = self._active.get(name)
            if spec is None:
                return
            if spec.skip > 0:
                spec.skip -= 1
                return
            if spec.remaining is not None and spec.remaining <= 0:
                return
            if spec.p < 1.0 and self._rng.random() >= spec.p:
                return
            if spec.remaining is not None:
                spec.remaining -= 1
            self.fired[name] = self.fired.get(name, 0) + 1
            latency = spec.latency_ms
            point = self._points.get(name, {})
            exc = point.get("exc", InjectedFault)
            crash = point.get("crash", False)
        global_stats.add_value("faults.injected." + name, kind="counter")
        if crash:
            # hard process abort at the seam — the stderr note is the
            # only trace (the harness watches for exit code 134)
            import sys as _sys
            print(f"CRASHPOINT {name!r} fired: aborting process",
                  file=_sys.stderr, flush=True)
            os._exit(134)
        if latency is not None:
            time.sleep(latency / 1e3)
            return
        raise exc(f"injected fault at {name!r}")

    # -------------------------------------------------------- nemesis
    def link_actions(self, src: Optional[str],
                     dst: str) -> Optional[Dict[str, Any]]:
        """Evaluate the nemesis link rules for one transport call on
        the (src, dst) link. Returns None (the overwhelmingly common
        case — one list probe when no nemesis is armed) or an action
        dict the transport executes IN ORDER: `latency_s` sleep first,
        then at most one of `drop` / `hang` / `dup`. First matching
        rule wins; rolls that produce no action consume nothing."""
        if not self._links:             # fast path: no nemesis armed
            return None
        acts: Optional[Dict[str, Any]] = None
        with self._lock:
            for rule in self._links:
                if not rule.matches(src, dst):
                    continue
                if rule.remaining is not None and rule.remaining <= 0:
                    continue
                if rule.p < 1.0 and self._rng.random() >= rule.p:
                    return None
                out: Dict[str, Any] = {}
                if rule.latency_ms or rule.jitter_ms:
                    out["latency_s"] = (
                        rule.latency_ms
                        + self._rng.random() * rule.jitter_ms) / 1e3
                if rule.hang_p and self._rng.random() < rule.hang_p:
                    out["hang"] = True
                elif rule.drop_p and self._rng.random() < rule.drop_p:
                    out["drop"] = True
                elif rule.dup_p and self._rng.random() < rule.dup_p:
                    out["dup"] = True
                if not out:
                    return None
                if rule.remaining is not None:
                    rule.remaining -= 1
                self.fired[rule.label] = \
                    self.fired.get(rule.label, 0) + 1
                acts = out
                break
        if acts is None:
            return None
        # counters outside the lock (stats has its own)
        for mode in ("latency_s", "drop", "hang", "dup"):
            if mode in acts:
                global_stats.add_value(
                    "rpc.nemesis." + mode.replace("latency_s",
                                                  "latency"),
                    kind="counter")
        return acts

    # ----------------------------------------------------------- plan
    @staticmethod
    def _parse_plan(plan: str) -> Tuple[Dict[str, _FaultSpec],
                                        List[_LinkRule], Optional[int]]:
        """Shared plan parser (module doc grammar). An entry carrying
        a `peer=` arg parses as a link rule; anything else is a point
        spec. Raises ValueError on malformed input."""
        points: Dict[str, _FaultSpec] = {}
        links: List[_LinkRule] = []
        seed: Optional[int] = None
        for part in (plan or "").split(";"):
            part = part.strip()
            if not part:
                continue
            if part.startswith("seed="):
                seed = int(part[5:])
                continue
            name, _, args = part.partition(":")
            name = name.strip()
            if not name:
                raise ValueError(f"bad fault plan entry {part!r}")
            kw: Dict[str, Any] = {}
            for a in args.split(","):
                a = a.strip()
                if not a:
                    continue
                k, eq, v = a.partition("=")
                if not eq:
                    raise ValueError(f"bad fault arg {a!r} in {part!r}")
                if k == "p":
                    kw["p"] = float(v)
                elif k == "n":
                    kw["n"] = int(v)
                elif k == "latency":
                    kw["latency_ms"] = float(v)
                elif k == "after":
                    kw["after"] = int(v)
                elif k == "peer":
                    kw["peer"] = v.strip()
                elif k in ("drop", "hang", "dup"):
                    kw[k] = float(v)
                elif k == "jitter":
                    kw["jitter_ms"] = float(v)
                else:
                    raise ValueError(f"unknown fault arg {k!r} in "
                                     f"{part!r}")
            if "peer" in kw:
                if not kw["peer"]:
                    raise ValueError(f"empty peer= in {part!r}")
                if "after" in kw:
                    raise ValueError(
                        f"after= is a point-spec arg; not valid on "
                        f"link rule {part!r}")
                try:
                    links.append(_LinkRule(name, **kw))
                except TypeError:
                    raise ValueError(f"bad link rule {part!r}")
            else:
                for bad in ("drop", "hang", "dup", "jitter_ms"):
                    if bad in kw:
                        raise ValueError(
                            f"{bad.split('_')[0]}= requires peer= in "
                            f"{part!r}")
                points[name] = _FaultSpec(**kw)
        return points, links, seed

    def set_plan(self, plan: str) -> None:
        """Parse + install a plan string (see module doc). An empty
        plan clears every armed point AND link rule. Raises ValueError
        on a malformed plan, leaving the previous plan installed."""
        points, links, seed = self._parse_plan(plan)
        with self._lock:
            self._active = points
            self._links = links
            if seed is not None:
                self._rng = random.Random(seed)

    def set_link_plan(self, plan: str) -> None:
        """Install ONLY the link rules of `plan`, leaving armed point
        specs untouched (so a nemesis can run alongside a crash plan).
        Raises ValueError if the plan contains point specs, or on any
        malformed entry. An empty plan heals every link."""
        points, links, seed = self._parse_plan(plan)
        if points:
            raise ValueError(
                f"set_link_plan accepts only peer= link rules; got "
                f"point specs {sorted(points)}")
        with self._lock:
            self._links = links
            if seed is not None:
                self._rng = random.Random(seed)

    def clear_links(self) -> None:
        """Heal every nemesis link rule (point specs stay armed)."""
        with self._lock:
            self._links = []

    def clear(self) -> None:
        with self._lock:
            self._active = {}
            self._links = []

    def reset(self) -> None:
        """Disarm everything AND zero the fire counters (test
        isolation; production observability never resets)."""
        with self._lock:
            self._active = {}
            self._links = []
            self.fired = {}

    # ---------------------------------------------------- observation
    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.fired)

    def total_fired(self) -> int:
        with self._lock:
            return sum(self.fired.values())

    def describe(self) -> Dict[str, Any]:
        """JSON-able registry state for the /faults admin endpoint."""
        with self._lock:
            return {
                "active": {n: s.describe()
                           for n, s in self._active.items()},
                "links": [r.describe() for r in self._links],
                "fired": dict(self.fired),
                "total_fired": sum(self.fired.values()),
                "points": {n: p["doc"] for n, p in self._points.items()},
            }


# process-global instance (the gflags-style singleton every fault
# point imports)
faults = FaultRegistry()

# the load-bearing device-serve-path sites (registered here so the
# /faults catalog is complete even before the sites are first hit)
faults.register("csr.build",
                doc="CSR snapshot build from the provider scan")
faults.register("csr.delta_apply",
                doc="committed-write delta apply onto a live snapshot")
faults.register("kernel.launch",
                doc="device traversal-kernel launch (single query and "
                    "dispatcher windows)")
faults.register("mesh.collective",
                doc="sharded collective entry points in mesh_exec")
faults.register("index.build",
                doc="secondary-index sorted-array build on a fresh "
                    "snapshot (engine_tpu/index.py); a fired build "
                    "degrades that (tag, prop) to the CPU scan")
faults.register("index.search",
                doc="device LOOKUP index search; a fired search feeds "
                    "the 'index' breaker and the storaged CPU scan "
                    "serves the query")
faults.register("encode.rows", doc="native nbc_encode_rows batch row "
                                   "encode (falls back to pure python)")
faults.register("rpc.send", exc=InjectedConnectionFault,
                doc="framed RPC transport send path")
# the cluster's durability path (kvstore/wal.py): a fired wal.append
# surfaces as the genuine failure mode — Wal.append returns False, so
# the raft layers' E_WAL_FAIL handling engages exactly as it would for
# a full disk; wal.sync raises (a failed fsync is not ignorable)
faults.register("wal.append",
                doc="segmented-WAL record append (raft leader local "
                    "append AND follower replication appends)")
faults.register("wal.sync",
                doc="explicit WAL fsync (Wal.sync / "
                    "wal_sync_every_append durability path)")
faults.register("followerread.stale",
                doc="follower-read fence lie: the replica reports a "
                    "perfectly fresh time watermark regardless of how "
                    "stale it really is (raft_part.read_fence) — the "
                    "commit-index fence must reject it on its own, "
                    "and a slip past both would surface in the PR 15 "
                    "digest/shadow-read verification")
faults.register("wal.torn_tail",
                doc="truncate trailing bytes off the newest WAL "
                    "segment at close — the shape a power cut "
                    "mid-append leaves; the next open must "
                    "CRC-truncate the torn record and recover the "
                    "prefix (kvstore/wal.py close)")
# crashpoints: hard process aborts (os._exit) at the recovery-critical
# seams — armed only by crash harnesses (bench --crash), they force
# the exact window `kill -9` races against (docs/manual/12-replication
# .md crash recovery protocol)
faults.register("crashpoint.wal_applied", crash=True,
                doc="CRASHPOINT: abort after a commit batch is "
                    "durable in the WAL but BEFORE the engine apply "
                    "(raft_part._commit_range_locked) — restart must "
                    "replay the tail")
faults.register("crashpoint.snapshot_recv", crash=True,
                doc="CRASHPOINT: abort mid-snapshot-install on the "
                    "receiving replica (raft_part."
                    "process_send_snapshot) — the restarted receiver "
                    "must re-request and converge")

if os.environ.get("NEBULA_TPU_FAULTS"):
    faults.set_plan(os.environ["NEBULA_TPU_FAULTS"])


def _wire_flag() -> None:
    """`fault_plan` graphd flag: hot-settable through /flags and the
    meta config pull, mirroring every other MUTABLE flag."""
    from .flags import MUTABLE, graph_flags
    graph_flags.declare(
        "fault_plan", "", MUTABLE,
        "fault-injection plan (common/faults.py grammar); empty clears")

    def _apply(name: str, value: Any) -> None:
        if name == "fault_plan":
            try:
                faults.set_plan(str(value or ""))
            except ValueError:
                pass    # a bad hot-set must never kill the watcher

    graph_flags.watch(_apply)


_wire_flag()


# ---------------------------------------------------------------------------
# Nemesis scenario driver (docs/manual/9-robustness.md "Nemesis catalog")
# ---------------------------------------------------------------------------

class Nemesis:
    """Builds and installs link-rule plans for the canonical partition
    scenarios. The plan-string builders are static (pure string
    assembly, unit-testable); an instance binds an `apply_plan`
    callable so the same driver works in-process (default: the local
    registry's `set_link_plan`) or against subprocess clusters (pass a
    closure that PUTs the plan to every node's `/nemesis` endpoint —
    link rules evaluate in the CALLER's process, so every process that
    dials peers must receive the plan)."""

    def __init__(self, apply_plan=None):
        self._apply = apply_plan or faults.set_link_plan
        self.installed = ""

    # ----------------------------------------------- plan builders
    @staticmethod
    def symmetric_split(a_addrs, b_addrs) -> str:
        """Full two-way partition between groups A and B."""
        rules = []
        for a in a_addrs:
            for b in b_addrs:
                rules.append(f"split:peer={a}>{b},hang=1")
                rules.append(f"split:peer={b}>{a},hang=1")
        return ";".join(rules)

    @staticmethod
    def asymmetric_split(from_addrs, to_addrs) -> str:
        """One-way partition: from->to blackholed, replies/reverse
        direction untouched (the asymmetric-link failure shape)."""
        return ";".join(f"oneway:peer={a}>{b},hang=1"
                        for a in from_addrs for b in to_addrs)

    @staticmethod
    def isolate(addrs) -> str:
        """Blackhole every link to AND from each addr (node unplugged
        at the switch, sockets still accept)."""
        rules = []
        for a in addrs:
            rules.append(f"iso:peer=*>{a},hang=1")
            rules.append(f"iso:peer={a}>*,hang=1")
        return ";".join(rules)

    @staticmethod
    def slow_node(addrs, latency_ms: float = 250.0,
                  jitter_ms: float = 0.0) -> str:
        """Gray failure: every call TO each addr pays added latency —
        the node is alive, correct, and slow."""
        j = f",jitter={jitter_ms:g}" if jitter_ms else ""
        return ";".join(f"slow:peer=*>{a},latency={latency_ms:g}{j}"
                        for a in addrs)

    @staticmethod
    def lossy_link(addrs, drop: float = 0.3) -> str:
        """Probabilistic frame loss toward each addr (retry pressure
        without a full partition)."""
        return ";".join(f"lossy:peer=*>{a},drop={drop:g}"
                        for a in addrs)

    # ------------------------------------------------- application
    def apply(self, plan: str) -> str:
        self._apply(plan)
        self.installed = plan
        return plan

    def heal(self) -> str:
        return self.apply("")

    def flap(self, plan: str, cycles: int, on_s: float,
             off_s: float) -> None:
        """Flapping link: install/heal `plan` for `cycles` rounds
        (blocking — run from a scenario thread, never a serve path)."""
        for _ in range(max(int(cycles), 0)):
            self.apply(plan)
            time.sleep(on_s)
            self.heal()
            time.sleep(off_s)


def jittered_delay(base_s: float, cap_s: float, attempt: int) -> float:
    """Capped exponential backoff with half-jitter — the one formula
    every retry loop shares (transport reconnects, storage-client KV
    retries): min(base * 2^attempt, cap) scaled by [0.5, 1.0)."""
    return min(base_s * (2 ** attempt), cap_s) \
        * (0.5 + random.random() * 0.5)


# Serve-path sections that run while holding a hot lock (the engine
# snapshot lock during a first-touch refresh) set this contextvar so
# the SHARED retry loops they may reach (transport reconnect,
# storage-client KV/scan backoff) rotate leader hints immediately but
# never sleep: sleeping there blocks every other query on the held
# lock for the backoff duration, which is strictly worse than failing
# fast into the degradation ladder (CPU pipe + background repack with
# its own pacing). Found at runtime by the lock-order witness during
# `bench --cluster` failover (docs/manual/15-static-analysis.md).
no_retry_sleep: "contextvars.ContextVar[bool]" = \
    contextvars.ContextVar("nebula_no_retry_sleep", default=False)


def pace_retry(delay_s: float) -> None:
    """The shared retry pause: `time.sleep(delay_s)` unless the
    current context suppresses retry sleeps (hot-lock sections)."""
    if not no_retry_sleep.get():
        time.sleep(delay_s)


# ---------------------------------------------------------------------------
# circuit breaker (the degradation ladder's state machine)
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Per-feature breaker: CLOSED until `threshold` CONSECUTIVE
    failures, then OPEN (every `allow()` denied) for an exponentially
    backed-off window, then HALF-OPEN (probes admitted); a probe
    success closes it, a probe failure re-opens with doubled backoff.

    States are derived, not stored: tripped + now < next_probe = open;
    tripped + now >= next_probe = half_open. That keeps `allow()` a
    couple of comparisons and makes concurrent probes harmless (each
    records its own outcome; the first success closes).

    Thread-safe; `on_trip`/`on_recover` hooks run outside the lock."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, threshold: int = 3, base_backoff_s: float = 0.5,
                 max_backoff_s: float = 30.0, clock=time.monotonic,
                 on_trip=None, on_recover=None):
        self.threshold = max(int(threshold), 1)
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self._clock = clock
        self._lock = threading.Lock()
        self._consecutive = 0
        self._backoff = base_backoff_s
        self._next_probe = 0.0
        self._tripped = False
        self.trips = 0
        self.recoveries = 0
        self.half_open_probes = 0
        self._on_trip = on_trip
        self._on_recover = on_recover

    # ------------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if not self._tripped:
            return self.CLOSED
        if self._clock() < self._next_probe:
            return self.OPEN
        return self.HALF_OPEN

    def allow(self) -> bool:
        """May the protected path run now? True when closed, or when
        the open window has elapsed (half-open probe — counted)."""
        with self._lock:
            st = self._state_locked()
            if st == self.OPEN:
                return False
            if st == self.HALF_OPEN:
                self.half_open_probes += 1
            return True

    def record_success(self) -> None:
        recovered = False
        with self._lock:
            if self._tripped:
                recovered = True
                self.recoveries += 1
            self._tripped = False
            self._consecutive = 0
            self._backoff = self.base_backoff_s
        if recovered and self._on_recover is not None:
            self._on_recover(self)

    def record_failure(self) -> bool:
        """Returns True when THIS failure tripped the breaker (closed
        -> open transition), so the caller can log/demote once."""
        tripped_now = False
        with self._lock:
            now = self._clock()
            if self._tripped:
                # probe failure (or late failure racing the trip):
                # re-open with doubled backoff
                self._backoff = min(self._backoff * 2,
                                    self.max_backoff_s)
                self._next_probe = now + self._backoff
                return False
            self._consecutive += 1
            if self._consecutive >= self.threshold:
                self._tripped = True
                self.trips += 1
                self._backoff = self.base_backoff_s
                self._next_probe = now + self._backoff
                tripped_now = True
        if tripped_now and self._on_trip is not None:
            self._on_trip(self)
        return tripped_now
