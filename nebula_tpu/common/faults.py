"""Named fault-point registry + circuit breaker for the device serve
path (docs/manual/9-robustness.md).

The reference survives partial failure by design (Raft-replicated
parts, leader-stale retry in the storage client, WAL restart
recovery); the TPU serve path needs the same discipline PROVABLE: a
fault point is a named site in load-bearing code (`faults.fire(name)`)
that is a no-op in production and, under an activated plan, injects a
failure — raise, added latency, probabilistically, or a bounded number
of times. Every injected fire is counted, so chaos runs (`bench.py
--chaos`, `tools/soak.py --faults`) can assert both that faults
actually landed and that no client ever saw one.

Activation, in priority order (all feed the same process registry):

- env var `NEBULA_TPU_FAULTS="kernel.launch:p=0.3;encode.rows:n=2"`
  read at import;
- the MUTABLE graphd flag `fault_plan` (hot-settable through /flags);
- the graphd admin endpoint `/faults` (GET = state, PUT plan=...).

Plan grammar: `point:arg[,arg]...` joined by `;`. Args:

    p=<0..1>      fire with this probability per evaluation (default 1)
    n=<int>       fire at most N times, then disarm
    latency=<ms>  sleep instead of raising (latency injection)
    after=<int>   skip the first K evaluations before arming

A bare `seed=<int>` entry reseeds the plan RNG so probabilistic plans
replay deterministically (the chaos smoke test pins one).

The module also hosts `CircuitBreaker` — the degradation ladder's
state machine (closed -> open on N consecutive failures -> half-open
probes after exponential backoff -> closed on a probe success), used
per-feature by `TpuGraphEngine`.
"""
from __future__ import annotations

import contextvars
import os
import random
import threading
import time
from typing import Any, Dict, Optional

from .stats import stats as global_stats


class InjectedFault(Exception):
    """Raised by an armed fault point (mode: raise)."""


class InjectedConnectionFault(InjectedFault, ConnectionError):
    """Transport-shaped injected fault: registered points whose real
    failure mode is a broken socket raise this, so the production
    retry machinery (reconnect loops, leader rotation) engages exactly
    as it would for the genuine failure."""


class _FaultSpec:
    __slots__ = ("p", "remaining", "latency_ms", "skip")

    def __init__(self, p: float = 1.0, n: Optional[int] = None,
                 latency_ms: Optional[float] = None, after: int = 0):
        self.p = p
        self.remaining = n          # None = unbounded
        self.latency_ms = latency_ms
        self.skip = after

    def describe(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"p": self.p}
        if self.remaining is not None:
            out["remaining"] = self.remaining
        if self.latency_ms is not None:
            out["latency_ms"] = self.latency_ms
        if self.skip:
            out["after"] = self.skip
        return out


class FaultRegistry:
    """Process-global named fault points. `fire(name)` costs one dict
    probe when no plan is active — cheap enough for the hot path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active: Dict[str, _FaultSpec] = {}
        self._points: Dict[str, Dict[str, Any]] = {}   # name -> catalog
        self.fired: Dict[str, int] = {}
        self._rng = random.Random()

    # -------------------------------------------------------- catalog
    def register(self, name: str, exc: type = InjectedFault,
                 doc: str = "", crash: bool = False) -> None:
        """Declare a fault point (idempotent): names the site in the
        /faults catalog and fixes the exception type a raise-mode fire
        uses (transport points raise InjectedConnectionFault).
        `crash=True` makes the point a CRASHPOINT: an armed fire
        hard-aborts the process (`os._exit`) instead of raising — the
        seam dies exactly where a `kill -9` would leave it, with no
        Python cleanup, atexit hooks or buffered-stream flushes. Only
        subprocess harnesses (`bench --crash`) arm these."""
        with self._lock:
            self._points.setdefault(name, {"exc": exc, "doc": doc,
                                           "crash": bool(crash)})

    # ----------------------------------------------------------- fire
    def fire(self, name: str) -> None:
        """Evaluate the fault point: no-op unless an active plan arms
        `name`; otherwise sleep (latency mode) or raise the point's
        exception type. Every injected fire is counted."""
        if not self._active:            # fast path: nothing armed
            return
        with self._lock:
            spec = self._active.get(name)
            if spec is None:
                return
            if spec.skip > 0:
                spec.skip -= 1
                return
            if spec.remaining is not None and spec.remaining <= 0:
                return
            if spec.p < 1.0 and self._rng.random() >= spec.p:
                return
            if spec.remaining is not None:
                spec.remaining -= 1
            self.fired[name] = self.fired.get(name, 0) + 1
            latency = spec.latency_ms
            point = self._points.get(name, {})
            exc = point.get("exc", InjectedFault)
            crash = point.get("crash", False)
        global_stats.add_value("faults.injected." + name, kind="counter")
        if crash:
            # hard process abort at the seam — the stderr note is the
            # only trace (the harness watches for exit code 134)
            import sys as _sys
            print(f"CRASHPOINT {name!r} fired: aborting process",
                  file=_sys.stderr, flush=True)
            os._exit(134)
        if latency is not None:
            time.sleep(latency / 1e3)
            return
        raise exc(f"injected fault at {name!r}")

    # ----------------------------------------------------------- plan
    def set_plan(self, plan: str) -> None:
        """Parse + install a plan string (see module doc). An empty
        plan clears every armed point. Raises ValueError on a
        malformed plan, leaving the previous plan installed."""
        new: Dict[str, _FaultSpec] = {}
        seed: Optional[int] = None
        for part in (plan or "").split(";"):
            part = part.strip()
            if not part:
                continue
            if part.startswith("seed="):
                seed = int(part[5:])
                continue
            name, _, args = part.partition(":")
            name = name.strip()
            if not name:
                raise ValueError(f"bad fault plan entry {part!r}")
            kw: Dict[str, Any] = {}
            for a in args.split(","):
                a = a.strip()
                if not a:
                    continue
                k, eq, v = a.partition("=")
                if not eq:
                    raise ValueError(f"bad fault arg {a!r} in {part!r}")
                if k == "p":
                    kw["p"] = float(v)
                elif k == "n":
                    kw["n"] = int(v)
                elif k == "latency":
                    kw["latency_ms"] = float(v)
                elif k == "after":
                    kw["after"] = int(v)
                else:
                    raise ValueError(f"unknown fault arg {k!r} in "
                                     f"{part!r}")
            new[name] = _FaultSpec(**kw)
        with self._lock:
            self._active = new
            if seed is not None:
                self._rng = random.Random(seed)

    def clear(self) -> None:
        with self._lock:
            self._active = {}

    def reset(self) -> None:
        """Disarm everything AND zero the fire counters (test
        isolation; production observability never resets)."""
        with self._lock:
            self._active = {}
            self.fired = {}

    # ---------------------------------------------------- observation
    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.fired)

    def total_fired(self) -> int:
        with self._lock:
            return sum(self.fired.values())

    def describe(self) -> Dict[str, Any]:
        """JSON-able registry state for the /faults admin endpoint."""
        with self._lock:
            return {
                "active": {n: s.describe()
                           for n, s in self._active.items()},
                "fired": dict(self.fired),
                "total_fired": sum(self.fired.values()),
                "points": {n: p["doc"] for n, p in self._points.items()},
            }


# process-global instance (the gflags-style singleton every fault
# point imports)
faults = FaultRegistry()

# the load-bearing device-serve-path sites (registered here so the
# /faults catalog is complete even before the sites are first hit)
faults.register("csr.build",
                doc="CSR snapshot build from the provider scan")
faults.register("csr.delta_apply",
                doc="committed-write delta apply onto a live snapshot")
faults.register("kernel.launch",
                doc="device traversal-kernel launch (single query and "
                    "dispatcher windows)")
faults.register("mesh.collective",
                doc="sharded collective entry points in mesh_exec")
faults.register("index.build",
                doc="secondary-index sorted-array build on a fresh "
                    "snapshot (engine_tpu/index.py); a fired build "
                    "degrades that (tag, prop) to the CPU scan")
faults.register("index.search",
                doc="device LOOKUP index search; a fired search feeds "
                    "the 'index' breaker and the storaged CPU scan "
                    "serves the query")
faults.register("encode.rows", doc="native nbc_encode_rows batch row "
                                   "encode (falls back to pure python)")
faults.register("rpc.send", exc=InjectedConnectionFault,
                doc="framed RPC transport send path")
# the cluster's durability path (kvstore/wal.py): a fired wal.append
# surfaces as the genuine failure mode — Wal.append returns False, so
# the raft layers' E_WAL_FAIL handling engages exactly as it would for
# a full disk; wal.sync raises (a failed fsync is not ignorable)
faults.register("wal.append",
                doc="segmented-WAL record append (raft leader local "
                    "append AND follower replication appends)")
faults.register("wal.sync",
                doc="explicit WAL fsync (Wal.sync / "
                    "wal_sync_every_append durability path)")
faults.register("followerread.stale",
                doc="follower-read fence lie: the replica reports a "
                    "perfectly fresh time watermark regardless of how "
                    "stale it really is (raft_part.read_fence) — the "
                    "commit-index fence must reject it on its own, "
                    "and a slip past both would surface in the PR 15 "
                    "digest/shadow-read verification")
faults.register("wal.torn_tail",
                doc="truncate trailing bytes off the newest WAL "
                    "segment at close — the shape a power cut "
                    "mid-append leaves; the next open must "
                    "CRC-truncate the torn record and recover the "
                    "prefix (kvstore/wal.py close)")
# crashpoints: hard process aborts (os._exit) at the recovery-critical
# seams — armed only by crash harnesses (bench --crash), they force
# the exact window `kill -9` races against (docs/manual/12-replication
# .md crash recovery protocol)
faults.register("crashpoint.wal_applied", crash=True,
                doc="CRASHPOINT: abort after a commit batch is "
                    "durable in the WAL but BEFORE the engine apply "
                    "(raft_part._commit_range_locked) — restart must "
                    "replay the tail")
faults.register("crashpoint.snapshot_recv", crash=True,
                doc="CRASHPOINT: abort mid-snapshot-install on the "
                    "receiving replica (raft_part."
                    "process_send_snapshot) — the restarted receiver "
                    "must re-request and converge")

if os.environ.get("NEBULA_TPU_FAULTS"):
    faults.set_plan(os.environ["NEBULA_TPU_FAULTS"])


def _wire_flag() -> None:
    """`fault_plan` graphd flag: hot-settable through /flags and the
    meta config pull, mirroring every other MUTABLE flag."""
    from .flags import MUTABLE, graph_flags
    graph_flags.declare(
        "fault_plan", "", MUTABLE,
        "fault-injection plan (common/faults.py grammar); empty clears")

    def _apply(name: str, value: Any) -> None:
        if name == "fault_plan":
            try:
                faults.set_plan(str(value or ""))
            except ValueError:
                pass    # a bad hot-set must never kill the watcher

    graph_flags.watch(_apply)


_wire_flag()


def jittered_delay(base_s: float, cap_s: float, attempt: int) -> float:
    """Capped exponential backoff with half-jitter — the one formula
    every retry loop shares (transport reconnects, storage-client KV
    retries): min(base * 2^attempt, cap) scaled by [0.5, 1.0)."""
    return min(base_s * (2 ** attempt), cap_s) \
        * (0.5 + random.random() * 0.5)


# Serve-path sections that run while holding a hot lock (the engine
# snapshot lock during a first-touch refresh) set this contextvar so
# the SHARED retry loops they may reach (transport reconnect,
# storage-client KV/scan backoff) rotate leader hints immediately but
# never sleep: sleeping there blocks every other query on the held
# lock for the backoff duration, which is strictly worse than failing
# fast into the degradation ladder (CPU pipe + background repack with
# its own pacing). Found at runtime by the lock-order witness during
# `bench --cluster` failover (docs/manual/15-static-analysis.md).
no_retry_sleep: "contextvars.ContextVar[bool]" = \
    contextvars.ContextVar("nebula_no_retry_sleep", default=False)


def pace_retry(delay_s: float) -> None:
    """The shared retry pause: `time.sleep(delay_s)` unless the
    current context suppresses retry sleeps (hot-lock sections)."""
    if not no_retry_sleep.get():
        time.sleep(delay_s)


# ---------------------------------------------------------------------------
# circuit breaker (the degradation ladder's state machine)
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Per-feature breaker: CLOSED until `threshold` CONSECUTIVE
    failures, then OPEN (every `allow()` denied) for an exponentially
    backed-off window, then HALF-OPEN (probes admitted); a probe
    success closes it, a probe failure re-opens with doubled backoff.

    States are derived, not stored: tripped + now < next_probe = open;
    tripped + now >= next_probe = half_open. That keeps `allow()` a
    couple of comparisons and makes concurrent probes harmless (each
    records its own outcome; the first success closes).

    Thread-safe; `on_trip`/`on_recover` hooks run outside the lock."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, threshold: int = 3, base_backoff_s: float = 0.5,
                 max_backoff_s: float = 30.0, clock=time.monotonic,
                 on_trip=None, on_recover=None):
        self.threshold = max(int(threshold), 1)
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self._clock = clock
        self._lock = threading.Lock()
        self._consecutive = 0
        self._backoff = base_backoff_s
        self._next_probe = 0.0
        self._tripped = False
        self.trips = 0
        self.recoveries = 0
        self.half_open_probes = 0
        self._on_trip = on_trip
        self._on_recover = on_recover

    # ------------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if not self._tripped:
            return self.CLOSED
        if self._clock() < self._next_probe:
            return self.OPEN
        return self.HALF_OPEN

    def allow(self) -> bool:
        """May the protected path run now? True when closed, or when
        the open window has elapsed (half-open probe — counted)."""
        with self._lock:
            st = self._state_locked()
            if st == self.OPEN:
                return False
            if st == self.HALF_OPEN:
                self.half_open_probes += 1
            return True

    def record_success(self) -> None:
        recovered = False
        with self._lock:
            if self._tripped:
                recovered = True
                self.recoveries += 1
            self._tripped = False
            self._consecutive = 0
            self._backoff = self.base_backoff_s
        if recovered and self._on_recover is not None:
            self._on_recover(self)

    def record_failure(self) -> bool:
        """Returns True when THIS failure tripped the breaker (closed
        -> open transition), so the caller can log/demote once."""
        tripped_now = False
        with self._lock:
            now = self._clock()
            if self._tripped:
                # probe failure (or late failure racing the trip):
                # re-open with doubled backoff
                self._backoff = min(self._backoff * 2,
                                    self.max_backoff_s)
                self._next_probe = now + self._backoff
                return False
            self._consecutive += 1
            if self._consecutive >= self.threshold:
                self._tripped = True
                self.trips += 1
                self._backoff = self.base_backoff_s
                self._next_probe = now + self._backoff
                tripped_now = True
        if tripped_now and self._on_trip is not None:
            self._on_trip(self)
        return tripped_now
