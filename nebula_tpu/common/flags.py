"""Runtime flag registry.

Role parity with the reference's gflags usage + cluster config registry
integration (ref §5 of SURVEY: daemons declare flags, the meta configMan
stores them, clients hot-update MUTABLE ones). `declare` at import time,
`get`/`set` anywhere; `sync_to_meta` registers declared flags in the
meta config registry and `pull_from_meta` applies remote values —
mirroring MetaClient's gflags pull loop (meta/client/MetaClient.cpp:
1294-1429).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

MUTABLE = "MUTABLE"
REBOOT = "REBOOT"
IMMUTABLE = "IMMUTABLE"


class _Flag:
    __slots__ = ("name", "value", "default", "mode", "help")

    def __init__(self, name, default, mode, help_):
        self.name = name
        self.value = default
        self.default = default
        self.mode = mode
        self.help = help_


class FlagRegistry:
    def __init__(self, module: str = "GRAPH"):
        self.module = module
        self._flags: Dict[str, _Flag] = {}
        self._lock = threading.Lock()
        self._watchers: List[Callable[[str, Any], None]] = []

    def declare(self, name: str, default: Any, mode: str = MUTABLE,
                help_: str = "") -> None:
        with self._lock:
            if name not in self._flags:
                self._flags[name] = _Flag(name, default, mode, help_)

    def get(self, name: str, default: Any = None) -> Any:
        f = self._flags.get(name)
        return f.value if f is not None else default

    def get_or(self, name: str, default: Any, cast=None) -> Any:
        """Typed read with fallback: the live value coerced through
        `cast` (default: `type(default)`), or `default` when the flag
        is unset or its value doesn't coerce — the shared shape for
        call sites that consult a MUTABLE flag per use (hot-settable)
        but must survive a malformed hot-set."""
        v = self.get(name, default)
        try:
            return (cast or type(default))(v)
        except (TypeError, ValueError):
            return default

    def set(self, name: str, value: Any) -> bool:
        with self._lock:
            f = self._flags.get(name)
            if f is None or f.mode == IMMUTABLE:
                return False
            f.value = value
        for w in self._watchers:
            try:
                w(name, value)
            except Exception:
                pass
        return True

    def watch(self, fn: Callable[[str, Any], None]) -> None:
        self._watchers.append(fn)

    def unwatch(self, fn: Callable[[str, Any], None]) -> None:
        try:
            self._watchers.remove(fn)
        except ValueError:
            pass

    def items(self) -> List[Tuple[str, Any, str]]:
        return [(f.name, f.value, f.mode) for f in
                sorted(self._flags.values(), key=lambda f: f.name)]

    # ---------------------------------------------------------- flagfile
    def load_flagfile(self, path: str) -> int:
        """Apply `--name=value` lines from a gflags-style flagfile (ref:
        etc/nebula-*.conf.default + --flagfile). Values are coerced to
        the declared default's type; undeclared names are declared as
        string flags. Returns the number of flags applied."""
        n = 0
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if line.startswith("--"):
                    line = line[2:]
                name, had_eq, raw = line.partition("=")
                name, raw = name.strip(), raw.strip()
                if not name:
                    continue
                flag = self._flags.get(name)
                if not had_eq:
                    # bare `--flag` is boolean true in gflags
                    if flag is None:
                        self.declare(name, True)
                    else:
                        with self._lock:
                            flag.value = True
                    n += 1
                    continue
                value: Any = raw
                if flag is not None and not isinstance(flag.default, str):
                    try:
                        if isinstance(flag.default, bool):
                            value = raw.lower() in ("1", "true", "yes")
                        elif isinstance(flag.default, int):
                            value = int(raw)
                        elif isinstance(flag.default, float):
                            value = float(raw)
                    except ValueError:
                        raise ValueError(
                            f"{path}:{lineno}: flag {name!r} expects "
                            f"{type(flag.default).__name__}, got {raw!r}"
                        ) from None
                elif flag is None:
                    self.declare(name, raw)
                with self._lock:
                    f2 = self._flags[name]
                    f2.value = value  # flagfiles may set REBOOT/IMMUTABLE
                n += 1
        return n

    # ---------------------------------------------------------- meta sync
    def sync_to_meta(self, meta) -> None:
        for name, value, mode in self.items():
            meta.reg_config(self.module, name, value, mode)

    def pull_from_meta(self, meta) -> int:
        n = 0
        for mod_name, value, mode in meta.list_configs(self.module):
            name = mod_name.split(":", 1)[1]
            if mode != IMMUTABLE and name in self._flags and \
                    self._flags[name].value != value:
                self.set(name, value)
                n += 1
        return n


# per-daemon registries (the reference's per-binary gflags)
graph_flags = FlagRegistry("GRAPH")
storage_flags = FlagRegistry("STORAGE")
meta_flags = FlagRegistry("META")

# core declared flags, mirroring the reference defaults
graph_flags.declare("session_idle_timeout_secs", 28800, MUTABLE,
                    "idle session reclamation age")
graph_flags.declare("slow_op_threshold_ms", 50, MUTABLE,
                    "log queries slower than this")
graph_flags.declare("tpu_query_deadline_ms", 60000, MUTABLE,
                    "per-query device-path time budget (dispatcher wait "
                    "+ kernel + materialize); past it the device path "
                    "yields to the CPU pipe and deadline_exceeded is "
                    "counted in /tpu_stats. 0 disables.")
graph_flags.declare("storage_client_timeout_ms", 30000, MUTABLE,
                    "graphd data-plane RPC timeout per storaged "
                    "connection (read when a host proxy is first "
                    "created). A bounded budget is gray-failure "
                    "hygiene: a blackholed storaged costs this much "
                    "per attempt, letting peer-health ejection and "
                    "hedged reads react inside the query deadline — "
                    "the reference's --storage_client_timeout_ms")
graph_flags.declare("cache_mode", "plan", MUTABLE,
                    "serve-path cache ladder (common/cache.py; docs/"
                    "manual/11-caching.md): off = no caching, plan = "
                    "statement plan + compiled-filter-plan rungs "
                    "(default; no observable semantics change), full = "
                    "plan + snapshot-versioned device result cache + "
                    "in-window request dedupe + negative decline "
                    "caches")
storage_flags.declare("cache_mode", "plan", MUTABLE,
                      "storaged cache ladder: full enables the "
                      "bound-stats response cache and the (part, "
                      "version) columnar scan cache; off/plan disable "
                      "both (docs/manual/11-caching.md)")
storage_flags.declare("scan_cache_mb", 256, MUTABLE,
                      "byte budget for the storaged (part, version) "
                      "columnar scan cache — whole part scans are "
                      "large, so the rung is byte-capped, not just "
                      "entry-capped")
storage_flags.declare("download_dir", "/tmp/nebula_tpu_staging", REBOOT,
                      "staging dir for DOWNLOAD-ed bulk-load SST files")
storage_flags.declare("snapshot_dir", "/tmp/nebula_tpu_snapshots", REBOOT,
                      "root dir for CREATE SNAPSHOT checkpoints")
storage_flags.declare("max_edge_returned_per_vertex", 10000, MUTABLE,
                      "per-vertex edge truncation cap applied when a "
                      "bound request doesn't carry its own (default "
                      "matches the storage service's historical "
                      "DEFAULT_MAX_EDGES_PER_VERTEX)")
storage_flags.declare("kv_engine_options", "", MUTABLE,
                      "JSON map of native-engine tunables hot-applied to "
                      'every space engine, e.g. {"flush_bytes": 1048576, '
                      '"max_runs": 4} (ref role: the nested rocksdb option '
                      "maps, RocksEngineConfig.cpp)")
storage_flags.declare("heartbeat_interval_secs", 10, MUTABLE,
                      "storaged -> metad heartbeat period")
storage_flags.declare("raft_heartbeat_ms", 150, REBOOT,
                      "raft leader heartbeat/replication round period "
                      "for replicated parts (read at part bind time)")
storage_flags.declare("wal_sync_every_append", False, REBOOT,
                      "fsync the raft WAL on every record append "
                      "(read at part bind time). Default off: appends "
                      "ride buffered I/O — process-crash durability "
                      "holds (restart replays the WAL) but a "
                      "quorum-wide power loss can lose the tail. On "
                      "buys power-loss durability at a per-append "
                      "fsync (~0.1-10ms per record depending on the "
                      "device; docs/manual/12-replication.md)")
storage_flags.declare("wal_compact_lag", 4096, MUTABLE,
                      "entries of headroom kept BEHIND each part's "
                      "applied anchor when the storaged compaction "
                      "task truncates raft WAL prefixes — bounds both "
                      "WAL disk and restart replay length (negative "
                      "disables compaction; docs/manual/"
                      "12-replication.md crash recovery & compaction)")
storage_flags.declare("wal_compact_interval_secs", 20.0, MUTABLE,
                      "period of the storaged WAL-compaction task "
                      "(flush engines, then truncate each part's WAL "
                      "behind its pre-flush applied anchor; also runs "
                      "the wal_ttl_secs sweep)")
storage_flags.declare("wal_file_size", 16 * 1024 * 1024, REBOOT,
                      "raft WAL segment roll size in bytes (read at "
                      "part bind); compaction drops whole sealed "
                      "segments, so smaller files bound disk tighter "
                      "at more file churn")
storage_flags.declare("wal_ttl_secs", 86400, REBOOT,
                      "age after which sealed raft WAL segments are "
                      "eligible for the TTL sweep (read at part "
                      "bind; the compaction task is the caller)")
storage_flags.declare("raft_election_timeout_ms", 450, REBOOT,
                      "raft election timeout base (randomized 1-2x); "
                      "failover completes within ~2x this after a "
                      "leader dies")
storage_flags.declare("follower_read_max_ms", 0, MUTABLE,
                      "bounded-staleness follower reads: a follower "
                      "replica may serve device-window reads whose "
                      "staleness is provably under this bound "
                      "(raft_part.read_fence — commit-index fence + "
                      "time lease capped at the election timeout). "
                      "0 disables: every read routes to the leader "
                      "(docs/manual/12-replication.md)")
storage_flags.declare("device_shard_max_ms", 250, MUTABLE,
                      "storaged device-shard staleness budget: a "
                      "local CSR shard whose build version has fallen "
                      "behind the engine's write version keeps "
                      "serving for this long before the part refuses "
                      "to vouch and the read falls back to the row "
                      "scan (docs/manual/13-device-speed.md)")
storage_flags.declare("device_shard_refresh_ms", 50, MUTABLE,
                      "period of the storaged device-shard refresh "
                      "task (rebuild the local CSR shard when the "
                      "engine write version moved; off the raft "
                      "apply path)")
graph_flags.declare("cluster_device_serve", True, MUTABLE,
                    "graphd scatter/gather v2: fan GO windows out to "
                    "per-storaged device partials (device_window RPC) "
                    "instead of leader-routed row scans when the "
                    "engine runs against a remote provider "
                    "(docs/manual/13-device-speed.md)")
meta_flags.declare("expired_threshold_sec", 10 * 60, MUTABLE,
                   "host liveness horizon")
