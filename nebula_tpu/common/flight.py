"""Flight recorder: the daemon notices its own anomalies and captures
the evidence unprompted (docs/manual/10-observability.md).

Every incident artifact this repo produced before — soak bundles,
chaos JSON — existed because a harness asked for it at the right
moment. The flight recorder inverts that: the sites that already
COUNT interesting transitions (breaker trips, shed/admission denials,
leader changes, snapshot poisons, fused-program compiles, deadline
balks) now also RECORD a structured event into a bounded lock-free
ring, and a small set of TRIGGER RULES watches the event stream for
anomalies. When a rule fires, the recorder atomically dumps a BUNDLE
(event ring + stats snapshot + registered collectors such as graphd's
/tpu_stats block and the active-query registry + the last sampled
traces) to disk and the in-memory list served by ``/flight`` — and
auto-arms trace sampling for the next N queries, so the *aftermath*
of the anomaly is captured at full fidelity (the events and exemplar
histograms recorded while armed carry trace ids; the bundle's
``aftermath_events`` section collects them).

Lock-free steady state: `record()` appends to `collections.deque`
rings and draws its seq from `itertools.count` — single C calls,
GIL-atomic, no lock acquired on the hot path (nothing for the
lock-order witness to even see). Only the rare trigger-fire path and
the bounded aftermath window after one take a small lock (bundle
capture + cooldown bookkeeping must not race between two threads
tripping at once).

Trigger catalog (docs/manual/10-observability.md):

  breaker_open      any ``breaker_trip`` event         (immediate)
  snapshot_poison   any ``snapshot_poisoned`` event    (immediate)
  identity_failure  any ``identity_failure`` event     (immediate)
  slo_burn          any ``slo_burn`` event (common/slo.py breach)
  leader_churn      >= 3 ``leader_change`` in 10 s
  shed_storm        >= 20 ``shed``/``admission_denied`` in 5 s
  deadline_storm    >= 10 ``deadline_balk`` in 5 s
  hot_part          any ``hot_part`` event (common/heat.py, gated by
                    ``heat_hot_part_pct``)
  staleness_breach  any ``staleness_breach`` event (kvstore/raftex,
                    gated by ``staleness_breach_ms``)
  replica_divergence  any ``replica_divergence`` or
                    ``snapshot_audit_mismatch`` event — a replica (or
                    device snapshot) whose content digest disagrees
                    with the committed log (common/consistency.py)
  shadow_mismatch   any ``shadow_mismatch`` event — a sampled
                    production serve whose CPU-pipe re-execution
                    returned different rows (common/consistency.py)

Each fire is rate-limited by ``flight_cooldown_s`` per rule, so a
storm produces one bundle, not hundreds.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .flags import (MUTABLE, REBOOT, graph_flags, meta_flags,
                    storage_flags)
from .stats import current_trace_id
from .stats import stats as global_stats

# every daemon serves /flight, so the knobs must be settable through
# every daemon's OWN /flags registry (a standalone storaged's
# WebService serves storage_flags — a graph_flags-only declare would
# make `PUT /flags flight_dir=...` there silently return false)
_REGISTRIES = (graph_flags, storage_flags, meta_flags)


def _flag(name: str, default):
    """First non-default value across the three registries (graph
    first) — in a daemon process only its own registry is ever set
    over HTTP; in-process clusters keep using graph_flags."""
    for reg in _REGISTRIES:
        v = reg.get(name, default)
        if v is not None and v != default:
            return v
    return default

# events appended to a fired bundle AFTER its trigger — the armed-
# sampling aftermath window, sized to comfortably cover the armed
# queries' own events
AFTERMATH_EVENTS = 64


class TriggerRule:
    """One anomaly rule: fire when >= `threshold` events of any of
    `kinds` land within `window_s` seconds (threshold 1 + window 0 =
    immediate)."""

    __slots__ = ("name", "kinds", "threshold", "window_s",
                 "fires", "last_fire_ts")

    def __init__(self, name: str, kinds: Tuple[str, ...],
                 threshold: int = 1, window_s: float = 0.0):
        self.name = name
        self.kinds = tuple(kinds)
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self.fires = 0
        self.last_fire_ts = 0.0

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "kinds": list(self.kinds),
                "threshold": self.threshold, "window_s": self.window_s,
                "fires": self.fires, "last_fire_ts": self.last_fire_ts}


def _default_rules() -> List[TriggerRule]:
    return [
        TriggerRule("breaker_open", ("breaker_trip",)),
        TriggerRule("snapshot_poison", ("snapshot_poisoned",)),
        TriggerRule("identity_failure", ("identity_failure",)),
        TriggerRule("slo_burn", ("slo_burn",)),
        TriggerRule("leader_churn", ("leader_change",), 3, 10.0),
        TriggerRule("shed_storm", ("shed", "admission_denied"), 20, 5.0),
        TriggerRule("deadline_storm", ("deadline_balk",), 10, 5.0),
        # workload & data observatory (common/heat.py): a part
        # dominating its space's heat / a follower past the staleness
        # bound — both flag-gated at the recording site, immediate
        # here, rate-limited by the per-rule cooldown
        TriggerRule("hot_part", ("hot_part",)),
        TriggerRule("staleness_breach", ("staleness_breach",)),
        # consistency observatory (common/consistency.py): a replica
        # or device snapshot whose content digest drifted from the
        # committed log, and a shadow-read identity failure — both
        # immediate (the recording sites already gate on transition /
        # the sampling budget; the per-rule cooldown bounds bundles)
        TriggerRule("replica_divergence",
                    ("replica_divergence", "snapshot_audit_mismatch")),
        TriggerRule("shadow_mismatch", ("shadow_mismatch",)),
        # partition observatory (rpc/transport.py + storage/client.py):
        # a storm of per-peer transport timeouts / health ejections is
        # the network-partition signature — a single straggler stays
        # below threshold, a split or blackholed node does not
        TriggerRule("partition_suspected",
                    ("peer_timeout", "peer_ejected"), 8, 5.0),
        # write-path observatory (common/writepath.py): a change-ring
        # overrun (snapshot consumer must repack), a WAL fsync past
        # fsync_stall_ms, an acked write not device-visible past
        # visibility_stall_ms — all flag-gated/throttled at the
        # recording site, immediate here; the "writepath" collector
        # embeds the snapshot lifecycle ledger in every bundle
        TriggerRule("ring_overrun", ("ring_overrun",)),
        TriggerRule("fsync_stall", ("fsync_stall",)),
        TriggerRule("visibility_stall", ("visibility_stall",)),
    ]


class FlightRecorder:
    """Process-global event ring + trigger engine + bundle store."""

    def __init__(self, ring_size: Optional[int] = None,
                 clock=time.time):
        if ring_size is None:
            ring_size = int(_flag("flight_ring_size", 512) or 512)
        self._clock = clock
        # deque appends are GIL-atomic: the RECORD path takes no lock
        self._ring: "deque[Dict[str, Any]]" = deque(
            maxlen=max(int(ring_size), 16))
        self._kind_ts: Dict[str, deque] = {}
        self._rules = _default_rules()
        self._rules_by_kind: Dict[str, List[TriggerRule]] = {}
        for r in self._rules:
            for k in r.kinds:
                self._rules_by_kind.setdefault(k, []).append(r)
        # guards ONLY cooldown/seq/inflight bookkeeping and the
        # aftermath counter — never held across collectors or disk
        # I/O (see _fire: the recording thread may hold daemon locks)
        self._fire_lock = threading.Lock()
        # serializes disk dumps per recorder (a capture thread and an
        # aftermath-close re-dump must not interleave tmp files)
        self._dump_lock = threading.Lock()
        self._inflight = 0
        self._idle = threading.Event()
        self._idle.set()
        self.bundles: "deque[Dict[str, Any]]" = deque(maxlen=8)
        self._bundle_seq = 0
        # itertools.count: next() is a single C call — atomic under
        # the GIL, unlike `self._n += 1` (the read-modify-write loses
        # increments under thread interleaving)
        self._event_seq = itertools.count(1)
        self._last_seq = 0
        self._aftermath: Optional[Dict[str, Any]] = None
        self._aftermath_left = 0
        # collectors: name -> zero-arg callable returning a JSON-able
        # blob, captured into every bundle (graphd registers its
        # /tpu_stats block + active queries; storaged its raft status)
        self._collectors: Dict[str, Callable[[], Any]] = {}

    # -------------------------------------------------------- wiring
    def add_collector(self, name: str, fn: Callable[[], Any]) -> None:
        """Idempotent by name — re-serving a daemon in one process
        (tests) replaces its collector instead of stacking stale
        closures."""
        self._collectors[name] = fn

    # ------------------------------------------------------ recording
    def record(self, kind: str, trace_id: Optional[str] = None,
               **detail: Any) -> Dict[str, Any]:
        """Append one structured event; evaluates the trigger rules
        watching `kind`. Lock-free on the non-firing path."""
        if trace_id is None:
            trace_id = current_trace_id()
        now = self._clock()
        seq = next(self._event_seq)     # atomic (single C call)
        self._last_seq = seq
        ev: Dict[str, Any] = {"seq": seq, "ts": now, "kind": kind}
        if trace_id:
            ev["trace_id"] = trace_id
        if detail:
            ev.update(detail)
        self._ring.append(ev)
        global_stats.add_value("flight.events", kind="counter")
        if self._aftermath_left > 0:
            self._append_aftermath(ev)
        rules = self._rules_by_kind.get(kind)
        if rules:
            ts = self._kind_ts.get(kind)
            if ts is None:
                ts = self._kind_ts.setdefault(kind, deque(maxlen=256))
            ts.append(now)
            for rule in rules:
                if self._rule_hot(rule, now):
                    self._fire(rule, ev)
        return ev

    def _rule_hot(self, rule: TriggerRule, now: float) -> bool:
        if rule.threshold <= 1:
            return True
        n = 0
        for k in rule.kinds:
            ts = self._kind_ts.get(k)
            if ts is None:
                continue
            # list(deque) is one C call (atomic under the GIL); a
            # Python-level `for t in ts` would raise "deque mutated
            # during iteration" against concurrent recorders
            for t in list(ts):
                if now - t <= rule.window_s:
                    n += 1
        return n >= rule.threshold

    # ------------------------------------------------------- triggers
    def _fire(self, rule: TriggerRule,
              ev: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Fire a rule. Synchronously (cheap, lock-free beyond the
        small fire lock): cooldown bookkeeping, the bundle SKELETON
        (id/trigger/event + the ring snapshot + the live aftermath
        window), the bundles-list publish and the sampling arm.
        Asynchronously (a short-lived capture thread): collectors,
        the stats/trace snapshots and the disk dump — the recording
        thread may hold arbitrary daemon locks (a raft election
        records leader_change under its part lock, a poisoned apply
        under the engine lock) and collectors acquire daemon locks /
        do blocking RPC / disk I/O; running them inline would extend
        those critical sections by the whole capture and let two
        different rules' captures deadlock ABBA across daemon locks.
        Harnesses that read collector fields call `flush()` first.
        Returns the bundle (skeleton, enriched in place), or None when
        the rule was cooling down."""
        cooldown = float(_flag("flight_cooldown_s", 30) or 30)
        with self._fire_lock:
            now = self._clock()
            if now - rule.last_fire_ts < cooldown:
                return None
            rule.last_fire_ts = now
            rule.fires += 1
            self._bundle_seq += 1
            bundle: Dict[str, Any] = {
                "id": self._bundle_seq,
                "ts": now,
                "trigger": rule.name,
                "event": dict(ev),
                "events": list(self._ring),
                "aftermath_events": [],
                "path": None,
            }
            # open the aftermath window NOW: events recorded while the
            # capture thread is still enriching must not be lost
            self._aftermath = bundle
            self._aftermath_left = AFTERMATH_EVENTS
            self._inflight += 1
            self._idle.clear()
        self.bundles.append(bundle)
        global_stats.add_value("flight.triggers." + rule.name,
                               kind="counter")
        # arm the trace head immediately: the aftermath of the anomaly
        # is sampled at full fidelity for the next N queries (their
        # spans, degradation tags and histogram exemplars all carry
        # trace ids the bundle's aftermath events correlate with)
        arm_n = int(_flag("flight_arm_samples", 25) or 0)
        if arm_n > 0:
            from . import tracing
            tracing.tracer.arm(max(tracing.tracer.armed(), arm_n))
        # nlint: disable=NL002 -- one-shot capture worker, not
        # request-scoped work (must NOT inherit the recording
        # thread's context or locks)
        threading.Thread(target=self._capture, args=(bundle,),
                         daemon=True,
                         name=f"flight-capture-{bundle['id']}").start()
        return bundle

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until no capture threads are in flight — harnesses
        (soak bundle attach, bench correlation checks, tests reading
        collector fields) call this before inspecting bundles."""
        return self._idle.wait(timeout)

    def trigger(self, rule_name: str
                ) -> Tuple[Optional[Dict[str, Any]], bool]:
        """Manual fire (the /flight?fire= ops knob). Returns
        (bundle, known): (bundle, True) on a capture, (None, True)
        when the rule exists but is cooling down, (None, False) for an
        unknown rule — the endpoint must not hand back a stale bundle
        as if freshly fired."""
        for rule in self._rules:
            if rule.name == rule_name:
                b = self._fire(rule, {"kind": "manual",
                                      "ts": self._clock(),
                                      "rule": rule_name})
                return b, True
        return None, False

    # -------------------------------------------------------- bundles
    def _capture(self, bundle: Dict[str, Any]) -> None:
        """Enrich + dump one published bundle skeleton. Runs on its
        own short-lived thread (see _fire) — collectors may block on
        daemon locks or RPC, dumps on disk I/O; neither may run on
        (or stall) a recording thread."""
        try:
            bundle["stats"] = global_stats.snapshot()
            for name, fn in list(self._collectors.items()):
                try:
                    bundle.setdefault("collectors", {})[name] = fn()
                except Exception as e:   # broken collector: evidence,
                    bundle.setdefault("collectors", {})[name] = \
                        {"error": repr(e)}   # never a failed capture
            try:
                from . import tracing
                bundle["traces"] = tracing.tracer.ring.list(limit=16)
            except Exception:
                bundle["traces"] = []
            with self._dump_lock:
                bundle["path"] = self._dump(bundle)
        finally:
            with self._fire_lock:
                self._inflight -= 1
                if self._inflight <= 0:
                    self._idle.set()

    def _append_aftermath(self, ev: Dict[str, Any]) -> None:
        # only reached while an aftermath window is open (the 64
        # events after a trigger); the lock guards the counter, the
        # one-time close re-dump runs on its own thread (a record()
        # caller may hold daemon locks — it must never do disk I/O)
        closed = None
        with self._fire_lock:
            bundle = self._aftermath
            if bundle is None:
                return
            bundle["aftermath_events"].append(ev)
            self._aftermath_left -= 1
            if self._aftermath_left <= 0:
                self._aftermath = None
                closed = bundle
                self._inflight += 1
                self._idle.clear()
        if closed is not None:
            # nlint: disable=NL002 -- one-shot re-dump worker, not
            # request-scoped work
            threading.Thread(target=self._close_dump, args=(closed,),
                             daemon=True,
                             name="flight-redump").start()

    def _close_dump(self, bundle: Dict[str, Any]) -> None:
        try:
            with self._dump_lock:   # serialize vs the capture thread
                bundle["path"] = self._dump(bundle) \
                    or bundle.get("path")
        finally:
            with self._fire_lock:
                self._inflight -= 1
                if self._inflight <= 0:
                    self._idle.set()

    def _dump(self, bundle: Dict[str, Any]) -> Optional[str]:
        """Atomic disk dump (tmp + rename) under `flight_dir`; None
        (in-memory only) when the flag is unset."""
        d = str(_flag("flight_dir", "") or "")
        if not d:
            return None
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight-{bundle['id']:04d}-{bundle['trigger']}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(bundle, f, default=str)
            os.replace(tmp, path)
            return path
        except OSError:
            return None

    # ---------------------------------------------------- observation
    def describe(self, limit: int = 100) -> Dict[str, Any]:
        """The /flight endpoint body: recent events newest-first,
        trigger rule states, bundle summaries."""
        events = list(self._ring)
        return {
            "event_count": self._last_seq,
            "ring": len(events),
            "events": list(reversed(events))[:max(int(limit), 1)],
            "triggers": [r.describe() for r in self._rules],
            "bundles": [{"id": b["id"], "ts": b["ts"],
                         "trigger": b["trigger"],
                         "events": len(b["events"]),
                         "aftermath_events": len(b["aftermath_events"]),
                         "path": b.get("path")}
                        for b in self.bundles],
        }

    def get_bundle(self, bundle_id: int) -> Optional[Dict[str, Any]]:
        for b in self.bundles:
            if b["id"] == int(bundle_id):
                return b
        return None

    def last_bundle(self) -> Optional[Dict[str, Any]]:
        return self.bundles[-1] if self.bundles else None

    def gauges(self) -> Dict[str, float]:
        """Flat /metrics gauges (per-fire counters additionally stream
        through the StatsManager as flight.triggers.<rule>)."""
        out = {"flight.ring_events": float(len(self._ring)),
               "flight.bundles": float(len(self.bundles))}
        for r in self._rules:
            out[f"flight.rule_fires.{r.name}"] = float(r.fires)
        return out

    def reset(self) -> None:
        """Test/bench isolation: clear events, bundles and rule state
        (the process-global stats counters are left alone)."""
        self._ring.clear()
        self._kind_ts.clear()
        self.bundles.clear()
        self._aftermath = None
        self._aftermath_left = 0
        self._event_seq = itertools.count(1)
        self._last_seq = 0
        for r in self._rules:
            r.fires = 0
            r.last_fire_ts = 0.0


# declared on EVERY registry: each daemon's /flags serves only its
# own (graph/storage/meta), and all three daemons run the recorder
for _reg in _REGISTRIES:
    _reg.declare(
        "flight_ring_size", 512, REBOOT,
        "flight-recorder event ring size (recent structured anomaly "
        "events served by /flight and captured into bundles)")
    _reg.declare(
        "flight_cooldown_s", 30, MUTABLE,
        "per-rule flight-recorder trigger cooldown: one bundle per "
        "rule per this many seconds, however hard the storm")
    _reg.declare(
        "flight_arm_samples", 25, MUTABLE,
        "queries force-sampled after a flight trigger fires (the "
        "aftermath is captured at full trace fidelity; 0 disables)")
    _reg.declare(
        "flight_dir", "", MUTABLE,
        "directory flight bundles are atomically dumped to on "
        "trigger (empty = in-memory /flight only)")

# process-global instance (the stats/tracer/faults singleton idiom)
recorder = FlightRecorder()
