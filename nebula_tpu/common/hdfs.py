"""HDFS helper: stage bulk-load files from a remote or local source.

Role parity with the reference's `common/hdfs/HdfsCommandHelper.cpp:
13-40`, which shells out to the `hdfs dfs` CLI for ls/copyToLocal. URLs
beginning with hdfs:// go through the CLI when it exists; plain paths
are treated as local directories (the test/bench path — also what a
mounted NFS/GCS-fuse volume looks like in a TPU pod)."""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Tuple

from .status import ErrorCode, Status


class HdfsHelper:
    def __init__(self, hdfs_bin: str = "hdfs"):
        self.hdfs_bin = hdfs_bin

    def available(self) -> bool:
        return shutil.which(self.hdfs_bin) is not None

    # ------------------------------------------------------------------
    def ls(self, url: str) -> Tuple[Status, List[str]]:
        if url.startswith("hdfs://"):
            if not self.available():
                return (Status.error(ErrorCode.E_EXECUTION_ERROR,
                                     "hdfs CLI not available"), [])
            r = subprocess.run([self.hdfs_bin, "dfs", "-ls", "-C", url],
                               capture_output=True, text=True)
            if r.returncode != 0:
                return (Status.error(ErrorCode.E_EXECUTION_ERROR,
                                     r.stderr.strip()), [])
            return Status.OK(), [l for l in r.stdout.splitlines() if l]
        if not os.path.isdir(url):
            return (Status.error(ErrorCode.E_EXECUTION_ERROR,
                                 f"{url}: not a directory"), [])
        return Status.OK(), sorted(
            os.path.join(url, f) for f in os.listdir(url))

    # ------------------------------------------------------------------
    def copy_to_local(self, url: str, dest_dir: str,
                      names: List[str] = None) -> Status:
        """Stage files under `url` into dest_dir (ref: the per-part
        `/download` handler pulling SSTs before INGEST). With `names`,
        only those file names are staged — each storaged pulls ITS OWN
        parts' SSTs, so an N-host cluster downloads the dataset once in
        aggregate instead of N times (the Spark generator's per-part
        download posture, StorageHttpDownloadHandler)."""
        os.makedirs(dest_dir, exist_ok=True)
        if url.startswith("hdfs://"):
            if not self.available():
                return Status.error(ErrorCode.E_EXECUTION_ERROR,
                                    "hdfs CLI not available")
            base = url.rstrip("/")
            if names:
                # filter to names that EXIST: an empty partition
                # legitimately produced no SST file (the generator
                # skips zero-row parts and ingest tolerates absence) —
                # an explicit copy of a missing source must not fail
                # the whole DOWNLOAD
                st, files = self.ls(base)
                if not st.ok():
                    return st
                have = {f.rsplit("/", 1)[-1] for f in files}
                srcs = [f"{base}/{n}" for n in names if n in have]
                if not srcs:
                    return Status.OK()
            else:
                srcs = [base + "/*"]
            r = subprocess.run(
                [self.hdfs_bin, "dfs", "-copyToLocal", "-f",
                 *srcs, dest_dir],
                capture_output=True, text=True)
            if r.returncode != 0:
                return Status.error(ErrorCode.E_EXECUTION_ERROR,
                                    r.stderr.strip())
            return Status.OK()
        st, files = self.ls(url)
        if not st.ok():
            return st
        want = set(names) if names else None
        for f in files:
            if os.path.isfile(f) and \
                    (want is None or os.path.basename(f) in want):
                shutil.copy2(f, dest_dir)
        return Status.OK()
