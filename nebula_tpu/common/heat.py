"""Workload & data observatory: per-part heat accounting, hot-vertex
sketches and skew indices (docs/manual/10-observability.md, "Workload
& data observatory").

PRs 10-13 made the *process* observable; the *data and workload* were
still dark: nothing could answer "which parts are hot, which vertices
are hubs, how stale is each follower, where should data live?" — the
inputs placement decisions (ROADMAP items 1/2/5) need. This module is
the shared core all three daemons feed:

PART HEAT SLABS — per-(space, part) accumulators with 60 s / 600 s
rolling windows plus lifetime totals, charged at the seams that
already charge the PR 12 cost ledger:

  reads / rows_scanned / bytes_returned   storage/processors.py
                                          (server-side, real parts)
  writes                                  storage/processors.py
                                          mutation handlers, per part
  device_us                               TpuGraphEngine._record_profile
                                          (graphd; attributed to the
                                          parts of the serving query's
                                          start vids — coalesced-window
                                          riders land on the LEADER's
                                          parts, same attributed-time
                                          discipline as the ledger)
  raft_appends                            kvstore/raftex/raft_part.py
                                          leader append path

One scalar HEAT SCORE (documented weights below) ranks parts/hosts;
the per-space SKEW INDEX is the p99-to-mean score ratio across that
space's parts — ~1.0 under uniform load, growing with concentration —
an SLO-able gauge (`nebula_heat_skew_index_s<sid>`).

HOT-VERTEX SKETCH — a bounded space-saving top-K sketch per space over
frontier start vids (graphd) + scanned src vids (storaged). Classic
Metwally et al. guarantees: with K counters over N observations every
reported count overestimates by at most its recorded `err`, and any
vid with true frequency > N/K is present. Disarmed (heat_vertices_k=0,
the default) the observe path is a single flag read.

Steady-state cost when armed: dict lookup + float adds under a
per-slab lock per charge; the whole observatory disarms via the
MUTABLE `heat_enabled` flag — disarmed, every charge site is one flag
read and /metrics is byte-identical to a heat-free build (the
profile_hz=0 idiom).

FLIGHT TRIGGERS — a part drawing more than `heat_hot_part_pct` percent
of its space's 60 s heat (flag-gated, time-throttled evaluation)
records a `hot_part` event; kvstore/raftex records `staleness_breach`
past `staleness_breach_ms`. Both are immediate flight-recorder rules,
and every bundle embeds the /heat capture via the collector registered
below.
"""
from __future__ import annotations

import contextvars
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .flags import MUTABLE, graph_flags, meta_flags, storage_flags
from .stats import stats as global_stats

# accounting fields, the order /heat and the heartbeat payload use
FIELDS: Tuple[str, ...] = ("reads", "writes", "rows_scanned",
                           "bytes_returned", "device_us",
                           "raft_appends")

# heat-score weights: one scalar so parts/hosts rank on a single axis.
# Reads and writes count as one unit of serving work; bulk byte/row/
# microsecond streams are scaled so one "unit" is roughly one row-level
# storage touch (100 rows scanned ~ 1 read, 4 KiB returned ~ 1 read,
# 1 ms of device time ~ 1 read, 1 raft append ~ 1 write).
SCORE_WEIGHTS: Dict[str, float] = {
    "reads": 1.0,
    "writes": 2.0,
    "rows_scanned": 0.01,
    "bytes_returned": 1.0 / 4096.0,
    "device_us": 0.001,
    "raft_appends": 2.0,
}

# rolling-window geometry: 60 buckets of 10 s = 600 s of history; the
# 60 s window reads the newest 6 buckets
BUCKET_SECS = 10
N_BUCKETS = 60
WINDOWS = (60, 600)

# at most this many distinct start vids are part-attributed per query
# (a piped GO can fan out thousands; the sample keeps entry-seam cost
# bounded while the part histogram stays representative)
QUERY_PART_SAMPLE = 128

# hot-part evaluation is time-throttled per space: the trigger check
# is O(parts), so it runs at most once per this many seconds per space
HOT_PART_CHECK_SECS = 5.0
# a space must carry at least this much 60s heat before a dominant
# part is an anomaly (an idle space's single touched part is 100%)
HOT_PART_MIN_SCORE = 50.0

# every daemon charges heat and serves /heat knobs via its OWN /flags
# registry (the flight/profiler multi-registry idiom)
_REGISTRIES = (graph_flags, storage_flags, meta_flags)
for _reg in _REGISTRIES:
    _reg.declare(
        "heat_enabled", True, MUTABLE,
        "workload & data observatory master switch: per-(space,part) "
        "heat accounting (/heat, nebula_part_heat_* families), "
        "heartbeat-carried placement telemetry AND the replica-"
        "staleness metric families (nebula_raftex_staleness_ms + "
        "per-part gauges; the /raft watermarks themselves stay); "
        "off = every charge site is one flag read and /metrics is "
        "byte-identical to a heat-free build")
    _reg.declare(
        "heat_vertices_k", 0, MUTABLE,
        "hot-vertex space-saving sketch size per space (top-K over "
        "frontier start vids + scanned src vids; /heat?vertices=1); "
        "0 disarms the sketch entirely (one flag read per query)")
    _reg.declare(
        "heat_hot_part_pct", 0, MUTABLE,
        "flight-recorder hot_part trigger: fire when one part draws "
        "more than this percent of its space's 60s heat (evaluated at "
        "most every 5s per space); 0 disarms")
    _reg.declare(
        "staleness_breach_ms", 0, MUTABLE,
        "flight-recorder staleness_breach trigger: a follower whose "
        "estimated replica staleness exceeds this many ms records a "
        "breach event on the leader (kvstore/raftex); 0 disarms")


def _flag(name: str, default):
    """First non-default value across the registries (graph first) —
    the flight-recorder idiom: a daemon process sets only its own
    registry over HTTP, in-process harnesses use graph_flags."""
    for reg in _REGISTRIES:
        v = reg.get(name, default)
        if v is not None and v != default:
            return v
    return default


def enabled() -> bool:
    return bool(_flag("heat_enabled", True))


def score_of(fields: Dict[str, float]) -> float:
    return sum(SCORE_WEIGHTS[f] * fields.get(f, 0.0) for f in FIELDS)


# field name -> slab index (hot charge path — FIELDS.index is O(n))
_FIDX: Dict[str, int] = {f: i for i, f in enumerate(FIELDS)}


class SpaceSaving:
    """Bounded space-saving top-K frequency sketch (Metwally et al.):
    `k` counters, each (count, err). On overflow the minimum counter
    is evicted and the newcomer inherits its count as both floor and
    error bound — every reported count is within `err` of truth, and
    any item with true frequency > total/k is guaranteed present.

    Eviction finds the minimum through a lazy-deletion heap of
    (count, vid) entries (stale entries skipped on pop, heap rebuilt
    past 4k entries) — O(log k) amortized per unseen vid instead of
    an O(k) min() scan under the per-space lock every serving thread
    shares (a cold high-cardinality scan stream evicts on every
    observation)."""

    __slots__ = ("k", "counts", "total", "evictions", "_lock", "_heap")

    def __init__(self, k: int):
        self.k = max(int(k), 1)
        # vid -> [count, err]
        self.counts: Dict[int, List[float]] = {}
        self.total = 0.0
        self.evictions = 0
        self._lock = threading.Lock()
        self._heap: List[Tuple[float, int]] = []

    def observe(self, vid: int, w: float = 1.0) -> None:
        with self._lock:
            self._observe_locked(int(vid), float(w))

    def observe_many(self, vids: Sequence[int], w: float = 1.0) -> None:
        with self._lock:
            for v in vids:
                self._observe_locked(int(v), float(w))

    def _observe_locked(self, vid: int, w: float) -> None:
        import heapq
        self.total += w
        c = self.counts.get(vid)
        if c is not None:
            c[0] += w
            heapq.heappush(self._heap, (c[0], vid))
            return
        if len(self.counts) < self.k:
            self.counts[vid] = [w, 0.0]
            heapq.heappush(self._heap, (w, vid))
            return
        # evict the minimum counter; the newcomer inherits its count
        # (cardinality cap: the dict NEVER exceeds k entries). Heap
        # entries are stale once their counter was bumped or evicted
        # — the top is valid only when it matches the live count.
        mc = None
        while self._heap:
            hc, hv = self._heap[0]
            cur = self.counts.get(hv)
            if cur is not None and cur[0] == hc:
                heapq.heappop(self._heap)
                del self.counts[hv]
                mc = hc
                break
            heapq.heappop(self._heap)
        if mc is None:      # heap starved (all stale): full rescan
            hv = min(self.counts, key=lambda x: self.counts[x][0])
            mc = self.counts.pop(hv)[0]
        self.counts[vid] = [mc + w, mc]
        heapq.heappush(self._heap, (mc + w, vid))
        self.evictions += 1
        if len(self._heap) > 4 * self.k:
            self._heap = [(c[0], v) for v, c in self.counts.items()]
            heapq.heapify(self._heap)

    def topk(self, n: Optional[int] = None) -> List[Dict[str, float]]:
        with self._lock:
            items = sorted(self.counts.items(),
                           key=lambda kv: kv[1][0], reverse=True)
        if n is not None:
            items = items[:int(n)]
        return [{"vid": v, "count": c[0], "err": c[1]} for v, c in items]

    def describe(self) -> Dict[str, Any]:
        return {"k": self.k, "tracked": len(self.counts),
                "total": self.total, "evictions": self.evictions,
                "top": self.topk(16)}


class _Slab:
    """One (space, part)'s accumulators: lifetime totals + a ring of
    10 s buckets covering 600 s, advanced lazily on charge/read."""

    __slots__ = ("lock", "life", "ring", "head")

    def __init__(self, now_bucket: int):
        self.lock = threading.Lock()
        self.life = [0.0] * len(FIELDS)
        self.ring = [None] * N_BUCKETS     # lazily allocated lists
        self.head = now_bucket

    def _advance(self, now_bucket: int) -> None:
        gap = now_bucket - self.head
        if gap <= 0:
            return
        for k in range(1, min(gap, N_BUCKETS) + 1):
            self.ring[(self.head + k) % N_BUCKETS] = None
        self.head = now_bucket

    def add(self, now_bucket: int, idx_vals) -> None:
        with self.lock:
            self._advance(now_bucket)
            b = self.ring[now_bucket % N_BUCKETS]
            if b is None:
                b = self.ring[now_bucket % N_BUCKETS] = [0.0] * len(FIELDS)
            for i, v in idx_vals:
                b[i] += v
                self.life[i] += v

    def window(self, now_bucket: int, secs: int) -> List[float]:
        n = max(1, min(secs // BUCKET_SECS, N_BUCKETS))
        out = [0.0] * len(FIELDS)
        with self.lock:
            self._advance(now_bucket)
            for k in range(n):
                b = self.ring[(now_bucket - k) % N_BUCKETS]
                if b is None:
                    continue
                for i in range(len(FIELDS)):
                    out[i] += b[i]
        return out

    def lifetime(self) -> List[float]:
        with self.lock:
            return list(self.life)


class HeatAccountant:
    """Process-global heat registry (instantiable for tests): slabs
    per (space, part), hot-vertex sketches per space, and the derived
    skew / hot-part / heartbeat / Prometheus views."""

    def __init__(self, clock=time.time):
        self._clock = clock
        self._slabs: Dict[Tuple[int, int], _Slab] = {}
        self._sketches: Dict[int, SpaceSaving] = {}
        self._lock = threading.Lock()       # slab/sketch creation only
        self._hot_checked: Dict[int, float] = {}

    # ------------------------------------------------------------ charge
    def _slab(self, space: int, part: int) -> _Slab:
        key = (int(space), int(part))
        s = self._slabs.get(key)
        if s is None:
            with self._lock:
                s = self._slabs.setdefault(
                    key, _Slab(int(self._clock()) // BUCKET_SECS))
        return s

    def charge(self, space: int, part: int, **fields: float) -> None:
        """Bump one part's slab (one flag read when disarmed)."""
        if not enabled():
            return
        now = self._clock()
        iv = [(_FIDX[f], float(v)) for f, v in fields.items() if v]
        if iv:
            self._slab(space, part).add(int(now) // BUCKET_SECS, iv)
            self._maybe_hot_part(int(space), now)

    def charge_parts(self, space: int, parts: Sequence[int],
                     **fields: float) -> None:
        """Split a charge evenly across `parts` (device-time
        attribution from a query's start-vid parts)."""
        if not parts or not enabled():
            return
        share = 1.0 / len(parts)
        now = self._clock()
        nb = int(now) // BUCKET_SECS
        iv = [(_FIDX[f], float(v) * share)
              for f, v in fields.items() if v]
        if iv:
            for p in parts:
                self._slab(space, p).add(nb, iv)
        self._maybe_hot_part(int(space), now)

    # ------------------------------------------------- hot-vertex sketch
    def observe_vids(self, space: int, vids: Sequence[int]) -> None:
        """Feed the per-space sketch (frontier start vids on graphd,
        scanned src vids on storaged). Disarmed (heat_vertices_k=0 or
        heat off) this is one or two flag reads and no allocation."""
        k = int(_flag("heat_vertices_k", 0) or 0)
        if k <= 0 or not vids or not enabled():
            return
        sk = self._sketches.get(int(space))
        if sk is None or sk.k != k:
            with self._lock:
                sk = self._sketches.get(int(space))
                if sk is None or sk.k != k:
                    sk = self._sketches[int(space)] = SpaceSaving(k)
        sk.observe_many(vids)
        global_stats.add_value("heat.sketch.observed", len(vids),
                               kind="counter")

    def sketch(self, space: int) -> Optional[SpaceSaving]:
        return self._sketches.get(int(space))

    # ------------------------------------------------------------- reads
    def _slab_items(self) -> List[Tuple[Tuple[int, int], _Slab]]:
        """Point-in-time (key, slab) list — readers must not iterate
        the live dict while serving threads insert new slabs."""
        with self._lock:
            return list(self._slabs.items())

    def parts_snapshot(self) -> List[Dict[str, Any]]:
        """Every known (space, part) with its 60s/600s/lifetime fields
        and scores — the /heat body."""
        nb = int(self._clock()) // BUCKET_SECS
        out = []
        for (space, part), slab in sorted(self._slab_items()):
            row: Dict[str, Any] = {"space": space, "part": part}
            for secs in WINDOWS:
                w = slab.window(nb, secs)
                row[f"{secs}s"] = dict(zip(FIELDS, w))
                row[f"score_{secs}s"] = round(
                    score_of(row[f"{secs}s"]), 3)
            row["life"] = dict(zip(FIELDS, slab.lifetime()))
            row["score_life"] = round(score_of(row["life"]), 3)
            out.append(row)
        return out

    def space_scores(self, window: int = 600) -> Dict[int, Dict[int, float]]:
        """{space: {part: score}} over the trailing window."""
        nb = int(self._clock()) // BUCKET_SECS
        out: Dict[int, Dict[int, float]] = {}
        for (space, part), slab in self._slab_items():
            out.setdefault(space, {})[part] = score_of(
                dict(zip(FIELDS, slab.window(nb, window))))
        return out

    @staticmethod
    def _skew_of(part_scores: Dict[int, float]) -> Dict[str, float]:
        scores = sorted(part_scores.values())
        n = len(scores)
        if n == 0 or sum(scores) <= 0:
            return {"index": 0.0, "p99": 0.0, "mean": 0.0, "parts": n}
        mean = sum(scores) / n
        p99 = scores[min(n - 1, max(0, int(-(-n * 99 // 100)) - 1))]
        return {"index": round(p99 / mean, 4), "p99": round(p99, 3),
                "mean": round(mean, 3), "parts": n}

    def skew_index(self, space: int,
                   window: int = 600) -> Dict[str, float]:
        """p99-to-mean heat-score ratio across one space's parts:
        ~1.0 uniform, growing with concentration. Parts a space has
        but never touched contribute zero heat only once ANY slab for
        them exists — callers wanting exact part counts pass them via
        the /heat surface; the index is about relative concentration
        among serving parts."""
        return self._skew_of(self.space_scores(window)
                             .get(int(space), {}))

    def skew_indices(self, window: int = 600) -> Dict[int, Dict[str, float]]:
        # ONE slab walk for every space's index — this runs on every
        # /metrics scrape (gauges) and /heat request
        return {s: self._skew_of(parts)
                for s, parts in self.space_scores(window).items()}

    # ------------------------------------------------ heartbeat payload
    def heartbeat_payload(self, lead_parts: Optional[Dict[int, List[int]]]
                          = None) -> Optional[Dict[str, Any]]:
        """The additive heartbeat field storaged carries to metad
        (meta/client.py heat_source): per-(space, part) 600s window
        fields + score. `lead_parts` restricts to parts this node
        LEADS (the authoritative copy — every replica serves reads of
        parts it leads, so summing leader payloads never double-counts
        a part). None when heat is disarmed (the heartbeat then
        carries no heat field at all)."""
        if not enabled():
            return None
        nb = int(self._clock()) // BUCKET_SECS
        parts: Dict[int, Dict[int, Dict[str, float]]] = {}
        for (space, part), slab in self._slab_items():
            if lead_parts is not None and \
                    part not in (lead_parts.get(space) or ()):
                continue
            w = dict(zip(FIELDS, slab.window(nb, 600)))
            w["score"] = round(score_of(w), 3)
            parts.setdefault(space, {})[part] = w
        return {"parts": parts}

    # ------------------------------------------------------ hot-part eval
    def check_hot_part(self, space: int) -> None:
        """Force one hot_part evaluation for `space`, bypassing the
        time throttle (harness/ops seam — the charge path goes through
        the throttled _maybe_hot_part)."""
        self._hot_checked.pop(int(space), None)
        self._maybe_hot_part(int(space), self._clock())

    def _maybe_hot_part(self, space: int, now: float) -> None:
        """Flag-gated, time-throttled hot_part trigger evaluation:
        at most once per HOT_PART_CHECK_SECS per space, O(parts).
        Throttle FIRST — one dict read per charge in the steady
        state, the flag consulted only once per window."""
        last = self._hot_checked.get(space, 0.0)
        if now - last < HOT_PART_CHECK_SECS:
            return
        self._hot_checked[space] = now
        pct = float(_flag("heat_hot_part_pct", 0) or 0)
        if pct <= 0:
            return
        scores = self.space_scores(60).get(space)
        if not scores:
            return
        total = sum(scores.values())
        if total < HOT_PART_MIN_SCORE:
            return
        part, top = max(scores.items(), key=lambda kv: kv[1])
        share = 100.0 * top / total
        if share > pct:
            from .flight import recorder
            recorder.record("hot_part", space=space, part=part,
                            share=round(share, 1),
                            score=round(top, 1),
                            space_score=round(total, 1))

    # ------------------------------------------------------- /metrics
    def gauges(self) -> Dict[str, float]:
        """Flat /metrics source: `nebula_part_heat_s<sid>_p<pid>_<f>`
        60s-window families + per-part scores + per-space skew
        indices. Empty (zero families — byte-identical /metrics) when
        disarmed."""
        if not enabled():
            return {}
        nb = int(self._clock()) // BUCKET_SECS
        out: Dict[str, float] = {}
        for (space, part), slab in sorted(self._slab_items()):
            w = slab.window(nb, 60)
            base = f"part_heat.s{space}.p{part}"
            for i, f in enumerate(FIELDS):
                out[f"{base}.{f}"] = w[i]
            out[f"{base}.score"] = round(
                score_of(dict(zip(FIELDS, w))), 3)
        for space, sk in self.skew_indices(600).items():
            out[f"heat.skew_index.s{space}"] = sk["index"]
        return out

    # ---------------------------------------------------------- surface
    def describe(self, vertices: bool = False) -> Dict[str, Any]:
        """The /heat endpoint body (shared by graphd + storaged;
        daemons merge their extras — staleness, degree stats)."""
        out: Dict[str, Any] = {
            "enabled": enabled(),
            "fields": list(FIELDS),
            "score_weights": dict(SCORE_WEIGHTS),
            "parts": self.parts_snapshot(),
            "skew": {str(s): v
                     for s, v in self.skew_indices(600).items()},
        }
        if vertices:
            k = int(_flag("heat_vertices_k", 0) or 0)
            with self._lock:
                sketches = list(self._sketches.items())
            out["vertices"] = {
                "k": k,
                "spaces": {str(s): sk.describe() for s, sk in sketches},
            }
        return out

    def capture(self) -> Dict[str, Any]:
        """The flight-bundle collector body: the /heat view including
        sketches, captured at trigger time."""
        return self.describe(vertices=True)

    def drop_space(self, space: int) -> None:
        """Forget a dropped space's slabs/sketch — without this a
        long-running daemon's /metrics would keep scraping dead
        nebula_part_heat_* families forever (storaged calls it from
        the space_removed topology event)."""
        space = int(space)
        with self._lock:
            for key in [k for k in self._slabs if k[0] == space]:
                del self._slabs[key]
            self._sketches.pop(space, None)
            self._hot_checked.pop(space, None)

    def reset(self) -> None:
        """Test/bench isolation (phase boundaries): drop every slab,
        sketch and hot-part throttle."""
        with self._lock:
            self._slabs.clear()
            self._sketches.clear()
            self._hot_checked.clear()


# ----------------------------------------------------------------------
# device-time attribution note: the engine entry seam records WHICH
# parts the query's start vids live in; _record_profile (which only
# knows stage timings) charges device_us against the note. ContextVar,
# like the ledger — but deliberately NOT re-pointed by the dispatcher:
# a coalesced window's riders charge the LEADER's parts (same space,
# attributed time — see the module docstring).
# ----------------------------------------------------------------------
_note: contextvars.ContextVar[Optional[Tuple[int, Tuple[int, ...]]]] = \
    contextvars.ContextVar("nebula_heat_note", default=None)


def observe_query(space: int, starts: Sequence[int],
                  num_parts: int):
    """Engine entry seam (execute_go / aggregate / find_path): charge
    one read per start-vid part, feed the hot-vertex sketch, and note
    the parts for device-time attribution. Returns the note token to
    hand back to `restore` (None when disarmed — one flag read)."""
    if not enabled():
        return None
    if not starts or num_parts <= 0:
        return None
    from .keys import part_id
    sample = starts[:QUERY_PART_SAMPLE]
    parts: Dict[int, int] = {}
    for v in sample:
        p = part_id(int(v), num_parts)
        parts[p] = parts.get(p, 0) + 1
    scale = len(starts) / len(sample)
    for p, n in parts.items():
        accountant.charge(space, p, reads=n * scale)
    accountant.observe_vids(space, sample)
    return _note.set((int(space), tuple(parts)))


def restore(token) -> None:
    if token is not None:
        _note.reset(token)


def charge_device(us: float) -> None:
    """Charge device microseconds against the noted parts (one
    ContextVar read when no query noted parts; one flag read when heat
    is disarmed — checked inside charge_parts)."""
    note = _note.get()
    if note is not None:
        accountant.charge_parts(note[0], note[1], device_us=us)


# process-global instance (the stats/flight/profiler singleton idiom)
accountant = HeatAccountant()

# every flight bundle embeds the workload view at trigger time — the
# recorder is process-global and collectors are idempotent by name
from .flight import recorder as _flight_recorder  # noqa: E402

_flight_recorder.add_collector("heat", accountant.capture)
