"""Graph key codec: how vertices/edges map onto ordered KV keys.

Role parity with the reference's `common/base/NebulaKeyUtils.{h,cpp}`
(vertex key = type+part+vid+tag+version; edge key = type+part+src+etype+
rank+dst+version, ref NebulaKeyUtils.h:14-21) — but a fresh layout
designed for prefix-scan locality:

  vertex : [part u32][0x01][vid i64*][tag i32*][ver u64]
  edge   : [part u32][0x02][src i64*][etype i32*][rank i64*][dst i64*][ver u64]
  system : [part u32][0x00][subkey u8]
  uuid   : [part u32][0x03][name bytes]
  index  : [part u32][0x04][...]

All fields big-endian; signed fields (*) are stored with the sign bit
flipped so that byte order == numeric order (the reference relies on
int64 keys already being non-negative instead). The version field is
`UINT64_MAX - now_micros` so the *newest* write sorts first within a
(vid,tag) / (src,etype,rank,dst) group, matching the reference's
decreasing time-based version trick (ref: storage/AddVerticesProcessor
.cpp:32-35). In-edges are stored under the destination's partition with
a negated edge type, mirroring the reference's +/- edge type convention.
"""
from __future__ import annotations

import struct
import time
from typing import Optional, Tuple

KIND_SYSTEM = 0x00
KIND_VERTEX = 0x01
KIND_EDGE = 0x02
KIND_UUID = 0x03
KIND_INDEX = 0x04

_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")
_I64_BIAS = 1 << 63
_I32_BIAS = 1 << 31
_U64_MAX = (1 << 64) - 1


def _i64(v: int) -> bytes:
    """Order-preserving encoding of a signed 64-bit int."""
    return _U64.pack((v + _I64_BIAS) & _U64_MAX)


def _d64(b: bytes) -> int:
    return _U64.unpack(b)[0] - _I64_BIAS


def _i32(v: int) -> bytes:
    return _U32.pack((v + _I32_BIAS) & 0xFFFFFFFF)


def _d32(b: bytes) -> int:
    return _U32.unpack(b)[0] - _I32_BIAS


def now_version() -> int:
    """Decreasing, time-based version: newest sorts first."""
    return _U64_MAX - time.time_ns() // 1000


# --------------------------------------------------------------------------
# vertex keys
# --------------------------------------------------------------------------

def vertex_key(part: int, vid: int, tag_id: int, version: Optional[int] = None) -> bytes:
    if version is None:
        version = now_version()
    return _U32.pack(part) + bytes([KIND_VERTEX]) + _i64(vid) + _i32(tag_id) + _U64.pack(version)


def vertex_prefix(part: int, vid: int, tag_id: Optional[int] = None) -> bytes:
    p = _U32.pack(part) + bytes([KIND_VERTEX]) + _i64(vid)
    if tag_id is not None:
        p += _i32(tag_id)
    return p


def parse_vertex_key(key: bytes) -> Tuple[int, int, int, int]:
    """-> (part, vid, tag_id, version)."""
    part = _U32.unpack(key[0:4])[0]
    vid = _d64(key[5:13])
    tag = _d32(key[13:17])
    ver = _U64.unpack(key[17:25])[0]
    return part, vid, tag, ver


# --------------------------------------------------------------------------
# edge keys
# --------------------------------------------------------------------------

def edge_key(part: int, src: int, edge_type: int, rank: int, dst: int,
             version: Optional[int] = None) -> bytes:
    if version is None:
        version = now_version()
    return (_U32.pack(part) + bytes([KIND_EDGE]) + _i64(src) + _i32(edge_type)
            + _i64(rank) + _i64(dst) + _U64.pack(version))


def edge_prefix(part: int, src: int, edge_type: Optional[int] = None) -> bytes:
    p = _U32.pack(part) + bytes([KIND_EDGE]) + _i64(src)
    if edge_type is not None:
        p += _i32(edge_type)
    return p


def edge_group_prefix(part: int, src: int, edge_type: int, rank: int, dst: int) -> bytes:
    """Prefix identifying one logical edge (all versions)."""
    return (_U32.pack(part) + bytes([KIND_EDGE]) + _i64(src) + _i32(edge_type)
            + _i64(rank) + _i64(dst))


def parse_edge_key(key: bytes) -> Tuple[int, int, int, int, int, int]:
    """-> (part, src, edge_type, rank, dst, version)."""
    part = _U32.unpack(key[0:4])[0]
    src = _d64(key[5:13])
    etype = _d32(key[13:17])
    rank = _d64(key[17:25])
    dst = _d64(key[25:33])
    ver = _U64.unpack(key[33:41])[0]
    return part, src, etype, rank, dst, ver


def is_vertex_key(key: bytes) -> bool:
    return len(key) >= 5 and key[4] == KIND_VERTEX


def is_edge_key(key: bytes) -> bool:
    return len(key) >= 5 and key[4] == KIND_EDGE


# --------------------------------------------------------------------------
# part-level prefixes & system keys
# --------------------------------------------------------------------------

def part_prefix(part: int) -> bytes:
    return _U32.pack(part)

def part_data_prefix(part: int, kind: int) -> bytes:
    return _U32.pack(part) + bytes([kind])


def system_commit_key(part: int) -> bytes:
    """Persists (last committed log id, term) transactionally with data
    (ref: kvstore/Part.cpp:350-356)."""
    return _U32.pack(part) + bytes([KIND_SYSTEM, 0x01])


def system_balance_key(part: int) -> bytes:
    return _U32.pack(part) + bytes([KIND_SYSTEM, 0x02])


def uuid_key(part: int, name: bytes) -> bytes:
    return _U32.pack(part) + bytes([KIND_UUID]) + name


def encode_commit_value(log_id: int, term: int) -> bytes:
    return struct.pack(">qq", log_id, term)


def decode_commit_value(v: bytes) -> Tuple[int, int]:
    return struct.unpack(">qq", v)


# --------------------------------------------------------------------------
# partitioner
# --------------------------------------------------------------------------

def hash_vid(vid: int) -> int:
    """64-bit mix hash (splitmix64 finalizer) — used for UUID allocation
    and bucket spreading, NOT for partition routing (see part_id)."""
    x = vid & _U64_MAX
    x = (x + 0x9E3779B97F4A7C15) & _U64_MAX
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _U64_MAX
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _U64_MAX
    return x ^ (x >> 31)


def part_id(vid: int, num_parts: int) -> int:
    """Partition ids are 1-based. Plain uint64-cast modulo, matching the
    reference exactly (`static_cast<uint64_t>(id) % numShards + 1`, ref:
    storage/client/StorageClient.cpp:10-11) — no hashing, which also keeps
    the on-device owner-partition computation a single cheap `vid % P`.
    """
    return (vid & _U64_MAX) % num_parts + 1
