"""Per-query resource ledger: WHERE a query's microseconds and bytes
actually went, attributed per host (docs/manual/10-observability.md,
"Cost ledger & critical path").

PR 4's spans record wall time per seam and PR 10's histograms record
distributions — neither can answer "how much device compute, H2D/D2H
transfer, rows scanned, queue wait and RPC payload did THIS query
consume, on which host?". The ledger closes that gap: one accumulator
per query, carried on its own ContextVar with the same propagation
rules as the trace context (copy_context across pool threads, an
explicit re-point when the dispatcher leader serves a waiter's
request) — but populated for EVERY query, trace sampling on or off,
because the slow-query log and the per-tenant cost rollups must cover
what head sampling misses.

Charge sites (each one ContextVar read when no ledger is active):
  - dispatcher queue wait + window share  (engine_tpu/engine.py)
  - fused-kernel device time + launches   (TpuGraphEngine._record_profile)
  - H2D staged frontier bytes             (fused.FrontierPool.stage)
  - D2H fetched mask bytes                (the chunk-loop fetches)
  - rows scanned / row bytes returned     (storage/processors.py,
                                           charged SERVER-side)
  - RPC round-trips + payload bytes       (rpc/transport.py)
  - cache rung hits/misses                (common/cache.py CacheRung)
  - WAL bytes appended                    (kvstore/raft_store.py)

Server-side charges cross the RPC boundary piggybacked on the response
envelope exactly like PR 4's span fragments (an additive v1.2 wire
element, docs/manual/6-wire-protocol.md) and merge client-side under
the PEER's host key — so a cluster query's cost block reads "rows
scanned: 1200 on host B, 800 on host C".

Shared-launch attribution: a coalesced dispatcher window launches ONE
kernel for N queries; like the window span, every rider's ledger is
charged the full device time (attributed time, not exclusive time —
`launches` counts real launches once, on the leader). Window H2D/D2H
bytes are charged to the leader's query (the thread that staged and
fetched them); a solo PROFILE window (the common diagnostic case) is
exact.
"""
from __future__ import annotations

import contextvars
import threading
from typing import Any, Dict, Optional, Tuple

from .flags import MUTABLE, graph_flags
from . import profiler as _profiler

# Accounting fields, in WIRE ORDER — append-only (the piggybacked RPC
# fragment is a positional int tuple; reordering breaks mixed-version
# merges the same way reordering a wire struct would).
FIELDS: Tuple[str, ...] = (
    "queue_wait_us",    # dispatcher enqueue -> wake (the waiter's wait)
    "window_share_us",  # wall time of the shared window that served it
    "device_us",        # kernel dispatch+fetch wall time (attributed)
    "launches",         # device program launches
    "h2d_bytes",        # host->device staged bytes (frontier stacks)
    "d2h_bytes",        # device->host fetched bytes (mask stacks)
    "rows_scanned",     # storage rows iterated server-side
    "bytes_returned",   # raw row-value bytes the processors decoded
    "rpc_calls",        # client-side round trips
    "rpc_bytes_out",    # request payload bytes
    "rpc_bytes_in",     # response payload bytes
    "cache_hits",       # cache-rung hits on the query's path
    "cache_misses",     # cache-rung misses
    "wal_bytes",        # raft WAL bytes appended for this query
    # write-path observatory (ISSUE 19, common/writepath.py): the
    # synchronous write stages' per-query microseconds — appended wire
    # fields (positional tuple: older peers simply truncate), charged
    # at the same seams that feed the write.stage.* histograms, so
    # PROFILE on a mutation renders a per-stage cost block
    "write_exec_us",    # graph mutation executor run
    "write_fanout_us",  # StorageClient write fan-out extent
    "wal_append_us",    # leader WAL append (server-side)
    "replicate_us",     # replication quorum wait (server-side)
    "commit_apply_us",  # commit_logs engine apply (server-side)
)

graph_flags.declare(
    "cost_ledger_enabled", True, MUTABLE,
    "attach a per-query resource ledger (cost attribution in PROFILE/"
    "slow-query log + graph.cost.* rollups); off = queries carry no "
    "ledger and every charge site is a single ContextVar read")


class Ledger:
    """One query's cost accumulator. Direct attribute adds are for
    sites that provably run on the query's single serving thread (the
    dispatcher charges under the owner's re-pointed context); charge /
    charge_host / merge_wire take the ledger lock because the storage
    fan-out runs them from concurrent pool threads (a lost increment
    would silently under-report cost)."""

    __slots__ = FIELDS + ("hosts", "verb", "_lock")

    def __init__(self):
        for f in FIELDS:
            setattr(self, f, 0)
        # host addr -> {field: int}: the per-host slice merged back
        # from RPC response fragments (and, server-side, local charges
        # recorded under the serving host's own name)
        self.hosts: Dict[str, Dict[str, int]] = {}
        self.verb = ""   # first statement kind (rollup dimension)
        self._lock = threading.Lock()

    # ------------------------------------------------------------- charge
    def charge(self, **fields: int) -> None:
        with self._lock:
            for f, v in fields.items():
                setattr(self, f, getattr(self, f) + int(v))

    def charge_host(self, host: str, **fields: int) -> None:
        """Charge totals AND the per-host slice (server-side sites
        pass their own advertised host)."""
        with self._lock:
            hd = self.hosts.get(host)
            if hd is None:
                hd = self.hosts[host] = {}
            for f, v in fields.items():
                v = int(v)
                setattr(self, f, getattr(self, f) + v)
                hd[f] = hd.get(f, 0) + v

    # --------------------------------------------------------------- wire
    def to_wire(self) -> Tuple:
        """(field ints in FIELDS order, {host: {field: int}}) — the
        additive response-envelope element (manual 6, v1.2)."""
        return (tuple(getattr(self, f) for f in FIELDS),
                {h: dict(d) for h, d in self.hosts.items()})

    def merge_wire(self, w, host: Optional[str] = None) -> None:
        """Merge a piggybacked fragment. Nested host slices merge
        under their original names; `host` (the RPC peer that produced
        the fragment) labels only the REMAINDER of the top-level
        charges — what the server charged without host attribution
        (e.g. wal_bytes at the consensus hook). Charges the server
        already attributed via charge_host would otherwise appear
        twice in the per-host breakdown (once under the server's own
        name, once under the peer address — the same host). Malformed
        fragments are dropped — cost attribution must never break a
        query."""
        try:
            vals, hosts = w[0], w[1]
            with self._lock:
                for f, v in zip(FIELDS, vals):
                    setattr(self, f, getattr(self, f) + int(v))
                nested: Dict[str, int] = {}
                for h, d in hosts.items():
                    hd = self.hosts.setdefault(h, {})
                    for f, v in d.items():
                        hd[f] = hd.get(f, 0) + int(v)
                        nested[f] = nested.get(f, 0) + int(v)
                if host is not None:
                    rem = {f: int(v) - nested.get(f, 0)
                           for f, v in zip(FIELDS, vals)}
                    if any(v > 0 for v in rem.values()):
                        hd = self.hosts.setdefault(host, {})
                        for f, v in rem.items():
                            if v > 0:
                                hd[f] = hd.get(f, 0) + v
        except Exception:
            return

    # --------------------------------------------------------------- view
    def to_dict(self) -> Dict[str, Any]:
        """The PROFILE `cost` block / slow-query ledger slice: every
        field (stable shape) plus the nonzero per-host breakdown."""
        out: Dict[str, Any] = {f: getattr(self, f) for f in FIELDS}
        hosts = {}
        for h, d in self.hosts.items():
            nz = {f: v for f, v in d.items() if v}
            if nz:
                hosts[h] = nz
        if hosts:
            out["hosts"] = hosts
        return out

    def nonzero(self) -> Dict[str, int]:
        return {f: getattr(self, f) for f in FIELDS if getattr(self, f)}


# The query's ledger; None = no accounting (internal/background work,
# or cost_ledger_enabled off). contextvars, not threading.local, for
# the same reason as the trace context: executor fan-outs carry it
# into pool threads via copy_context().
_current: contextvars.ContextVar[Optional[Ledger]] = \
    contextvars.ContextVar("nebula_ledger", default=None)


def current() -> Optional[Ledger]:
    return _current.get()


def charge(**fields: int) -> None:
    """Ambient charge — one ContextVar read when no ledger is live."""
    led = _current.get()
    if led is not None:
        led.charge(**fields)


def charge_host(host: str, **fields: int) -> None:
    led = _current.get()
    if led is not None:
        led.charge_host(host, **fields)


def begin() -> Tuple[Optional[Ledger], Optional[object]]:
    """Attach a fresh ledger to the calling context (the graph-service
    query head). Returns (ledger, token) — (None, None) when the
    cost_ledger_enabled flag is off. The token also carries the
    profiler's per-thread verb mirror, cleared here and restored at
    end() (set_verb below fills it once the statement kind is
    known)."""
    if not graph_flags.get("cost_ledger_enabled", True):
        return None, None
    led = Ledger()
    return led, (_current.set(led), _profiler.note_verb(None))


def set_verb(led: Ledger, verb: str) -> None:
    """Record the statement kind on the ledger AND mirror it as the
    calling thread's live verb, so a stack sample of this thread is
    tagged with what query shape it was serving
    (common/profiler.py)."""
    led.verb = verb
    tid = threading.get_ident()
    _profiler._thread_verb[tid] = verb


def end(token) -> None:
    if token is not None:
        cv_tok, verb_tok = token
        _current.reset(cv_tok)
        _profiler.restore_verb(verb_tok)


class _UseCtx:
    """Temporarily re-point the current thread at another request's
    ledger (the dispatcher leader charging a waiter's request). A None
    ledger DETACHES — charges recorded while serving a ledger-less
    request must not land on the leader's own query."""

    __slots__ = ("_led", "_token", "_vtok")

    def __init__(self, led: Optional[Ledger]):
        self._led = led
        self._token = None
        self._vtok = None

    def __enter__(self):
        self._token = _current.set(self._led)
        self._vtok = _profiler.note_verb(
            self._led.verb if self._led is not None else None)
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if self._vtok is not None:
            _profiler.restore_verb(self._vtok)
            self._vtok = None
        return False


def use(led: Optional[Ledger]) -> _UseCtx:
    return _UseCtx(led)


class adopt:
    """Server-side adoption around an RPC handler whose request carried
    the cost flag: charges recorded in the extent land in a fresh
    ledger, exposed wire-shaped as `.wire` for the response envelope
    (rpc/transport.py) — the cost twin of tracing.RemoteTrace."""

    __slots__ = ("ledger", "wire", "_token")

    def __init__(self):
        self.ledger = Ledger()
        self.wire: Optional[Tuple] = None
        self._token = None

    def __enter__(self) -> "adopt":
        self._token = _current.set(self.ledger)
        return self

    def __exit__(self, *exc) -> bool:
        _current.reset(self._token)
        self.wire = self.ledger.to_wire()
        return False
