"""Runtime lock-order witness: deadlock + blocked-under-lock detection.

The static suite (nebula_tpu/tools/lint, NL001) catches blocking calls
syntactically under a hot lock; this module catches what only the
RUNTIME can see — the cross-thread lock *acquisition-order graph*.
While installed, every `threading.Lock` / `RLock` / `Condition`
constructed from code under `nebula_tpu/` (~44 sites: dispatcher cv,
engine snapshot lock, stats leaf lock, cache rungs, raft parts, client
pools) is wrapped in a recording proxy. Each acquisition that happens
while the same thread already holds other witnessed locks adds edges
`held-site -> acquired-site`; at the end of a run:

- a CYCLE in that graph is a potential ABBA deadlock — two threads
  interleaving those sites in opposite orders can hang the process;
- a `time.sleep` observed while ANY witnessed lock is held is a
  blocked-under-hot-lock event (the runtime twin of NL001).

Nodes are lock CREATION SITES (file:line), not instances — the
lockdep-style class aggregation that keeps the graph tiny and stable
across runs. Same-site nestings (two instances born at one line, e.g.
two raft parts) are reported separately as `self_edges`: they are only
a deadlock risk when instance order can invert, so they don't fail
`assert_clean()` but stay visible in the report.

Opt-in, three ways:
- env `NEBULA_TPU_LOCK_WITNESS=1` before importing `nebula_tpu`
  (installs at import; tests/conftest.py honors it for tier-1);
- `bench.py --chaos` / `--cluster` install it for the whole run and
  embed `report()` in the output JSON (the smokes assert it clean);
- `tools/soak.py --witness` does the same for soaks and dumps the
  observed graph into the debug bundle on identity failure.

Overhead: one `sys._getframe` walk per lock CONSTRUCTION and per
acquisition-with-locks-held, plus two list ops per acquire/release —
single-digit microseconds, measured ~2-3x on a bare uncontended
acquire/release pair (docs/manual/15-static-analysis.md#witness).
Locks created before install() are not wrapped; install early.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_SLEEP = time.sleep
_THREADING_FILE = getattr(threading, "__file__", "<threading>")
_SELF_FILE = __file__
# the contention profiler (common/profiler.py) constructs the real
# lock INSIDE profiled_lock()/profiled_rlock(); without this skip
# every profiled lock — engine snapshot lock, dispatcher cv, part
# locks — would collapse into ONE witness node at that factory line,
# masking real ABBA orderings between them
_PROFILER_FILE = os.path.join(os.path.dirname(__file__), "profiler.py")
_INFRA_FILES = (_SELF_FILE, _THREADING_FILE, _PROFILER_FILE)


class LockOrderViolation(AssertionError):
    """Raised by assert_clean(): cycle or blocked-under-lock event."""


def _caller_site() -> str:
    """file:line of the nearest frame outside this module,
    threading.py (Condition(None) constructs its RLock from inside
    threading.py — the witness attributes it to the real caller) and
    the contention-profiler factories."""
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename in _INFRA_FILES:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{f.f_code.co_filename}:{f.f_lineno}"


class _WitnessProxy:
    """Wraps one real Lock/RLock; maintains the per-thread held stack
    and feeds the order graph. Exposes the `_release_save` /
    `_acquire_restore` / `_is_owned` triple so threading.Condition
    treats it exactly like the lock it wraps (wait() pops ALL
    recursion levels from the held stack and restores them)."""

    __slots__ = ("_real", "_w", "site")

    def __init__(self, real, witness: "LockWitness", site: str):
        self._real = real
        self._w = witness
        self.site = site

    # ------------------------------------------------------ lock API
    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._real.acquire(blocking, timeout)
        if got:
            self._w._note_acquire(self)
        return got

    def release(self) -> None:
        self._w._note_release(self)
        self._real.release()

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self) -> "_WitnessProxy":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<witnessed {self._real!r} from {self.site}>"

    # ------------------------------------- Condition integration
    def _release_save(self):
        n = self._w._pop_all(self)
        real = self._real
        rs = getattr(real, "_release_save", None)
        if rs is not None:
            return (rs(), n)
        real.release()
        return (None, n)

    def _acquire_restore(self, state) -> None:
        inner, n = state
        real = self._real
        ar = getattr(real, "_acquire_restore", None)
        if ar is not None:
            ar(inner)
        else:
            real.acquire()
        self._w._push_n(self, n)

    def _is_owned(self) -> bool:
        real = self._real
        io = getattr(real, "_is_owned", None)
        if io is not None:
            return io()
        if real.acquire(False):
            real.release()
            return False
        return True


class LockWitness:
    """One installable witness. The module-level `witness` instance is
    scoped to locks created from nebula_tpu/ code; tests build private
    instances with `scope=None` (wrap everything) for synthetic
    scenarios."""

    def __init__(self, scope: Optional[Tuple[str, ...]] = ("nebula_tpu",),
                 sleep_floor_s: float = 0.0):
        self.scope = scope          # None = wrap every creation site
        self.sleep_floor_s = sleep_floor_s
        self._installed = False
        self._prev = (_REAL_LOCK, _REAL_RLOCK, _REAL_SLEEP)
        self._tls = threading.local()
        self._mu = _REAL_LOCK()
        # (held_site, acquired_site) -> example detail (first sighting)
        self._edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._self_edges: Dict[str, Dict[str, Any]] = {}
        self._blocking: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._sites: Set[str] = set()
        self.acquisitions = 0
        self.wrapped = 0

    # -------------------------------------------------- install/uninstall
    def _in_scope(self, site: str) -> bool:
        if self.scope is None:
            return True
        return any(s in site for s in self.scope)

    def install(self) -> "LockWitness":
        if self._installed:
            return self
        self._installed = True
        # restore exactly what we displaced: a test witness installed
        # inside an env-armed tier-1 run must hand control back to the
        # outer witness's factories, not to the raw originals
        self._prev = (threading.Lock, threading.RLock, time.sleep)

        # delegate to what we DISPLACED, not the raw originals: with
        # an outer witness installed (env-armed tier-1) its factory
        # keeps seeing every creation/sleep made while an inner test
        # witness is active, so locks born in that window — which may
        # outlive the inner witness — still feed the outer graph
        def make_lock():
            site = _caller_site()
            real = self._prev[0]()
            if not self._in_scope(site):
                return real
            self.wrapped += 1
            self._sites.add(site)
            return _WitnessProxy(real, self, site)

        def make_rlock():
            site = _caller_site()
            real = self._prev[1]()
            if not self._in_scope(site):
                return real
            self.wrapped += 1
            self._sites.add(site)
            return _WitnessProxy(real, self, site)

        def traced_sleep(secs):
            held = getattr(self._tls, "held", None)
            if held and secs is not None and secs > self.sleep_floor_s:
                self._note_blocking(f"time.sleep({secs!r})",
                                    [p.site for p in held])
            return self._prev[2](secs)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        time.sleep = traced_sleep
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        threading.Lock, threading.RLock, time.sleep = self._prev

    # ------------------------------------------------------ recording
    def _held(self) -> List[_WitnessProxy]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _note_acquire(self, proxy: _WitnessProxy) -> None:
        held = self._held()
        self.acquisitions += 1
        if held:
            acq_at = _caller_site()
            seen: Set[str] = set()
            for h in held:
                if h is proxy or h.site in seen:
                    continue      # recursive re-acquire / duplicate site
                seen.add(h.site)
                if h.site == proxy.site:
                    if proxy.site not in self._self_edges:
                        with self._mu:
                            self._self_edges.setdefault(proxy.site, {
                                "site": proxy.site,
                                "thread": threading.current_thread().name,
                                "acquired_at": acq_at,
                            })
                    continue
                key = (h.site, proxy.site)
                if key not in self._edges:
                    with self._mu:
                        self._edges.setdefault(key, {
                            "held": h.site, "acquired": proxy.site,
                            "thread": threading.current_thread().name,
                            "acquired_at": acq_at,
                        })
        held.append(proxy)

    def _note_release(self, proxy: _WitnessProxy) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is proxy:
                del held[i]
                return

    def _pop_all(self, proxy: _WitnessProxy) -> int:
        """Condition.wait: drop every recursion level of `proxy`."""
        held = self._held()
        n = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i] is proxy:
                del held[i]
                n += 1
        return n

    def _push_n(self, proxy: _WitnessProxy, n: int) -> None:
        held = self._held()
        for _ in range(max(n, 1)):
            held.append(proxy)

    def _note_blocking(self, op: str, lock_sites: List[str]) -> None:
        at = _caller_site()
        key = (at, op.split("(")[0])
        if key not in self._blocking:
            with self._mu:
                self._blocking.setdefault(key, {
                    "op": op, "at": at,
                    "locks_held": sorted(set(lock_sites)),
                    "thread": threading.current_thread().name,
                })

    # ----------------------------------------------------- analysis
    def graph(self) -> Dict[str, List[str]]:
        with self._mu:
            out: Dict[str, List[str]] = {}
            for a, b in self._edges:
                out.setdefault(a, []).append(b)
            for a in out:
                out[a].sort()
            return out

    def find_cycle(self) -> Optional[List[str]]:
        """A site cycle in the acquisition-order graph, or None."""
        g = self.graph()
        WHITE, GREY, BLACK = 0, 1, 2
        color = {n: WHITE for n in
                 set(g) | {b for bs in g.values() for b in bs}}
        stack: List[str] = []

        def dfs(n: str) -> Optional[List[str]]:
            color[n] = GREY
            stack.append(n)
            for m in g.get(n, ()):
                if color[m] == GREY:
                    return stack[stack.index(m):] + [m]
                if color[m] == WHITE:
                    found = dfs(m)
                    if found:
                        return found
            stack.pop()
            color[n] = BLACK
            return None

        for n in sorted(color):
            if color[n] == WHITE:
                found = dfs(n)
                if found:
                    return found
        return None

    def blocking_events(self) -> List[Dict[str, Any]]:
        with self._mu:
            return sorted(self._blocking.values(),
                          key=lambda e: (e["at"], e["op"]))

    def report(self) -> Dict[str, Any]:
        cycle = self.find_cycle()
        with self._mu:
            edges = sorted(self._edges.values(),
                           key=lambda e: (e["held"], e["acquired"]))
            self_edges = sorted(self._self_edges.values(),
                                key=lambda e: e["site"])
        return {
            "installed": self._installed,
            "locks_wrapped": self.wrapped,
            "acquisitions": self.acquisitions,
            "edges": edges,
            "self_edges": self_edges,
            "cycle": cycle,
            "blocking": self.blocking_events(),
            "clean": cycle is None and not self._blocking,
        }

    def summary(self) -> Dict[str, Any]:
        """Compact report for bench/soak JSON artifacts: edge/self-edge
        LISTS are collapsed to counts (hundreds of rows on a cluster
        run); cycles and blocking events — the failure evidence — are
        embedded whole. One shape for every artifact consumer."""
        rep = self.report()
        return {
            "installed": rep["installed"],
            "locks_wrapped": rep["locks_wrapped"],
            "acquisitions": rep["acquisitions"],
            "edges": len(rep["edges"]),
            "self_edges": len(rep["self_edges"]),
            "cycle": rep["cycle"],
            "blocking": rep["blocking"],
            "clean": rep["clean"],
        }

    def assert_clean(self) -> Dict[str, Any]:
        """Report, raising LockOrderViolation on a cycle or any
        blocked-under-lock event. Returns the report when clean."""
        rep = self.report()
        if rep["cycle"] is not None:
            raise LockOrderViolation(
                "lock-order cycle (potential ABBA deadlock): "
                + " -> ".join(rep["cycle"]))
        if rep["blocking"]:
            ev = rep["blocking"][0]
            raise LockOrderViolation(
                f"blocking op {ev['op']} at {ev['at']} while holding "
                f"witnessed lock(s) {ev['locks_held']} "
                f"(+{len(rep['blocking']) - 1} more event(s))")
        return rep

    def reset(self) -> None:
        """Drop recorded edges/events (NOT the wrapping) — phase
        isolation inside one run."""
        with self._mu:
            self._edges.clear()
            self._self_edges.clear()
            self._blocking.clear()


# the process-global witness (scoped to nebula_tpu/ creation sites)
witness = LockWitness()

if os.environ.get("NEBULA_TPU_LOCK_WITNESS"):
    witness.install()
