"""Thread-safe bounded LRU cache (role parity: the reference's
`common/base/ConcurrentLRUCache.h` — sharded folly EvictingCacheMap;
here one OrderedDict under a lock, which is plenty for CPython where
the contended path is IO-bound)."""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Hashable, Optional, Tuple


class ConcurrentLRUCache:
    _MISS = object()

    def __init__(self, capacity: int = 1024):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self._cap = capacity
        self._map: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: Hashable, default: Any = None) -> Any:
        with self._lock:
            v = self._map.get(key, self._MISS)
            if v is self._MISS:
                self.misses += 1
                return default
            self._map.move_to_end(key)
            self.hits += 1
            return v

    def contains(self, key: Hashable) -> bool:
        with self._lock:
            return key in self._map

    def put(self, key: Hashable, value: Any) -> None:
        with self._lock:
            self._map[key] = value
            self._map.move_to_end(key)
            while len(self._map) > self._cap:
                self._map.popitem(last=False)

    def get_or_compute(self, key: Hashable, compute) -> Any:
        """Single-call read-through. `compute` may run more than once
        under contention (same as the reference's racy insert; callers
        cache idempotent lookups)."""
        v = self.get(key, self._MISS)
        if v is not self._MISS:
            return v
        v = compute()
        self.put(key, v)
        return v

    def evict(self, key: Hashable) -> bool:
        with self._lock:
            return self._map.pop(key, self._MISS) is not self._MISS

    def clear(self) -> None:
        with self._lock:
            self._map.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)
