"""Continuous profiling observatory: always-on stack sampling, lock
contention and runtime-health (GC / XLA compile / device memory)
profiling, joined to traces and flight bundles
(docs/manual/10-observability.md, "Continuous profiling").

The stack can say *what* happened (traces), *that* it breached
(flight/SLO) and *what a query cost* (ledger/critpath) — this module
says what the PROCESS was doing meanwhile: which thread roles burned
or waited the wall time, on which frames, behind which locks, and how
much of it was the runtime's own overhead (GC pauses, XLA compiles).
Three instruments, all daemon-resident and negligible-overhead:

1. SAMPLING PROFILER (`SamplingProfiler`): a sampler thread walks
   `sys._current_frames()` at the MUTABLE `profile_hz` flag (default
   ~19 Hz — deliberately co-prime with 1 kHz timer ticks; 0 = off,
   and off means NO sampler thread and zero metric families). Each
   tick folds every other thread's stack into a collapsed-stack key
   aggregated per thread ROLE (the thread's `name=` with digit runs
   normalized — the thread-naming hygiene rule NL008 exists so this
   attribution works), into 60 s / 600 s rotating windows plus
   lifetime totals. Samples are tagged with the sampled thread's live
   trace/ledger context (a per-thread mirror maintained by
   common/tracing.py + common/ledger.py at the points they re-point
   their ContextVars — zero cost for unsampled queries), so a profile
   answers "this query's dispatcher_wait was spent under
   `_serve_group` waiting on the round cv" and flight bundles can
   correlate hot frames with exemplar trace ids. Served at
   `/profile` on every daemon (webservice built-in): JSON top-N
   self-time, `?format=collapsed` (flamegraph.pl / inferno input —
   scripts/flame.sh), `?seconds=N` on-demand high-rate capture,
   `?thread=<role>` filter.

2. LOCK-CONTENTION PROFILER (`profiled_lock`/`profiled_rlock`): the
   hot serve-path locks (engine snapshot lock, dispatcher cv, raft/kv
   part locks) are constructed through a thin always-on wrapper that
   sits UNDER the lockwitness layer (it wraps whatever
   `threading.Lock()` returns, so a witness-armed run still sees
   every acquisition). The uncontended path is one extra try-acquire
   + a holder stamp; only CONTENDED acquires pay for accounting:
   per-site acquire-wait histograms (`lock.wait_us.<site>` — native
   OpenMetrics histograms with trace exemplars, scraping as
   `nebula_lock_wait_us_<site>`), last-holder attribution (which
   thread role made me wait), and the `/profile?locks=1`
   top-contended table.

3. RUNTIME-HEALTH PROFILE: GC pause tracking via `gc.callbacks`
   (`graph.gc.pause_us` histogram + a `gc_pause` flight event past
   the `gc_pause_flight_ms` flag), XLA compile accounting wrapped
   around the fused-program registry (`tpu_engine.compile_us`
   histogram + the per-signature table at `/profile?compiles=1`),
   and the per-snapshot device-memory ledger
   (TpuGraphEngine.device_mem_stats, gauges next to the bench's
   tier1_hbm_model estimate).

Overhead contract (tests/test_profiler.py): the sampler measures its
own per-tick cost (`self_us`); a 19 Hz burst run must keep that under
`SAMPLER_OVERHEAD_BUDGET` of wall time, and `profile_hz=0` must leave
zero sampler thread and a byte-identical /metrics exposition.
"""
from __future__ import annotations

import gc as _gc
import re
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .flags import MUTABLE, graph_flags, meta_flags, storage_flags
from .stats import stats as global_stats

_REGISTRIES = (graph_flags, storage_flags, meta_flags)

# declared on EVERY registry: all three daemons serve /profile and
# each daemon's /flags serves only its own registry (the flight-flag
# precedent, common/flight.py)
for _reg in _REGISTRIES:
    _reg.declare(
        "profile_hz", 19.0, MUTABLE,
        "always-on sampling-profiler rate in Hz (sys._current_frames "
        "walks aggregated per thread role, served at /profile). "
        "Default ~19 Hz is co-prime with common timer frequencies; "
        "0 disables the sampler entirely (no thread, no metrics)")
    _reg.declare(
        "profile_capture_hz", 97.0, MUTABLE,
        "sampling rate of on-demand /profile?seconds=N captures "
        "(bounded high-rate bursts; the always-on rate stays "
        "profile_hz)")
    _reg.declare(
        "gc_pause_flight_ms", 50.0, MUTABLE,
        "GC stop-the-world pauses longer than this become gc_pause "
        "flight-recorder events (every pause feeds the graph.gc."
        "pause_us histogram regardless; 0 records every pause as an "
        "event)")


def _flag(name: str, default):
    """First non-default value across the three registries (the
    common/flight.py idiom: one process may host all three daemons)."""
    for reg in _REGISTRIES:
        v = reg.get(name, default)
        if v is not None and v != default:
            return v
    return default


# ---------------------------------------------------------------------------
# per-thread trace/ledger context mirror
# ---------------------------------------------------------------------------
# ContextVars cannot be read across threads, but the sampler must tag
# a sample with the SAMPLED thread's live query context. tracing.py
# and ledger.py mirror their ContextVar re-points into these plain
# dicts (GIL-atomic store/delete per entry, keyed by thread ident).
# Only SAMPLED traces and attached ledgers ever write here — the
# unsampled hot path never touches the mirror.

_thread_trace: Dict[int, str] = {}
_thread_verb: Dict[int, str] = {}


def note_trace(trace_id: Optional[str]) -> Tuple[int, Optional[str]]:
    """Mirror `trace_id` as the calling thread's live trace (None
    detaches). Returns an opaque token for restore_trace."""
    tid = threading.get_ident()
    prev = _thread_trace.get(tid)
    if trace_id:
        _thread_trace[tid] = trace_id
    else:
        _thread_trace.pop(tid, None)
    return (tid, prev)


def restore_trace(token: Tuple[int, Optional[str]]) -> None:
    tid, prev = token
    if prev:
        _thread_trace[tid] = prev
    else:
        _thread_trace.pop(tid, None)


def note_verb(verb: Optional[str]) -> Tuple[int, Optional[str]]:
    """Mirror the ledger's statement verb (the sample's 'what query
    shape was this thread serving' tag)."""
    tid = threading.get_ident()
    prev = _thread_verb.get(tid)
    if verb:
        _thread_verb[tid] = verb
    else:
        _thread_verb.pop(tid, None)
    return (tid, prev)


def restore_verb(token: Tuple[int, Optional[str]]) -> None:
    tid, prev = token
    if prev:
        _thread_verb[tid] = prev
    else:
        _thread_verb.pop(tid, None)


# ---------------------------------------------------------------------------
# sampling profiler
# ---------------------------------------------------------------------------

# sampler self-time budget as a fraction of wall time — the declared
# bound the tier-1 overhead guard asserts at 19 Hz under a query burst
SAMPLER_OVERHEAD_BUDGET = 0.05

_DIGITS = re.compile(r"\d+")
# CPython names unnamed threads "Thread-N (target_name)": the target
# is the only role information there is (stdlib spawns — http.server
# request handlers — can't be renamed by NL008)
_ANON = re.compile(r"^Thread-\d+ \((.+)\)$")


def thread_role(name: str) -> str:
    """Thread name -> stable ROLE: digit runs collapse to 'N' so
    per-instance names (raft-repl-1-3-127.0.0.1:5001) aggregate into
    one role (raft-repl-N-N-N.N.N.N:N); anonymous stdlib spawns fall
    back to their target-function hint."""
    if not name:
        return "unnamed"
    m = _ANON.match(name)
    if m:
        name = m.group(1)
    return _DIGITS.sub("N", name)


class SamplingProfiler:
    """Always-on wall-clock stack sampler (instrument 1 above).

    Aggregation: (role, collapsed-stack) -> [seconds, samples,
    last_trace_id, last_verb] in BUCKET_S-second epoch buckets kept
    for the largest window, plus a lifetime dict; `seconds` weights
    each sample by the live sampling period, so a mid-run hz change
    never skews the wall-time shares. A bounded ring of trace-tagged
    samples feeds the flight-bundle profile capture (the trace-id
    correlation bench --chaos asserts)."""

    BUCKET_S = 10
    WINDOWS = (60, 600)
    MAX_STACK_DEPTH = 48
    MAX_KEYS = 20000          # lifetime fold-to-<other> cardinality cap
    TAGGED_RING = 512

    def __init__(self, clock=time.time, stats=global_stats):
        self._clock = clock
        self._stats = stats
        self._mu = threading.Lock()
        # deque[(bucket_epoch, {key: [secs, n, trace_id, verb]})]
        self._buckets: "deque[Tuple[int, Dict]]" = deque()
        self._life: Dict[Tuple[str, str], List] = {}
        self._tagged: "deque[Dict[str, Any]]" = deque(
            maxlen=self.TAGGED_RING)
        self._hz = 0.0
        self._enabled = False
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._role_cache: Dict[str, str] = {}
        self._code_names: Dict[Any, str] = {}
        self.ticks = 0
        self.samples = 0          # thread-stacks recorded
        self.self_us = 0          # the sampler's OWN per-tick cost
        self._t_started: Optional[float] = None

    # ------------------------------------------------------- lifecycle
    def ensure(self, hz: Optional[float] = None) -> None:
        """Arm the sampler at `hz` (default: the profile_hz flag).
        Idempotent; hz <= 0 means NO sampler thread is ever created
        (the zero-cost fast path the tier-1 test proves)."""
        if hz is None:
            hz = float(_flag("profile_hz", 19.0) or 0.0)
        self._enabled = True
        self.set_hz(hz)

    def set_hz(self, hz: float) -> None:
        try:
            hz = max(0.0, float(hz))
        except (TypeError, ValueError):
            return
        self._hz = hz
        if hz > 0 and self._thread is None:
            if self._t_started is None:
                self._t_started = time.monotonic()
            # nlint: disable=NL002 -- process-lifetime sampler loop;
            # it observes every thread and must not adopt any trace
            t = threading.Thread(target=self._loop, daemon=True,
                                 name="profiler-sampler")
            self._thread = t
            t.start()
        self._wake.set()

    def on_flag(self, hz) -> None:
        """profile_hz watcher seam: applies only once a daemon armed
        the profiler (ensure) — a bare library import must stay
        thread-free."""
        if self._enabled:
            self.set_hz(hz)

    def thread_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _loop(self) -> None:
        while True:
            hz = self._hz
            if hz <= 0:
                self._wake.wait(1.0)
                self._wake.clear()
                continue
            period = 1.0 / hz
            t0 = time.perf_counter()
            try:
                self._sample_once(period)
            except Exception:
                pass        # a sampler bug must never take a daemon down
            cost = time.perf_counter() - t0
            self.self_us += int(cost * 1e6)
            self.ticks += 1
            if period > cost:
                time.sleep(period - cost)

    # -------------------------------------------------------- sampling
    def _frame_name(self, code) -> str:
        s = self._code_names.get(code)
        if s is None:
            fn = code.co_filename
            i = fn.rfind("/")
            s = f"{fn[i + 1:]}:{code.co_name}"
            if len(self._code_names) < 100000:
                self._code_names[code] = s
        return s

    def _fold(self, frame) -> Tuple[str, str]:
        """(leaf, collapsed root;..;leaf) of one thread's stack."""
        parts: List[str] = []
        f = frame
        while f is not None and len(parts) < self.MAX_STACK_DEPTH:
            parts.append(self._frame_name(f.f_code))
            f = f.f_back
        return parts[0] if parts else "<empty>", ";".join(reversed(parts))

    def _role_of(self, name: str) -> str:
        role = self._role_cache.get(name)
        if role is None:
            role = thread_role(name)
            if len(self._role_cache) < 8192:
                self._role_cache[name] = role
        return role

    def _sample_once(self, period: float,
                     sink: Optional[Dict] = None,
                     role_filter: Optional[str] = None) -> int:
        frames = sys._current_frames()
        own = threading.get_ident()
        now = self._clock()
        bucket_epoch = int(now) // self.BUCKET_S
        n = 0
        recs = []
        # tid -> name resolved FRESH each tick (one list copy under
        # threading's lock, ~µs): thread idents are reused by the OS,
        # so a cross-tick cache would pin a dead thread's role onto
        # whatever thread inherits its ident
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid, frame in frames.items():
            if tid == own:
                continue
            role = self._role_of(names.get(tid, ""))
            if role_filter is not None and role != role_filter:
                continue
            leaf, stack = self._fold(frame)
            trace_id = _thread_trace.get(tid)
            verb = _thread_verb.get(tid)
            recs.append((role, stack, leaf, trace_id, verb))
            n += 1
        if sink is not None:
            for role, stack, leaf, trace_id, verb in recs:
                v = sink.get((role, stack))
                if v is None:
                    v = sink[(role, stack)] = [0.0, 0, None, None]
                v[0] += period
                v[1] += 1
                if trace_id:
                    v[2] = trace_id
                if verb:
                    v[3] = verb
            return n
        with self._mu:
            if not self._buckets or self._buckets[-1][0] != bucket_epoch:
                self._buckets.append((bucket_epoch, {}))
                horizon = bucket_epoch - \
                    (self.WINDOWS[-1] // self.BUCKET_S) - 1
                while self._buckets and self._buckets[0][0] < horizon:
                    self._buckets.popleft()
            cur = self._buckets[-1][1]
            for role, stack, leaf, trace_id, verb in recs:
                key = (role, stack)
                if key not in self._life and \
                        len(self._life) >= self.MAX_KEYS:
                    key = (role, "<other>")
                for d in (cur, self._life):
                    v = d.get(key)
                    if v is None:
                        v = d[key] = [0.0, 0, None, None]
                    v[0] += period
                    v[1] += 1
                    if trace_id:
                        v[2] = trace_id
                    if verb:
                        v[3] = verb
                if trace_id:
                    self._tagged.append(
                        {"ts": now, "role": role, "frame": leaf,
                         "trace_id": trace_id, "verb": verb or ""})
            self.samples += n
        return n

    # --------------------------------------------------------- reading
    def _merged(self, window: Optional[int],
                role: Optional[str] = None) -> Dict[Tuple[str, str], List]:
        """Aggregation over the trailing `window` seconds (None =
        lifetime), optionally filtered to one role."""
        with self._mu:
            if window is None:
                items = [dict(self._life)]
            else:
                horizon = (int(self._clock()) - window) // self.BUCKET_S
                items = [dict(d) for ep, d in self._buckets
                         if ep >= horizon]
        out: Dict[Tuple[str, str], List] = {}
        for d in items:
            for key, v in d.items():
                if role is not None and key[0] != role:
                    continue
                cur = out.get(key)
                if cur is None:
                    out[key] = list(v)
                else:
                    cur[0] += v[0]
                    cur[1] += v[1]
                    cur[2] = v[2] or cur[2]
                    cur[3] = v[3] or cur[3]
        return out

    def top(self, window: Optional[int] = 60, n: int = 20,
            role: Optional[str] = None) -> Dict[str, Any]:
        """Top-N SELF-time frames (the leaf frame owns the sample) per
        the trailing window — the /profile JSON body."""
        merged = self._merged(window, role)
        total_s = sum(v[0] for v in merged.values())
        total_n = sum(v[1] for v in merged.values())
        frames: Dict[Tuple[str, str], List] = {}
        roles: Dict[str, int] = {}
        for (r, stack), v in merged.items():
            leaf = stack.rsplit(";", 1)[-1]
            cur = frames.get((r, leaf))
            if cur is None:
                frames[(r, leaf)] = list(v)
            else:
                cur[0] += v[0]
                cur[1] += v[1]
                cur[2] = v[2] or cur[2]
                cur[3] = v[3] or cur[3]
            roles[r] = roles.get(r, 0) + v[1]
        rows = sorted(frames.items(), key=lambda kv: -kv[1][0])[:n]
        return {
            "window_s": window, "wall_s": round(total_s, 3),
            "samples": total_n,
            "threads": dict(sorted(roles.items(),
                                   key=lambda kv: -kv[1])),
            "frames": [
                {"role": r, "frame": leaf,
                 "self_s": round(v[0], 3), "samples": v[1],
                 "share": round(v[0] / total_s, 4) if total_s else 0.0,
                 **({"trace_id": v[2]} if v[2] else {}),
                 **({"verb": v[3]} if v[3] else {})}
                for (r, leaf), v in rows],
        }

    def collapsed(self, window: Optional[int] = 600,
                  role: Optional[str] = None) -> str:
        """flamegraph.pl / inferno collapsed-stack output: one
        `role;frame;frame;... weight` line per distinct stack. The
        weight is the stack's period-weighted wall time in ms (not a
        raw sample count): a mid-run profile_hz change must not skew
        flamegraph widths — same discipline as top()'s seconds."""
        merged = self._merged(window, role)
        lines = [f"{r};{stack} {max(1, round(v[0] * 1000))}"
                 for (r, stack), v in sorted(merged.items())]
        return "\n".join(lines) + ("\n" if lines else "")

    def capture(self, seconds: float, hz: Optional[float] = None,
                role: Optional[str] = None) -> Dict[str, Any]:
        """On-demand high-rate capture (/profile?seconds=N): sample
        synchronously into a private sink at `hz` (default: the
        profile_capture_hz flag) for `seconds` (bounded), leaving the
        always-on aggregation untouched."""
        seconds = min(max(float(seconds), 0.05), 30.0)
        if hz is None:
            hz = float(_flag("profile_capture_hz", 97.0) or 97.0)
        hz = min(max(float(hz), 1.0), 500.0)
        period = 1.0 / hz
        sink: Dict[Tuple[str, str], List] = {}
        deadline = time.monotonic() + seconds
        ticks = 0
        while time.monotonic() < deadline:
            t0 = time.perf_counter()
            self._sample_once(period, sink=sink, role_filter=role)
            ticks += 1
            cost = time.perf_counter() - t0
            if period > cost:
                time.sleep(period - cost)
        total_n = sum(v[1] for v in sink.values())
        frames: Dict[Tuple[str, str], float] = {}
        for (r, stack), v in sink.items():
            leaf = stack.rsplit(";", 1)[-1]
            frames[(r, leaf)] = frames.get((r, leaf), 0.0) + v[0]
        top = sorted(frames.items(), key=lambda kv: -kv[1])[:20]
        return {
            "seconds": seconds, "hz": hz, "ticks": ticks,
            "samples": total_n,
            "frames": [{"role": r, "frame": leaf,
                        "self_s": round(s, 4)} for (r, leaf), s in top],
            "collapsed": "\n".join(
                f"{r};{stack} {max(1, round(v[0] * 1000))}"
                for (r, stack), v in sorted(sink.items())),
        }

    def tagged_samples(self, limit: int = 64) -> List[Dict[str, Any]]:
        """Newest trace-tagged samples — the profile <-> trace join
        evidence embedded in flight bundles."""
        with self._mu:
            items = list(self._tagged)
        return items[-limit:]

    def state(self) -> Dict[str, Any]:
        wall = (time.monotonic() - self._t_started) \
            if self._t_started else 0.0
        return {
            "hz": self._hz,
            "thread_alive": self.thread_alive(),
            "ticks": self.ticks,
            "samples": self.samples,
            "self_us": self.self_us,
            "overhead": round(self.self_us / 1e6 / wall, 5)
            if wall > 0 else 0.0,
            "overhead_budget": SAMPLER_OVERHEAD_BUDGET,
        }

    def overhead(self) -> float:
        """Sampler self-time as a fraction of wall time since the
        sampler started — the tier-1 overhead-guard metric."""
        if not self._t_started:
            return 0.0
        wall = time.monotonic() - self._t_started
        return (self.self_us / 1e6) / wall if wall > 0 else 0.0

    def reset(self) -> None:
        with self._mu:
            self._buckets.clear()
            self._life.clear()
            self._tagged.clear()
        self.ticks = 0
        self.samples = 0
        self.self_us = 0
        if self._t_started is not None:
            self._t_started = time.monotonic()


# ---------------------------------------------------------------------------
# lock-contention profiler
# ---------------------------------------------------------------------------

# cv re-acquire waits under this are indistinguishable from scheduler
# noise — _acquire_restore has no try-first fast path, so a floor
# keeps every cv.wait from fabricating "contention"
CV_CONTENDED_MIN_US = 100


class _LockSite:
    """Per-creation-site contention aggregate, shared by every lock
    instance born with this name (all raft part locks are ONE site —
    the lockdep-style class aggregation). `acquires` is a GIL-racy
    monitoring counter (exactness would put a second lock on the
    uncontended hot path); the contended stats are exact under the
    site mutex."""

    __slots__ = ("name", "acquires", "contended", "wait_us_total",
                 "wait_us_max", "last_wait_us", "last_holder", "blame",
                 "_mu")

    def __init__(self, name: str):
        self.name = name
        self.acquires = 0
        self.contended = 0
        self.wait_us_total = 0
        self.wait_us_max = 0
        self.last_wait_us = 0
        self.last_holder = ""
        self.blame: Dict[str, int] = {}
        self._mu = threading.Lock()

    def note_contended(self, wait_us: int,
                       blamed: Optional[str]) -> None:
        holder = thread_role(blamed) if blamed else ""
        with self._mu:
            self.contended += 1
            self.wait_us_total += wait_us
            self.last_wait_us = wait_us
            if wait_us > self.wait_us_max:
                self.wait_us_max = wait_us
            if holder:
                self.last_holder = holder
                self.blame[holder] = self.blame.get(holder, 0) + 1
        # native histogram with trace exemplars: the WAITER's ambient
        # trace context (if sampled) pins the exemplar — the metric ->
        # trace join for lock waits (scrapes as
        # nebula_lock_wait_us_<site>)
        global_stats.add_value(f"lock.wait_us.{self.name}", wait_us,
                               kind="histogram")

    def snapshot(self) -> Dict[str, Any]:
        with self._mu:
            top_blame = sorted(self.blame.items(),
                               key=lambda kv: -kv[1])[:3]
            return {
                "name": self.name,
                "acquires": self.acquires,
                "contended": self.contended,
                "wait_us_total": self.wait_us_total,
                "wait_us_max": self.wait_us_max,
                "last_wait_us": self.last_wait_us,
                "last_holder": self.last_holder,
                "blame": dict(top_blame),
            }


_lock_sites: Dict[str, _LockSite] = {}
_lock_sites_mu = threading.Lock()


def _site(name: str) -> _LockSite:
    s = _lock_sites.get(name)
    if s is None:
        with _lock_sites_mu:
            s = _lock_sites.setdefault(name, _LockSite(name))
    return s


class ProfiledLock:
    """Always-on contention wrapper around one Lock/RLock instance.

    Sits UNDER the lockwitness: it wraps whatever `threading.Lock()` /
    `threading.RLock()` returned at construction (the witness proxy
    when armed, the raw primitive otherwise), and forwards the
    `_release_save`/`_acquire_restore`/`_is_owned` triple so
    `threading.Condition(profiled_lock(...))` behaves exactly like a
    Condition over the wrapped lock — the cv re-acquire after notify
    is real dispatcher contention and is timed in _acquire_restore.

    Uncontended cost: one failed-is-impossible try-acquire plus a
    holder-ident stamp. Contended cost: two clock reads + the site
    accounting + one histogram add — paid only after the thread
    already burned a context switch waiting."""

    __slots__ = ("_real", "_site", "_holder")

    def __init__(self, real, site: _LockSite):
        self._real = real
        self._site = site
        # last holder's thread NAME, stamped at acquire (resolving an
        # ident later races the holder thread's teardown)
        self._holder: Optional[str] = None

    # ------------------------------------------------------- lock API
    def acquire(self, blocking: bool = True, timeout: float = -1):
        site = self._site
        if self._real.acquire(False):
            self._holder = threading.current_thread().name
            site.acquires += 1      # monitoring-grade (see _LockSite)
            return True
        if not blocking:
            return False
        blamed = self._holder
        t0 = time.perf_counter()
        got = self._real.acquire(True, timeout)
        wait_us = int((time.perf_counter() - t0) * 1e6)
        if got:
            self._holder = threading.current_thread().name
            site.acquires += 1
            site.note_contended(wait_us, blamed)
        return got

    def release(self) -> None:
        # the holder stamp survives release ON PURPOSE: last-holder
        # attribution ("who was in there when I had to wait")
        self._real.release()

    def locked(self) -> bool:
        real = self._real
        if hasattr(real, "locked"):
            return real.locked()
        if real.acquire(False):
            real.release()
            return False
        return True

    def __enter__(self) -> "ProfiledLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<profiled[{self._site.name}] {self._real!r}>"

    # ------------------------------------------- Condition integration
    def _release_save(self):
        real = self._real
        rs = getattr(real, "_release_save", None)
        if rs is not None:
            return rs()
        real.release()
        return None

    def _acquire_restore(self, state) -> None:
        blamed = self._holder
        t0 = time.perf_counter()
        real = self._real
        ar = getattr(real, "_acquire_restore", None)
        if ar is not None:
            ar(state)
        else:
            real.acquire()
        wait_us = int((time.perf_counter() - t0) * 1e6)
        self._holder = threading.current_thread().name
        site = self._site
        site.acquires += 1
        if wait_us >= CV_CONTENDED_MIN_US:
            site.note_contended(wait_us, blamed)

    def _is_owned(self) -> bool:
        real = self._real
        io = getattr(real, "_is_owned", None)
        if io is not None:
            return io()
        if real.acquire(False):
            real.release()
            return False
        return True


def profiled_lock(name: str) -> ProfiledLock:
    """A contention-profiled `threading.Lock()` under site `name`
    (lowercase_with_underscores — it becomes the
    nebula_lock_wait_us_<name> metric family)."""
    return ProfiledLock(threading.Lock(), _site(name))


def profiled_rlock(name: str) -> ProfiledLock:
    """RLock twin of profiled_lock (the engine snapshot lock and raft
    part locks are re-entrant)."""
    return ProfiledLock(threading.RLock(), _site(name))


def lock_table(top: int = 16) -> List[Dict[str, Any]]:
    """The /profile?locks=1 top-contended table, most-waited first."""
    with _lock_sites_mu:
        sites = list(_lock_sites.values())
    rows = [s.snapshot() for s in sites]
    rows.sort(key=lambda r: -r["wait_us_total"])
    return rows[:top]


# ---------------------------------------------------------------------------
# GC pause profiler
# ---------------------------------------------------------------------------

class GcProfiler:
    """gc.callbacks-driven pause tracking: every collection's
    stop-the-world wall time feeds the graph.gc.pause_us native
    histogram; pauses past the gc_pause_flight_ms flag become
    `gc_pause` flight events (the p99 burn that lines up with a gen-2
    collection becomes visible in the ring)."""

    def __init__(self, stats=global_stats):
        self._stats = stats
        self._installed = False
        self._t0: Dict[int, Tuple[float, int]] = {}   # tid -> (t0, gen)
        self._mu = threading.Lock()
        self.collections = [0, 0, 0]
        self.pause_us_total = 0
        self.pause_us_max = 0
        self.last_pause_us = 0
        self.last_collected = 0
        self.uncollectable = 0

    def install(self) -> None:
        if not self._installed:
            self._installed = True
            _gc.callbacks.append(self._cb)

    def uninstall(self) -> None:
        if self._installed:
            self._installed = False
            try:
                _gc.callbacks.remove(self._cb)
            except ValueError:
                pass

    def _cb(self, phase: str, info: Dict[str, Any]) -> None:
        tid = threading.get_ident()
        if phase == "start":
            self._t0[tid] = (time.perf_counter(),
                             int(info.get("generation", 0)))
            return
        t0g = self._t0.pop(tid, None)
        if t0g is None:
            return
        pause_us = int((time.perf_counter() - t0g[0]) * 1e6)
        gen = t0g[1]
        collected = int(info.get("collected", 0))
        with self._mu:
            if 0 <= gen < 3:
                self.collections[gen] += 1
            self.pause_us_total += pause_us
            self.last_pause_us = pause_us
            self.last_collected = collected
            self.uncollectable += int(info.get("uncollectable", 0))
            if pause_us > self.pause_us_max:
                self.pause_us_max = pause_us
        self._stats.add_value("graph.gc.pause_us", pause_us,
                              kind="histogram")
        threshold_ms = float(_flag("gc_pause_flight_ms", 50.0) or 0.0)
        if pause_us >= threshold_ms * 1000.0:
            try:
                from .flight import recorder
                recorder.record("gc_pause", gen=gen, pause_us=pause_us,
                                collected=collected)
            except Exception:
                pass

    def table(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "installed": self._installed,
                "collections": list(self.collections),
                "pause_us_total": self.pause_us_total,
                "pause_us_max": self.pause_us_max,
                "last_pause_us": self.last_pause_us,
                "last_collected": self.last_collected,
                "uncollectable": self.uncollectable,
            }

    def gauges(self) -> Dict[str, float]:
        with self._mu:
            out = {"graph.gc.collections.gen" + str(g):
                   float(self.collections[g]) for g in range(3)}
            out["graph.gc.pause_us_max"] = float(self.pause_us_max)
            out["graph.gc.uncollectable"] = float(self.uncollectable)
            return out


# ---------------------------------------------------------------------------
# XLA compile accounting
# ---------------------------------------------------------------------------

class CompileTable:
    """Per-signature XLA compile accounting around the fused-program
    registry: TpuGraphEngine._fused_entry wraps each registry MISS in
    `timed_first_call`, so the first launch — the call that pays
    trace + XLA compile — lands in the tpu_engine.compile_us
    histogram and the /profile?compiles=1 table. Subsequent launches
    go through one delegating call (fractions of a µs next to a
    device launch)."""

    def __init__(self, stats=global_stats, clock=time.time):
        self._stats = stats
        self._clock = clock
        self._mu = threading.Lock()
        self._table: Dict[str, Dict[str, Any]] = {}

    def note(self, signature: str, dur_us: int) -> None:
        self._stats.add_value("tpu_engine.compile_us", dur_us,
                              kind="histogram")
        now = self._clock()
        with self._mu:
            rec = self._table.get(signature)
            if rec is None:
                rec = self._table[signature] = {
                    "signature": signature, "compiles": 0,
                    "total_us": 0, "last_us": 0, "last_ts": 0.0}
            rec["compiles"] += 1
            rec["total_us"] += int(dur_us)
            rec["last_us"] = int(dur_us)
            rec["last_ts"] = now

    def timed_first_call(self, fn: Callable, signature: str) -> Callable:
        return _TimedFirstCall(fn, signature, self)

    def table(self, top: int = 32) -> List[Dict[str, Any]]:
        with self._mu:
            rows = [dict(r) for r in self._table.values()]
        rows.sort(key=lambda r: -r["total_us"])
        return rows[:top]

    def totals(self) -> Dict[str, int]:
        with self._mu:
            return {
                "signatures": len(self._table),
                "compiles": sum(r["compiles"]
                                for r in self._table.values()),
                "total_us": sum(r["total_us"]
                                for r in self._table.values()),
            }


class _TimedFirstCall:
    """Times exactly the FIRST invocation (trace + XLA compile + first
    execute — compile-dominated on any cold signature) into the
    CompileTable; later calls delegate straight through."""

    __slots__ = ("fn", "signature", "_table", "_done")

    def __init__(self, fn: Callable, signature: str, table: CompileTable):
        self.fn = fn
        self.signature = signature
        self._table = table
        self._done = False

    def __call__(self, *args, **kwargs):
        if self._done:
            return self.fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        dur_us = int((time.perf_counter() - t0) * 1e6)
        self._done = True      # GIL-atomic; a concurrent double-note
        self._table.note(self.signature, dur_us)   # is harmless
        return out

    def __getattr__(self, name):
        # jit callables expose _cache_size/lower/etc. — pass through
        return getattr(self.fn, name)


# ---------------------------------------------------------------------------
# process-global instruments + daemon wiring
# ---------------------------------------------------------------------------

profiler = SamplingProfiler()
gc_profiler = GcProfiler()
compiles = CompileTable()

_armed = False


def flight_block() -> Dict[str, Any]:
    """The profile capture embedded in EVERY flight bundle: the
    anomaly window's hot frames (60 s), the trace-tagged samples
    (trace-id correlation evidence), the top contended locks and the
    runtime-health tables."""
    return {
        "state": profiler.state(),
        "top": profiler.top(window=60, n=12),
        "tagged_samples": profiler.tagged_samples(48),
        "locks": lock_table(8),
        "gc": gc_profiler.table(),
        "compiles": compiles.table(8),
    }


def ensure_started() -> None:
    """Arm the observatory for this process (idempotent): start the
    sampler at the profile_hz flag, install the GC callbacks, watch
    the flag on every registry, and register the flight-bundle
    collector. Called by WebService.start() — a daemon serving
    /profile is a daemon being profiled; bare library imports stay
    inert."""
    global _armed
    if not _armed:
        _armed = True
        for reg in _REGISTRIES:
            reg.watch(_on_flag)
        gc_profiler.install()
        try:
            from .flight import recorder
            recorder.add_collector("profile", flight_block)
        except Exception:
            pass
    profiler.ensure()


def _on_flag(name: str, value) -> None:
    if name == "profile_hz":
        profiler.on_flag(value)


def profile_endpoint(params: Dict[str, str], body: bytes
                     ) -> Tuple[int, Any]:
    """The /profile handler body (webservice built-in, every daemon):
      GET /profile                 top-N self-time JSON (?window=60|600|life,
                                   ?top=N, ?thread=<role>)
      GET /profile?format=collapsed  flamegraph.pl collapsed stacks
      GET /profile?seconds=N       on-demand high-rate capture (?hz=)
      GET /profile?locks=1         top-contended lock table
      GET /profile?compiles=1      per-signature XLA compile table
    """
    def _top(default: int):
        try:
            return int(params.get("top", default) or default)
        except ValueError:
            return None

    if params.get("locks"):
        n = _top(16)
        if n is None:
            return 400, {"error": "top must be an integer"}
        return 200, {"locks": lock_table(n)}
    if params.get("compiles"):
        n = _top(32)
        if n is None:
            return 400, {"error": "top must be an integer"}
        return 200, {"totals": compiles.totals(),
                     "compiles": compiles.table(n)}
    role = params.get("thread")
    if "seconds" in params:
        try:
            seconds = float(params["seconds"])
        except ValueError:
            return 400, {"error": "seconds must be numeric"}
        hz = None
        if "hz" in params:
            try:
                hz = float(params["hz"])
            except ValueError:
                return 400, {"error": "hz must be numeric"}
        cap = profiler.capture(seconds, hz=hz, role=role)
        if params.get("format") == "collapsed":
            return 200, (cap["collapsed"] + "\n").encode()
        cap.pop("collapsed", None)
        return 200, cap
    window_s = params.get("window", "60")
    window: Optional[int]
    if window_s in ("life", "lifetime", "0"):
        window = None
    else:
        try:
            window = int(window_s)
        except ValueError:
            return 400, {"error": "window must be 60, 600 or 'life'"}
        if window not in SamplingProfiler.WINDOWS:
            return 400, {"error": "window must be 60, 600 or 'life'"}
    if params.get("format") == "collapsed":
        return 200, profiler.collapsed(window=window, role=role).encode()
    top_n = _top(20)
    if top_n is None:
        return 400, {"error": "top must be an integer"}
    return 200, {
        "state": profiler.state(),
        **profiler.top(window=window, n=top_n, role=role),
        "locks": lock_table(8),
        "gc": gc_profiler.table(),
        "compiles": compiles.totals(),
    }


def gauges() -> Dict[str, float]:
    """Flat /metrics gauges: sampler health + GC tables (the pause
    distribution itself is the graph.gc.pause_us histogram)."""
    st = profiler.state()
    out = {
        "profiler.hz": float(st["hz"]),
        "profiler.samples": float(st["samples"]),
        "profiler.ticks": float(st["ticks"]),
        "profiler.self_us": float(st["self_us"]),
    }
    out.update(gc_profiler.gauges())
    ct = compiles.totals()
    out["tpu_engine.compile.signatures"] = float(ct["signatures"])
    out["tpu_engine.compile.total_us"] = float(ct["total_us"])
    return out
