"""Cluster-wide OpenMetrics federation: merge every daemon's /metrics
into ONE strict exposition with `instance`/`role` labels
(docs/manual/10-observability.md, "Cluster rollup / nebtop").

graphd's `/cluster_metrics` (daemons/graphd.py) fetches its own
exposition plus every registered storaged/metad `/metrics` (targets
from the heartbeat-carried web-port registry, meta/service.py) and
feeds them through `merge_expositions`:

 - every sample line gains `instance="host:ws_port"` and
   `role="graph|storage|meta"` labels (prepended, so an upstream
   label named the same would fail the strict duplicate-label check
   rather than be silently shadowed);
 - family TYPE lines are emitted ONCE per family, with all instances'
   samples contiguous under it (the strict parser forbids
   interleaving); a family whose declared type disagrees across
   instances keeps the first and DROPS the dissenters' samples
   (counted in the scrape gauge) instead of emitting a malformed doc;
 - per-target scrape health is itself a family
   (`nebula_cluster_scrape{instance,role}` 1|0), so a dead daemon is
   visible in the rollup instead of silently absent;
 - exemplars ride along untouched (they live after the value, which
   the label injection never touches).

The output strict-parses with tests/openmetrics.py (histogram
bucket/_count consistency is validated per label-series there, which
multi-instance federation requires).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple


def _inject_labels(line: str, extra: str) -> Optional[str]:
    """Prepend `extra` (already rendered `k="v",k2="v2"`) into a
    sample line's label set. Returns None for a line that does not
    look like a sample (caller drops it rather than corrupting the
    merged document)."""
    i = 0
    n = len(line)
    while i < n and (line[i].isalnum() or line[i] in "_:"):
        i += 1
    if i == 0:
        return None
    if i < n and line[i] == "{":
        return line[:i + 1] + extra + "," + line[i + 1:]
    if i < n and line[i] == " ":
        return line[:i] + "{" + extra + "}" + line[i:]
    return None


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


def merge_expositions(
        sources: List[Tuple[str, str, Optional[str]]]) -> str:
    """`sources` = [(instance, role, exposition_text | None)]; None
    text = the scrape failed (recorded in nebula_cluster_scrape and
    skipped). Returns one strict OpenMetrics document."""
    # family name -> (type, [sample lines]) in first-seen order
    families: Dict[str, Tuple[str, List[str]]] = {}
    scrape_lines: List[str] = []
    for instance, role, text in sources:
        extra_full = (f'instance="{_escape(instance)}",'
                      f'role="{_escape(role)}"')
        scrape_lines.append(
            f"nebula_cluster_scrape{{{extra_full}}} "
            f"{1 if text is not None else 0}")
        if text is None:
            continue
        current: Optional[str] = None       # current family name
        cur_type: Optional[str] = None
        for line in text.split("\n"):
            if not line or line == "# EOF":
                continue
            if line.startswith("#"):
                toks = line.split(" ")
                kind = toks[1] if len(toks) > 1 else ""
                if kind == "TYPE" and len(toks) == 4:
                    current, cur_type = toks[2], toks[3]
                    if current not in families:
                        families[current] = (cur_type, [])
                    elif families[current][0] != cur_type:
                        # type conflict across instances: keep the
                        # first declaration, drop this instance's
                        # samples of the family (a mixed-type family
                        # would fail every strict consumer)
                        current = None
                # HELP/UNIT dropped: per-instance help text would
                # duplicate across the merged family
                continue
            if current is None:
                continue                    # orphan or conflicting
            # a sample that already carries a role label (the
            # nebula_build_info join gauge labels its daemon role)
            # gets only `instance` — a duplicate label key would fail
            # the strict parser
            extra = extra_full if 'role="' not in line else \
                f'instance="{_escape(instance)}"'
            merged = _inject_labels(line, extra)
            if merged is not None:
                families[current][1].append(merged)
    out: List[str] = []
    for name, (type_, samples) in families.items():
        if not samples:
            continue
        out.append(f"# TYPE {name} {type_}")
        out.extend(samples)
    out.append("# TYPE nebula_cluster_scrape gauge")
    out.extend(scrape_lines)
    out.append("# EOF")
    return "\n".join(out) + "\n"
