"""Multi-tenant QoS: admission control, priority lanes, load shedding
(docs/manual/14-qos.md).

The serve path survives injected faults (the degradation ladder in
common/faults.py) and host loss (the replicated cluster) — this module
protects it BEFORE overload: one abusive tenant issuing bulk scans must
not starve every interactive session. Three rungs, each engaging one
step earlier than the next:

  1. ADMISSION — per-space (tenant) token buckets at the graphd session
     layer. Over-budget queries get a typed, retryable ``E_OVERLOAD``
     with a retry-after hint: never a hang, never a generic failure.
  2. PRIORITY LANES — the dispatcher classifies every GO as
     ``interactive`` or ``bulk`` (statement kind + steps, overridable
     per session or per space plan) and schedules group rounds
     weighted-fair, so bulk scans cannot monopolize the concurrent
     round slots (engine_tpu/engine.py).
  3. LOAD SHEDDING — queue-depth + group-wait-p95 watermarks shed the
     lowest-priority admitted work first (``shed:<reason>``-tagged
     ``E_OVERLOAD``), engaging before ``tpu_query_deadline_ms`` blows
     so deadline balks stay the last resort, ahead of the breakers.

Activation mirrors common/faults.py: the MUTABLE graphd flag
``qos_plan`` (hot-settable through /flags and the meta config pull) and
the graphd admin endpoint ``/qos`` both feed the process-global
``admission`` controller.

Plan grammar: ``space:arg[,arg]...`` entries joined by ``;``. Args:

    rate=<per_s>   token refill rate (required; 0 = deny all)
    burst=<n>      bucket capacity (default max(rate, 1))
    lane=<name>    force this space's queries onto a lane
                   (``interactive`` | ``bulk``)

A ``*`` entry is the default policy for spaces the plan does not name;
with no ``*`` entry, unnamed spaces are unlimited. An empty plan clears
everything (admission wide open).

The module also hosts the per-query DEADLINE context (`set_query_
deadline` / `deadline_remaining_s`): the graph service arms it from
``tpu_query_deadline_ms`` at query start, and every retry loop
downstream (StorageClient fan-out rounds) consults it so no retry
budget can outlive the query's own deadline.
"""
from __future__ import annotations

import threading
import time
from contextvars import ContextVar
from typing import Any, Dict, Optional, Tuple

from .stats import stats as global_stats

LANE_INTERACTIVE = "interactive"
LANE_BULK = "bulk"
LANES = (LANE_INTERACTIVE, LANE_BULK)


def bulk_shape(steps: int, n_starts: int) -> bool:
    """THE statement-shape bulk rule, shared by the graph-layer
    classifier and the dispatcher's fallback (one copy: a threshold
    change cannot silently diverge the two): deep (>= qos_bulk_steps)
    or wide (>= qos_bulk_starts start vids) traversals are bulk."""
    from .flags import graph_flags
    return steps >= int(graph_flags.get("qos_bulk_steps", 3) or 3) \
        or n_starts >= int(graph_flags.get("qos_bulk_starts", 32) or 32)

# retry-after hints are clamped: a zero-rate (deny-all) bucket would
# otherwise suggest an infinite wait, and sub-ms hints just busy-spin
# well-behaved clients
MIN_RETRY_AFTER_MS = 25
MAX_RETRY_AFTER_MS = 60_000


class OverloadShed(Exception):
    """Raised by the dispatcher when a watermark sheds this request.
    Converted to a typed ``E_OVERLOAD`` Result at the engine seam —
    shedding must surface as a retryable client error, NEVER degrade to
    the CPU pipe (that would shift the overload, not shed it)."""

    def __init__(self, reason: str, retry_after_ms: int):
        self.reason = reason
        self.retry_after_ms = int(retry_after_ms)
        super().__init__(
            f"overloaded: shed at {reason} watermark (E_OVERLOAD, "
            f"retryable); retry in ~{self.retry_after_ms}ms")


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill up to `burst`.
    `try_acquire` never blocks — it returns (admitted, retry_after_s),
    the retry hint being the exact refill time the missing tokens
    need."""

    __slots__ = ("rate", "burst", "_tokens", "_t", "_clock", "_lock")

    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic):
        self.rate = max(float(rate), 0.0)
        self.burst = max(float(burst), 1.0)
        self._tokens = self.burst
        self._clock = clock
        self._t = clock()
        self._lock = threading.Lock()

    def try_acquire(self, cost: float = 1.0) -> Tuple[bool, float]:
        with self._lock:
            if self.rate <= 0:
                # rate=0 is the deny-all policy (emergency tenant
                # block): no refill means any banked burst would be a
                # one-shot leak per plan swap, so deny outright
                return False, MAX_RETRY_AFTER_MS / 1e3
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= cost:
                self._tokens -= cost
                return True, 0.0
            return False, (cost - self._tokens) / self.rate

    def tokens(self) -> float:
        with self._lock:
            now = self._clock()
            if self.rate > 0:
                self._tokens = min(
                    self.burst, self._tokens + (now - self._t) * self.rate)
            self._t = now
            return self._tokens


class _Policy:
    __slots__ = ("rate", "burst", "lane")

    def __init__(self, rate: float, burst: Optional[float] = None,
                 lane: Optional[str] = None):
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None \
            else max(self.rate, 1.0)
        self.lane = lane

    def describe(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"rate": self.rate, "burst": self.burst}
        if self.lane:
            out["lane"] = self.lane
        return out


class AdmissionController:
    """Per-space token-bucket admission. `admit(space)` costs one dict
    probe + one bucket op when a plan is armed, nothing when it is not
    — cheap enough for every statement."""

    def __init__(self, clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self._plan = ""
        self._policies: Dict[str, _Policy] = {}
        self._default: Optional[_Policy] = None
        self._buckets: Dict[str, TokenBucket] = {}
        self.admitted: Dict[str, int] = {}
        self.denied: Dict[str, int] = {}

    # ----------------------------------------------------------- plan
    def set_plan(self, plan: str) -> None:
        """Parse + install a plan string (module doc grammar). An empty
        plan clears every policy. Raises ValueError on a malformed
        plan, leaving the previous plan installed. Counters survive a
        plan swap (observability never resets); buckets reset so the
        new budgets take effect immediately."""
        policies: Dict[str, _Policy] = {}
        default: Optional[_Policy] = None
        for part in (plan or "").split(";"):
            part = part.strip()
            if not part:
                continue
            name, _, args = part.partition(":")
            name = name.strip()
            if not name:
                raise ValueError(f"bad qos plan entry {part!r}")
            kw: Dict[str, Any] = {}
            for a in args.split(","):
                a = a.strip()
                if not a:
                    continue
                k, eq, v = a.partition("=")
                if not eq:
                    raise ValueError(f"bad qos arg {a!r} in {part!r}")
                if k == "rate":
                    kw["rate"] = float(v)
                elif k == "burst":
                    kw["burst"] = float(v)
                elif k == "lane":
                    if v not in LANES:
                        raise ValueError(
                            f"unknown lane {v!r} in {part!r} "
                            f"(expected one of {LANES})")
                    kw["lane"] = v
                else:
                    raise ValueError(f"unknown qos arg {k!r} in {part!r}")
            if "rate" not in kw:
                raise ValueError(f"qos entry {part!r} needs rate=<per_s>")
            if name == "*":
                default = _Policy(**kw)
            else:
                policies[name] = _Policy(**kw)
        with self._lock:
            self._plan = plan or ""
            self._policies = policies
            self._default = default
            self._buckets = {}

    def clear(self) -> None:
        self.set_plan("")

    def armed(self) -> bool:
        return bool(self._policies) or self._default is not None

    # ---------------------------------------------------------- admit
    def admit(self, space: str) -> Tuple[bool, int, Optional[str]]:
        """-> (admitted, retry_after_ms, lane_override). Unlimited
        spaces admit with no counter churn beyond the per-space
        admitted tally."""
        with self._lock:
            pol = self._policies.get(space) or self._default
            if pol is None:
                self.admitted[space] = self.admitted.get(space, 0) + 1
                return True, 0, None
            bucket = self._buckets.get(space)
            if bucket is None:
                bucket = TokenBucket(pol.rate, pol.burst,
                                     clock=self._clock)
                self._buckets[space] = bucket
        ok, retry_s = bucket.try_acquire()
        retry_ms = min(max(int(retry_s * 1000) + 1, MIN_RETRY_AFTER_MS),
                       MAX_RETRY_AFTER_MS)
        with self._lock:
            if ok:
                self.admitted[space] = self.admitted.get(space, 0) + 1
            else:
                self.denied[space] = self.denied.get(space, 0) + 1
        if ok:
            global_stats.add_value("graph.qos.admitted", kind="counter")
            # per-tenant good/bad slices: the availability SLOs ride
            # these (common/slo.py — bad=graph.qos.denied.<space>,
            # good=graph.qos.admitted.<space>)
            global_stats.add_value("graph.qos.admitted." + space,
                                   kind="counter")
        else:
            global_stats.add_value("graph.qos.admission_denied",
                                   kind="counter")
            global_stats.add_value("graph.qos.denied." + space,
                                   kind="counter")
            # retry-after distribution (histogram: exemplars join a
            # denial to the trace that was denied) + the flight
            # recorder's shed_storm input
            global_stats.add_value("graph.qos.retry_after_ms",
                                   retry_ms, kind="histogram")
            from . import flight
            flight.recorder.record("admission_denied", space=space,
                                   retry_after_ms=retry_ms)
        return ok, (0 if ok else retry_ms), pol.lane

    # ---------------------------------------------------- observation
    def describe(self) -> Dict[str, Any]:
        """JSON-able controller state for /qos and the /tpu_stats qos
        block — the per-tenant admission slices."""
        with self._lock:
            spaces: Dict[str, Any] = {}
            names = set(self._policies) | set(self.admitted) \
                | set(self.denied)
            for name in sorted(names):
                pol = self._policies.get(name)
                entry: Dict[str, Any] = {
                    "admitted": self.admitted.get(name, 0),
                    "denied": self.denied.get(name, 0),
                }
                if pol is not None:
                    entry["policy"] = pol.describe()
                    b = self._buckets.get(name)
                    if b is not None:
                        entry["tokens"] = round(b.tokens(), 2)
                spaces[name] = entry
            return {
                "plan": self._plan,
                "armed": bool(self._policies) or self._default is not None,
                "default": self._default.describe()
                if self._default else None,
                "spaces": spaces,
            }

    def reset(self) -> None:
        """Disarm AND zero counters (test isolation only)."""
        with self._lock:
            self._plan = ""
            self._policies = {}
            self._default = None
            self._buckets = {}
            self.admitted = {}
            self.denied = {}


# process-global instance (the gflags-style singleton, like faults)
admission = AdmissionController()


def _wire_flags() -> None:
    """QoS graphd flags, declared next to the controller they drive
    (the `fault_plan` idiom — common/faults.py)."""
    from .flags import MUTABLE, graph_flags
    graph_flags.declare(
        "qos_plan", "", MUTABLE,
        "per-space admission plan (common/qos.py grammar, e.g. "
        "'bulkspace:rate=5,burst=10,lane=bulk;*:rate=500'); empty "
        "clears (admission wide open)")
    graph_flags.declare(
        "qos_shed_queue_depth", 0, MUTABLE,
        "dispatcher queue-depth shed watermark: bulk-lane requests "
        "shed (typed E_OVERLOAD) when the dispatch queue is this "
        "deep, interactive at 2x. 0 disables")
    graph_flags.declare(
        "qos_shed_wait_p95_ms", 0, MUTABLE,
        "group-wait p95 shed watermark (ms over the recent-round "
        "window): bulk sheds at 1x, interactive at 2x — engages "
        "before tpu_query_deadline_ms so deadline balks stay the "
        "last resort. 0 disables")
    graph_flags.declare(
        "qos_bulk_steps", 3, MUTABLE,
        "GO statements with at least this many steps classify onto "
        "the bulk dispatcher lane (session/plan overrides win)")
    graph_flags.declare(
        "qos_bulk_starts", 32, MUTABLE,
        "GO statements expanding at least this many start vertices "
        "classify onto the bulk lane")

    def _apply(name: str, value: Any) -> None:
        if name == "qos_plan":
            try:
                admission.set_plan(str(value or ""))
            except ValueError as e:
                # a bad hot-set must never kill the watcher — but the
                # flag value and the armed controller have just
                # diverged, and that must be VISIBLE (the /qos
                # endpoint 400s; this path can't): log + count
                import logging
                logging.getLogger("nebula_tpu.qos").warning(
                    "qos_plan flag rejected, previous plan kept: %s", e)
                global_stats.add_value("graph.qos.bad_plan",
                                       kind="counter")

    graph_flags.watch(_apply)


_wire_flags()


# ---------------------------------------------------------------------------
# per-query deadline context (satellite: retry budgets must not outlive
# the query's own deadline — docs/manual/14-qos.md, watermark ladder)
# ---------------------------------------------------------------------------

_query_deadline: ContextVar[Optional[float]] = ContextVar(
    "nebula_tpu_query_deadline", default=None)


def set_query_deadline(deadline_monotonic: Optional[float]):
    """Arm this thread/context's query deadline (absolute
    time.monotonic() seconds). Returns the reset token."""
    return _query_deadline.set(deadline_monotonic)


def clear_query_deadline(token) -> None:
    _query_deadline.reset(token)


def deadline_remaining_s() -> Optional[float]:
    """Seconds left on the current query's deadline; None when no
    deadline is armed. Negative means it already passed."""
    dl = _query_deadline.get()
    if dl is None:
        return None
    return dl - time.monotonic()
