"""SLO engine: declarative objectives evaluated as multi-window burn
rates over the native histograms (docs/manual/10-observability.md).

An OBJECTIVE declares what "good" means for a slice of traffic —
availability (good/bad event counters, e.g. the QoS per-tenant
admission slices) or a latency threshold (fraction of a histogram
metric's samples at or under ``le_ms``) — plus a target (0.999 means
an error budget of 0.1%). The engine evaluates each objective over
the StatsManager's trailing windows (60 s / 600 s / 3600 s) as a BURN
RATE: ``bad_ratio / error_budget`` — burn 1.0 spends the budget
exactly at the sustainable rate, burn 10 spends a day of budget in
~2.4 hours. An objective BREACHES when the burn rate is over its
threshold on BOTH the short (60 s) and medium (600 s) windows — the
short window confirms the problem is happening *now*, the longer one
that it is material, the standard multi-window guard against
one-blip paging.

A breach transition records a ``slo_burn`` event into the flight
recorder (common/flight.py) whose ``slo_burn`` trigger captures a
bundle and arms trace sampling — closing the loop: breach -> bundle
-> exemplar -> trace.

Plan grammar (the qos_plan/fault_plan idiom; MUTABLE flag ``slo_plan``
and the graphd ``/slo`` endpoint):

    <name>:kind=latency,metric=<hist>,le_ms=<N>,target=<0..1>[,burn=<N>]
    <name>:kind=availability,good=<metric>,bad=<metric>,target=<0..1>[,burn=<N>]

entries joined by ``;``. ``burn`` defaults to 10. Objectives are
surfaced at ``/slo`` (JSON) and as Prometheus gauges
(``nebula_slo_<name>_burn_60s`` / ``_burn_600s`` / ``_burn_3600s`` /
``_breached`` / ``_breaches``) on every daemon's ``/metrics``.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .flags import MUTABLE, graph_flags
from .stats import StatsManager, WINDOWS
from .stats import stats as global_stats

DEFAULT_BURN_THRESHOLD = 10.0
# the multi-window breach pair: short confirms "now", medium "material"
BREACH_WINDOWS = (WINDOWS[0], WINDOWS[1])


class Objective:
    """One parsed SLO."""

    __slots__ = ("name", "kind", "target", "burn_threshold",
                 "metric", "le_us", "good", "bad",
                 "breached", "breaches", "last_breach_ts")

    def __init__(self, name: str, kind: str, target: float,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD,
                 metric: Optional[str] = None,
                 le_us: Optional[float] = None,
                 good: Optional[str] = None,
                 bad: Optional[str] = None):
        if kind not in ("latency", "availability"):
            raise ValueError(f"slo {name!r}: unknown kind {kind!r}")
        if not (0.0 < target < 1.0):
            raise ValueError(f"slo {name!r}: target must be in (0, 1)")
        if burn_threshold <= 0:
            raise ValueError(f"slo {name!r}: burn must be > 0")
        if kind == "latency" and (not metric or not le_us or le_us <= 0):
            raise ValueError(
                f"slo {name!r}: latency needs metric= and le_ms= > 0")
        if kind == "availability" and (not good or not bad):
            raise ValueError(
                f"slo {name!r}: availability needs good= and bad=")
        self.name = name
        self.kind = kind
        self.target = float(target)
        self.burn_threshold = float(burn_threshold)
        self.metric = metric
        self.le_us = le_us
        self.good = good
        self.bad = bad
        self.breached = False
        self.breaches = 0
        self.last_breach_ts = 0.0

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def describe(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name, "kind": self.kind, "target": self.target,
            "burn_threshold": self.burn_threshold,
            "breached": self.breached, "breaches": self.breaches,
            "last_breach_ts": self.last_breach_ts,
        }
        if self.kind == "latency":
            out["metric"] = self.metric
            out["le_ms"] = (self.le_us or 0) / 1000.0
        else:
            out["good"] = self.good
            out["bad"] = self.bad
        return out


def parse_plan(plan: str) -> List[Objective]:
    """Plan string -> objectives; raises ValueError on any malformed
    entry (the caller keeps its previous plan, like qos/fault plans)."""
    out: List[Objective] = []
    seen = set()
    for part in (plan or "").split(";"):
        part = part.strip()
        if not part:
            continue
        name, colon, args = part.partition(":")
        name = name.strip()
        if not name or not colon:
            raise ValueError(f"bad slo entry {part!r} "
                             f"(want <name>:k=v,...)")
        if name in seen:
            raise ValueError(f"duplicate slo name {name!r}")
        seen.add(name)
        kw: Dict[str, Any] = {}
        for a in args.split(","):
            a = a.strip()
            if not a:
                continue
            k, eq, v = a.partition("=")
            if not eq:
                raise ValueError(f"bad slo arg {a!r} in {part!r}")
            if k == "kind":
                kw["kind"] = v
            elif k == "metric":
                kw["metric"] = v
            elif k == "le_ms":
                kw["le_us"] = float(v) * 1000.0
            elif k == "target":
                kw["target"] = float(v)
            elif k == "burn":
                kw["burn_threshold"] = float(v)
            elif k == "good":
                kw["good"] = v
            elif k == "bad":
                kw["bad"] = v
            else:
                raise ValueError(f"unknown slo arg {k!r} in {part!r}")
        if "kind" not in kw or "target" not in kw:
            raise ValueError(f"slo entry {part!r} needs kind= and "
                             f"target=")
        out.append(Objective(name, **kw))
    return out


class SloEngine:
    """Objectives + evaluation + the background evaluator that makes
    breaches fire without anyone scraping."""

    EVAL_PERIOD_S = 1.0

    def __init__(self, stats: Optional[StatsManager] = None,
                 flight_recorder=None):
        self._stats = stats if stats is not None else global_stats
        self._flight = flight_recorder
        self._lock = threading.Lock()
        self._plan = ""
        self._objectives: List[Objective] = []
        self._stop: Optional[threading.Event] = None
        # (monotonic ts, result) of the last evaluate() — scrape-path
        # readers (gauges/describe) serve this instead of
        # re-evaluating: a read endpoint must not do O(window) work
        # per scrape nor flip breach state on its own cadence
        self._last_eval: Optional[Tuple[float, List[Dict[str, Any]]]] \
            = None

    # ----------------------------------------------------------- plan
    def set_plan(self, plan: str) -> None:
        objectives = parse_plan(plan)      # raises before any mutation
        with self._lock:
            self._plan = plan or ""
            self._objectives = objectives
            self._last_eval = None   # never serve the old plan's view
            if objectives and self._stop is None:
                self._start_evaluator_locked()
            elif not objectives and self._stop is not None:
                self._stop.set()
                self._stop = None

    def clear(self) -> None:
        self.set_plan("")

    def _start_evaluator_locked(self) -> None:
        stop = self._stop = threading.Event()

        def run() -> None:
            while not stop.wait(self.EVAL_PERIOD_S):
                try:
                    self.evaluate()
                except Exception:   # the evaluator must never die
                    pass

        # nlint: disable=NL002 -- plan-lifetime evaluator loop, not
        # request-scoped work (stops when the plan empties)
        t = threading.Thread(target=run, daemon=True,
                             name="slo-evaluator")
        t.start()

    # ----------------------------------------------------- evaluation
    def _ratio(self, obj: Objective, window: int) -> Dict[str, float]:
        """Bad-event ratio for one window: {bad, total, ratio, burn}."""
        if obj.kind == "latency":
            good, total = self._stats.window_le(
                obj.metric, obj.le_us, window)
            bad = total - good
        else:
            good = self._stats.read_stats(
                f"{obj.good}.sum.{window}") or 0.0
            bad = self._stats.read_stats(
                f"{obj.bad}.sum.{window}") or 0.0
            total = good + bad
        ratio = (bad / total) if total else 0.0
        return {"bad": bad, "total": total, "ratio": round(ratio, 6),
                "burn": round(ratio / obj.budget, 4)}

    def evaluate(self) -> List[Dict[str, Any]]:
        """Evaluate every objective over all windows; update breach
        state; record breach transitions into the flight recorder
        (slo_burn trigger) and the breach counters."""
        with self._lock:
            objectives = list(self._objectives)
        out: List[Dict[str, Any]] = []
        for obj in objectives:
            windows = {w: self._ratio(obj, w) for w in WINDOWS}
            burning = all(windows[w]["burn"] >= obj.burn_threshold
                          for w in BREACH_WINDOWS)
            # transition under the lock: evaluate() runs concurrently
            # from the evaluator thread, /metrics scrapes and /slo
            # GETs — an unguarded check-then-set would double-count a
            # breach (two slo_burn events, double-paged alerting)
            fired = recovered = False
            with self._lock:
                if burning and not obj.breached:
                    obj.breached = True
                    obj.breaches += 1
                    obj.last_breach_ts = time.time()
                    fired = True
                elif not burning and obj.breached:
                    obj.breached = False
                    recovered = True
            if fired:
                global_stats.add_value("slo.breach." + obj.name,
                                       kind="counter")
                fr = self._flight
                if fr is None:
                    from . import flight
                    fr = flight.recorder
                fr.record("slo_burn", objective=obj.name,
                          burn_60s=windows[BREACH_WINDOWS[0]]["burn"],
                          burn_600s=windows[BREACH_WINDOWS[1]]["burn"],
                          target=obj.target)
            elif recovered:
                global_stats.add_value("slo.recovered." + obj.name,
                                       kind="counter")
            rec = obj.describe()
            rec["windows"] = {str(w): windows[w] for w in WINDOWS}
            out.append(rec)
        with self._lock:
            self._last_eval = (time.monotonic(), out)
        return out

    def _cached_eval(self) -> List[Dict[str, Any]]:
        """Last evaluate() result if fresher than one evaluator
        period; re-evaluates otherwise. With a plan armed, the
        evaluator thread keeps this fresh, so scrape-path readers
        never re-do the O(window) work nor flip breach state on the
        scrape cadence."""
        with self._lock:
            cached = self._last_eval
        if cached is not None and \
                time.monotonic() - cached[0] < self.EVAL_PERIOD_S:
            return cached[1]
        return self.evaluate()

    # ---------------------------------------------------- observation
    def describe(self) -> Dict[str, Any]:
        with self._lock:
            plan = self._plan
        return {"plan": plan, "objectives": self._cached_eval(),
                "windows": list(WINDOWS),
                "breach_windows": list(BREACH_WINDOWS)}

    def gauges(self) -> Dict[str, float]:
        """Flat /metrics gauges per objective: burn rate per window,
        the breached flag, lifetime breach count."""
        out: Dict[str, float] = {}
        for rec in self._cached_eval():
            base = "slo." + rec["name"]
            for w, wrec in rec["windows"].items():
                out[f"{base}.burn_{w}s"] = wrec["burn"]
            out[base + ".breached"] = 1.0 if rec["breached"] else 0.0
            out[base + ".breaches"] = float(rec["breaches"])
        return out

    def reset(self) -> None:
        """Test/bench isolation: drop the plan and stop the
        evaluator."""
        self.set_plan("")


# declared + watched on EVERY registry: each daemon's /flags serves
# only its own (graph/storage/meta), and all three daemons serve /slo
from .flags import meta_flags, storage_flags  # noqa: E402

for _reg in (graph_flags, storage_flags, meta_flags):
    _reg.declare(
        "slo_plan", "", MUTABLE,
        "declarative SLO objectives (common/slo.py grammar, e.g. "
        "'latency:kind=latency,metric=graph.query_latency_us,"
        "le_ms=50,target=0.99'); empty disarms")


def _on_flag(name: str, value: Any) -> None:
    if name != "slo_plan":
        return
    try:
        engine.set_plan(str(value or ""))
    except ValueError as e:
        # a bad hot-set keeps the previous plan, visibly (the /slo
        # endpoint 400s; the flag path can only log + count)
        import logging
        logging.getLogger("nebula_tpu.slo").warning(
            "slo_plan flag rejected, previous plan kept: %s", e)
        global_stats.add_value("slo.bad_plan", kind="counter")


# process-global instance (the qos/faults singleton idiom)
engine = SloEngine()
for _reg in (graph_flags, storage_flags, meta_flags):
    _reg.watch(_on_flag)
