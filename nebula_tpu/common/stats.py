"""Metrics: counters + windowed histograms.

Role parity with the reference's `common/stats/StatsManager.{h,cpp}`:
metrics are registered once and fed values; readers query dotted names
like `query.rate.60`, `query_latency_us.p99.600` — method ∈ {sum, count,
avg, rate, p<NN>} over trailing windows of 60 s / 600 s / 3600 s (the
reference's 1 m / 10 m / 1 h granularity, StatsManager.h:20-88).

Implementation: per metric a ring of per-second buckets (sum, count,
plus a small fixed log-scale histogram for percentiles) covering the
largest window; thread-safe; O(window) reads, O(1) writes.
"""
from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Dict, List, Optional, Tuple

WINDOWS = (60, 600, 3600)

# log-scale histogram bounds: 1..10^9, 90 buckets (10 per decade)
_BOUNDS: List[float] = [
    10 ** (d + i / 10.0) for d in range(9) for i in range(10)]


def _bucket_of(v: float) -> int:
    if v <= 1:
        return 0
    return min(bisect.bisect_left(_BOUNDS, v), len(_BOUNDS) - 1)


class _Metric:
    __slots__ = ("lock", "sums", "counts", "hists", "head_sec",
                 "kind", "life_sum", "life_count")

    def __init__(self, now_sec: int, kind: Optional[str] = None):
        n = WINDOWS[-1]
        self.lock = threading.Lock()
        self.sums = [0.0] * n
        self.counts = [0] * n
        self.hists = [None] * n          # lazily allocated per-second hist
        self.head_sec = now_sec
        # "counter" | "timing" | None (legacy, untagged) — fixed by the
        # first add_value call-site that opts in; drives which snapshot
        # methods make sense (a pure counter never fed a histogram-worthy
        # value distribution, so p95/p99/avg over it are noise) and the
        # Prometheus # TYPE annotation
        self.kind = kind
        # lifetime accumulators: Prometheus counters are cumulative,
        # the trailing windows above are not
        self.life_sum = 0.0
        self.life_count = 0

    def _advance(self, now_sec: int) -> None:
        gap = now_sec - self.head_sec
        if gap <= 0:
            return
        n = WINDOWS[-1]
        for k in range(1, min(gap, n) + 1):
            i = (self.head_sec + k) % n
            self.sums[i] = 0.0
            self.counts[i] = 0
            self.hists[i] = None
        self.head_sec = now_sec

    def add(self, value: float, now_sec: int) -> None:
        with self.lock:
            self._advance(now_sec)
            i = now_sec % WINDOWS[-1]
            self.sums[i] += value
            self.counts[i] += 1
            self.life_sum += value
            self.life_count += 1
            h = self.hists[i]
            if h is None:
                h = self.hists[i] = {}
            b = _bucket_of(value)
            h[b] = h.get(b, 0) + 1

    def read(self, method: str, window: int, now_sec: int) -> float:
        with self.lock:
            self._advance(now_sec)
            n = WINDOWS[-1]
            idxs = [(now_sec - k) % n for k in range(window)]
            if method == "sum":
                return sum(self.sums[i] for i in idxs)
            if method == "count":
                return float(sum(self.counts[i] for i in idxs))
            if method == "avg":
                c = sum(self.counts[i] for i in idxs)
                return sum(self.sums[i] for i in idxs) / c if c else 0.0
            if method == "rate":
                return sum(self.counts[i] for i in idxs) / float(window)
            if method.startswith("p"):
                digits = method[1:]
                # p50 -> 50, p99 -> 99, p999 -> 99.9
                q = float(digits) / (10 ** (len(digits) - 2))
                merged: Dict[int, int] = {}
                for i in idxs:
                    h = self.hists[i]
                    if h:
                        for b, c in h.items():
                            merged[b] = merged.get(b, 0) + c
                total = sum(merged.values())
                if total == 0:
                    return 0.0
                target = math.ceil(total * q / 100.0)
                acc = 0
                for b in sorted(merged):
                    acc += merged[b]
                    if acc >= target:
                        return _BOUNDS[b]
                return _BOUNDS[max(merged)]
            raise ValueError(f"bad stats method {method!r}")


class StatsManager:
    """Process-global metric registry (instantiable for tests)."""

    def __init__(self, clock=time.time):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._clock = clock

    def add_value(self, name: str, value: float = 1.0,
                  kind: Optional[str] = None) -> None:
        """`kind` is a call-site opt-in fixed at FIRST registration:
        "counter" (monotonic event counts — snapshot/Prometheus emit
        rate + totals only) or "timing" (a value distribution — avg and
        percentiles are meaningful). Untagged metrics keep the legacy
        emit-everything behavior; read_stats accepts any method for any
        kind (backward-compatible specs)."""
        now_sec = int(self._clock())
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.setdefault(name, _Metric(now_sec, kind))
        m.add(value, now_sec)

    def read_stats(self, spec: str) -> Optional[float]:
        """spec = '<name>.<method>.<window-secs>'."""
        try:
            name, method, window_s = spec.rsplit(".", 2)
            window = int(window_s)
        except ValueError:
            return None
        if window not in WINDOWS:
            return None
        m = self._metrics.get(name)
        if m is None:
            return None
        try:
            return m.read(method, window, int(self._clock()))
        except ValueError:
            return None

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def lifetime_total(self, name: str) -> float:
        """Cumulative sum since process start (the Prometheus `_total`
        value) — 0.0 for a metric never reported."""
        m = self._metrics.get(name)
        return float(m.life_sum) if m is not None else 0.0

    # which snapshot methods make sense per metric kind: counters get
    # rate/sum (their p95 would always be the bucket of 1.0 — noise),
    # timings get the distribution views, untagged keeps legacy output
    _KIND_METHODS = {"counter": ("rate", "sum"),
                     "timing": ("rate", "avg", "p95", "p99"),
                     None: ("rate", "sum", "avg", "p95", "p99")}

    def snapshot(self, windows: Tuple[int, ...] = (60,)) -> Dict[str, float]:
        out = {}
        for name in self.names():
            methods = self._KIND_METHODS.get(self._metrics[name].kind,
                                             self._KIND_METHODS[None])
            for w in windows:
                for method in methods:
                    v = self.read_stats(f"{name}.{method}.{w}")
                    if v is not None:
                        out[f"{name}.{method}.{w}"] = v
        return out

    def prometheus_lines(self, prefix: str = "nebula") -> List[str]:
        """Prometheus text exposition of every metric (served by
        /metrics). Counters (and untagged metrics' totals) become
        cumulative `_total` counters from the lifetime accumulators;
        timings additionally expose 60s-window avg/p95/p99 gauges.
        Names are stable: `<prefix>_<name>` with non-alphanumerics
        folded to '_'."""
        now = int(self._clock())
        lines: List[str] = []
        for name in self.names():
            m = self._metrics[name]
            base = _prom_name(prefix, name)
            with m.lock:
                life_sum, life_count = m.life_sum, m.life_count
            lines.append(f"# TYPE {base}_total counter")
            lines.append(f"{base}_total {_prom_num(life_sum)}")
            if m.kind == "counter":
                continue
            lines.append(f"# TYPE {base}_count_total counter")
            lines.append(f"{base}_count_total {life_count}")
            for method in ("avg", "p95", "p99"):
                v = m.read(method, 60, now)
                lines.append(f"# TYPE {base}_{method}_60s gauge")
                lines.append(f"{base}_{method}_60s {_prom_num(v)}")
        return lines


def _prom_name(prefix: str, name: str) -> str:
    safe = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    return f"{prefix}_{safe}"


def _prom_num(v: float) -> str:
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


# process-global instance (the reference's static StatsManager)
stats = StatsManager()


class Duration:
    """Scoped latency helper feeding a metric in microseconds."""

    def __init__(self, manager: StatsManager, metric: str):
        self._m = manager
        self._metric = metric
        self._t0 = time.perf_counter()

    def elapsed_us(self) -> int:
        return int((time.perf_counter() - self._t0) * 1e6)

    def record(self) -> int:
        us = self.elapsed_us()
        self._m.add_value(self._metric, us, kind="timing")
        return us
