"""Metrics: counters + windowed histograms.

Role parity with the reference's `common/stats/StatsManager.{h,cpp}`:
metrics are registered once and fed values; readers query dotted names
like `query.rate.60`, `query_latency_us.p99.600` — method ∈ {sum, count,
avg, rate, p<NN>} over trailing windows of 60 s / 600 s / 3600 s (the
reference's 1 m / 10 m / 1 h granularity, StatsManager.h:20-88).

Implementation: per metric a ring of per-second buckets (sum, count,
plus a small fixed log-scale histogram for percentiles) covering the
largest window; thread-safe; O(window) reads, O(1) writes.
"""
from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Dict, List, Optional, Tuple

WINDOWS = (60, 600, 3600)

# log-scale histogram bounds: 1..10^9, 90 buckets (10 per decade)
_BOUNDS: List[float] = [
    10 ** (d + i / 10.0) for d in range(9) for i in range(10)]

# exposition buckets for kind="histogram" (native Prometheus
# histograms): every EXPO_STEP-th internal bound — 30 `le` bounds per
# series plus +Inf keeps /metrics readable while window_le/percentile
# math keeps the full 90-bucket resolution
_EXPO_STEP = 3
EXPO_BOUNDS: List[float] = [
    _BOUNDS[i] for i in range(_EXPO_STEP - 1, len(_BOUNDS), _EXPO_STEP)]


def _bucket_of(v: float) -> int:
    if v <= 1:
        return 0
    return min(bisect.bisect_left(_BOUNDS, v), len(_BOUNDS) - 1)


class _Metric:
    __slots__ = ("lock", "sums", "counts", "hists", "head_sec",
                 "kind", "life_sum", "life_count", "life_buckets",
                 "life_over", "exemplars")

    def __init__(self, now_sec: int, kind: Optional[str] = None):
        n = WINDOWS[-1]
        self.lock = threading.Lock()
        self.sums = [0.0] * n
        self.counts = [0] * n
        self.hists = [None] * n          # lazily allocated per-second hist
        self.head_sec = now_sec
        # "counter" | "timing" | "histogram" | None (legacy, untagged)
        # — fixed by the first add_value call-site that opts in; drives
        # which snapshot methods make sense (a pure counter never fed a
        # histogram-worthy value distribution, so p95/p99/avg over it
        # are noise) and the Prometheus # TYPE annotation. "histogram"
        # additionally keeps cumulative bucket counts + per-bucket
        # exemplars and exposes real `_bucket`/`_sum`/`_count` series.
        self.kind = kind
        # lifetime accumulators: Prometheus counters are cumulative,
        # the trailing windows above are not
        self.life_sum = 0.0
        self.life_count = 0
        if kind == "histogram":
            self.life_buckets = [0] * len(EXPO_BOUNDS)
            self.life_over = 0           # the +Inf bucket's own count
            # exposition-bucket idx -> (trace_id, value, unix_ts): the
            # OpenMetrics exemplar linking a bucket to the trace of a
            # sample that landed in it (newest kept)
            self.exemplars: Dict[int, Tuple[str, float, float]] = {}
        else:
            self.life_buckets = None
            self.life_over = 0
            self.exemplars = None

    def _advance(self, now_sec: int) -> None:
        gap = now_sec - self.head_sec
        if gap <= 0:
            return
        n = WINDOWS[-1]
        for k in range(1, min(gap, n) + 1):
            i = (self.head_sec + k) % n
            self.sums[i] = 0.0
            self.counts[i] = 0
            self.hists[i] = None
        self.head_sec = now_sec

    def add(self, value: float, now_sec: int,
            trace_id: Optional[str] = None,
            now: Optional[float] = None) -> None:
        with self.lock:
            self._advance(now_sec)
            i = now_sec % WINDOWS[-1]
            self.sums[i] += value
            self.counts[i] += 1
            self.life_sum += value
            self.life_count += 1
            h = self.hists[i]
            if h is None:
                h = self.hists[i] = {}
            b = _bucket_of(value)
            h[b] = h.get(b, 0) + 1
            if self.life_buckets is not None:
                if value > _BOUNDS[-1]:
                    self.life_over += 1
                    eb = len(EXPO_BOUNDS)
                else:
                    eb = b // _EXPO_STEP
                    self.life_buckets[eb] += 1
                if trace_id:
                    self.exemplars[eb] = (
                        trace_id, float(value),
                        float(now if now is not None else now_sec))

    def read(self, method: str, window: int, now_sec: int) -> float:
        with self.lock:
            self._advance(now_sec)
            n = WINDOWS[-1]
            idxs = [(now_sec - k) % n for k in range(window)]
            if method == "sum":
                return sum(self.sums[i] for i in idxs)
            if method == "count":
                return float(sum(self.counts[i] for i in idxs))
            if method == "avg":
                c = sum(self.counts[i] for i in idxs)
                return sum(self.sums[i] for i in idxs) / c if c else 0.0
            if method == "rate":
                return sum(self.counts[i] for i in idxs) / float(window)
            if method.startswith("p"):
                digits = method[1:]
                # p50 -> 50, p99 -> 99, p999 -> 99.9
                q = float(digits) / (10 ** (len(digits) - 2))
                merged: Dict[int, int] = {}
                for i in idxs:
                    h = self.hists[i]
                    if h:
                        for b, c in h.items():
                            merged[b] = merged.get(b, 0) + c
                total = sum(merged.values())
                if total == 0:
                    return 0.0
                target = math.ceil(total * q / 100.0)
                acc = 0
                for b in sorted(merged):
                    acc += merged[b]
                    if acc >= target:
                        return _BOUNDS[b]
                return _BOUNDS[max(merged)]
            raise ValueError(f"bad stats method {method!r}")


class StatsManager:
    """Process-global metric registry (instantiable for tests)."""

    def __init__(self, clock=time.time):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._clock = clock

    def add_value(self, name: str, value: float = 1.0,
                  kind: Optional[str] = None,
                  trace_id: Optional[str] = None) -> None:
        """`kind` is a call-site opt-in fixed at FIRST registration:
        "counter" (monotonic event counts — snapshot/Prometheus emit
        rate + totals only), "timing" (a value distribution — avg and
        percentiles are meaningful) or "histogram" (a native Prometheus
        histogram: real `_bucket`/`_sum`/`_count` series with
        OpenMetrics exemplars carrying the trace_id of a sample in
        that bucket). Untagged metrics keep the legacy emit-everything
        behavior; read_stats accepts any method for any kind
        (backward-compatible specs).

        For histograms, `trace_id` pins the exemplar explicitly (the
        dispatcher records waiters' waits under their own traces);
        left None, the current ContextVar trace context — if any — is
        captured. Pass "" to SUPPRESS the exemplar entirely — a
        call-site recording on behalf of another request (an unsampled
        waiter) must not fall back to the ambient (leader's) trace."""
        now = self._clock()
        now_sec = int(now)
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.setdefault(name, _Metric(now_sec, kind))
        if m.kind == "histogram" and trace_id is None:
            trace_id = current_trace_id()
        m.add(value, now_sec, trace_id=trace_id or None, now=now)

    def read_stats(self, spec: str) -> Optional[float]:
        """spec = '<name>.<method>.<window-secs>'."""
        try:
            name, method, window_s = spec.rsplit(".", 2)
            window = int(window_s)
        except ValueError:
            return None
        if window not in WINDOWS:
            return None
        m = self._metrics.get(name)
        if m is None:
            return None
        try:
            return m.read(method, window, int(self._clock()))
        except ValueError:
            return None

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def window_le(self, name: str, le: float,
                  window: int) -> Tuple[float, float]:
        """(samples <= `le`, total samples) over the trailing `window`
        seconds of a histogram/timing metric — the SLO engine's
        latency-compliance read (common/slo.py). Bucket-resolution:
        a threshold landing inside a bucket counts that bucket as bad
        (conservative: burn alerts err pessimistic). (0, 0) for an
        unknown metric or window."""
        if window not in WINDOWS:
            return 0.0, 0.0
        m = self._metrics.get(name)
        if m is None:
            return 0.0, 0.0
        now_sec = int(self._clock())
        # highest internal bucket whose upper bound is <= le
        cutoff = bisect.bisect_right(_BOUNDS, le) - 1
        with m.lock:
            m._advance(now_sec)
            n = WINDOWS[-1]
            good = 0
            total = 0
            for k in range(window):
                h = m.hists[(now_sec - k) % n]
                if not h:
                    continue
                for b, c in h.items():
                    total += c
                    if b <= cutoff:
                        good += c
        return float(good), float(total)

    def histogram_snapshot(self, name: str) -> Optional[Dict[str, object]]:
        """Lifetime bucket vector + exemplars of a histogram metric —
        what bench.py records into its JSON artifacts (bucket shape,
        not just p50/p95). None for unknown/non-histogram metrics."""
        m = self._metrics.get(name)
        if m is None or m.life_buckets is None:
            return None
        with m.lock:
            counts = list(m.life_buckets) + [m.life_over]
            exemplars = {
                i: {"trace_id": t, "value": v, "ts": ts}
                for i, (t, v, ts) in m.exemplars.items()}
            return {"bounds": list(EXPO_BOUNDS), "counts": counts,
                    "sum": m.life_sum, "count": m.life_count,
                    "exemplars": exemplars}

    def histogram_names(self) -> List[str]:
        return sorted(n for n, m in self._metrics.items()
                      if m.kind == "histogram")

    def lifetime_total(self, name: str) -> float:
        """Cumulative sum since process start (the Prometheus `_total`
        value) — 0.0 for a metric never reported."""
        m = self._metrics.get(name)
        return float(m.life_sum) if m is not None else 0.0

    # which snapshot methods make sense per metric kind: counters get
    # rate/sum (their p95 would always be the bucket of 1.0 — noise),
    # timings/histograms get the distribution views, untagged keeps
    # legacy output
    _KIND_METHODS = {"counter": ("rate", "sum"),
                     "timing": ("rate", "avg", "p95", "p99"),
                     "histogram": ("rate", "avg", "p95", "p99"),
                     None: ("rate", "sum", "avg", "p95", "p99")}

    def snapshot(self, windows: Tuple[int, ...] = (60,)) -> Dict[str, float]:
        out = {}
        for name in self.names():
            methods = self._KIND_METHODS.get(self._metrics[name].kind,
                                             self._KIND_METHODS[None])
            for w in windows:
                for method in methods:
                    v = self.read_stats(f"{name}.{method}.{w}")
                    if v is not None:
                        out[f"{name}.{method}.{w}"] = v
        return out

    def prometheus_lines(self, prefix: str = "nebula") -> List[str]:
        """OpenMetrics text exposition of every metric (served by
        /metrics; docs/manual/10-observability.md). Family TYPE lines
        declare the BASE name — counter samples carry the `_total`
        suffix per the OpenMetrics counter contract (the strict parser
        in tests/ enforces this). Counters (and untagged metrics'
        totals) expose cumulative `_total` samples from the lifetime
        accumulators; timings additionally expose `_count` +
        60s-window avg/p95/p99 gauges; histograms expose native
        `_bucket`/`_sum`/`_count` series with per-bucket OpenMetrics
        exemplars carrying the trace_id of a sample that landed in
        that bucket. Names are stable: `<prefix>_<name>` with
        non-alphanumerics folded to '_'."""
        now = int(self._clock())
        lines: List[str] = []
        for name in self.names():
            m = self._metrics[name]
            base = _prom_name(prefix, name)
            if m.kind == "histogram":
                lines.extend(self._histogram_lines(m, base, now))
                continue
            with m.lock:
                life_sum, life_count = m.life_sum, m.life_count
            lines.append(f"# TYPE {base} counter")
            lines.append(f"{base}_total {_prom_num(life_sum)}")
            if m.kind == "counter":
                continue
            lines.append(f"# TYPE {base}_count counter")
            lines.append(f"{base}_count_total {life_count}")
            for method in ("avg", "p95", "p99"):
                v = m.read(method, 60, now)
                lines.append(f"# TYPE {base}_{method}_60s gauge")
                lines.append(f"{base}_{method}_60s {_prom_num(v)}")
        return lines

    def _histogram_lines(self, m: _Metric, base: str,
                         now: int) -> List[str]:
        with m.lock:
            life_sum = m.life_sum
            counts = list(m.life_buckets)
            over = m.life_over
            exemplars = dict(m.exemplars)
        lines = [f"# TYPE {base} histogram"]
        acc = 0
        for i, le in enumerate(EXPO_BOUNDS):
            acc += counts[i]
            line = f'{base}_bucket{{le="{le:.6g}"}} {acc}'
            ex = exemplars.get(i)
            if ex is not None:
                line += _exemplar_suffix(ex)
            lines.append(line)
        total = acc + over
        line = f'{base}_bucket{{le="+Inf"}} {total}'
        ex = exemplars.get(len(EXPO_BOUNDS))
        if ex is not None:
            line += _exemplar_suffix(ex)
        lines.append(line)
        lines.append(f"{base}_sum {_prom_num(life_sum)}")
        lines.append(f"{base}_count {total}")
        # window gauges ride along (dashboard parity with timings —
        # the histogram series carry the shape, these the hot view)
        for method in ("avg", "p95", "p99"):
            v = m.read(method, 60, now)
            lines.append(f"# TYPE {base}_{method}_60s gauge")
            lines.append(f"{base}_{method}_60s {_prom_num(v)}")
        return lines


def _prom_name(prefix: str, name: str) -> str:
    safe = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    return f"{prefix}_{safe}"


def _prom_num(v: float) -> str:
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _exemplar_suffix(ex: Tuple[str, float, float]) -> str:
    """OpenMetrics exemplar: ` # {trace_id="<id>"} <value> <ts>` —
    the metric -> trace join (docs/manual/10-observability.md)."""
    trace_id, value, ts = ex
    return (f' # {{trace_id="{trace_id}"}} {_prom_num(value)} '
            f'{ts:.3f}')


_tracer_ref = None


def current_trace_id() -> Optional[str]:
    """trace_id of the live sampled trace, if any — one ContextVar
    read (lazy import: tracing itself reports metrics here). THE
    shared lookup for histogram exemplar capture and flight-recorder
    events (common/flight.py)."""
    global _tracer_ref
    if _tracer_ref is None:
        try:
            from . import tracing
        except Exception:
            return None
        _tracer_ref = tracing.tracer
    ctx = _tracer_ref.current_ctx()
    return ctx[0] if ctx else None


# process-global instance (the reference's static StatsManager)
stats = StatsManager()


class Duration:
    """Scoped latency helper feeding a metric in microseconds."""

    def __init__(self, manager: StatsManager, metric: str):
        self._m = manager
        self._metric = metric
        self._t0 = time.perf_counter()

    def elapsed_us(self) -> int:
        return int((time.perf_counter() - self._t0) * 1e6)

    def record(self) -> int:
        us = self.elapsed_us()
        self._m.add_value(self._metric, us, kind="timing")
        return us
