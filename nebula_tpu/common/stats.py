"""Metrics: counters + windowed histograms.

Role parity with the reference's `common/stats/StatsManager.{h,cpp}`:
metrics are registered once and fed values; readers query dotted names
like `query.rate.60`, `query_latency_us.p99.600` — method ∈ {sum, count,
avg, rate, p<NN>} over trailing windows of 60 s / 600 s / 3600 s (the
reference's 1 m / 10 m / 1 h granularity, StatsManager.h:20-88).

Implementation: per metric a ring of per-second buckets (sum, count,
plus a small fixed log-scale histogram for percentiles) covering the
largest window; thread-safe; O(window) reads, O(1) writes.
"""
from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Dict, List, Optional, Tuple

WINDOWS = (60, 600, 3600)

# log-scale histogram bounds: 1..10^9, 90 buckets (10 per decade)
_BOUNDS: List[float] = [
    10 ** (d + i / 10.0) for d in range(9) for i in range(10)]


def _bucket_of(v: float) -> int:
    if v <= 1:
        return 0
    return min(bisect.bisect_left(_BOUNDS, v), len(_BOUNDS) - 1)


class _Metric:
    __slots__ = ("lock", "sums", "counts", "hists", "head_sec")

    def __init__(self, now_sec: int):
        n = WINDOWS[-1]
        self.lock = threading.Lock()
        self.sums = [0.0] * n
        self.counts = [0] * n
        self.hists = [None] * n          # lazily allocated per-second hist
        self.head_sec = now_sec

    def _advance(self, now_sec: int) -> None:
        gap = now_sec - self.head_sec
        if gap <= 0:
            return
        n = WINDOWS[-1]
        for k in range(1, min(gap, n) + 1):
            i = (self.head_sec + k) % n
            self.sums[i] = 0.0
            self.counts[i] = 0
            self.hists[i] = None
        self.head_sec = now_sec

    def add(self, value: float, now_sec: int) -> None:
        with self.lock:
            self._advance(now_sec)
            i = now_sec % WINDOWS[-1]
            self.sums[i] += value
            self.counts[i] += 1
            h = self.hists[i]
            if h is None:
                h = self.hists[i] = {}
            b = _bucket_of(value)
            h[b] = h.get(b, 0) + 1

    def read(self, method: str, window: int, now_sec: int) -> float:
        with self.lock:
            self._advance(now_sec)
            n = WINDOWS[-1]
            idxs = [(now_sec - k) % n for k in range(window)]
            if method == "sum":
                return sum(self.sums[i] for i in idxs)
            if method == "count":
                return float(sum(self.counts[i] for i in idxs))
            if method == "avg":
                c = sum(self.counts[i] for i in idxs)
                return sum(self.sums[i] for i in idxs) / c if c else 0.0
            if method == "rate":
                return sum(self.counts[i] for i in idxs) / float(window)
            if method.startswith("p"):
                digits = method[1:]
                # p50 -> 50, p99 -> 99, p999 -> 99.9
                q = float(digits) / (10 ** (len(digits) - 2))
                merged: Dict[int, int] = {}
                for i in idxs:
                    h = self.hists[i]
                    if h:
                        for b, c in h.items():
                            merged[b] = merged.get(b, 0) + c
                total = sum(merged.values())
                if total == 0:
                    return 0.0
                target = math.ceil(total * q / 100.0)
                acc = 0
                for b in sorted(merged):
                    acc += merged[b]
                    if acc >= target:
                        return _BOUNDS[b]
                return _BOUNDS[max(merged)]
            raise ValueError(f"bad stats method {method!r}")


class StatsManager:
    """Process-global metric registry (instantiable for tests)."""

    def __init__(self, clock=time.time):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._clock = clock

    def add_value(self, name: str, value: float = 1.0) -> None:
        now_sec = int(self._clock())
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.setdefault(name, _Metric(now_sec))
        m.add(value, now_sec)

    def read_stats(self, spec: str) -> Optional[float]:
        """spec = '<name>.<method>.<window-secs>'."""
        try:
            name, method, window_s = spec.rsplit(".", 2)
            window = int(window_s)
        except ValueError:
            return None
        if window not in WINDOWS:
            return None
        m = self._metrics.get(name)
        if m is None:
            return None
        try:
            return m.read(method, window, int(self._clock()))
        except ValueError:
            return None

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def snapshot(self, windows: Tuple[int, ...] = (60,)) -> Dict[str, float]:
        out = {}
        for name in self.names():
            for w in windows:
                for method in ("rate", "sum", "avg", "p95", "p99"):
                    v = self.read_stats(f"{name}.{method}.{w}")
                    if v is not None:
                        out[f"{name}.{method}.{w}"] = v
        return out


# process-global instance (the reference's static StatsManager)
stats = StatsManager()


class Duration:
    """Scoped latency helper feeding a metric in microseconds."""

    def __init__(self, manager: StatsManager, metric: str):
        self._m = manager
        self._metric = metric
        self._t0 = time.perf_counter()

    def elapsed_us(self) -> int:
        return int((time.perf_counter() - self._t0) * 1e6)

    def record(self) -> int:
        us = self.elapsed_us()
        self._m.add_value(self._metric, us)
        return us
