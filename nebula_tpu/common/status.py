"""Status / StatusOr / error codes.

Role parity with the reference's `common/base/Status.h` (Status/StatusOr)
and the per-service ResultCode enums (storage.thrift, raftex.thrift):
every cross-service boundary returns typed error codes rather than
raising, so leader-redirects and partial failures can be handled per
partition exactly like the reference's per-part ResultCode plumbing.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Generic, Optional, TypeVar

T = TypeVar("T")


class ErrorCode(enum.IntEnum):
    """Unified error codes across services.

    Mirrors the union of the reference's graph/storage/meta/raft error
    enums (e.g. storage.thrift ErrorCode, meta.thrift ErrorCode) without
    copying their numbering.
    """

    SUCCEEDED = 0
    # generic
    E_ERROR = -1
    E_NOT_FOUND = -2
    E_EXISTED = -3
    E_INVALID_ARGUMENT = -4
    E_UNSUPPORTED = -5
    E_INTERNAL = -6
    E_TIMEOUT = -7
    # topology / routing
    E_LEADER_CHANGED = -11
    E_SPACE_NOT_FOUND = -12
    E_PART_NOT_FOUND = -13
    E_HOST_NOT_FOUND = -14
    E_WRONG_PARTITION = -15
    E_NO_HOSTS = -16
    E_WRONG_CLUSTER = -17
    # schema
    E_TAG_NOT_FOUND = -21
    E_EDGE_NOT_FOUND = -22
    E_SCHEMA_NOT_FOUND = -23
    E_INVALID_SCHEMA_VER = -24
    E_CONFLICT = -25
    E_INDEX_NOT_FOUND = -26
    # storage
    E_KEY_NOT_FOUND = -31
    E_CONSENSUS_ERROR = -32
    E_FILTER_OUT = -33
    E_INVALID_FILTER = -34
    E_INVALID_UPDATER = -35
    E_INVALID_DATA = -36
    E_CHECKPOINT_ERROR = -37
    # raft
    E_LOG_GAP = -41
    E_LOG_STALE = -42
    E_TERM_OUT_OF_DATE = -43
    E_WAITING_SNAPSHOT = -44
    E_BAD_STATE = -45
    E_NOT_A_LEADER = -46
    E_WAL_FAIL = -47
    # session / auth
    E_SESSION_INVALID = -51
    E_BAD_USERNAME_PASSWORD = -52
    E_BAD_PERMISSION = -53
    # query
    E_SYNTAX_ERROR = -61
    E_EXECUTION_ERROR = -62
    E_STATEMENT_EMPTY = -63
    # balance
    E_BALANCED = -71
    E_BALANCER_RUNNING = -72
    E_NO_VALID_HOST = -73
    E_CORRUPTED_BALANCE_PLAN = -74
    # multi-tenant QoS (common/qos.py; docs/manual/14-qos.md): the
    # typed, RETRYABLE overload signal — admission denial or load shed.
    # Clients back off by the retry-after hint and re-issue; it is
    # never a hang and never masquerades as an execution failure
    E_OVERLOAD = -81


class NebulaError(Exception):
    """Raised when an in-process call fails and the caller asked to unwrap."""

    def __init__(self, status: "Status"):
        super().__init__(str(status))
        self.status = status


@dataclass(frozen=True)
class Status:
    code: ErrorCode = ErrorCode.SUCCEEDED
    msg: str = ""

    def ok(self) -> bool:
        return self.code == ErrorCode.SUCCEEDED

    def __bool__(self) -> bool:
        return self.ok()

    def __str__(self) -> str:
        if self.ok():
            return "OK"
        return f"{self.code.name}: {self.msg}" if self.msg else self.code.name

    # --- constructors -------------------------------------------------
    @staticmethod
    def OK() -> "Status":
        return _OK

    @staticmethod
    def error(code: ErrorCode, msg: str = "") -> "Status":
        return Status(code, msg)

    @staticmethod
    def syntax_error(msg: str) -> "Status":
        return Status(ErrorCode.E_SYNTAX_ERROR, msg)

    @staticmethod
    def not_found(msg: str = "") -> "Status":
        return Status(ErrorCode.E_NOT_FOUND, msg)

    @staticmethod
    def leader_changed(msg: str = "") -> "Status":
        return Status(ErrorCode.E_LEADER_CHANGED, msg)


_OK = Status()


class StatusOr(Generic[T]):
    """Either a value or a failure Status (ref: common/base/StatusOr.h)."""

    __slots__ = ("_status", "_value")

    def __init__(self, status: Status, value: Optional[T]):
        self._status = status
        self._value = value

    @staticmethod
    def of(value: T) -> "StatusOr[T]":
        return StatusOr(_OK, value)

    @staticmethod
    def err(code: ErrorCode, msg: str = "") -> "StatusOr[T]":
        return StatusOr(Status(code, msg), None)

    @staticmethod
    def from_status(status: Status) -> "StatusOr[T]":
        assert not status.ok()
        return StatusOr(status, None)

    def ok(self) -> bool:
        return self._status.ok()

    def __bool__(self) -> bool:
        return self.ok()

    @property
    def status(self) -> Status:
        return self._status

    def value(self) -> T:
        if not self._status.ok():
            raise NebulaError(self._status)
        return self._value  # type: ignore[return-value]

    def value_or(self, default: T) -> T:
        return self._value if self._status.ok() else default  # type: ignore[return-value]

    def __repr__(self) -> str:
        if self.ok():
            return f"StatusOr(OK, {self._value!r})"
        return f"StatusOr({self._status})"
