"""Thread-spawn helper that propagates contextvars (tracing) across
the thread boundary.

ContextVars do not cross `threading.Thread` on their own: a thread
spawned while a trace is live would record its spans into nothing
(docs/manual/10-observability.md). `traced_thread` is the shared
compliant spawn for work done ON BEHALF OF the current request —
the thread runs inside `contextvars.copy_context()`, so the caller's
trace (and any other context vars) follow the work.

Long-lived daemon loops (raft tick/replication, heartbeats, accept
loops) must NOT use this: they outlive any single request and would
pin whatever trace happened to be live at boot. Those sites keep a
raw `threading.Thread` with an inline `# nlint: disable=NL002`
suppression naming that reason (nebula-lint rule NL002;
docs/manual/15-static-analysis.md).
"""
from __future__ import annotations

import contextvars
import threading
from typing import Any, Callable, Mapping, Optional, Sequence


def traced_thread(target: Callable[..., Any],
                  args: Sequence[Any] = (),
                  kwargs: Optional[Mapping[str, Any]] = None,
                  *, name: Optional[str] = None,
                  daemon: bool = True) -> threading.Thread:
    """A not-yet-started Thread whose target runs inside a COPY of the
    spawner's contextvars context (trace propagation, NL002)."""
    ctx = contextvars.copy_context()
    kw = dict(kwargs or {})

    def run() -> None:
        ctx.run(target, *args, **kw)

    return threading.Thread(target=run, name=name, daemon=daemon)
