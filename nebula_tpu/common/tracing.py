"""End-to-end query tracing: span trees across graphd -> storaged -> TPU.

Role parity with the reference's per-request observability surface
(`latency_in_us` threaded through every thrift response, the
StatsManager windows behind /get_stats, the slow-op log) extended the
way production graph stores actually debug tail latency: Dapper-style
propagated trace contexts. One query = one TRACE; every interesting
seam on its path (parse, plan, executor, dispatcher enqueue /
group-wait / window launch, kernel, materialize, encode, each storage
RPC and the storaged-side processor + KV work behind it) records a
SPAN (name, tags, t0, dur_us, parent) into that trace. Spans cross the
RPC boundary by riding the wire envelope (trace_id/span_id out,
child spans back in the response), so graphd joins the full tree.

Head sampling keeps the cost off the hot path: one flag check per
query (`trace_sample_rate`), forced to 1 for a statement carrying the
`PROFILE` prefix or while the `/traces?arm=N` admin knob (the
X-Trace-style force) has armed samples left. Unsampled queries pay a
single context-var read per would-be span. Finished traces land in a
bounded in-memory ring served by `/traces`; what sampling misses is
covered by the slow-query log (`slow_query_threshold_ms`) and the
active-query registry (`/queries`, SHOW QUERIES-style).

Degradation events (breaker trips, CPU-pipe retries, deadline balks,
mesh demotions) tag the trace ROOT, so a degraded query is visibly
degraded in its own trace (docs/manual/10-observability.md).
"""
from __future__ import annotations

import contextvars
import itertools
import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .flags import MUTABLE, REBOOT, graph_flags
from . import profiler as _profiler

# (state, current-span) of the sampled trace this thread of control is
# inside; None = unsampled (the off-path case: every span() call is one
# ContextVar read). contextvars (not threading.local) so executor
# fan-outs can carry the trace into pool threads via copy_context().
_current: contextvars.ContextVar[Optional[Tuple["_TraceState", "Span"]]] = \
    contextvars.ContextVar("nebula_trace", default=None)

_ids = random.Random()        # span/trace id generator (non-crypto)


def _new_id(bits: int = 64) -> str:
    return f"{_ids.getrandbits(bits):0{bits // 4}x}"


def _wire_tag(v: Any) -> Any:
    """Tags cross the RPC wire: keep primitives, stringify the rest."""
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    return str(v)


class Span:
    """One timed operation inside a trace. `t0` is epoch seconds (for
    display/merge across hosts), `dur_us` wall microseconds."""

    __slots__ = ("span_id", "parent_id", "name", "t0", "dur_us", "tags")

    def __init__(self, name: str, parent_id: str = "",
                 t0: Optional[float] = None,
                 tags: Optional[Dict[str, Any]] = None):
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.name = name
        self.t0 = time.time() if t0 is None else t0
        self.dur_us = 0
        self.tags: Dict[str, Any] = dict(tags) if tags else {}

    def to_wire(self) -> Tuple:
        return (self.span_id, self.parent_id, self.name,
                int(self.t0 * 1e6), int(self.dur_us),
                {k: _wire_tag(v) for k, v in self.tags.items()})

    def to_dict(self) -> Dict[str, Any]:
        return {"span_id": self.span_id, "parent_id": self.parent_id,
                "name": self.name, "t0_us": int(self.t0 * 1e6),
                "dur_us": int(self.dur_us),
                "tags": {k: _wire_tag(v) for k, v in self.tags.items()}}


def span_from_wire(w: Tuple) -> Span:
    s = Span.__new__(Span)
    s.span_id, s.parent_id, s.name = w[0], w[1], w[2]
    s.t0 = w[3] / 1e6
    s.dur_us = int(w[4])
    s.tags = dict(w[5])
    return s


class _TraceState:
    """Mutable collector for one in-flight trace. `spans` is appended
    from the owning thread AND any thread serving on its behalf (the
    dispatcher leader, fan-out pool threads) — list.append is atomic
    under the GIL, and readers only see the list after finish()."""

    __slots__ = ("trace_id", "root", "spans")

    def __init__(self, trace_id: str, root: Span):
        self.trace_id = trace_id
        self.root = root
        self.spans: List[Span] = []


class _NullSpan:
    """Shared no-op for unsampled queries — usable as a context manager
    or imperatively (open/close)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def open(self):
        return self

    def close(self, **tags) -> None:
        pass

    def tag(self, key, value) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _SpanCtx:
    """A live span: sets itself as the current span for its dynamic
    extent, appends to the trace on close."""

    __slots__ = ("_state", "_span", "_token", "_t0")

    def __init__(self, state: _TraceState, parent: Span, name: str,
                 tags: Optional[Dict[str, Any]]):
        self._state = state
        self._span = Span(name, parent.span_id, tags=tags)
        self._token = None
        self._t0 = 0.0

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.perf_counter()
        self._token = _current.set((self._state, self._span))
        return self

    open = __enter__

    def __exit__(self, *exc) -> bool:
        self._span.dur_us = int((time.perf_counter() - self._t0) * 1e6)
        if exc and exc[0] is not None:
            self._span.tags.setdefault("error", exc[0].__name__)
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        self._state.spans.append(self._span)
        return False

    def close(self, **tags) -> None:
        self._span.tags.update(tags)
        self.__exit__(None, None, None)

    def tag(self, key, value) -> None:
        self._span.tags[key] = value


class _UseCtx:
    """Temporarily re-point the current thread at another request's
    trace context (the dispatcher leader serving a waiter's request).
    A None ctx DETACHES: serving an UNSAMPLED request must not record
    its spans/degradation tags into the (possibly sampled) leader's
    own trace — an N-query window would give the leader N duplicates
    of every stage span and other requests' failure tags. The
    re-point also mirrors into the profiler's per-thread context
    (common/profiler.py), so a stack sample of the leader serving a
    waiter's request is tagged with the WAITER's trace."""

    __slots__ = ("_ctx", "_token", "_ptok")

    def __init__(self, ctx):
        self._ctx = ctx
        self._token = None
        self._ptok = None

    def __enter__(self):
        self._token = _current.set(self._ctx)
        self._ptok = _profiler.note_trace(
            self._ctx[0].trace_id if self._ctx else None)
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if self._ptok is not None:
            _profiler.restore_trace(self._ptok)
            self._ptok = None
        return False


class TraceHandle:
    """One sampled query trace, begin() -> finish(). The root span is
    the current span for the extent between the two calls."""

    __slots__ = ("_tracer", "_state", "_token", "_t0", "sampled",
                 "trace_id", "_ptok")

    def __init__(self, tracer: "Tracer", name: str,
                 tags: Optional[Dict[str, Any]]):
        self._tracer = tracer
        root = Span(name, "", tags=tags)
        self._state = _TraceState(_new_id(128), root)
        self.trace_id = self._state.trace_id
        self.sampled = True
        self._t0 = time.perf_counter()
        self._token = _current.set((self._state, root))
        # per-thread mirror for the sampling profiler: only SAMPLED
        # queries pay these two dict stores (common/profiler.py)
        self._ptok = _profiler.note_trace(self.trace_id)

    def finish(self, **tags) -> Optional[Dict[str, Any]]:
        state = self._state
        root = state.root
        root.dur_us = int((time.perf_counter() - self._t0) * 1e6)
        root.tags.update(tags)
        _current.reset(self._token)
        _profiler.restore_trace(self._ptok)
        state.spans.append(root)
        trace = {"trace_id": state.trace_id, "name": root.name,
                 "t0_us": int(root.t0 * 1e6), "dur_us": root.dur_us,
                 "tags": {k: _wire_tag(v) for k, v in root.tags.items()},
                 "spans": [s.to_dict() for s in state.spans]}
        self._tracer.ring.add(trace)
        return trace


class _NullHandle:
    __slots__ = ()
    sampled = False
    trace_id = ""

    def finish(self, **tags) -> None:
        return None


_NULL_HANDLE = _NullHandle()


class RemoteTrace:
    """Server-side adoption of a propagated trace context: opens a
    root span with the CALLER's span as parent under the caller's
    trace_id, collects every span recorded in its extent, and exposes
    them wire-shaped for the RPC response. The fragment is also
    deposited in the LOCAL ring, so storaged's /traces serves the
    work it did for remote queries."""

    __slots__ = ("_tracer", "_state", "_token", "_t0", "wire_spans",
                 "_ptok")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_span_id: str):
        self._tracer = tracer
        root = Span(name, parent_span_id)
        self._state = _TraceState(trace_id, root)
        self.wire_spans: List[Tuple] = []

    def __enter__(self) -> "RemoteTrace":
        self._t0 = time.perf_counter()
        self._token = _current.set((self._state, self._state.root))
        self._ptok = _profiler.note_trace(self._state.trace_id)
        return self

    def __exit__(self, etype, evalue, tb) -> bool:
        state = self._state
        root = state.root
        root.dur_us = int((time.perf_counter() - self._t0) * 1e6)
        if etype is not None:
            root.tags["error"] = etype.__name__
        _current.reset(self._token)
        _profiler.restore_trace(self._ptok)
        state.spans.append(root)
        self.wire_spans = [s.to_wire() for s in state.spans]
        self._tracer.ring.add(
            {"trace_id": state.trace_id, "name": root.name,
             "t0_us": int(root.t0 * 1e6), "dur_us": root.dur_us,
             "tags": dict(root.tags), "remote_fragment": True,
             "spans": [s.to_dict() for s in state.spans]})
        return False


class TraceRing:
    """Bounded ring of finished traces (newest kept)."""

    def __init__(self, maxlen: int = 256):
        self._dq: "deque[Dict[str, Any]]" = deque(maxlen=max(int(maxlen), 1))
        self._lock = threading.Lock()

    def add(self, trace: Dict[str, Any]) -> None:
        with self._lock:
            self._dq.append(trace)

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            for t in reversed(self._dq):
                if t["trace_id"] == trace_id:
                    return t
        return None

    def list(self, min_dur_us: int = 0, feature: Optional[str] = None,
             limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-first summaries (no span bodies — GET by id for the
        full tree). `feature` matches the root 'feature' tag."""
        with self._lock:
            traces = list(self._dq)
        out = []
        for t in reversed(traces):
            if t["dur_us"] < min_dur_us:
                continue
            if feature is not None and \
                    t.get("tags", {}).get("feature") != feature:
                continue
            out.append({"trace_id": t["trace_id"], "name": t["name"],
                        "t0_us": t["t0_us"], "dur_us": t["dur_us"],
                        "tags": t.get("tags", {}),
                        "n_spans": len(t.get("spans", ())),
                        "remote_fragment": t.get("remote_fragment",
                                                 False)})
            if len(out) >= limit:
                break
        return out

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._dq)

    def clear(self) -> None:
        with self._lock:
            self._dq.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)


class Tracer:
    """Process-global trace head: sampling decisions, the span API the
    serve path calls, the finished-trace ring."""

    def __init__(self, ring_size: int = 256):
        self.sample_rate = 0.0
        self.ring = TraceRing(ring_size)
        self._armed = 0
        self._lock = threading.Lock()
        self._rng = random.Random()

    # ------------------------------------------------------- sampling
    def arm(self, n: int) -> int:
        """The X-Trace admin knob: force-sample the next `n` queries
        regardless of trace_sample_rate (served by /traces?arm=N)."""
        with self._lock:
            self._armed = max(int(n), 0)
            return self._armed

    def armed(self) -> int:
        return self._armed

    def _take_armed(self) -> bool:
        if not self._armed:
            return False
        with self._lock:
            if self._armed <= 0:
                return False
            self._armed -= 1
            return True

    def begin(self, name: str, force: bool = False,
              **tags) -> "TraceHandle | _NullHandle":
        """Head-sampling decision + trace start. The off-path cost for
        unsampled queries is this method: one float compare (plus one
        armed-counter check)."""
        if not (force or self._take_armed()
                or (self.sample_rate > 0.0
                    and self._rng.random() < self.sample_rate)):
            return _NULL_HANDLE
        return TraceHandle(self, name, tags or None)

    # ------------------------------------------------------- span API
    def active(self) -> bool:
        return _current.get() is not None

    def span(self, name: str, **tags) -> "_SpanCtx | _NullSpan":
        cur = _current.get()
        if cur is None:
            return _NULL_SPAN
        return _SpanCtx(cur[0], cur[1], name, tags or None)

    def add_span(self, name: str, dur_us: float,
                 t_end: Optional[float] = None, **tags) -> None:
        """Backdated child of the current span — for stages whose
        duration was measured before the tracer is consulted (kernel
        fetch, window-level encode)."""
        cur = _current.get()
        if cur is None:
            return
        state, parent = cur
        end = time.time() if t_end is None else t_end
        s = Span(name, parent.span_id, t0=end - dur_us / 1e6,
                 tags=tags or None)
        s.dur_us = int(dur_us)
        state.spans.append(s)

    def tag(self, key: str, value: Any) -> None:
        cur = _current.get()
        if cur is not None:
            cur[1].tags[key] = value

    def tag_root(self, key: str, value: Any) -> None:
        """Tag the trace root — degradation events use this so a
        degraded query is visible from the trace summary alone."""
        cur = _current.get()
        if cur is not None:
            cur[0].root.tags[key] = value

    # --------------------------------------------- cross-thread / RPC
    def current_state(self):
        """Opaque context for cross-THREAD handoff (tracer.use)."""
        return _current.get()

    def use(self, ctx) -> _UseCtx:
        return _UseCtx(ctx)

    def current_ctx(self) -> Optional[Tuple[str, str]]:
        """(trace_id, span_id) for the RPC envelope, None when
        unsampled."""
        cur = _current.get()
        if cur is None:
            return None
        return cur[0].trace_id, cur[1].span_id

    def remote(self, name: str, trace_id: str,
               parent_span_id: str) -> RemoteTrace:
        return RemoteTrace(self, name, trace_id, parent_span_id)

    def graft(self, wire_spans) -> None:
        """Join a remote fragment (RPC response spans) into the
        current trace. No-op when unsampled (a response can only carry
        spans if the request carried a context, but a retry race may
        outlive the trace)."""
        cur = _current.get()
        if cur is None or not wire_spans:
            return
        state = cur[0]
        for w in wire_spans:
            try:
                state.spans.append(span_from_wire(w))
            except Exception:
                return   # malformed fragment: drop, never break a query


# ---------------------------------------------------------------------------
# slow-query log + active-query registry (the cases sampling misses)
# ---------------------------------------------------------------------------

class SlowQueryLog:
    """Bounded log of queries over `slow_query_threshold_ms` (ref role:
    the SlowOpTracker log lines, made queryable)."""

    def __init__(self, maxlen: int = 128):
        self._dq: "deque[Dict[str, Any]]" = deque(maxlen=max(int(maxlen), 1))
        self._lock = threading.Lock()

    def add(self, stmt: str, latency_us: int, session: int = -1,
            user: str = "", trace_id: str = "", ok: bool = True,
            cost: Optional[Dict[str, Any]] = None) -> None:
        """`cost` is the offender's resource-ledger slice
        (common/ledger.py to_dict) — the slow-query log records WHERE
        a slow query's time and bytes went, not just that it was
        slow."""
        entry = {"stmt": stmt[:512], "latency_us": int(latency_us),
                 "session": session, "user": user,
                 "trace_id": trace_id, "ok": bool(ok),
                 "ts": time.time()}
        if cost:
            entry["cost"] = cost
        with self._lock:
            self._dq.append(entry)

    def snapshot(self, limit: int = 50) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._dq)
        return list(reversed(items))[:limit]

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)


class ActiveQueryRegistry:
    """What is running RIGHT NOW (SHOW QUERIES-style, served by
    /queries): per-session current statement + elapsed. graphd
    registers executing statements; storaged registers in-flight
    processor work."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next = itertools.count(1)
        self._active: Dict[int, Dict[str, Any]] = {}

    def register(self, stmt: str, session: int = -1, user: str = "",
                 trace_id: str = "") -> int:
        tok = next(self._next)
        with self._lock:
            self._active[tok] = {"id": tok, "stmt": stmt[:512],
                                 "session": session, "user": user,
                                 "trace_id": trace_id,
                                 "t0": time.time(),
                                 "_mono": time.monotonic()}
        return tok

    def unregister(self, token: int) -> None:
        with self._lock:
            self._active.pop(token, None)

    def finish(self, token: int) -> Optional[float]:
        """Unregister AND return the op's elapsed milliseconds (None
        for an unknown token) — so finished storage-processor ops can
        be checked against slow_query_threshold_ms instead of being
        dropped without a duration (ISSUE 12 satellite)."""
        now = time.monotonic()
        with self._lock:
            entry = self._active.pop(token, None)
        if entry is None:
            return None
        return round((now - entry["_mono"]) * 1e3, 2)

    def snapshot(self) -> List[Dict[str, Any]]:
        now = time.monotonic()
        with self._lock:
            items = [dict(v) for v in self._active.values()]
        out = []
        for v in items:
            v["elapsed_ms"] = round((now - v.pop("_mono")) * 1e3, 2)
            out.append(v)
        out.sort(key=lambda v: -v["elapsed_ms"])
        return out

    def count(self) -> int:
        with self._lock:
            return len(self._active)


def _skip_ws_and_comments(s: str, i: int = 0) -> int:
    """Advance past whitespace and the lexer's comment forms ('#' and
    '//' line comments, '/* */' blocks) — the text sniff must see the
    same first token the parser does."""
    n = len(s)
    while i < n:
        if s[i] in " \t\r\n":
            i += 1
        elif s[i] == "#" or s[i:i + 2] == "//":
            while i < n and s[i] != "\n":
                i += 1
        elif s[i:i + 2] == "/*":
            j = s.find("*/", i + 2)
            if j < 0:
                return i   # unterminated: let the lexer error on it
            i = j + 2
        else:
            break
    return i


def split_profile_prefix(stmt: str) -> Tuple[bool, str]:
    """Text-level `PROFILE` prefix detection — THE shared rule for the
    trace head (graph/engine) and the client retry classifier
    (client/pool); GQLParser is the authority that actually consumes
    the prefix token. Returns (profiled, rest-of-statement).
    Comment-aware to match the lexer: the prefix is the first
    identifier token PROFILE followed by any non-identifier
    character (space, tab, newline, '(' ...)."""
    s = stmt[_skip_ws_and_comments(stmt):]
    if len(s) >= 7 and s[:7].upper() == "PROFILE" and \
            (len(s) == 7 or not (s[7].isalnum() or s[7] == "_")):
        rest = s[7:]
        return True, rest[_skip_ws_and_comments(rest):]
    return False, s


# ---------------------------------------------------------------------------
# rendering + aggregation
# ---------------------------------------------------------------------------

def render_tree(trace: Dict[str, Any]) -> List[Tuple[str, int, str]]:
    """Trace dict -> rows (indented span name, dur_us, tags) in tree
    order — what `PROFILE <stmt>` returns to the console."""
    spans = trace.get("spans", [])
    ids = {s["span_id"] for s in spans}
    children: Dict[str, List[Dict[str, Any]]] = {}
    roots = []
    for s in spans:
        if s["parent_id"] in ids:
            children.setdefault(s["parent_id"], []).append(s)
        else:
            roots.append(s)
    rows: List[Tuple[str, int, str]] = []

    def fmt_tags(tags: Dict[str, Any]) -> str:
        return " ".join(f"{k}={v}" for k, v in sorted(tags.items()))

    def walk(s, depth):
        rows.append(((". " * depth) + s["name"], int(s["dur_us"]),
                     fmt_tags(s.get("tags", {}))))
        for c in sorted(children.get(s["span_id"], ()),
                        key=lambda x: x["t0_us"]):
            walk(c, depth + 1)

    for r in sorted(roots, key=lambda x: x["t0_us"]):
        walk(r, 0)
    return rows


def stage_breakdown(traces: List[Dict[str, Any]],
                    stages: Tuple[str, ...] = ("dispatcher.wait", "kernel",
                                               "materialize", "encode")
                    ) -> Dict[str, Dict[str, int]]:
    """Per-stage p50/p95 (us) across traces — the bench tier-2/3
    span-level breakdown (where the time goes, not just end-to-end)."""
    buckets: Dict[str, List[int]] = {s: [] for s in stages}
    for t in traces:
        for s in t.get("spans", ()):
            if s["name"] in buckets:
                buckets[s["name"]].append(int(s["dur_us"]))
    out: Dict[str, Dict[str, int]] = {}
    for name, vals in buckets.items():
        key = name.replace(".", "_")
        if not vals:
            out[key] = {"p50_us": 0, "p95_us": 0, "n": 0}
            continue
        vals.sort()
        out[key] = {"p50_us": vals[len(vals) // 2],
                    "p95_us": vals[min(len(vals) - 1,
                                       int(len(vals) * 0.95))],
                    "n": len(vals)}
    return out


# ---------------------------------------------------------------------------
# flags + the process-global tracer
# ---------------------------------------------------------------------------

graph_flags.declare(
    "trace_sample_rate", 0.0, MUTABLE,
    "fraction of queries head-sampled into the trace ring (0 disables; "
    "PROFILE <stmt> and /traces?arm=N force-sample regardless)")
graph_flags.declare(
    "slow_query_threshold_ms", 500, MUTABLE,
    "queries slower than this land in the slow-query log (/queries); "
    "0 disables")
graph_flags.declare(
    "trace_ring_size", 256, REBOOT,
    "finished traces kept in the in-memory ring served by /traces")

tracer = Tracer(int(graph_flags.get("trace_ring_size", 256) or 256))
tracer.sample_rate = float(graph_flags.get("trace_sample_rate", 0.0) or 0.0)


def _on_flag(name: str, value) -> None:
    if name == "trace_sample_rate":
        try:
            tracer.sample_rate = float(value)
        except (TypeError, ValueError):
            pass


graph_flags.watch(_on_flag)
