"""Write-path observatory: per-stage attribution from client ack to
device visibility (docs/manual/10-observability.md, "Write-path
observatory").

PRs 4/10/12-15 saturated the READ path with observability; the write
path was still dark — PAPER.md's FileBasedWal batching and the PR 13
engine-snapshot-lock convoy are both claims about a pipeline nothing
could see end to end. This module is the shared core every daemon
feeds; ROADMAP item 2 (group-commit pipelined raft writes, on-device
delta compaction) is designed against the numbers it produces.

STAGE TIMELINE — native histograms (`write.stage.<name>_us`, trace
exemplars) + ledger charges for every write seam:

  execute        graph/engine.py: the mutation sentence's executor run
  fanout         storage/client.py: the StorageClient write fan-out
  wal_append     kvstore/raft_store.py: leader WAL append (the
                 append_async extent, part lock included)
  replicate      kvstore/raft_store.py: the quorum wait (append future)
  commit_apply   raft leaders backdate from RaftPart.last_commit_us;
                 DirectCommit (single replica) times commit_logs itself
  ring_publish   engine_tpu/provider.py changes_since: the committed-
                 write feed pull + logical-delta resolve
  delta_apply    engine_tpu/engine.py _try_apply_deltas (runs under
                 `engine_snapshot`, so the duration IS lock-hold time)
  repack         engine_tpu/engine.py _build_fresh full host rebuild

The first six are synchronous with the acking query and ALSO charge
the PR 12 cost ledger (`write_exec_us` .. `commit_apply_us`, appended
wire fields), so PROFILE on a mutation renders a per-stage cost block
the way reads already do. ring_publish/delta_apply/repack are
asynchronous (device-visibility machinery) and surface through the
watermark below instead.

ACK-TO-VISIBLE WATERMARK — `watermark.note_ack(space, host, version)`
at the storage commit ack; `watermark.note_visible(space, token,
cause)` when a device snapshot advances past that version (delta apply
or repack install). The gap is the MVCC currency ROADMAP item 2
optimizes: histogram `write.ack_to_visible_ms` + per-space lag gauges,
with a `visibility_stall` flight event past `visibility_stall_ms`.

SNAPSHOT LIFECYCLE LEDGER — every live snapshot's build/delta/repack/
poison/overrun history with durations, trigger causes, lock-hold time
and device-mem deltas; served by `/snapshots` (a webservice built-in,
so graphd AND every storaged with device serving expose it) and
embedded in flight bundles via the "writepath" collector — a
ring_overrun bundle carries the full lifecycle that led to it.

CHANGE-RING TELEMETRY — occupancy/floor/dropped per space (gauges via
registered stores), overrun counters with cause attribution: ring
overrun -> snapshot poison -> full host repack is one attributed chain
in the ledger, not three disconnected counters.

Disarm contract (the `heat_enabled`/`profile_hz=0` idiom): the MUTABLE
`write_obs_enabled` flag disarms the whole observatory — every charge
site is one flag read, no `write.*`/`snapshot.*`/`wal.fsync*` families
ever register, /metrics is byte-identical to an observatory-free
build, and /snapshots reports only {"enabled": false}.
"""
from __future__ import annotations

import contextvars
import threading
import time
import weakref
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Optional

from . import ledger as _ledger
from .flags import MUTABLE, graph_flags, meta_flags, storage_flags
from .flight import recorder as _flight_recorder
from .stats import stats as _global_stats
from .tracing import tracer as _tracer

# stage names in pipeline order (the /snapshots + bench render order)
STAGES = ("execute", "fanout", "wal_append", "replicate",
          "commit_apply", "ring_publish", "delta_apply", "repack")

# the synchronous stages' ledger twins (cost observatory, PR 12):
# stage name -> appended Ledger field
LEDGER_FIELDS = {
    "execute": "write_exec_us",
    "fanout": "write_fanout_us",
    "wal_append": "wal_append_us",
    "replicate": "replicate_us",
    "commit_apply": "commit_apply_us",
}

# bounded history: per-space lifecycle events / pending acks per host
LEDGER_EVENTS_CAP = 128
PENDING_ACKS_CAP = 4096

_REGISTRIES = (graph_flags, storage_flags, meta_flags)
for _wflags in _REGISTRIES:
    _wflags.declare(
        "write_obs_enabled", True, MUTABLE,
        "write-path observatory master switch: per-stage write "
        "histograms (write.stage.*), ack-to-visible watermark, "
        "snapshot lifecycle ledger (/snapshots), change-ring & WAL "
        "fsync telemetry and the ring_overrun/fsync_stall/"
        "visibility_stall flight triggers; off = every charge site is "
        "one flag read and /metrics is byte-identical to an "
        "observatory-free build")
    _wflags.declare(
        "visibility_stall_ms", 0, MUTABLE,
        "flight-recorder visibility_stall trigger: an acked write not "
        "servable from the device snapshot after this many ms records "
        "a stall event (evaluated on watermark advance + /metrics "
        "scrape, throttled to 1/s per space); 0 disarms")
    _wflags.declare(
        "fsync_stall_ms", 0, MUTABLE,
        "flight-recorder fsync_stall trigger: a WAL fsync (or "
        "sync-every-append durable append) slower than this many ms "
        "records a stall event with the fsync latency; 0 disarms")
    _wflags.declare(
        "change_ring_ops", 0, MUTABLE,
        "override the engine change-ring op capacity (entries) at ring "
        "construction — REBOOT-effective per engine; the write bench "
        "shrinks it to force genuine overruns; 0 = built-in 4096")
    _wflags.declare(
        "change_ring_kvs", 0, MUTABLE,
        "override the engine change-ring kv capacity at ring "
        "construction (REBOOT-effective per engine); 0 = built-in "
        "131072")


def _flag(name: str, default):
    """First non-default value across the registries (graph first) —
    the flight/heat multi-registry idiom."""
    for reg in _REGISTRIES:
        v = reg.get(name, default)
        if v is not None and v != default:
            return v
    return default


def enabled() -> bool:
    return bool(_flag("write_obs_enabled", True))


# swappable for the disarm byte-identity test (tier-1 runs share one
# process-global StatsManager, so the test injects a private one)
stats = _global_stats


def _trace_id() -> Optional[str]:
    cur = _tracer.current_state()
    return cur[0].trace_id if cur is not None else None


def stage(name: str, us: float,
          trace_id: Optional[str] = None) -> None:
    """One write-stage observation -> native histogram with exemplar."""
    if not enabled():
        return
    stats.add_value(f"write.stage.{name}_us", int(us), kind="histogram",
                    trace_id=trace_id if trace_id is not None
                    else (_trace_id() or ""))


# nested same-name stages (DELETE VERTEX fans out edge deletes through
# the same client, whose delete_edges times its own fanout) must not
# double-charge: the outer extent already contains the inner one
_in_stage = contextvars.ContextVar("writepath_in_stage", default=())


@contextmanager
def timed_stage(name: str, ledger_field: Optional[str] = None,
                host: Optional[str] = None):
    """Time a synchronous write seam: records the stage histogram when
    armed AND charges the cost-ledger twin unconditionally (the PR 12
    ledger has its own gating contract). Reentrant per stage name —
    the inner extent is a no-op."""
    active = _in_stage.get()
    if name in active:
        yield
        return
    tok = _in_stage.set(active + (name,))
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _in_stage.reset(tok)
        us = int((time.perf_counter() - t0) * 1e6)
        if ledger_field is not None:
            led = _ledger.current()
            if led is not None:
                if host is not None:
                    led.charge_host(host, **{ledger_field: us})
                else:
                    led.charge(**{ledger_field: us})
        stage(name, us)


# ---------------------------------------------------------------------------
# ack-to-visible watermark
# ---------------------------------------------------------------------------
class VisibilityWatermark:
    """Per-space registry of acked-but-not-yet-device-visible writes.

    `note_ack` runs at the storage commit ack with the space engine's
    post-commit write_version — the same monotonic token the snapshot
    providers ride, so visibility is a pure version comparison, never a
    clock guess. `note_visible` accepts both provider token shapes: a
    bare int (LocalStoreProvider) satisfies every host's acks at or
    below it; a {host: version} dict (RemoteStorageProvider) satisfies
    per host, and pending hosts the token doesn't know are satisfied
    against min(token values) — conservative, never early."""

    def __init__(self):
        self._lock = threading.Lock()
        # space -> host -> deque[(version, t_mono)]
        self._pending: Dict[int, Dict[str, deque]] = {}
        self._acked: Dict[int, int] = {}
        self._visible: Dict[int, int] = {}
        self._dropped: Dict[int, int] = {}
        self._last_cause: Dict[int, str] = {}
        self._stall_ts: Dict[int, float] = {}

    def reset(self) -> None:
        with self._lock:
            self._pending.clear()
            self._acked.clear()
            self._visible.clear()
            self._dropped.clear()
            self._last_cause.clear()
            self._stall_ts.clear()

    def note_ack(self, space_id: int, host: str, version: int) -> None:
        if not enabled():
            return
        now = time.monotonic()
        with self._lock:
            hosts = self._pending.setdefault(int(space_id), {})
            dq = hosts.get(host)
            if dq is None:
                dq = hosts[host] = deque()
            dq.append((int(version), now))
            if len(dq) > PENDING_ACKS_CAP:
                dq.popleft()
                self._dropped[space_id] = \
                    self._dropped.get(space_id, 0) + 1
            self._acked[space_id] = self._acked.get(space_id, 0) + 1
        stats.add_value("write.acked", kind="counter")

    def note_visible(self, space_id: int, token,
                     cause: str = "delta") -> None:
        if not enabled() or token is None:
            return
        space_id = int(space_id)
        now = time.monotonic()
        popped = []
        with self._lock:
            hosts = self._pending.get(space_id)
            if hosts:
                if isinstance(token, dict):
                    floor = min(token.values()) if token else 0
                    vfor = lambda h: token.get(h, floor)  # noqa: E731
                else:
                    tv = int(token)
                    vfor = lambda h: tv                   # noqa: E731
                for h, dq in hosts.items():
                    v = vfor(h)
                    while dq and dq[0][0] <= v:
                        popped.append(dq.popleft()[1])
            if popped:
                self._visible[space_id] = \
                    self._visible.get(space_id, 0) + len(popped)
                self._last_cause[space_id] = cause
        for t_ack in popped:
            stats.add_value("write.ack_to_visible_ms",
                            (now - t_ack) * 1e3, kind="histogram",
                            trace_id=_trace_id() or "")
        if popped:
            stats.add_value("write.visible", len(popped),
                            kind="counter")
        self._check_stall(space_id, now)

    def lag_ms(self, space_id: int) -> float:
        """Age of the oldest acked-but-not-visible write (0 = none)."""
        now = time.monotonic()
        with self._lock:
            hosts = self._pending.get(int(space_id)) or {}
            oldest = min((dq[0][1] for dq in hosts.values() if dq),
                        default=None)
        return 0.0 if oldest is None else (now - oldest) * 1e3

    def _check_stall(self, space_id: int, now: float) -> None:
        thr = float(_flag("visibility_stall_ms", 0) or 0)
        if thr <= 0:
            return
        if now - self._stall_ts.get(space_id, 0.0) < 1.0:
            return
        lag = self.lag_ms(space_id)
        if lag > thr:
            self._stall_ts[space_id] = now
            with self._lock:
                hosts = self._pending.get(space_id) or {}
                pending = sum(len(dq) for dq in hosts.values())
            _flight_recorder.record(
                "visibility_stall", space=space_id,
                lag_ms=round(lag, 1), pending=pending,
                threshold_ms=thr)

    def scrape(self) -> None:
        """Gauge-time stall evaluation (a stalled space with no further
        note_visible calls must still fire)."""
        with self._lock:
            spaces = list(self._pending)
        now = time.monotonic()
        for sid in spaces:
            self._check_stall(sid, now)

    def stats_view(self) -> Dict[int, Dict[str, Any]]:
        with self._lock:
            out = {}
            for sid, hosts in self._pending.items():
                out[sid] = {
                    "pending": sum(len(dq) for dq in hosts.values()),
                    "acked": self._acked.get(sid, 0),
                    "visible": self._visible.get(sid, 0),
                    "dropped": self._dropped.get(sid, 0),
                    "last_cause": self._last_cause.get(sid),
                }
        for sid in out:
            out[sid]["lag_ms"] = round(self.lag_ms(sid), 2)
        return out


# ---------------------------------------------------------------------------
# snapshot lifecycle ledger
# ---------------------------------------------------------------------------
class SnapshotLedger:
    """Bounded per-space history of device-snapshot lifecycle events:
    build / delta_apply / poison / repack / overrun, each with
    duration, trigger cause, lock-hold time under `engine_snapshot`
    and device-mem delta where the event changes residency. The
    /snapshots body and the flight "writepath" collector both read it,
    so every ring_overrun bundle carries the chain that led to it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: Dict[int, deque] = {}
        self._counts: Dict[str, int] = {}

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._counts.clear()

    def note(self, space_id: int, event: str, **detail) -> None:
        if not enabled():
            return
        rec = {"t": round(time.time(), 3), "event": event}
        rec.update({k: v for k, v in detail.items() if v is not None})
        with self._lock:
            dq = self._events.get(int(space_id))
            if dq is None:
                dq = self._events[int(space_id)] = deque(
                    maxlen=LEDGER_EVENTS_CAP)
            dq.append(rec)
            self._counts[event] = self._counts.get(event, 0) + 1
        stats.add_value(f"snapshot.{event}", kind="counter")

    def view(self) -> Dict[str, Any]:
        with self._lock:
            return {"counts": dict(self._counts),
                    "spaces": {sid: list(dq)
                               for sid, dq in self._events.items()}}


watermark = VisibilityWatermark()
snapshots = SnapshotLedger()

# live info sources: TPU engines (per-space snapshot status) and
# GraphStores (change-ring occupancy) register weakly at construction
_ENGINES: "weakref.WeakSet" = weakref.WeakSet()
_STORES: "weakref.WeakSet" = weakref.WeakSet()


def register_engine(engine) -> None:
    _ENGINES.add(engine)


def register_store(store) -> None:
    _STORES.add(store)


def ring_status() -> Dict[int, Dict[str, int]]:
    """Change-ring occupancy per space, summed across registered
    stores (one store per daemon; the in-proc bench sums replicas)."""
    out: Dict[int, Dict[str, int]] = {}
    for store in list(_STORES):
        try:
            spaces = store.spaces()
        except Exception:
            continue
        for sid in spaces:
            eng = store.space_engine(sid)
            ring = getattr(eng, "changes", None)
            if ring is None:
                continue
            occ = ring.occupancy()
            acc = out.setdefault(int(sid), {"ops": 0, "kvs": 0,
                                            "floor": 0, "dropped": 0,
                                            "cap_ops": 0})
            for k in acc:
                acc[k] += occ.get(k, 0)
    return out


def note_ring_overrun(space_id: int, cause: str = "truncated",
                      **detail) -> None:
    """A snapshot consumer found the change ring no longer reaches its
    cursor (or a host-set change / injected overrun forced the same
    decline): counter + flight event + lifecycle ledger entry. The
    poison and repack that follow carry this cause forward."""
    if not enabled():
        return
    stats.add_value("write.ring.overrun", kind="counter")
    occ = ring_status().get(int(space_id))
    snapshots.note(space_id, "overrun", cause=cause, ring=occ, **detail)
    _flight_recorder.record("ring_overrun", space=space_id, cause=cause,
                            ring=occ, **detail)


def note_ring_barrier(space_id: int) -> None:
    if not enabled():
        return
    stats.add_value("write.ring.barrier", kind="counter")


def note_fsync(us: float) -> None:
    """One durable WAL sync (explicit sync() or a sync-every-append
    durable append): latency histogram with trace exemplar, plus the
    fsync_stall flight event past `fsync_stall_ms`."""
    if not enabled():
        return
    stats.add_value("wal.fsync_us", int(us), kind="histogram",
                    trace_id=_trace_id() or "")
    thr = float(_flag("fsync_stall_ms", 0) or 0)
    if thr > 0 and us > thr * 1e3:
        _flight_recorder.record("fsync_stall", us=int(us),
                                threshold_ms=thr)


def ring_cap_ops(default: int) -> int:
    return int(_flag("change_ring_ops", 0) or 0) or default


def ring_cap_kvs(default: int) -> int:
    return int(_flag("change_ring_kvs", 0) or 0) or default


# ---------------------------------------------------------------------------
# surfaces: /snapshots body, flight collector, /metrics gauges
# ---------------------------------------------------------------------------
def snapshots_view() -> Dict[str, Any]:
    """The /snapshots endpoint body (graphd + every storaged; the
    flight "writepath" collector captures the same view)."""
    if not enabled():
        return {"enabled": False}
    engines = []
    for eng in list(_ENGINES):
        try:
            engines.append(eng.snapshots_status())
        except Exception:
            continue
    return {
        "enabled": True,
        "watermark": watermark.stats_view(),
        "ledger": snapshots.view(),
        "rings": ring_status(),
        "engines": engines,
    }


def gauges() -> Dict[str, float]:
    """Per-space /metrics gauges (registered as a webservice metric
    source on every daemon). Disarmed -> {} (byte-identity)."""
    if not enabled():
        return {}
    watermark.scrape()   # stalled spaces fire without fresh advances
    # NOTE: gauge-source names are UNPREFIXED dotted paths — the
    # webservice runs every source through _prom_name("nebula", ...),
    # so a literal "nebula_" here would scrape as nebula_nebula_*.
    out: Dict[str, float] = {}
    for sid, wm in watermark.stats_view().items():
        out[f"write.visible_lag_ms_s{sid}"] = float(wm["lag_ms"])
        out[f"write.pending_acks_s{sid}"] = float(wm["pending"])
    for sid, occ in ring_status().items():
        out[f"write.ring_ops_s{sid}"] = float(occ["ops"])
        out[f"write.ring_kvs_s{sid}"] = float(occ["kvs"])
        out[f"write.ring_dropped_s{sid}"] = float(occ["dropped"])
    return out


def reset() -> None:
    """Bench/test helper: drop watermark + lifecycle state (stats
    families live in the process-global StatsManager and stay)."""
    watermark.reset()
    snapshots.reset()


def seam_cost_probe(n: int = 2000) -> float:
    """Measured per-write cost of the armed observatory seams, in µs —
    the PR 14 deterministic overhead idiom (time the seam itself, not
    a noisy A/B workload). One probe write = every synchronous stage
    record + an ack + a visible advance."""
    sid = 1 << 30   # private space id, cleaned below
    t0 = time.perf_counter()
    for i in range(n):
        for s in ("execute", "fanout", "wal_append", "replicate",
                  "commit_apply"):
            stage(s, 5.0, trace_id="")
        watermark.note_ack(sid, "probe", i)
        watermark.note_visible(sid, i, cause="delta")
    per_write_us = (time.perf_counter() - t0) * 1e6 / max(n, 1)
    with watermark._lock:
        watermark._pending.pop(sid, None)
        watermark._acked.pop(sid, None)
        watermark._visible.pop(sid, None)
        watermark._last_cause.pop(sid, None)
    return per_write_us


# every flight bundle (and specifically ring_overrun bundles) embeds
# the lifecycle ledger + watermark via this collector — idempotent,
# process-global, the heat-collector idiom
_flight_recorder.add_collector("writepath", snapshots_view)
