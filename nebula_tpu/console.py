"""Interactive console.

Role parity with the reference's `src/console/` (CliManager + the table
rendering in CmdProcessor.cpp): a readline REPL with history, an `-e`
one-shot mode and an `-f` batch-file mode, ASCII result tables, and
per-query latency reporting.

Run: python -m nebula_tpu.console [-e STMT] [-f FILE] [--user U] [--password P]
"""
from __future__ import annotations

import argparse
import sys
import time
from typing import Any, List, Optional, Sequence


def render_table(columns: Sequence[str], rows: Sequence[Sequence[Any]]) -> str:
    """ASCII table identical in spirit to the reference console output:
    =-delimited header, |-separated cells, width = max cell."""
    if not columns:
        return ""
    cells = [[_fmt(c) for c in row] for row in rows]
    widths = [len(str(c)) for c in columns]
    for row in cells:
        for i, c in enumerate(row):
            if i < len(widths):
                widths[i] = max(widths[i], len(c))
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    hdr = "|" + "|".join(f" {str(c):<{w}} " for c, w in zip(columns, widths)) + "|"
    out = [sep, hdr, sep]
    for row in cells:
        out.append("|" + "|".join(
            f" {c:<{w}} " for c, w in zip(row, widths)) + "|")
    out.append(sep)
    return "\n".join(out)


def _fmt(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return f"{v:g}"
    if v is None:
        return "__NULL__"
    return str(v)


# the parser's keyword table is the single source of truth for verbs
from .parser.lexer import KEYWORDS as _LEXER_KEYWORDS

NGQL_KEYWORDS = sorted(k.upper() for k in _LEXER_KEYWORDS)


class ConsoleCompleter:
    """readline completer: nGQL verbs/clauses plus live space/tag/edge
    names pulled from the connected catalog (ref role: the console
    autocomplete machinery in console/CliManager.h:14-40)."""

    def __init__(self, conn, ttl: float = 5.0):
        self._conn = conn
        self._ttl = ttl
        self._cached_at = 0.0
        self._names: List[str] = []
        self._matches: List[str] = []

    def schema_names(self) -> List[str]:
        now = time.monotonic()
        if now - self._cached_at < self._ttl:
            return self._names
        names: List[str] = []
        for stmt in ("SHOW SPACES", "SHOW TAGS", "SHOW EDGES"):
            try:
                r = self._conn.execute(stmt)
            except Exception:
                continue
            if r.ok() and r.rows:
                # name is the last column (SPACES: [Name]; TAGS/EDGES:
                # [ID, Name])
                names.extend(str(row[-1]) for row in r.rows)
        self._cached_at = now
        self._names = names
        return names

    def complete(self, text: str, state: int):
        if state == 0:
            up = text.upper()
            self._matches = [k + " " for k in NGQL_KEYWORDS
                             if k.startswith(up)]
            self._matches += [n for n in self.schema_names()
                              if n.startswith(text)]
        return self._matches[state] if state < len(self._matches) else None

    def install(self) -> bool:
        try:
            import readline
        except ImportError:
            return False
        readline.set_completer(self.complete)
        readline.set_completer_delims(" \t\n,;()=<>!|")
        readline.parse_and_bind("tab: complete")
        return True


class Console:
    def __init__(self, connection, out=None, show_profile=False):
        self.conn = connection
        self.out = out or sys.stdout
        self.show_profile = show_profile

    def run_statement(self, text: str) -> bool:
        """Execute one (possibly ';'-chained) statement; print results.
        Returns False when the statement asks to quit."""
        text = text.strip()
        if not text:
            return True
        if text.lower() in ("exit", "quit", "exit;", "quit;"):
            return False
        if text.lower().rstrip(";") == ":profile":
            # console-local toggle: show the device path's per-stage
            # breakdown after each query (snapshot/kernel/materialize)
            self.show_profile = not self.show_profile
            print(f"profile display "
                  f"{'on' if self.show_profile else 'off'}", file=self.out)
            return True
        t0 = time.monotonic()
        resp = self.conn.execute(text)
        wall_ms = (time.monotonic() - t0) * 1e3
        if not resp.ok():
            print(f"[ERROR ({resp.code.name})]: {resp.error_msg}",
                  file=self.out)
            return True
        if resp.columns:
            print(render_table(resp.columns, resp.rows), file=self.out)
            n = len(resp.rows)
            print(f"Got {n} rows (server {resp.latency_us} us, "
                  f"wall {wall_ms:.2f} ms)", file=self.out)
        else:
            print(f"Execution succeeded (server {resp.latency_us} us, "
                  f"wall {wall_ms:.2f} ms)", file=self.out)
        prof = getattr(resp, "profile", None)
        if self.show_profile and prof and "mode" in prof:
            print(f"[tpu {prof['mode']}] snapshot {prof['snapshot_us']} us"
                  f" | kernel {prof['kernel_us']} us"
                  f" | materialize {prof['materialize_us']} us"
                  f" | delta edges {prof['delta_edges']}", file=self.out)
        spans = getattr(resp, "trace_spans", None)
        if spans:
            # PROFILE <stmt>: the query's span tree, rendered as rows
            # under the result table (common/tracing.render_tree)
            from .common.tracing import render_tree
            tree = render_tree(
                {"spans": [{"span_id": s[0], "parent_id": s[1],
                            "name": s[2], "t0_us": s[3], "dur_us": s[4],
                            "tags": s[5]} for s in spans]})
            print(render_table(["span", "dur_us", "tags"],
                               [(n, d, t) for n, d, t in tree]),
                  file=self.out)
            print(f"Trace {getattr(resp, 'trace_id', '')} "
                  f"({len(spans)} spans)", file=self.out)
        cost = (prof or {}).get("cost")
        if spans and cost:
            # the PROFILE cost block next to the span tree: nonzero
            # totals on one line, per-host slices under it
            totals = " | ".join(
                f"{k} {v}" for k, v in cost.items()
                if k != "hosts" and v)
            print(f"Cost: {totals or '(all zero)'}", file=self.out)
            for h, d in sorted((cost.get("hosts") or {}).items()):
                hs = " ".join(f"{k}={v}" for k, v in sorted(d.items()))
                print(f"  host {h}: {hs}", file=self.out)
        return True

    def run_file(self, path: str) -> None:
        with open(path) as f:
            buf = ""
            for line in f:
                line = line.strip()
                if not line or line.startswith("--") or line.startswith("#"):
                    continue
                buf += (" " if buf else "") + line
                if buf.endswith(";"):
                    self.run_statement(buf)
                    buf = ""
            if buf:
                self.run_statement(buf)

    def repl(self, in_stream=None) -> None:
        prompt = "(nebula-tpu) > "
        if in_stream is None and sys.stdin.isatty():
            # history + line editing + tab completion over verbs and
            # live schema names
            ConsoleCompleter(self.conn).install()
            while True:
                try:
                    line = input(prompt)
                except (EOFError, KeyboardInterrupt):
                    print("", file=self.out)
                    return
                if not self.run_statement(line):
                    return
        else:
            stream = in_stream or sys.stdin
            for line in stream:
                if not self.run_statement(line):
                    return


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="nebula-tpu console")
    ap.add_argument("-e", metavar="STMT", help="execute one statement")
    ap.add_argument("-f", metavar="FILE", help="batch file of statements")
    ap.add_argument("--user", default="root")
    ap.add_argument("--password", default="")
    ap.add_argument("--addr", metavar="HOST:PORT",
                    help="connect to a running graphd over rpc "
                         "(default: boot an in-proc cluster)")
    args = ap.parse_args(argv)

    if args.addr:
        from .client import GraphClient
        conn = GraphClient(args.addr).connect(args.user, args.password)
    else:
        # single-process deployment: boot an in-proc cluster with the
        # TPU engine attached
        from .cluster import InProcCluster
        from .engine_tpu import TpuGraphEngine
        cluster = InProcCluster(tpu_engine=TpuGraphEngine())
        conn = cluster.connect(args.user, args.password)
    console = Console(conn)
    if args.e:
        console.run_statement(args.e)
    elif args.f:
        console.run_file(args.f)
    else:
        print("Welcome to nebula-tpu console. Type `exit` to leave.")
        console.repl()
    return 0


if __name__ == "__main__":
    sys.exit(main())
