"""The three daemons (ref: src/daemons/{Meta,Storage,Graph}Daemon.cpp):
each boots its services behind the rpc/ transport; `serve_*` returns a
running handle for in-process cluster tests (the reference's TestEnv
idiom), `main()`s are the CLI entry points."""
from .metad import MetadHandle, serve_metad
from .storaged import StoragedHandle, serve_storaged
from .graphd import GraphdHandle, serve_graphd

__all__ = ["serve_metad", "serve_storaged", "serve_graphd",
           "MetadHandle", "StoragedHandle", "GraphdHandle"]
