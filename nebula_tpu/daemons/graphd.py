"""graphd: the stateless query daemon (ref: daemons/GraphDaemon.cpp:
128-158 boots GraphService::init → ExecutionEngine::init → MetaClient →
SchemaManager → StorageClient, then serves the graph thrift API)."""
from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Dict

from ..common.flags import graph_flags
from ..common.stats import stats
from ..graph.engine import ExecutionEngine, GraphService
from ..meta.client import MetaClient
from ..meta.schema_manager import SchemaManager
from ..rpc import RpcServer, proxy
from ..storage.client import StorageClient
from ..webservice import WebService


class _StorageHostMap(dict):
    """host addr -> storage service proxy, created on first use — new
    storaged hosts become reachable without re-wiring (the reference's
    ThriftClientManager creates clients per address on demand)."""

    def __missing__(self, addr: str):
        # bounded data-plane timeout (gray-failure hygiene, ISSUE 18):
        # a blackholed storaged costs a caller this budget per attempt
        # — not the transport's liberal default — so peer-health
        # ejection and hedged reads can react within a query deadline.
        # Mirrors the reference's --storage_client_timeout_ms.
        ms = graph_flags.get_or("storage_client_timeout_ms", 30000, int)
        p = proxy(addr, "storage", timeout=ms / 1000.0)
        self[addr] = p
        return p


@dataclass
class GraphdHandle:
    service: GraphService
    engine: ExecutionEngine
    meta_client: MetaClient
    server: RpcServer
    web: "WebService" = None

    @property
    def addr(self) -> str:
        return self.server.addr

    @property
    def ws_port(self):
        return self.web.port if self.web else None

    def stop(self) -> None:
        self.meta_client.stop()
        self.engine.client.close()   # ends the version-watch threads
        self.server.stop()
        if self.web:
            self.web.stop()


def serve_graphd(meta_addr: str, host: str = "127.0.0.1", port: int = 0,
                 tpu_engine=None, ws_port=None) -> GraphdHandle:
    mc = MetaClient(meta_addr, role="graph")
    mc.start(heartbeat=False)  # topology snapshot for part routing
    sm = SchemaManager(mc)
    hosts = _StorageHostMap()

    def refresh_hosts():
        for h in mc.storage_hosts():
            hosts[h]  # admin fan-out must reach late-joining hosts too

    refresh_hosts()
    client = StorageClient(sm, hosts=hosts, part_to_host=mc.part_host,
                           refresh_hosts=refresh_hosts)
    if tpu_engine is not None:
        # the real 3-daemon --tpu path: snapshots sync from remote
        # storaged parts over the storage RPC boundary (ref seam:
        # storage/StorageServer.cpp:32-55, FLAGS_store_type)
        from ..engine_tpu.provider import RemoteStorageProvider
        tpu_engine.attach_provider(RemoteStorageProvider(client, sm),
                                   sm, meta=mc)
    engine = ExecutionEngine(mc, sm, client, tpu_engine=tpu_engine)
    service = GraphService(engine)
    server = RpcServer(host, port).register("graph", service).start()
    web = None
    if ws_port is not None:
        import os as _os
        web = WebService("graphd", flags=graph_flags, stats=stats,
                         host=host, port=ws_port,
                         build_labels={
                             "role": "graph",
                             "tpu": "1" if tpu_engine is not None
                             else "0",
                             "wide_csr": "1" if _os.environ.get(
                                 "NEBULA_TPU_WIDE_CSR") else "0"})
        # observability surface (docs/manual/10-observability.md):
        # /traces (trace ring + ?arm=N force knob), /queries (active
        # statements + slow-query log), /metrics (OpenMetrics — the
        # WebService built-in, extended with engine counters below),
        # /flight + /slo (WebService built-ins; the collectors below
        # put this daemon's serve-path state into every flight bundle)
        web.register_observability(active=service.active_queries,
                                   slow=service.slow_log)

        def cluster_metrics(params, body):
            # /cluster_metrics (docs/manual/10-observability.md,
            # "Cluster rollup / nebtop"): this graphd's own exposition
            # plus every registered storaged/metad /metrics (targets
            # from metad's heartbeat-carried web-port registry),
            # merged into ONE strict OpenMetrics document with
            # instance/role labels — one scrape for the whole cluster,
            # dead daemons visible as nebula_cluster_scrape 0.
            import urllib.request
            from ..common import promfed
            from ..webservice import OPENMETRICS_CTYPE
            _code, own = web._metrics_handler({}, b"")
            sources = [(f"{host}:{web.port}", "graph",
                        own[0].decode() if isinstance(own, tuple)
                        else str(own))]
            try:
                endpoints = mc.web_endpoints()
            except Exception:
                endpoints = []
            try:
                timeout = float(params.get("timeout", 2.0))
            except ValueError:
                timeout = 2.0

            def fetch(ep):
                try:
                    with urllib.request.urlopen(
                            f"http://{ep['web']}/metrics",
                            timeout=timeout) as r:
                        return r.read().decode()
                except Exception:
                    return None     # scraped as down, not dropped
            # concurrent fan-out: one slow/dead target costs ONE
            # timeout for the whole scrape, not one per target
            if endpoints:
                from concurrent.futures import ThreadPoolExecutor
                with ThreadPoolExecutor(
                        max_workers=min(len(endpoints), 16)) as pool:
                    texts = list(pool.map(fetch, endpoints))
                sources.extend(
                    (ep["web"], ep["role"], text)
                    for ep, text in zip(endpoints, texts))
            doc = promfed.merge_expositions(sources)
            return 200, (doc.encode(), OPENMETRICS_CTYPE)

        web.register("/cluster_metrics", cluster_metrics)
        from ..common.flight import recorder as flight_recorder
        flight_recorder.add_collector("graphd.queries", lambda: {
            "active": service.active_queries.snapshot(),
            "slow": service.slow_log.snapshot(20)})
        flight_recorder.add_collector("graphd.routing",
                                      client.routing_stats)

        def faults_handler(params, body):
            # /faults: GET = registry state (armed plan, per-point fire
            # counts, catalog); PUT body `plan=<grammar>` arms a plan,
            # `?clear=1` (or an empty plan) disarms everything. The
            # same plan grammar as NEBULA_TPU_FAULTS and the
            # `fault_plan` flag (common/faults.py).
            from ..common.faults import faults as freg
            from urllib.parse import parse_qs as _pq
            if body:
                # keep_blank_values so an explicit `plan=` (clear) is
                # distinguishable from a body MISSING the plan key —
                # the latter must not silently disarm a live chaos run
                fields = {k: v[0] for k, v in
                          _pq(body.decode(),
                              keep_blank_values=True).items()}
                if "plan" not in fields:
                    return 400, {"error": "body must carry plan=<spec>"}
                try:
                    freg.set_plan(fields["plan"])
                except ValueError as e:
                    return 400, {"error": str(e)}
            elif params.get("clear"):
                freg.clear()
            return 200, freg.describe()

        web.register("/faults", faults_handler)

        def qos_handler(params, body):
            # /qos (docs/manual/14-qos.md): GET = admission controller
            # + dispatcher lane/shed state; PUT body `plan=<grammar>`
            # arms a per-space admission plan (same grammar as the
            # `qos_plan` flag, common/qos.py), `session=<id>:<lane>`
            # pins a session onto a lane (`<id>:` clears the pin);
            # `?clear=1` disarms admission entirely.
            from ..common.qos import LANES, admission
            from urllib.parse import parse_qs as _pq
            if body:
                fields = {k: v[0] for k, v in
                          _pq(body.decode(),
                              keep_blank_values=True).items()}
                if "plan" not in fields and "session" not in fields:
                    return 400, {"error": "body must carry plan=<spec> "
                                          "and/or session=<id>:<lane>"}
                # validate EVERYTHING before mutating anything: a 400
                # must mean "state untouched" — a body with a valid
                # plan and a bad session must not half-apply
                sess = None
                lane = None
                if "session" in fields:
                    sid_s, _, lane = fields["session"].partition(":")
                    if lane and lane not in LANES:
                        return 400, {"error": f"unknown lane {lane!r} "
                                              f"(expected {LANES})"}
                    try:
                        sr = service.sessions.find(int(sid_s))
                    except ValueError:
                        return 400, {"error": f"bad session id "
                                              f"{sid_s!r}"}
                    if not sr.ok():
                        return 404, {"error": sr.status.msg}
                    sess = sr.value()
                if "plan" in fields:
                    try:
                        admission.set_plan(fields["plan"])
                    except ValueError as e:
                        return 400, {"error": str(e)}
                if sess is not None:
                    sess.qos_lane = lane or None
            elif params.get("clear"):
                admission.clear()
            out = {"admission": admission.describe()}
            if tpu_engine is not None:
                out["dispatcher"] = tpu_engine.qos_stats()
            return 200, out

        web.register("/qos", qos_handler)

        def heat_handler(params, body):
            # /heat (docs/manual/10-observability.md, "Workload & data
            # observatory"): graphd's per-(space, part) heat slabs
            # (start-vid reads + attributed device time) + per-space
            # skew indices; ?vertices=1 adds the frontier hot-vertex
            # sketches and, with a TPU engine attached, the per-build
            # degree-skew stats (hub-split candidates vs cap_e)
            from ..common import heat as _heat
            want_v = bool(params.get("vertices"))
            out = _heat.accountant.describe(vertices=want_v)
            if want_v and tpu_engine is not None:
                degrees = {}
                for sid, snap in list(
                        getattr(tpu_engine, "_snapshots", {}).items()):
                    ds = getattr(snap, "degree_stats", None)
                    if ds:
                        degrees[str(sid)] = ds
                out.setdefault("vertices", {})["degrees"] = degrees
            return 200, out

        def consistency_handler(params, body):
            # /consistency (docs/manual/10-observability.md,
            # "Consistency observatory"): this graphd's shadow-read
            # verifier state + the device-snapshot audit, plus a
            # federated per-part digest view pulled from every
            # registered storaged's /consistency (the /cluster_metrics
            # target registry). ?audit=1 runs the snapshot audit now.
            from ..common import consistency as _cons
            out = {"enabled": _cons.enabled(),
                   "shadow": _cons.shadow.stats()}
            if tpu_engine is not None:
                if params.get("audit"):
                    _cons.run_audits()
                out["audit"] = tpu_engine.audit_state()
            try:
                endpoints = [ep for ep in mc.web_endpoints()
                             if ep.get("role") == "storage"]
            except Exception:
                endpoints = []
            try:
                timeout = float(params.get("timeout", 2.0))
            except ValueError:
                timeout = 2.0
            # concurrent fan-out (the /cluster_metrics idiom, shared
            # with SHOW CONSISTENCY): dead targets cost ONE timeout
            from ..graph.admin_executors import \
                _fetch_consistency_endpoints
            cluster = []
            for ep, doc in _fetch_consistency_endpoints(
                    endpoints, timeout=timeout):
                if doc is None:
                    cluster.append({"host": ep["web"],
                                    "error": "unreachable"})
                else:
                    cluster.append({"host": ep["web"], **doc})
            out["cluster"] = cluster
            divergent = []
            for host in cluster:
                for p in host.get("parts") or []:
                    for rep in p.get("digest_divergent") or []:
                        divergent.append(
                            {"host": host["host"], "space": p["space"],
                             "part": p["part"], "replica": rep})
            out["divergent"] = divergent
            return 200, out

        web.register("/consistency", consistency_handler)
        web.register("/heat", heat_handler)
        from ..common import heat as _heat_mod
        # nebula_part_heat_* / nebula_heat_skew_index_* families
        # (empty — byte-identical /metrics — when heat is disarmed)
        web.add_metrics_source(_heat_mod.accountant.gauges)

        def _heat_topology(event, **kw):
            # heat hygiene (same contract as storaged): a dropped
            # space's slabs must stop scraping as nebula_part_heat_*
            # families on this graphd too
            if event == "space_removed":
                _heat_mod.accountant.drop_space(kw["space_id"])

        mc.add_listener(_heat_topology)
        if tpu_engine is not None:
            def trace(params, body):
                # /trace?op=start&dir=/tmp/xprof | /trace?op=stop —
                # opt-in jax.profiler capture of the device path
                op = params.get("op")
                if op == "start":
                    d = params.get("dir")
                    if not d:
                        return 400, {"error": "dir param required"}
                    if not tpu_engine.start_trace(d):
                        return 409, {"error": "a trace is already "
                                              "running; stop it first"}
                    return 200, {"result": "tracing", "dir": d}
                if op == "stop":
                    if not tpu_engine.stop_trace():
                        return 409, {"error": "no trace running"}
                    return 200, {"result": "stopped"}
                return 400, {"error": f"unknown op {op!r}"}

            web.register("/trace", trace)

            def tpu_stats(params, body):
                # the engine's serving counters + decline reasons +
                # per-space budget fits, operator-visible like the
                # reference's storage stats (ref WebService.h:31-49).
                # `dispatcher` condenses the window-lifecycle counters
                # (docs/manual/7-dispatcher.md): rounds, group mixing,
                # early waiter releases, cross-group leader handoffs,
                # per-request dispatcher wait, native row-encode use.
                st = dict(tpu_engine.stats)
                rounds = max(st.get("disp_rounds", 0), 1)
                waits = max(st.get("group_wait_count", 0), 1)
                rb = tpu_engine.robustness_stats()
                # cluster block (docs/manual/12-replication.md): this
                # graphd's routing state + retry classifications, and
                # the metad-hosted balancer's plan progress — one stop
                # to see an election or rebalance from the serve side
                cluster = client.routing_stats()
                try:
                    cluster["balance"] = mc.balance_progress()
                except Exception:
                    cluster["balance"] = None
                from ..common.qos import admission as _adm
                return 200, {
                    "stats": st,
                    "cluster": cluster,
                    # multi-tenant QoS (docs/manual/14-qos.md): the
                    # per-tenant admission slices (admitted/denied/
                    # tokens per space) + the dispatcher's lane
                    # occupancy and shed watermark state — the one
                    # block that answers "who is being throttled, who
                    # is being shed, and is the interactive lane
                    # protected right now"
                    "qos": {
                        "admission": _adm.describe(),
                        "dispatcher": tpu_engine.qos_stats(),
                    },
                    # degradation ladder (docs/manual/9-robustness.md):
                    # live per-feature breaker states, trip/recovery
                    # counts, CPU-degraded serves, deadline bailouts,
                    # poisoned snapshots, and injected-fault counts
                    "robustness": rb,
                    "breaker_state": rb["breaker_state"],
                    "faults_injected": rb["faults_injected"],
                    "agg_decline_reasons":
                        dict(tpu_engine.agg_decline_reasons),
                    "path_decline_reasons":
                        dict(tpu_engine.path_decline_reasons),
                    # device secondary indexes (docs/manual/16-indexes
                    # .md): build/serve lifecycle — builds, resident
                    # bytes, searches, hits, declines by reason,
                    # invalidations, per-verb served counts
                    "index": tpu_engine.index_stats(),
                    # mesh execution service (docs/manual/8-mesh.md):
                    # device-served queries on SHARDED snapshots per
                    # feature, and the decline matrix {feature:
                    # {reason: n}} — on a meshed deployment every
                    # round-5 feature must show served > 0 here
                    "mesh": {
                        "served": dict(tpu_engine.mesh_served),
                        "declined": {
                            f: dict(d) for f, d in
                            tpu_engine.mesh_decline_reasons.items()},
                    },
                    "dispatcher": {
                        "rounds": st.get("disp_rounds", 0),
                        # avg distinct group keys VISIBLE at leader
                        # election (served + still queued): each round
                        # serves exactly one group, so > 1 here means
                        # mixed-key load ran as concurrent rounds
                        "groups_per_round": round(
                            st.get("disp_group_keys", 0) / rounds, 2),
                        "early_releases": st.get("early_releases", 0),
                        "leader_handoffs": st.get("leader_handoffs", 0),
                        "group_wait_us_avg": int(
                            st.get("group_wait_us_total", 0) / waits),
                        "group_wait_us_max":
                            st.get("group_wait_us_max", 0),
                        "native_encode_rows":
                            st.get("native_encode_rows", 0),
                        "encode_fallback_rows":
                            st.get("encode_fallback_rows", 0),
                    },
                    # cache ladder (docs/manual/11-caching.md): the
                    # live cache_mode plus per-rung hit/miss/evict/
                    # invalidate counters — plan (statement -> AST),
                    # filter_plan (per-snapshot compiled WHERE),
                    # result + negative + in-window dedupe
                    "cache": {
                        **tpu_engine.cache_stats(),
                        "plan": engine.plan_cache.stats(),
                    },
                    # fused device programs (docs/manual/13-device-
                    # speed.md): registry hits/misses, the distinct-
                    # signature gauge (recompile-bound contract), real
                    # XLA cache entries, fused launches/declines
                    "fused_programs": tpu_engine.fused_stats(),
                    # frontier double-buffering: H2D stages, prefetch
                    # hit/miss, kernel-overlapped transfers + the time
                    # they had to hide, donation fallbacks
                    "frontier_prefetch": tpu_engine.prefetch_stats(),
                    # per-snapshot device-memory ledger (continuous
                    # profiling, docs/manual/10-observability.md):
                    # live CSR bytes by packed width per space — the
                    # measured twin of bench's tier1_hbm_model
                    "device_mem": tpu_engine.device_mem_stats(),
                    "sparse_budget_calibrations": {
                        str(k): v for k, v in
                        tpu_engine.sparse_budget_calibrations.items()},
                    "batched_kernel_calibrations": {
                        str(k): v for k, v in
                        tpu_engine.batched_kernel_calibrations.items()},
                    "sparse_edge_budget": tpu_engine.sparse_edge_budget,
                }

            web.register("/tpu_stats", tpu_stats)
            # every flight bundle carries the full /tpu_stats block —
            # breaker states, qos slices, cache/fused counters — as
            # captured at trigger time (common/flight.py)
            flight_recorder.add_collector(
                "graphd.tpu_stats", lambda: tpu_stats({}, b"")[1])

            def tpu_metric_source():
                # engine counter dicts as flat Prometheus gauges:
                # tpu_engine_<counter>, plus the nested decline/serve
                # matrices with stable dotted names
                out = {}
                # snapshot EVERY dict under the stats lock: engine
                # threads insert new (feature, reason) keys under it,
                # and iterating live dicts would intermittently throw
                # mid-scrape (silently dropping all engine metrics)
                with tpu_engine._stats_lock:
                    st = dict(tpu_engine.stats)
                    mesh_served = dict(tpu_engine.mesh_served)
                    mesh_decl = {f: dict(d) for f, d in
                                 tpu_engine.mesh_decline_reasons.items()}
                    agg_decl = dict(tpu_engine.agg_decline_reasons)
                    path_decl = dict(tpu_engine.path_decline_reasons)
                for k, v in st.items():
                    out[f"tpu_engine.{k}"] = v
                for k, v in mesh_served.items():
                    out[f"tpu_engine.mesh_served.{k}"] = v
                for f, d in mesh_decl.items():
                    for reason, v in d.items():
                        out[f"tpu_engine.mesh_declined.{f}.{reason}"] = v
                for k, v in agg_decl.items():
                    out[f"tpu_engine.agg_declined.{k}"] = v
                for k, v in path_decl.items():
                    out[f"tpu_engine.path_declined.{k}"] = v
                # secondary-index lifecycle as tpu_engine.index.*
                # (docs/manual/16-indexes.md) — the scrape-flat twin
                # of the /tpu_stats "index" block
                for k, v in tpu_engine.index_stats().items():
                    if k == "decline_reasons":
                        for reason, n in v.items():
                            out[f"tpu_engine.index.declined.{reason}"] = n
                    else:
                        out[f"tpu_engine.index.{k}"] = v
                # cache rungs as flat gauges (the per-event counters
                # additionally stream through the StatsManager with
                # kind="counter" — see common/cache.py stats_prefix)
                for rung, st in tpu_engine.cache_stats().items():
                    if not isinstance(st, dict):
                        continue
                    for k, v in st.items():
                        out[f"tpu_engine.cache.{rung}.{k}"] = v
                for k, v in engine.plan_cache.stats().items():
                    out[f"graph.plan_cache.{k}"] = v
                # fused-program + frontier-prefetch blocks as flat
                # gauges (docs/manual/13-device-speed.md), so compile-
                # cache behavior scrapes like the PR 5 cache rungs
                for k, v in tpu_engine.fused_stats().items():
                    out[f"tpu_engine.fused.{k}"] = v
                for k, v in tpu_engine.prefetch_stats().items():
                    out[f"tpu_engine.prefetch.{k}"] = v
                # device-memory ledger gauges (continuous profiling):
                # live CSR bytes by width next to the modeled HBM
                # estimate's inputs
                dm = tpu_engine.device_mem_stats()
                out["tpu_engine.device_mem.bytes"] = dm["bytes"]
                out["tpu_engine.device_mem.snapshots"] = dm["snapshots"]
                out["tpu_engine.device_mem.frontier_h2d_bytes"] = \
                    dm["frontier_h2d_bytes"]
                for w, v in dm["by_width"].items():
                    out[f"tpu_engine.device_mem.bytes.{w}"] = v
                # QoS lane/shed gauges (docs/manual/14-qos.md):
                # scrape-flat twins of the /tpu_stats qos block (the
                # per-event counters additionally stream through the
                # StatsManager — graph.qos.* / tpu_engine.qos.shed.*)
                q = tpu_engine.qos_stats()
                out["tpu_engine.qos.queue_depth"] = q["queue_depth"]
                out["tpu_engine.qos.group_wait_p95_ms"] = \
                    q["group_wait_p95_ms"]
                out["tpu_engine.qos.shed"] = q["shed"]
                for lane, v in q["lane_rounds"].items():
                    out[f"tpu_engine.qos.lane_rounds.{lane}"] = v
                for lane, v in q["lane_rounds_in_flight"].items():
                    out[f"tpu_engine.qos.lane_in_flight.{lane}"] = v
                for reason, v in q["shed_reasons"].items():
                    out[f"tpu_engine.qos.shed_reason.{reason}"] = v
                for space, v in q["shed_by_space"].items():
                    out[f"tpu_engine.qos.shed_by_space.{space}"] = v
                return out

            web.add_metrics_source(tpu_metric_source)
        web.start()
    return GraphdHandle(service, engine, mc, server, web)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="nebula-tpu graph daemon")
    ap.add_argument("--meta", required=True, help="metad host:port")
    ap.add_argument("--flagfile", default=None,
                help="gflags-style config file (etc/*.conf)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=3699)
    ap.add_argument("--tpu", action="store_true",
                    help="enable the TPU graph engine for GO/FIND PATH")
    ap.add_argument("--ws-port", type=int, default=13000,
                    help="HTTP admin port (-1 disables)")
    args = ap.parse_args(argv)
    if args.flagfile:
        graph_flags.load_flagfile(args.flagfile)
    tpu = None
    if args.tpu:
        # fail LOUDLY here rather than silently serving CPU-only — an
        # operator who passed --tpu must know if the device is unusable
        import os
        import jax
        devs = jax.devices()
        if (all(d.platform == "cpu" for d in devs)
                and not os.environ.get("NEBULA_TPU_ALLOW_CPU")):
            raise SystemExit(
                f"graphd --tpu: no accelerator device (jax sees {devs}); "
                f"refusing to silently serve CPU-only. Set "
                f"NEBULA_TPU_ALLOW_CPU=1 to run the engine on the CPU "
                f"XLA backend anyway.")
        print(f"graphd --tpu: JAX backend up ({devs})")
        from ..engine_tpu import TpuGraphEngine
        mesh = None
        if len(devs) > 1:
            # multi-device host: serve over the partition mesh —
            # snapshots whose part count divides the mesh get sharded
            # kernels, and the full query surface runs distributed
            # (mesh_exec.py; docs/manual/8-mesh.md). NEBULA_TPU_NO_MESH
            # pins single-device serving for A/B comparison.
            if not os.environ.get("NEBULA_TPU_NO_MESH"):
                from ..engine_tpu.distributed import make_mesh
                mesh = make_mesh()
                print(f"graphd --tpu: {len(devs)}-device mesh enabled")
        tpu = TpuGraphEngine(mesh=mesh)
    ws = None if args.ws_port < 0 else args.ws_port
    h = serve_graphd(args.meta, args.host, args.port, tpu_engine=tpu,
                     ws_port=ws)
    print(f"graphd listening on {h.addr} (meta {args.meta}, "
          f"http {h.ws_port})")
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        h.stop()


if __name__ == "__main__":
    main()
