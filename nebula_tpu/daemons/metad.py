"""metad: the meta service daemon (ref: daemons/MetaDaemon.cpp:160-259
boots the meta KV, cluster id, gflags manager, and thrift handler)."""
from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Optional

from ..common.flags import meta_flags
from ..common.stats import stats
from ..meta.service import MetaService
from ..rpc import RpcServer
from ..webservice import WebService


@dataclass
class MetadHandle:
    meta: MetaService
    server: RpcServer
    web: Optional[WebService] = None

    @property
    def addr(self) -> str:
        return self.server.addr

    @property
    def ws_port(self) -> Optional[int]:
        return self.web.port if self.web else None

    def stop(self) -> None:
        self.server.stop()
        if self.web:
            self.web.stop()


def serve_metad(host: str = "127.0.0.1", port: int = 0,
                ws_port: Optional[int] = None,
                store=None,
                expired_threshold_secs: Optional[int] = None) -> MetadHandle:
    """`store`: a GraphStore backing the meta KV — pass the previous
    instance's store (or a persistent one) to restart metad with its
    catalog, cluster id AND any in-flight balance plan intact; the
    re-attached balancer resumes the plan on the next BALANCE DATA
    (Balancer::recovery). `expired_threshold_secs` overrides the
    ActiveHostsMan liveness horizon (defaults to the
    `expired_threshold_sec` flag)."""
    if expired_threshold_secs is None:
        expired_threshold_secs = int(meta_flags.get(
            "expired_threshold_sec",
            10 * 60))
    meta = MetaService(store=store,
                       expired_threshold_secs=expired_threshold_secs)
    # metad hosts the balancer; it drives replicated storaged through
    # their "admin" RPC services (ref: Balancer + AdminClient in metad)
    from ..meta.balancer import Balancer
    from ..meta.net_admin import NetAdminClient
    def active_storage_hosts():
        return [h.host for h in meta.active_hosts("storage")]

    admin = NetAdminClient(active_storage_hosts)
    meta.attach_balancer(Balancer(meta, admin,
                                  get_active_hosts=active_storage_hosts))
    server = RpcServer(host, port).register("meta", meta).start()
    web = None
    if ws_port is not None:
        web = WebService("metad", flags=meta_flags, stats=stats,
                         host=host, port=ws_port,
                         build_labels={"role": "meta"})
        # flight bundles captured on metad carry the balancer/liveness
        # view at trigger time (common/flight.py)
        from ..common.flight import recorder as _flight
        _flight.add_collector("metad.balance", meta.balance_progress)
        _flight.add_collector(
            "metad.active_hosts",
            lambda: [h.host for h in meta.active_hosts("storage")])

        def balance_handler(params, body):
            # /balance: plan progress + persisted task rows (the BALANCE
            # SHOW table, operator-readable without a console session);
            # ?heat=1 = the heat-aware ADVISORY plan — current vs
            # post-plan modeled per-host heat spread, nothing moved
            # (docs/manual/12-replication.md)
            if params.get("heat"):
                r = meta.balance_advise_heat()
                if not r.ok():
                    return 500, {"error": r.status.msg}
                return 200, r.value()
            pg = meta.balance_progress()
            pg["rows"] = meta.balance_show(
                int(params["plan"]) if params.get("plan") else None)
            return 200, pg

        web.register("/balance", balance_handler)
        # the heartbeat-carried workload heat view rides every metad
        # flight bundle next to the balancer state
        _flight.add_collector("metad.heat", meta.heat_overview)

        def meta_metric_source():
            out = {"meta.active_storage_hosts":
                   len(meta.active_hosts("storage"))}
            pg = meta.balance_progress()
            out["meta.balance.plan"] = pg["plan"]
            out["meta.balance.running"] = int(pg["running"])
            for st_name, n in pg["tasks"].items():
                out[f"meta.balance.tasks.{st_name}"] = n
            return out

        web.add_metrics_source(meta_metric_source)
        web.start()
        # self-register as a /cluster_metrics scrape target (metad
        # doesn't heartbeat to itself; storaged/graphd ports arrive
        # via heartbeat)
        meta.note_web_port(server.addr, web.port, "meta")
    return MetadHandle(meta, server, web)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="nebula-tpu meta daemon")
    ap.add_argument("--flagfile", default=None,
                    help="gflags-style config file (etc/*.conf)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=45500)
    ap.add_argument("--ws-port", type=int, default=11000,
                    help="HTTP admin port (-1 disables)")
    args = ap.parse_args(argv)
    if args.flagfile:
        meta_flags.load_flagfile(args.flagfile)
    ws = None if args.ws_port < 0 else args.ws_port
    h = serve_metad(args.host, args.port, ws_port=ws)
    print(f"metad listening on {h.addr} (http {h.ws_port})")
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        h.stop()


if __name__ == "__main__":
    main()
