"""metad: the meta service daemon (ref: daemons/MetaDaemon.cpp:160-259
boots the meta KV, cluster id, gflags manager, and thrift handler)."""
from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Optional

from ..common.flags import meta_flags
from ..common.stats import stats
from ..meta.service import MetaService
from ..rpc import RpcServer
from ..webservice import WebService


@dataclass
class MetadHandle:
    meta: MetaService
    server: RpcServer
    web: Optional[WebService] = None

    @property
    def addr(self) -> str:
        return self.server.addr

    @property
    def ws_port(self) -> Optional[int]:
        return self.web.port if self.web else None

    def stop(self) -> None:
        self.server.stop()
        if self.web:
            self.web.stop()


def serve_metad(host: str = "127.0.0.1", port: int = 0,
                ws_port: Optional[int] = None) -> MetadHandle:
    meta = MetaService()
    # metad hosts the balancer; it drives replicated storaged through
    # their "admin" RPC services (ref: Balancer + AdminClient in metad)
    from ..meta.balancer import Balancer
    from ..meta.net_admin import NetAdminClient
    def active_storage_hosts():
        return [h.host for h in meta.active_hosts("storage")]

    admin = NetAdminClient(active_storage_hosts)
    meta.attach_balancer(Balancer(meta, admin,
                                  get_active_hosts=active_storage_hosts))
    server = RpcServer(host, port).register("meta", meta).start()
    web = None
    if ws_port is not None:
        web = WebService("metad", flags=meta_flags, stats=stats,
                         host=host, port=ws_port)
        web.start()
    return MetadHandle(meta, server, web)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="nebula-tpu meta daemon")
    ap.add_argument("--flagfile", default=None,
                    help="gflags-style config file (etc/*.conf)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=45500)
    ap.add_argument("--ws-port", type=int, default=11000,
                    help="HTTP admin port (-1 disables)")
    args = ap.parse_args(argv)
    if args.flagfile:
        meta_flags.load_flagfile(args.flagfile)
    ws = None if args.ws_port < 0 else args.ws_port
    h = serve_metad(args.host, args.port, ws_port=ws)
    print(f"metad listening on {h.addr} (http {h.ws_port})")
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        h.stop()


if __name__ == "__main__":
    main()
