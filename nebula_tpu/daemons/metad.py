"""metad: the meta service daemon (ref: daemons/MetaDaemon.cpp:160-259
boots the meta KV, cluster id, gflags manager, and thrift handler)."""
from __future__ import annotations

import argparse
from dataclasses import dataclass

from ..meta.service import MetaService
from ..rpc import RpcServer


@dataclass
class MetadHandle:
    meta: MetaService
    server: RpcServer

    @property
    def addr(self) -> str:
        return self.server.addr

    def stop(self) -> None:
        self.server.stop()


def serve_metad(host: str = "127.0.0.1", port: int = 0) -> MetadHandle:
    meta = MetaService()
    server = RpcServer(host, port).register("meta", meta).start()
    return MetadHandle(meta, server)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="nebula-tpu meta daemon")
    ap.add_argument("--flagfile", default=None,
                help="gflags-style config file (etc/*.conf)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=45500)
    args = ap.parse_args(argv)
    if args.flagfile:
        from ..common.flags import meta_flags
        meta_flags.load_flagfile(args.flagfile)
    h = serve_metad(args.host, args.port)
    print(f"metad listening on {h.addr}")
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        h.stop()


if __name__ == "__main__":
    main()
