"""storaged: the storage daemon (ref: storage/StorageServer.cpp:88-144
wires MetaClient → waitForMetadReady → SchemaManager → NebulaStore with
a meta-driven PartManager → handlers → thrift serve; heartbeats keep
the host active so metad allocates parts here)."""
from __future__ import annotations

import argparse
from dataclasses import dataclass

from ..kvstore.store import GraphStore
from ..meta.client import MetaClient
from ..meta.schema_manager import SchemaManager
from ..rpc import RpcServer
from ..storage.processors import StorageService


@dataclass
class StoragedHandle:
    store: GraphStore
    storage: StorageService
    meta_client: MetaClient
    server: RpcServer

    @property
    def addr(self) -> str:
        return self.server.addr

    def stop(self) -> None:
        self.meta_client.stop()
        self.server.stop()


def serve_storaged(meta_addr: str, host: str = "127.0.0.1",
                   port: int = 0,
                   load_interval: float = 0.2) -> StoragedHandle:
    server = RpcServer(host, port)
    addr = server.addr
    store = GraphStore()
    mc = MetaClient(meta_addr, local_addr=addr, role="storage")

    def on_change(event: str, **kw):
        # the MetaServerBasedPartManager push: local parts follow the
        # meta allocation (ref: kvstore/PartManager.h handler methods)
        if event in ("space_added", "parts_added"):
            for p in kw.get("parts", []):
                store.add_part(kw["space_id"], p)
        elif event == "parts_removed":
            for p in kw.get("parts", []):
                store.remove_part(kw["space_id"], p)
        elif event == "space_removed":
            store.remove_space(kw["space_id"])

    mc.add_listener(on_change)
    # register with metad BEFORE the first topology sync so part
    # allocation can target this host (waitForMetadReady ordering)
    mc.heartbeat(addr, "storage")
    mc.start(load_interval=load_interval)
    sm = SchemaManager(mc)
    storage = StorageService(store, sm, host=addr)
    server.register("storage", storage).start()
    return StoragedHandle(store, storage, mc, server)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="nebula-tpu storage daemon")
    ap.add_argument("--meta", required=True, help="metad host:port")
    ap.add_argument("--flagfile", default=None,
                help="gflags-style config file (etc/*.conf)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=44500)
    args = ap.parse_args(argv)
    if args.flagfile:
        from ..common.flags import storage_flags
        storage_flags.load_flagfile(args.flagfile)
    h = serve_storaged(args.meta, args.host, args.port)
    print(f"storaged listening on {h.addr} (meta {args.meta})")
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        h.stop()


if __name__ == "__main__":
    main()
