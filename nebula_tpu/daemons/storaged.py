"""storaged: the storage daemon (ref: storage/StorageServer.cpp:88-144
wires MetaClient → waitForMetadReady → SchemaManager → NebulaStore with
a meta-driven PartManager → handlers → thrift serve; heartbeats keep
the host active so metad allocates parts here). The HTTP admin service
mirrors the reference's StorageHttp{Status,Download,Ingest,Admin}
Handler endpoints."""
from __future__ import annotations

import argparse
import sys
import threading
from dataclasses import dataclass
from typing import Optional

from ..common import heat
from ..common.flags import storage_flags
from ..common.stats import stats
from ..kvstore.store import GraphStore
from ..meta.client import MetaClient
from ..meta.schema_manager import SchemaManager
from ..rpc import RpcServer
from ..storage.processors import StorageService
from ..webservice import WebService


@dataclass
class StoragedHandle:
    store: GraphStore
    storage: StorageService
    meta_client: MetaClient
    server: RpcServer
    web: Optional[WebService] = None
    node: Optional[object] = None        # StorageNode when replicated
    raft_server: Optional[RpcServer] = None
    kv_watcher: Optional[object] = None  # storage_flags watcher to detach
    compactor_stop: Optional[threading.Event] = None
    compactor_thread: Optional[threading.Thread] = None
    # storaged-tier device shards (storage/device_serve.py)
    device_shards: Optional[object] = None
    shard_stop: Optional[threading.Event] = None
    shard_thread: Optional[threading.Thread] = None

    @property
    def addr(self) -> str:
        return self.server.addr

    @property
    def ws_port(self) -> Optional[int]:
        return self.web.port if self.web else None

    def stop(self) -> None:
        if self.shard_stop is not None:
            # device-shard refresh rebuilds scan the engine — stop and
            # join before the node (and its engines) go down
            self.shard_stop.set()
            if self.shard_thread is not None:
                self.shard_thread.join(timeout=10)
        if self.compactor_stop is not None:
            # stop AND join the compactor BEFORE the node goes down —
            # a round mid-flight must not flush an engine whose native
            # handle the shutdown is about to free
            self.compactor_stop.set()
            if self.compactor_thread is not None:
                self.compactor_thread.join(timeout=10)
        if self.kv_watcher is not None:
            storage_flags.unwatch(self.kv_watcher)
        self.meta_client.stop()
        self.server.stop()
        if self.node is not None:
            self.node.stop()
            net = getattr(self.node, "raft_net", None)
            if net is not None:
                net.shutdown()
        else:
            # unreplicated: no raft WAL below — flush engine buffers
            # on the way out (clean-shutdown durability)
            self.store.close()
        if self.raft_server is not None:
            self.raft_server.stop()
        if self.web:
            self.web.stop()


def _register_admin_handlers(web: WebService, storage: StorageService) -> None:
    """ref: /admin?op=compact|flush&space=<id>, /download?space=<id>&
    url=..., /ingest?space=<id> (StorageHttp*Handler)."""

    def _space(params):
        raw = params.get("space")
        if raw is None:
            return None
        try:
            return int(raw)
        except ValueError:
            return None

    def admin(params, body):
        op = params.get("op")
        space = _space(params)
        if space is None:
            return 400, {"error": "space param required (integer)"}
        if op == "compact":
            st, removed = storage.admin_compact(space)
            return (200, {"result": "ok", "removed": removed}) if st.ok() \
                else (500, {"error": st.msg})
        if op == "flush":
            st = storage.admin_flush(space)
            return (200, {"result": "ok"}) if st.ok() \
                else (500, {"error": st.msg})
        return 400, {"error": f"unknown op {op!r}"}

    def download(params, body):
        url = params.get("url")
        if not url:
            return 400, {"error": "url required"}
        space = _space(params)
        if space is None:
            return 400, {"error": "space param required (integer)"}
        st = storage.download(space, url)
        return (200, {"result": "ok"}) if st.ok() else (500, {"error": st.msg})

    def ingest(params, body):
        space = _space(params)
        if space is None:
            return 400, {"error": "space param required (integer)"}
        st, n = storage.ingest(space)
        return (200, {"result": "ok", "ingested": n}) if st.ok() \
            else (500, {"error": st.msg})

    web.register("/admin", admin)
    web.register("/download", download)
    web.register("/ingest", ingest)


def serve_storaged(meta_addr: str, host: str = "127.0.0.1",
                   port: int = 0, ws_port: Optional[int] = None,
                   load_interval: float = 0.2,
                   cluster_id_file: str = "",
                   replicated: bool = False,
                   data_dir: Optional[str] = None,
                   advertise_host: Optional[str] = None,
                   engine: str = "native") -> StoragedHandle:
    server = RpcServer(host, port)
    raft_server = None
    if replicated:
        # raft listens on storage-port+1. When the storage port was
        # auto-assigned (port=0), the neighbor can already be held by
        # ANY socket on the box (an outbound connection's ephemeral
        # source port, another daemon) — re-roll the pair instead of
        # failing the whole daemon boot on the unlucky draw.
        for attempt in range(16):
            try:
                raft_server = RpcServer(
                    host, int(server.addr.rsplit(":", 1)[1]) + 1)
                break
            except OSError:
                if port != 0 or attempt == 15:
                    raise
                server.stop()
                server = RpcServer(host, 0)
    # the address REGISTERED with metad (and dialed by graphd + raft
    # peers) must be routable from other hosts — binding to 0.0.0.0 in
    # a container needs a separate advertised hostname, or every peer
    # would dial its own loopback
    addr = server.addr
    if advertise_host:
        addr = f"{advertise_host}:{addr.rsplit(':', 1)[1]}"
    # the storage daemon persists through the native LSM engine like
    # the reference's always-RocksEngine storaged (kvstore/RocksEngine);
    # engine="mem" keeps the pure-python MemEngine (tests, no-toolchain
    # hosts — native_engine_factory itself falls back when the .so is
    # missing)
    from ..kvstore import native_engine_factory
    engine_factory = None
    if engine == "native":
        import os as _os
        engine_factory = native_engine_factory(
            _os.path.join(data_dir, "engines") if data_dir else None)
    node = None
    # filled once the DeviceShardManager exists (it needs the
    # StorageService built below); the raft leader-change callback
    # closes over the cell so elections invalidate shards immediately
    shard_state: dict = {}
    if replicated:
        # raft-replicated parts: the second RpcServer on port+1 (bound
        # above, next to the storage server so an unlucky ephemeral
        # pair re-rolls) hosts this node's RaftexService; peers reach
        # it via RpcTransport
        from ..kvstore.raft_store import StorageNode
        from ..kvstore.raftex.service import RpcTransport
        from ..meta.net_admin import raft_addr_of, storage_addr_of
        import tempfile
        raft_net = RpcTransport()

        def on_leader_change(space_id, part_id, leader):
            # counted for /metrics; when THIS replica takes over, its
            # view of the meta allocation may already include peers the
            # group hasn't admitted (heartbeat reconcile) — sync now.
            # Also a flight-recorder event: >= 3 leader changes in 10 s
            # is the leader_churn trigger (common/flight.py)
            stats.add_value("raftex.leader_changes", kind="counter")
            from ..common.flight import recorder as _flight
            _flight.record("leader_change", space=space_id,
                           part=part_id, leader=str(leader))
            # leadership moved: the local device shard's vouch set is
            # gone — drop it now (the old shard refuses to vouch, the
            # refresh task rebuilds against the new leadership
            # signature; docs/manual/13-device-speed.md)
            mgr = shard_state.get("mgr")
            if mgr is not None:
                mgr.invalidate(space_id, part_id)
            if leader == raft_addr_of(addr):
                _reconcile_part_membership(space_id, part_id)

        node = StorageNode(addr=raft_addr_of(addr),
                           data_root=data_dir or tempfile.mkdtemp(
                               prefix="nebula_tpu_storaged_"),
                           net=raft_net,
                           engine_factory=engine_factory,
                           leader_hint=storage_addr_of,
                           on_leader_change=on_leader_change,
                           heartbeat_interval=max(
                               0.01, storage_flags.get(
                                   "raft_heartbeat_ms", 150) / 1000.0),
                           election_timeout=max(
                               0.05, storage_flags.get(
                                   "raft_election_timeout_ms", 450)
                               / 1000.0),
                           # WAL sizing (REBOOT, read at part bind):
                           # segment roll size + TTL-sweep age
                           wal_file_size=storage_flags.get_or(
                               "wal_file_size", 16 * 1024 * 1024),
                           wal_ttl_secs=storage_flags.get_or(
                               "wal_ttl_secs", 86400))
        node.raft_net = raft_net  # shut down with the node (handle.stop)
        raft_server.register("raftex", node.service).start()
        store = node.store
    else:
        store = GraphStore(engine_factory=engine_factory)
    mc = MetaClient(meta_addr, local_addr=addr, role="storage",
                    cluster_id_file=cluster_id_file)

    def _reconcile_part_membership(space_id: int, part_id: int) -> None:
        """Leader-side membership sync against the meta allocation
        (satellite: CREATE SPACE replica_factor=N end-to-end). A host
        metad assigned to this part (heartbeat reconcile / balance)
        that the raft group doesn't know yet is added as a peer — the
        new replica, already materialized as a learner by its own
        topology watch, is promoted by the ADD_PEER command and caught
        up by gap/snapshot replication. Removal stays with the
        balancer's explicit member_remove."""
        if node is None:
            return
        from ..meta.net_admin import raft_addr_of as _ra
        raft = node.raft(space_id, part_id)
        if raft is None or not raft.is_leader():
            return
        try:
            want = {_ra(h) for h in mc.part_peers(space_id, part_id)
                    if h != "local"}
        except Exception:
            return
        # everything meta assigned that is not a VOTER yet: admits
        # unknown hosts and promotes meta-assigned replicas stuck as
        # learners (ADD_PEER both admits and promotes) — a learner
        # that never becomes a voter would silently shrink the quorum
        for target in sorted(want - set(raft.peers)):
            stats.add_value("raftex.membership_reconciled",
                            kind="counter")
            raft.add_peer_async(target)

    def _group_formed(space_id: int, part_id: int, others) -> bool:
        """Does a raft group for this part already run elsewhere? The
        peers' admin services are probed for term >= 1 (an election
        happened before this node ever saw the part) — including the
        boot path, where a late-started replica learns of the space
        via space_added, not parts_added. False on any doubt: at
        genuine space creation the sibling replicas materialize the
        part within one topology tick, so their probes answer
        no-part/term-0 and everyone starts as a voter."""
        from ..meta.net_admin import storage_addr_of
        from ..rpc import proxy as _proxy
        for rp in others:
            try:
                st = _proxy(storage_addr_of(rp), "admin", timeout=0.5,
                            max_attempts=1).raft_state(space_id,
                                                       part_id)
            except Exception:
                continue
            if st and st.get("term", 0) >= 1:
                return True
        return False

    def on_change(event: str, **kw):
        # the MetaServerBasedPartManager push: local parts follow the
        # meta allocation (ref: kvstore/PartManager.h handler methods)
        if event in ("space_added", "parts_added"):
            for p in kw.get("parts", []):
                if node is not None:
                    peers = [raft_addr_of(h) for h in
                             mc.part_peers(kw["space_id"], p)
                             if h != "local"]
                    others = [pe for pe in peers
                              if pe != raft_addr_of(addr)]
                    # a part that gains THIS host after its raft group
                    # already formed elsewhere (heartbeat reconcile,
                    # balance, late boot) joins as a LEARNER: an
                    # empty-log voter would campaign and depose the
                    # incumbent until ADD_PEER lands. The leader's
                    # membership reconcile promotes the learner; a
                    # group-log ADD_PEER that committed before this
                    # replica materialized replays into it and
                    # promotes it likewise.
                    joining = bool(others) and (
                        event == "parts_added"
                        or _group_formed(kw["space_id"], p, others))
                    node.add_part(kw["space_id"], p, peers or
                                  [raft_addr_of(addr)],
                                  as_learner=joining)
                else:
                    store.add_part(kw["space_id"], p)
        elif event == "parts_removed":
            for p in kw.get("parts", []):
                if node is not None:
                    node.remove_part(kw["space_id"], p)
                else:
                    store.remove_part(kw["space_id"], p)
        elif event == "peers_changed" and node is not None:
            # replica set changed on parts we host: the leader admits
            # any meta-assigned host the group doesn't know yet
            for p in kw.get("parts", {}):
                _reconcile_part_membership(kw["space_id"], p)
        elif event == "space_removed":
            if node is not None:
                node.remove_space(kw["space_id"])
            else:
                store.remove_space(kw["space_id"])
            # heat hygiene: a dropped space's slabs must stop
            # scraping as nebula_part_heat_* families
            heat.accountant.drop_space(kw["space_id"])

    # the web service is created after the heartbeat thread starts, so
    # the callback reads it through this box (and the box records the
    # event in case it fires inside that window)
    wc_state = {"fired": False, "web": None}

    def on_wrong_cluster():
        # a mis-pointed storaged must refuse ALL traffic — rpc, raft and
        # http admin alike (the reference daemon aborts the process)
        wc_state["fired"] = True
        server.stop()
        if raft_server is not None:
            raft_server.stop()
        if node is not None:
            node.stop()
            net = getattr(node, "raft_net", None)
            if net is not None:
                net.shutdown()
        if wc_state["web"] is not None:
            wc_state["web"].stop()

    mc.on_wrong_cluster = on_wrong_cluster
    mc.add_listener(on_change)

    def leader_source():
        # heartbeat-carried leadership: metad's ActiveHostsMan leader
        # view (SHOW HOSTS / SHOW PARTS leader columns). Unreplicated
        # nodes lead every part they host (DirectCommit).
        if node is not None:
            return node.leader_parts()
        out = {}
        for sid in store.spaces():
            led = store.leader_parts(sid)
            if led:
                out[sid] = led
        return out

    mc.leader_source = leader_source

    def heat_source():
        # heartbeat-carried placement telemetry (workload & data
        # observatory, common/heat.py): per-(space, part) 600s heat
        # for the parts this node LEADS, plus the leader-side replica
        # staleness watermarks — metad's heat view feeds SHOW HOSTS/
        # SHOW PARTS heat columns and the heat-aware BALANCE advisor.
        # None (no heartbeat field at all) when heat is disarmed.
        payload = heat.accountant.heartbeat_payload(
            lead_parts=leader_source())
        if payload is None:
            return None
        if node is not None:
            stale = {}
            for st in node.raft_status():
                reps = st.get("replicas") or []
                if reps:
                    stale.setdefault(st["space"], {})[st["part"]] = {
                        "max_ms": st.get("staleness_ms", 0.0),
                        "replicas": {m["addr"]: m["staleness_ms"]
                                     for m in reps}}
            if stale:
                payload["staleness"] = stale
        return payload

    mc.heat_source = heat_source
    # register with metad BEFORE the first topology sync so part
    # allocation can target this host (waitForMetadReady ordering)
    mc.heartbeat(addr, "storage")
    mc.start(load_interval=load_interval)

    # engine tuning rides the config registry: UPDATE CONFIGS
    # STORAGE:kv_engine_options='{"flush_bytes":...}' on any graphd
    # reaches this store within a heartbeat (the MetaClient hb loop
    # pulls MUTABLE flags; the watcher below hot-applies them — ref
    # role: RocksEngineConfig.cpp option maps applied at runtime)
    def _apply_kv_options(name, value):
        if name != "kv_engine_options" or not value:
            return
        import json as _json
        try:
            opts = _json.loads(value)
        except ValueError:
            print(f"storaged: bad kv_engine_options JSON ignored: "
                  f"{value!r}", file=sys.stderr)
            return
        store.apply_engine_options(opts)

    storage_flags.watch(_apply_kv_options)
    _apply_kv_options("kv_engine_options",
                      storage_flags.get("kv_engine_options"))
    try:
        storage_flags.sync_to_meta(mc)       # make flags UPDATE-able
        storage_flags.pull_from_meta(mc)     # adopt cluster-set values
    except Exception:
        pass
    sm = SchemaManager(mc)
    storage = StorageService(store, sm, host=addr)
    server.register("storage", storage)
    if node is not None:
        # part-admin surface the meta balancer drives (ref:
        # storaged's AdminProcessor)
        from ..meta.net_admin import AdminService
        server.register("admin", AdminService(node))
    server.start()
    device_shards = None
    shard_stop = None
    shard_thread = None
    if node is not None:
        # storaged-tier device shards (storage/device_serve.py;
        # docs/manual/13-device-speed.md): a local CSR snapshot over
        # this node's engines, refreshed off the raft apply path every
        # device_shard_refresh_ms, serving graphd's device_window
        # scatter/gather instead of leader-routed row scans
        from ..storage.device_serve import DeviceShardManager
        device_shards = DeviceShardManager(store, sm,
                                           raft_lookup=node.raft,
                                           host=addr)
        storage.device_serve = device_shards
        shard_state["mgr"] = device_shards
        shard_stop = threading.Event()

        def _shard_refresher(stop_ev=shard_stop, mgr=device_shards):
            while not stop_ev.wait(max(0.01, storage_flags.get_or(
                    "device_shard_refresh_ms", 50) / 1000.0)):
                try:
                    mgr.refresh()
                except Exception:
                    pass            # never die; next round retries

        # nlint: disable=NL002 -- node-lifetime background maintenance
        # loop; it serves every part and owes no request a trace
        shard_thread = threading.Thread(
            target=_shard_refresher, daemon=True,
            name=f"device-shards-{addr}")
        shard_thread.start()
    compactor_stop = None
    compactor_thread = None
    if node is not None:
        # snapshot-anchored WAL compaction task (docs/manual/
        # 12-replication.md): every wal_compact_interval_secs, capture
        # per-part applied anchors, flush engines, truncate each WAL
        # behind anchor - wal_compact_lag, and run the TTL sweep —
        # bounding WAL disk and restart replay length. Both flags are
        # MUTABLE and consulted per round.
        compactor_stop = threading.Event()

        def _wal_compactor(stop_ev=compactor_stop, n=node):
            last_anchors: dict = {}
            while not stop_ev.wait(max(0.05, storage_flags.get_or(
                    "wal_compact_interval_secs", 20.0))):
                lag = storage_flags.get_or("wal_compact_lag", 4096)
                if lag < 0:
                    continue            # negative disables, hot
                try:
                    # idle guard: the flush step is a full engine
                    # checkpoint — skip the round entirely when no
                    # part's applied anchor moved since last time
                    cur = {k: h.raft.committed_id
                           for k, h in list(n.hooks.items())
                           if h.raft is not None}
                    if cur and cur != last_anchors:
                        n.compact_wals(lag)
                        last_anchors = cur
                except Exception:
                    pass                # never die; next round retries

        # nlint: disable=NL002 -- node-lifetime background maintenance
        # loop; it serves every part and owes no request a trace
        compactor_thread = threading.Thread(
            target=_wal_compactor, daemon=True,
            name=f"wal-compact-{addr}")
        compactor_thread.start()
    web = None
    if ws_port is not None:
        web = WebService("storaged", flags=storage_flags, stats=stats,
                         host=host, port=ws_port,
                         build_labels={
                             "role": "storage",
                             "replicated": "1" if replicated else "0",
                             "engine": engine})
        _register_admin_handlers(web, storage)
        # observability surface: /traces serves this daemon's ring
        # (remote fragments it recorded for graphd-headed traces),
        # /queries its in-flight processor ops AND the finished ops
        # that crossed slow_query_threshold_ms (with their ledger
        # slice), /metrics the built-in Prometheus exposition
        # (docs/manual/10-observability.md)
        web.register_observability(active=storage.active_ops,
                                   slow=storage.slow_ops)

        def cache_metric_source():
            # storaged cache rungs as flat gauges (bound_stats
            # responses + (part, version) columnar scans; docs/manual/
            # 11-caching.md) — per-event counters additionally stream
            # through the StatsManager (common/cache.py stats_prefix)
            out = {}
            for rung, st in (("stats_cache", storage.stats_cache),
                             ("scan_cache", storage.scan_cache)):
                for k, v in st.stats().items():
                    out[f"storage.{rung}.{k}"] = v
            return out

        web.add_metrics_source(cache_metric_source)

        def raft_handler(params, body):
            # /raft: per-part consensus state — role/term/leader/
            # commit-lag/peers (docs/manual/12-replication.md)
            if node is None:
                return 200, {"replicated": False, "parts": []}
            return 200, {"replicated": True, "addr": addr,
                         "parts": node.raft_status()}

        web.register("/raft", raft_handler)

        def consistency_handler(params, body):
            # /consistency (docs/manual/10-observability.md,
            # "Consistency observatory"): per-part content-digest
            # anchors; leaders add every replica's match/applied/
            # digest_ok. ?scrub=1 deep-scrubs the incremental digests
            # against a full engine scan (catches silent store
            # mutation that bypassed the apply path).
            from ..common import consistency as _cons
            out = {"enabled": _cons.enabled(), "addr": addr,
                   "replicated": node is not None}
            if node is not None:
                out["parts"] = node.consistency_status()
                if params.get("scrub"):
                    out["scrub"] = node.digest_scrub()
            else:
                out["parts"] = _cons.store_rows(store)
                if params.get("scrub"):
                    out["scrub"] = [
                        p.digest_scrub()
                        for sid in store.spaces()
                        for p in store.space_parts(sid)]
            return 200, out

        web.register("/consistency", consistency_handler)

        def heat_handler(params, body):
            # /heat (docs/manual/10-observability.md, "Workload & data
            # observatory"): per-(space, part) heat slabs + per-space
            # skew indices; ?vertices=1 adds the scanned-src-vid
            # hot-vertex sketches; replicated nodes append the /raft
            # staleness watermarks
            out = heat.accountant.describe(
                vertices=bool(params.get("vertices")))
            if node is not None:
                out["staleness"] = [
                    {"space": st["space"], "part": st["part"],
                     "staleness_ms": st.get("staleness_ms", 0.0),
                     "replicas": st.get("replicas", [])}
                    for st in node.raft_status()
                    if st.get("replicas")]
            return 200, out

        web.register("/heat", heat_handler)

        def device_shards_handler(params, body):
            # /device_shards (docs/manual/13-device-speed.md): the
            # storaged-tier device-shard lifecycle — per-space build/
            # freshness state + the serve counters (leader vs follower
            # parts, fence refusals, measured max served staleness)
            if device_shards is None:
                return 200, {"enabled": False}
            return 200, {"enabled": True, "addr": addr,
                         "spaces": {sid: device_shards.snapshot_info(sid)
                                    for sid in store.spaces()},
                         "stats": dict(device_shards.stats)}

        web.register("/device_shards", device_shards_handler)
        if device_shards is not None:
            def device_shard_metric_source():
                return {f"storage.device_serve.{k}": v
                        for k, v in device_shards.stats.items()}

            web.add_metrics_source(device_shard_metric_source)
        # nebula_part_heat_* / nebula_heat_skew_index_* families
        # (empty — byte-identical /metrics — when heat is disarmed)
        web.add_metrics_source(heat.accountant.gauges)
        if node is not None:
            # flight bundles captured on this storaged carry the
            # per-part consensus state at trigger time
            from ..common.flight import recorder as _fl
            _fl.add_collector("storaged.raft", node.raft_status)
            # ... and the digest view, so a replica_divergence bundle
            # names the diverging part/replica/anchor in-band
            _fl.add_collector("storaged.consistency",
                              node.consistency_status)
            # ... and the armed network nemesis, so a
            # partition_suspected bundle shows whether the timeouts
            # were injected (link rules + fired counts) or organic
            from ..common.faults import faults as _freg

            def _nemesis_state():
                d = _freg.describe()
                return {"links": d.get("links", []),
                        "fired": d.get("fired", {})}

            _fl.add_collector("storaged.nemesis", _nemesis_state)

        if node is not None:
            def raft_metric_source():
                # per-part raft gauges: is_leader/term/commit_lag —
                # a scrape across the fleet shows leader placement and
                # stuck replication at a glance
                out = {}
                for st in node.raft_status():
                    base = (f"storage.raft.s{st['space']}."
                            f"p{st['part']}")
                    out[base + ".is_leader"] = \
                        1 if st["role"] == "LEADER" else 0
                    out[base + ".term"] = st["term"]
                    out[base + ".commit_lag"] = st["commit_lag"]
                    # crash-recovery/compaction surface: entries this
                    # boot re-applied + segment files compacted away
                    out[base + ".wal_replayed"] = st["wal_replayed"]
                    out[base + ".wal_cleaned"] = st["wal_cleaned"]
                    # replica staleness watermark (max over followers;
                    # 0 on non-leaders — the leader owns the signal);
                    # observatory telemetry, so the heat_enabled
                    # disarm contract removes the family too
                    if heat.enabled():
                        out[base + ".staleness_ms"] = \
                            st.get("staleness_ms", 0.0)
                    # consistency observatory: 1 while every replica's
                    # last digest check agreed (leader-side; families
                    # vanish when disarmed — the same byte-identity
                    # contract as heat)
                    from ..common import consistency as _cons
                    if _cons.enabled() and st.get("replicas"):
                        out[f"consistency.s{st['space']}."
                            f"p{st['part']}.digest_ok"] = \
                            0 if st.get("digest_divergent") else 1
                        out[f"consistency.s{st['space']}."
                            f"p{st['part']}.divergent_replicas"] = \
                            len(st.get("digest_divergent") or ())
                return out

            web.add_metrics_source(raft_metric_source)
        web.start()
        # advertise the admin port: future heartbeats carry it, and
        # one immediate beat makes this daemon a /cluster_metrics
        # scrape target without waiting a heartbeat period
        mc.ws_port = web.port
        try:
            mc.heartbeat(addr, "storage", ws_port=web.port)
        except Exception:
            pass
        wc_state["web"] = web
        if wc_state["fired"]:   # wrong-cluster fired before web existed
            web.stop()
    return StoragedHandle(store, storage, mc, server, web, node, raft_server,
                          kv_watcher=_apply_kv_options,
                          compactor_stop=compactor_stop,
                          compactor_thread=compactor_thread,
                          device_shards=device_shards,
                          shard_stop=shard_stop,
                          shard_thread=shard_thread)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="nebula-tpu storage daemon")
    ap.add_argument("--meta", required=True, help="metad host:port")
    ap.add_argument("--flagfile", default=None,
                    help="gflags-style config file (etc/*.conf)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=44500)
    ap.add_argument("--ws-port", type=int, default=12000,
                    help="HTTP admin port (-1 disables)")
    ap.add_argument("--cluster-id-file", default="",
                    help="persist/verify the cluster id here "
                         "(ClusterIdMan; empty = learn from metad)")
    ap.add_argument("--replicated", action="store_true",
                    help="raft-replicate parts across storaged peers "
                         "(raft listens on port+1)")
    ap.add_argument("--data-dir", default=None,
                    help="WAL/engine root for replicated mode")
    ap.add_argument("--advertise-host", default=None,
                    help="hostname to register with metad when binding "
                         "a wildcard address (containers: the service "
                         "hostname)")
    args = ap.parse_args(argv)
    if args.flagfile:
        storage_flags.load_flagfile(args.flagfile)
    ws = None if args.ws_port < 0 else args.ws_port
    h = serve_storaged(args.meta, args.host, args.port, ws_port=ws,
                       cluster_id_file=args.cluster_id_file,
                       replicated=args.replicated, data_dir=args.data_dir,
                       advertise_host=args.advertise_host)
    print(f"storaged listening on {h.addr} (meta {args.meta}, "
          f"http {h.ws_port})")
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        h.stop()


if __name__ == "__main__":
    main()
