"""storaged: the storage daemon (ref: storage/StorageServer.cpp:88-144
wires MetaClient → waitForMetadReady → SchemaManager → NebulaStore with
a meta-driven PartManager → handlers → thrift serve; heartbeats keep
the host active so metad allocates parts here). The HTTP admin service
mirrors the reference's StorageHttp{Status,Download,Ingest,Admin}
Handler endpoints."""
from __future__ import annotations

import argparse
from dataclasses import dataclass
from typing import Optional

from ..common.flags import storage_flags
from ..common.stats import stats
from ..kvstore.store import GraphStore
from ..meta.client import MetaClient
from ..meta.schema_manager import SchemaManager
from ..rpc import RpcServer
from ..storage.processors import StorageService
from ..webservice import WebService


@dataclass
class StoragedHandle:
    store: GraphStore
    storage: StorageService
    meta_client: MetaClient
    server: RpcServer
    web: Optional[WebService] = None

    @property
    def addr(self) -> str:
        return self.server.addr

    @property
    def ws_port(self) -> Optional[int]:
        return self.web.port if self.web else None

    def stop(self) -> None:
        self.meta_client.stop()
        self.server.stop()
        if self.web:
            self.web.stop()


def _register_admin_handlers(web: WebService, storage: StorageService) -> None:
    """ref: /admin?op=compact|flush&space=<id>, /download?space=<id>&
    url=..., /ingest?space=<id> (StorageHttp*Handler)."""

    def _space(params):
        raw = params.get("space")
        if raw is None:
            return None
        try:
            return int(raw)
        except ValueError:
            return None

    def admin(params, body):
        op = params.get("op")
        space = _space(params)
        if space is None:
            return 400, {"error": "space param required (integer)"}
        if op == "compact":
            st, removed = storage.admin_compact(space)
            return (200, {"result": "ok", "removed": removed}) if st.ok() \
                else (500, {"error": st.msg})
        if op == "flush":
            st = storage.admin_flush(space)
            return (200, {"result": "ok"}) if st.ok() \
                else (500, {"error": st.msg})
        return 400, {"error": f"unknown op {op!r}"}

    def download(params, body):
        url = params.get("url")
        if not url:
            return 400, {"error": "url required"}
        space = _space(params)
        if space is None:
            return 400, {"error": "space param required (integer)"}
        st = storage.download(space, url)
        return (200, {"result": "ok"}) if st.ok() else (500, {"error": st.msg})

    def ingest(params, body):
        space = _space(params)
        if space is None:
            return 400, {"error": "space param required (integer)"}
        st, n = storage.ingest(space)
        return (200, {"result": "ok", "ingested": n}) if st.ok() \
            else (500, {"error": st.msg})

    web.register("/admin", admin)
    web.register("/download", download)
    web.register("/ingest", ingest)


def serve_storaged(meta_addr: str, host: str = "127.0.0.1",
                   port: int = 0, ws_port: Optional[int] = None,
                   load_interval: float = 0.2,
                   cluster_id_file: str = "") -> StoragedHandle:
    server = RpcServer(host, port)
    addr = server.addr
    store = GraphStore()
    mc = MetaClient(meta_addr, local_addr=addr, role="storage",
                    cluster_id_file=cluster_id_file)

    def on_change(event: str, **kw):
        # the MetaServerBasedPartManager push: local parts follow the
        # meta allocation (ref: kvstore/PartManager.h handler methods)
        if event in ("space_added", "parts_added"):
            for p in kw.get("parts", []):
                store.add_part(kw["space_id"], p)
        elif event == "parts_removed":
            for p in kw.get("parts", []):
                store.remove_part(kw["space_id"], p)
        elif event == "space_removed":
            store.remove_space(kw["space_id"])

    mc.add_listener(on_change)
    # register with metad BEFORE the first topology sync so part
    # allocation can target this host (waitForMetadReady ordering)
    mc.heartbeat(addr, "storage")
    mc.start(load_interval=load_interval)
    sm = SchemaManager(mc)
    storage = StorageService(store, sm, host=addr)
    server.register("storage", storage).start()
    web = None
    if ws_port is not None:
        web = WebService("storaged", flags=storage_flags, stats=stats,
                         host=host, port=ws_port)
        _register_admin_handlers(web, storage)
        web.start()
    return StoragedHandle(store, storage, mc, server, web)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="nebula-tpu storage daemon")
    ap.add_argument("--meta", required=True, help="metad host:port")
    ap.add_argument("--flagfile", default=None,
                    help="gflags-style config file (etc/*.conf)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=44500)
    ap.add_argument("--ws-port", type=int, default=12000,
                    help="HTTP admin port (-1 disables)")
    ap.add_argument("--cluster-id-file", default="",
                    help="persist/verify the cluster id here "
                         "(ClusterIdMan; empty = learn from metad)")
    args = ap.parse_args(argv)
    if args.flagfile:
        storage_flags.load_flagfile(args.flagfile)
    ws = None if args.ws_port < 0 else args.ws_port
    h = serve_storaged(args.meta, args.host, args.port, ws_port=ws,
                       cluster_id_file=args.cluster_id_file)
    print(f"storaged listening on {h.addr} (meta {args.meta}, "
          f"http {h.ws_port})")
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        h.stop()


if __name__ == "__main__":
    main()
