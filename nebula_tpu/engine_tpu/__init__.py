import jax as _jax

# int64 must survive on device: vid-free device arrays are int32 by
# design, but traversal counters (edges traversed on billion-edge
# graphs x hops) need true 64-bit accumulation.
_jax.config.update("jax_enable_x64", True)

from .engine import TpuGraphEngine  # noqa: F401,E402
from .csr import CsrSnapshot, CsrShard  # noqa: F401,E402
