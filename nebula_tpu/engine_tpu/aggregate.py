"""Device-side aggregation pushdown: GO … | YIELD <aggregates>.

The reference ships aggregates to storage as bound_stats
(ref: storage/QueryStatsProcessor, storage.thrift StatType :65-69) so
SUM/COUNT/AVG never materialize edges on graphd. The TPU equivalent is
a masked reduction over the snapshot's [P, cap_e] edge block: the
final-hop mask comes from the same multi-hop kernel the GO path uses,
the value columns from the same FilterCompiler leaf loaders (so
null/err semantics are shared with WHERE compilation), and only the
per-partition partial aggregates leave the device.

Exactness discipline (the module's reason to exist — a float32
`jnp.sum` would silently diverge from the CPU's arbitrary-precision
Python sum):

  COUNT    popcount of the row mask in int32 — exact (cap_e < 2^31).
  SUM/AVG  int32 values are bias-shifted to uint32 and split into four
           8-bit digits; each digit column is summed per partition in
           CHUNKS of 2^22 slots (chunk_sum <= 2^22 * 255 < 2^30, so
           every int32 partial is exact at ANY cap_e) and the host
           reassembles the exact integer sum in Python ints. AVG
           divides the exact sum on the host, matching the CPU's
           sum()/len().
  MIN/MAX  int32 lattice ops under the mask — exact.

DOUBLE props are declined by the shared leaf loader (float32 mirror),
exactly as WHERE compilation declines them.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

# digit-partial chunk width: 2^22 slots * 255 < 2^30 keeps every int32
# chunk sum exact regardless of cap_e
SUM_CHUNK = 1 << 22

_BIAS = 1 << 31


def exact_int_sum(value, mask) -> int:
    """Exact sum of int32 `value` over bool `mask`, both [P, cap_e]
    device arrays, via chunked per-partition 8-bit digit partials."""
    import jax.numpy as jnp
    u = value.astype(jnp.uint32) + jnp.uint32(_BIAS)
    m = mask
    n = int(jnp.sum(m))
    P, cap = u.shape
    pad = (-cap) % SUM_CHUNK
    if pad:
        u = jnp.pad(u, ((0, 0), (0, pad)))
        m = jnp.pad(m, ((0, 0), (0, pad)))
    u = u.reshape(P, -1, SUM_CHUNK)
    m = m.reshape(P, -1, SUM_CHUNK)
    total = 0
    for k in range(4):
        d = ((u >> jnp.uint32(8 * k)) & jnp.uint32(0xFF)).astype(jnp.int32)
        part = np.asarray(jnp.sum(jnp.where(m, d, 0), axis=-1))
        total += int(part.astype(object).sum()) << (8 * k)
    return total - n * _BIAS


def reduce_specs(specs: List[Tuple[str, Optional[object]]], active,
                 vals: dict) -> Optional[List]:
    """Evaluate each (fun, key) agg spec over the `active` row mask.
    `vals` maps key -> the compiled _Val for that edge prop (key None =
    row-count only). Returns the single result row (CPU-identical
    Python values), or None when an exactness bound is hit."""
    import jax.numpy as jnp
    n_rows = int(jnp.sum(active))
    row: List = []
    for fun, key in specs:
        if fun == "COUNT":
            # CPU COUNT counts every row including NULL values
            row.append(n_rows)
            continue
        v = vals[key]
        m = active & ~v.null
        n = int(jnp.sum(m))
        if n == 0:
            row.append(None)     # CPU: no non-null values -> None
            continue
        if fun == "MIN":
            row.append(int(jnp.min(jnp.where(m, v.value,
                                             jnp.int32(2**31 - 1)))))
        elif fun == "MAX":
            row.append(int(jnp.max(jnp.where(m, v.value,
                                             jnp.int32(-(2**31))))))
        else:
            s = exact_int_sum(v.value, m)
            row.append(s if fun == "SUM" else s / n)   # AVG: host float
    return row
