"""Device-side aggregation pushdown: GO … | YIELD <aggregates>.

The reference ships aggregates to storage as bound_stats
(ref: storage/QueryStatsProcessor, storage.thrift StatType :65-69) so
SUM/COUNT/AVG never materialize edges on graphd. The TPU equivalent is
a masked reduction over the snapshot's [P, cap_e] edge block: the
final-hop mask comes from the same multi-hop kernel the GO path uses,
the value columns from the same FilterCompiler leaf loaders (so
null/err semantics are shared with WHERE compilation), and only the
per-partition partial aggregates leave the device.

Exactness discipline (the module's reason to exist — a float32
`jnp.sum` would silently diverge from the CPU's arbitrary-precision
Python sum):

  COUNT    popcount of the row mask in int32 — exact (cap_e < 2^31).
  SUM/AVG  int32 values are bias-shifted to uint32 and split into four
           8-bit digits; each digit column is summed per partition in
           CHUNKS of 2^22 slots (chunk_sum <= 2^22 * 255 < 2^30, so
           every int32 partial is exact at ANY cap_e) and the host
           reassembles the exact integer sum in Python ints. AVG
           divides the exact sum on the host, matching the CPU's
           sum()/len().
  MIN/MAX  int32 lattice ops under the mask — exact.

DOUBLE props are declined by the shared leaf loader (float32 mirror),
exactly as WHERE compilation declines them.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

# digit-partial chunk width: 2^22 slots * 255 < 2^30 keeps every int32
# chunk sum exact regardless of cap_e
SUM_CHUNK = 1 << 22

_BIAS = 1 << 31


def exact_int_sum(value, mask) -> int:
    """Exact sum of int32 `value` over bool `mask`, both [P, cap_e]
    device arrays, via chunked per-partition 8-bit digit partials."""
    import jax.numpy as jnp
    u = value.astype(jnp.uint32) + jnp.uint32(_BIAS)
    m = mask
    n = int(jnp.sum(m))
    P, cap = u.shape
    pad = (-cap) % SUM_CHUNK
    if pad:
        u = jnp.pad(u, ((0, 0), (0, pad)))
        m = jnp.pad(m, ((0, 0), (0, pad)))
    u = u.reshape(P, -1, SUM_CHUNK)
    m = m.reshape(P, -1, SUM_CHUNK)
    total = 0
    for k in range(4):
        d = ((u >> jnp.uint32(8 * k)) & jnp.uint32(0xFF)).astype(jnp.int32)
        part = np.asarray(jnp.sum(jnp.where(m, d, 0), axis=-1))
        total += int(part.astype(object).sum()) << (8 * k)
    return total - n * _BIAS


# single-pass bound: per-group digit sums accumulate across every
# partition into one int32 bin, exact while TOTAL masked rows * 255 <
# 2^31. Beyond it the reduction switches to chunked scatter partials
# (SUM_SEG slots per pass, each pass's bin sums bounded by SUM_SEG *
# 255 < 2^31) accumulated into host int64 totals — exact to ~2^55
# rows, so grouped SUM/AVG never falls back for scale (round-4
# verdict weak #6: the 8.4M-row silent decline).
MAX_GROUPED_SUM_ROWS = 1 << 23
SUM_SEG = 1 << 23

# COUNT / non-null-count scatter bins are int32 on device: a single
# pass is exact while the flat slot count stays below 2^31 (each slot
# contributes <= 1). Past that the count scatters run in COUNT_CHUNK
# passes accumulated into host int64 — the same chunking discipline as
# the digit sums, making grouped COUNT exact to ~2^63 rows.
COUNT_CHUNK = 1 << 30


def _scatter_count_i64(flat_mask, flat_g, n_groups: int) -> np.ndarray:
    """Masked per-group count with int64 exactness: one int32 scatter
    pass while every bin is provably < 2^31 (flat size < COUNT_CHUNK),
    chunked int32 passes accumulated on the host beyond."""
    import jax.numpy as jnp
    n = int(flat_g.shape[0])
    if n <= COUNT_CHUNK:
        return np.asarray(
            jnp.zeros(n_groups + 1, jnp.int32)
            .at[flat_g].add(flat_mask.astype(jnp.int32))
        )[:n_groups].astype(np.int64)
    total = np.zeros(n_groups, np.int64)
    for c in range(0, n, COUNT_CHUNK):
        part = np.asarray(
            jnp.zeros(n_groups + 1, jnp.int32)
            .at[flat_g[c:c + COUNT_CHUNK]]
            .add(flat_mask[c:c + COUNT_CHUNK].astype(jnp.int32))
        )[:n_groups]
        total += part
    return total


def grouped_reduce(specs: List[Tuple[str, Optional[object]]], active,
                   vals: dict, gidx, n_groups: int):
    """Segment reductions keyed by each edge's global dst slot (the
    GROUP BY $-._dst pushdown): one scatter-add per COUNT, four digit
    scatter-adds + a non-null count per SUM/AVG, scatter-min/max for
    MIN/MAX. Returns (sorted group slots np.int64, list of per-spec
    numpy arrays aligned with the group list). Exactness bounds:
    SUM/AVG to ~2^55 rows (chunked digit partials past
    MAX_GROUPED_SUM_ROWS, host int64 accumulation), COUNT and the
    non-null counts to ~2^63 rows (int32 scatter passes of at most
    COUNT_CHUNK slots each, host int64 accumulation) — neither
    silently wraps at the old single-pass 2^31 bin bound."""
    import jax.numpy as jnp
    flat_g = gidx.reshape(-1)
    m = active.reshape(-1)
    counts_np = _scatter_count_i64(m, flat_g, n_groups)
    groups = np.nonzero(counts_np)[0]
    # every emitted value is a PYTHON int/float/None — np scalars would
    # break wire encoding (isinstance int check) and repr identity
    out: List[List] = []
    cache: dict = {}
    for fun, key in specs:
        if fun == "COUNT":
            out.append([int(x) for x in counts_np[groups]])
            continue
        v = vals[key]
        if key not in cache:
            mk = (m & ~v.null.reshape(-1))
            nn = _scatter_count_i64(mk, flat_g, n_groups)
            cache[key] = (mk, nn)
        mk, nonnull = cache[key]
        nn = nonnull[groups]
        if fun in ("MIN", "MAX"):
            ident = (2**31 - 1) if fun == "MIN" else -(2**31)
            fill = jnp.where(mk, v.value.reshape(-1), jnp.int32(ident))
            seg = jnp.full(n_groups + 1, ident, jnp.int32)
            seg = (seg.at[flat_g].min(fill) if fun == "MIN"
                   else seg.at[flat_g].max(fill))
            sel = np.asarray(seg)[:n_groups][groups]
            out.append([int(x) if c else None
                        for x, c in zip(sel, nn)])
            continue
        u = v.value.reshape(-1).astype(jnp.uint32) + jnp.uint32(_BIAS)
        total = np.zeros(n_groups, np.int64)
        n_masked = int(np.asarray(mk.sum()))
        if n_masked <= MAX_GROUPED_SUM_ROWS:
            segs = [(u, mk, flat_g)]          # one pass, bins exact
        else:
            # chunked passes: each pass's int32 bin sums are bounded
            # by SUM_SEG * 255 < 2^31 no matter how rows distribute,
            # and the host int64 accumulation is exact to ~2^55 rows
            segs = [(u[c:c + SUM_SEG], mk[c:c + SUM_SEG],
                     flat_g[c:c + SUM_SEG])
                    for c in range(0, int(u.shape[0]), SUM_SEG)]
        for k in range(4):
            for useg, mseg, gseg in segs:
                d = ((useg >> jnp.uint32(8 * k)) & jnp.uint32(0xFF)) \
                    .astype(jnp.int32)
                part = np.asarray(jnp.zeros(n_groups + 1, jnp.int32)
                                  .at[gseg].add(jnp.where(mseg, d, 0))
                                  )[:n_groups]
                total += part.astype(np.int64) << (8 * k)
        total -= nonnull.astype(np.int64) * _BIAS
        sel = total[groups]
        if fun == "SUM":
            out.append([int(x) if c else None for x, c in zip(sel, nn)])
        else:                      # AVG: exact sum / count on host
            out.append([int(x) / int(c) if c else None
                        for x, c in zip(sel, nn)])
    return groups, out


def reduce_specs(specs: List[Tuple[str, Optional[object]]], active,
                 vals: dict) -> Optional[List]:
    """Evaluate each (fun, key) agg spec over the `active` row mask.
    `vals` maps key -> the compiled _Val for that edge prop (key None =
    row-count only). Returns the single result row (CPU-identical
    Python values), or None when an exactness bound is hit."""
    import jax.numpy as jnp
    n_rows = int(jnp.sum(active))
    row: List = []
    for fun, key in specs:
        if fun == "COUNT":
            # CPU COUNT counts every row including NULL values
            row.append(n_rows)
            continue
        v = vals[key]
        m = active & ~v.null
        n = int(jnp.sum(m))
        if n == 0:
            row.append(None)     # CPU: no non-null values -> None
            continue
        if fun == "MIN":
            row.append(int(jnp.min(jnp.where(m, v.value,
                                             jnp.int32(2**31 - 1)))))
        elif fun == "MAX":
            row.append(int(jnp.max(jnp.where(m, v.value,
                                             jnp.int32(-(2**31))))))
        else:
            s = exact_int_sum(v.value, m)
            row.append(s if fun == "SUM" else s / n)   # AVG: host float
    return row
