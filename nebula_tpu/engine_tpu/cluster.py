"""graphd scatter/gather v2: GO windows over per-host device partials.

The replicated cluster path used to degrade to leader-routed storaged
row scans (CLUSTER_bench: ~70-90 QPS vs 7489 cached single-host) —
the TPU engine lived only in graphd, and its remote snapshot
invalidated on every committed write. This module is the other half of
the storaged-tier device shards (storage/device_serve.py): instead of
row scans, each GO hop fans out as ONE `device_window` RPC per host
(storage/client.py, multiplexed over the existing pool), every storaged
serves the parts it can vouch for from its LOCAL CSR shard (leader
parts always; follower parts under the bounded-staleness raft read
fence), and graphd merges the per-host partials — the psum-shaped
merge is the vertices union (disjoint part sets: edges live at their
source's part), then the SAME row assembly the CPU pipe uses
(`executors._emit_go_rows`), which is the identity anchor: the cluster
device path and the CPU pipe build rows from byte-identical
BoundResponse-shaped partials.

Fallback ladder (docs/manual/9-robustness.md): a part no host vouches
for falls back to the row-scan `get_neighbors` path FOR THAT PART ONLY;
a storage error anywhere declines the whole query to the CPU pipe
(return None), which re-runs it — a client never sees a device-path
error. Cluster-served results never enter the graphd result cache:
bounded-staleness rows must not be published under the fresh token
(`_tpu_no_cache`).
"""
from __future__ import annotations

from typing import Dict, List, Set

from ..common.flags import graph_flags, storage_flags
from ..common.stats import stats as global_stats
from ..common.status import ErrorCode, StatusOr
from ..common.tracing import tracer as _tr


class ClusterDeviceServe:
    """Per-engine cluster GO serving over storaged device partials."""

    def __init__(self, engine, client):
        self.engine = engine
        self.client = client
        self.stats = {"served": 0, "declined": 0, "hops": 0,
                      "fallback_parts": 0, "fallback_errors": 0,
                      "hedged_hops": 0}

    def _decline(self, reason: str):
        self.stats["declined"] += 1
        self.engine.path_decline_reasons[f"cluster.{reason}"] = \
            self.engine.path_decline_reasons.get(
                f"cluster.{reason}", 0) + 1
        return None

    def serve_go(self, ctx, s, starts: List[int], edge_types: List[int],
                 alias_map, name_by_type, ex, yield_cols):
        """Returns a finalized Result, or None to decline (the caller
        then rides the dispatcher / CPU pipe). Plain-form GO only —
        the caller already excluded UPTO and input refs."""
        all_exprs = [c.expr for c in yield_cols]
        if s.where is not None:
            all_exprs.append(s.where.filter)
        vertex_props, needs_dst, _needs_input = \
            ex._collect_prop_requirements(all_exprs, ctx)
        if vertex_props:
            # $^ source-tag props: device partials don't carry tag rows
            return self._decline("src_props")
        space = ctx.space_id()
        # WHERE always evaluates graphd-side over full edge props —
        # the identity-preserving choice (pushdown skip == local skip)
        local_filter = s.where.filter if s.where is not None else None
        fmax = int(storage_flags.get_or("follower_read_max_ms", 0, int))
        allow_follower = fmax > 0
        columns = [c.name() for c in yield_cols]
        rows: List[tuple] = []
        frontier = list(starts)
        roots: Dict[int, Set[int]] = {v: {v} for v in starts}
        for step_no in range(1, s.step.steps + 1):
            final = step_no == s.step.steps
            eprops = None if final else []
            hedge_won0 = self.client.hedge_stats.get("won", 0)
            resp = self.client.device_window(
                space, frontier, edge_types, edge_props=eprops,
                allow_follower=allow_follower, follower_max_ms=fmax)
            self.stats["hops"] += 1
            if self.client.hedge_stats.get("won", 0) > hedge_won0:
                # a straggler replica was hedged around mid-hop
                # (storage/client.py peer health): the hop stayed on
                # the device path instead of riding the fallback
                # ladder — monitoring-grade, racy across concurrent
                # queries by design
                self.stats["hedged_hops"] += 1
            refused = [p for p, pr in resp.results.items()
                       if pr.code != ErrorCode.SUCCEEDED]
            if refused:
                # per-part row-scan fallback: only the unvouched parts'
                # vids ride the CPU storage path
                self.stats["fallback_parts"] += len(refused)
                parts_map = self.client.cluster_ids_to_parts(
                    space, frontier)
                fb_vids = [v for p in refused
                           for v in parts_map.get(p, [])]
                if fb_vids:
                    fb = self.client.get_neighbors(
                        space, fb_vids, edge_types, edge_props=eprops)
                    if any(r.code != ErrorCode.SUCCEEDED
                           for r in fb.results.values()):
                        self.stats["fallback_errors"] += 1
                        return self._decline("storage_error")
                    resp.vertices.extend(fb.vertices)
            if final:
                st = ex._emit_go_rows(ctx, resp, rows, yield_cols,
                                      local_filter, alias_map,
                                      name_by_type, roots, {}, False,
                                      needs_dst)
                if not st.ok():
                    return StatusOr.from_status(st)
                break
            next_roots: Dict[int, Set[int]] = {}
            seen: Set[int] = set()
            nxt: List[int] = []
            for v in resp.vertices:
                for e in v.edges:
                    if e.dst not in seen:
                        seen.add(e.dst)
                        nxt.append(e.dst)
                    next_roots.setdefault(e.dst, set()).update(
                        roots.get(v.vid, {v.vid}))
            frontier = nxt
            roots = next_roots
            if not frontier:
                break
        from ..graph.interim import InterimResult
        result = InterimResult(columns, rows)
        if s.yield_ and s.yield_.distinct:
            result = result.distinct()
        # bounded-staleness partials must never be published under the
        # fresh token (_result_cache_put checks this marker)
        result._tpu_no_cache = True
        self.stats["served"] += 1
        global_stats.add_value("tpu_engine.cluster_served",
                               kind="counter")
        _tr.tag_root("served", "cluster_device")
        return StatusOr.of(result)
