"""CSR snapshot builder: KV partitions → device-resident edge arrays.

This is the TPU-native storage engine behind the same seam where the
reference plugs alternative engines below the storage service (the
HBaseStore plugin slot, ref kvstore/plugins/hbase/ + SURVEY.md §2.5):
partition edge lists become CSR arrays in device memory, property
columns become aligned columnar arrays, and traversal runs as dense
masked gathers/scatters instead of RocksDB prefix iteration.

Layout decisions (TPU-first):
- Every partition is padded to the same (cap_v, cap_e) so the whole
  space stacks to [P, cap_v] / [P, cap_e] arrays — jittable on one chip
  and shard_map-able over a mesh without reshapes. Caps round up to
  multiples of 128 (lane width).
- Device arrays never hold 64-bit vids. Destinations are pre-resolved
  at build time to (dst_part, dst_local) and fused into one int32
  global index `dst_part * cap_v + dst_local`; padded/invalid edges
  point at a dump slot P*cap_v. The 64-bit vid/rank columns live in
  host numpy mirrors used only for result materialization.
- Version dedup and TTL visibility are applied at build time — the scan
  sees exactly what the CPU read path would see (newest version per
  logical edge/tag row, expired rows dropped).
- Numeric props: DOUBLE → float32, INT/TIMESTAMP → int32 when every
  value fits (else the column is marked host-only), BOOL → bool.
  STRING → int32 dictionary codes (per column dict, equality-only
  device filters). Full-fidelity values stay in the host mirrors.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..codec.row import RowReader, peek_schema_version
from ..codec.schema import PropType, Schema
from ..common import keys as ku

LANE = 128


def _round_up(n: int, m: int = LANE) -> int:
    return max(m, ((n + m - 1) // m) * m)


@dataclass
class PropColumn:
    """One property column, host mirror + device-encodable form."""
    name: str
    ptype: PropType
    host: np.ndarray                      # full-fidelity (object for strings)
    device_ok: bool                       # can this column go on device?
    device_vals: Optional[np.ndarray]     # f32/i32/bool codes, aligned
    present: Optional[np.ndarray] = None  # bool, False where value is null
    str_dict: Optional[Dict[str, int]] = None  # string -> code


@dataclass
class CsrShard:
    """Host-side CSR for one partition."""
    part_id: int
    vids: np.ndarray                      # int64[nv] sorted; local idx -> vid
    vid_to_local: Dict[int, int]
    num_edges: int
    # edge arrays, length cap_e (padded tail invalid)
    edge_src: np.ndarray                  # int32 local src index
    edge_etype: np.ndarray                # int32 signed edge type
    edge_rank: np.ndarray                 # int64 (host only)
    edge_dst_vid: np.ndarray              # int64 (host only)
    edge_dst_part: np.ndarray             # int32 0-based part index
    edge_dst_local: np.ndarray            # int32
    edge_valid: np.ndarray                # bool
    # per-(signed etype) columnar edge props (aligned to edge arrays)
    edge_props: Dict[int, Dict[str, PropColumn]] = field(default_factory=dict)
    # per-tag columnar vertex props (aligned to local index)
    tag_props: Dict[int, Dict[str, PropColumn]] = field(default_factory=dict)


class CsrSnapshot:
    """All partitions of one space, stacked for the device."""

    def __init__(self, space_id: int, shards: List[CsrShard], cap_v: int,
                 cap_e: int, write_version: int):
        import jax.numpy as jnp
        from .traverse import build_kernel
        self.space_id = space_id
        self.shards = shards
        self.num_parts = len(shards)
        self.cap_v = cap_v
        self.cap_e = cap_e
        self.write_version = write_version
        self.built_at = time.time()
        P = self.num_parts
        dump = P * cap_v  # dump slot for invalid edges (sorts to the tail)
        gidx = np.stack([
            np.where(s.edge_valid,
                     s.edge_dst_part.astype(np.int64) * cap_v + s.edge_dst_local,
                     dump).astype(np.int32)
            for s in shards])
        self.np_gidx = gidx  # kept for re-blocked kernels (mesh sharding)
        # Both layouts on device (EdgeKernel): canonical for result
        # materialization + host-permuted dst-sorted copies + segment
        # boundaries for the scatter-free, single-gather-per-hop advance.
        # Stacks are transient — shards retain the per-part host mirrors.
        self.kernel = build_kernel(*self._np_edge_stacks(), gidx, P, cap_v)[0]
        self.d_edge_src = self.kernel.src
        self.d_edge_gidx = jnp.asarray(gidx)
        self.d_edge_etype = self.kernel.etype
        self.d_edge_valid = self.kernel.valid
        self.total_edges = int(sum(s.num_edges for s in shards))
        self._device_prop_cache: Dict[Tuple, Any] = {}
        # global string dictionaries: (kind 'e'|'t', prop name) -> {str: code}
        self.str_dicts: Dict[Tuple[str, str], Dict[str, int]] = {}

    # ------------------------------------------------------------------
    def _np_edge_stacks(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(src, etype, valid) stacked [P, cap_e] — built on demand from
        the per-shard host mirrors (not stored: redundant with shards)."""
        return (np.stack([s.edge_src for s in self.shards]),
                np.stack([s.edge_etype for s in self.shards]),
                np.stack([s.edge_valid for s in self.shards]))

    # ------------------------------------------------------------------
    def locate(self, vid: int) -> Optional[Tuple[int, int]]:
        """vid -> (0-based part index, local index)."""
        p = ku.part_id(vid, self.num_parts) - 1
        loc = self.shards[p].vid_to_local.get(vid)
        return (p, loc) if loc is not None else None

    def frontier_from_vids(self, vids: List[int]) -> np.ndarray:
        f = np.zeros((self.num_parts, self.cap_v), dtype=bool)
        for vid in vids:
            loc = self.locate(vid)
            if loc is not None:
                f[loc[0], loc[1]] = True
        return f

    def _device_prop(self, kind: str, sid: int, name: str, cap: int):
        """Stacked [P, cap] device array for a filterable prop; shards
        without the column contribute an all-absent zero block (their
        presence masks are False there). None only when a shard that HAS
        the column can't host it on device (e.g. out-of-range ints)."""
        import jax.numpy as jnp
        key = (kind, sid, name)
        if key in self._device_prop_cache:
            return self._device_prop_cache[key]
        cols = []
        dtype = None
        for s in self.shards:
            props = (s.edge_props if kind == "e" else s.tag_props)
            col = props.get(sid, {}).get(name)
            if col is None:
                cols.append(None)
                continue
            if not col.device_ok:
                self._device_prop_cache[key] = None
                return None
            dtype = col.device_vals.dtype
            cols.append(col.device_vals)
        if dtype is None:
            self._device_prop_cache[key] = None
            return None
        filled = [c if c is not None else np.zeros(cap, dtype) for c in cols]
        out = jnp.asarray(np.stack(filled))
        self._device_prop_cache[key] = out
        return out

    def device_edge_prop(self, etype: int, name: str):
        return self._device_prop("e", etype, name, self.cap_e)

    def device_tag_prop(self, tag_id: int, name: str):
        return self._device_prop("t", tag_id, name, self.cap_v)

    def str_code(self, kind: str, name: str, value: str) -> int:
        """Dictionary code of a string constant for device equality
        filters; -1 if the string never occurs (matches nothing).
        Dictionaries are global per (kind, prop) across all shards and
        schema ids, so one code means one string everywhere."""
        return self.str_dicts.get((kind, name), {}).get(value, -1)


# ---------------------------------------------------------------------------
# builder
# ---------------------------------------------------------------------------

def _decode_rows_newest(engine, prefix: bytes, group_of, parse_key):
    """Yield (key_fields, value) keeping only the newest version per
    logical group, skipping tombstones."""
    last_group = None
    for k, v in engine.prefix(prefix):
        fields = parse_key(k)
        g = group_of(fields)
        if g == last_group:
            continue
        last_group = g
        if not v:
            continue
        yield fields, v


def build_snapshot(store, sm, space_id: int, num_parts: int) -> CsrSnapshot:
    """Scan every partition's KV range and assemble the CSR snapshot.

    The scan applies the same read semantics as the CPU getBound path:
    newest-version-wins within a (src, etype, rank, dst) group, TTL
    expiry honored (ref: storage/QueryBaseProcessor.inl:380-458)."""
    engine = store.space_engine(space_id)
    if engine is None:
        raise ValueError(f"space {space_id} not found")
    write_version = engine.write_version
    now = time.time()

    # ---- pass 1: local vid sets + raw edge lists per partition --------
    per_part_edges: List[List[Tuple[int, int, int, int, bytes]]] = []
    per_part_vids: List[set] = []
    for p in range(1, num_parts + 1):
        vids = set()
        for (part, vid, tag, ver), v in _decode_rows_newest(
                engine, ku.part_data_prefix(p, ku.KIND_VERTEX),
                group_of=lambda f: (f[1], f[2]), parse_key=ku.parse_vertex_key):
            vids.add(vid)
        edges = []
        for (part, src, et, rank, dst, ver), v in _decode_rows_newest(
                engine, ku.part_data_prefix(p, ku.KIND_EDGE),
                group_of=lambda f: (f[1], f[2], f[3], f[4]),
                parse_key=ku.parse_edge_key):
            vids.add(src)
            edges.append((src, et, rank, dst, v))
        per_part_edges.append(edges)
        per_part_vids.append(vids)
    # destinations must have a local slot in their own partition
    for p_edges in per_part_edges:
        for (_src, _et, _rank, dst, _v) in p_edges:
            per_part_vids[ku.part_id(dst, num_parts) - 1].add(dst)

    cap_v = _round_up(max((len(v) for v in per_part_vids), default=1))
    cap_e = _round_up(max((len(e) for e in per_part_edges), default=1))

    # schema lookups
    def edge_schema(et: int) -> Optional[Schema]:
        r = sm.edge_schema(space_id, et)
        return r.value() if r.ok() else None

    shards: List[CsrShard] = []
    # string dictionaries must be GLOBAL across shards AND schema ids so
    # a code identifies one string everywhere a prop of that name is
    # merged into a single device column: (kind, prop name) -> dict
    dict_registry: Dict[Tuple[str, str], Dict[str, int]] = {}
    for p0 in range(num_parts):
        vids_sorted = np.array(sorted(per_part_vids[p0]), dtype=np.int64)
        vid_to_local = {int(v): i for i, v in enumerate(vids_sorted)}
        edges = per_part_edges[p0]
        # sort by (src_local, etype, rank, dst) for CSR determinism
        edges.sort(key=lambda e: (vid_to_local[e[0]], e[1], e[2], e[3]))
        ne = len(edges)
        edge_src = np.zeros(cap_e, np.int32)
        edge_etype = np.zeros(cap_e, np.int32)
        edge_rank = np.zeros(cap_e, np.int64)
        edge_dst_vid = np.zeros(cap_e, np.int64)
        edge_dst_part = np.zeros(cap_e, np.int32)
        edge_dst_local = np.zeros(cap_e, np.int32)
        edge_valid = np.zeros(cap_e, bool)
        rows_by_etype: Dict[int, List[Tuple[int, bytes]]] = {}
        skipped = 0
        for i, (src, et, rank, dst, row) in enumerate(edges):
            edge_src[i] = vid_to_local[src]
            edge_etype[i] = et
            edge_rank[i] = rank
            edge_dst_vid[i] = dst
            edge_dst_part[i] = ku.part_id(dst, num_parts) - 1
            # edge_dst_local resolved after all shards' vid maps exist
            rows_by_etype.setdefault(et, []).append((i, row))
            edge_valid[i] = True
        shard = CsrShard(p0 + 1, vids_sorted, vid_to_local, ne, edge_src,
                         edge_etype, edge_rank, edge_dst_vid, edge_dst_part,
                         edge_dst_local, edge_valid)
        shards.append(shard)
        shard._rows_by_etype = rows_by_etype  # temp, consumed below

    # resolve dst locals now that every shard's vid map exists
    maps = [s.vid_to_local for s in shards]
    for s in shards:
        for i in range(s.num_edges):
            dp = int(s.edge_dst_part[i])
            s.edge_dst_local[i] = maps[dp][int(s.edge_dst_vid[i])]

    # ---- pass 2: decode property columns ------------------------------
    for s in shards:
        rows_by_etype = s._rows_by_etype
        del s._rows_by_etype
        for et, idx_rows in rows_by_etype.items():
            schema = edge_schema(et)
            if schema is None or not schema.fields:
                continue
            cols = _build_columns(schema, cap_e, idx_rows, now,
                                  dict_registry, ("e",))
            if cols:
                s.edge_props[et] = cols
        # vertex tag props: ONE scan per partition, bucketed by tag id
        rows_by_tag: Dict[int, List[Tuple[int, bytes]]] = {}
        for (part, vid, tag, ver), v in _decode_rows_newest(
                engine, ku.part_data_prefix(s.part_id, ku.KIND_VERTEX),
                group_of=lambda f: (f[1], f[2]),
                parse_key=ku.parse_vertex_key):
            if vid in s.vid_to_local:
                rows_by_tag.setdefault(tag, []).append((s.vid_to_local[vid], v))
        for tag_id, tag_rows in rows_by_tag.items():
            sr = sm.tag_schema(space_id, tag_id)
            if not sr.ok() or not sr.value().fields:
                continue
            schema = sr.value()
            if tag_rows:
                cols = _build_columns(schema, cap_v, tag_rows, now,
                                      dict_registry, ("t",))
                if cols:
                    s.tag_props[tag_id] = cols

    snap = CsrSnapshot(space_id, shards, cap_v, cap_e, write_version)
    snap.str_dicts = dict_registry
    return snap


_I32_MIN, _I32_MAX = -(1 << 31), (1 << 31) - 1


def _native_build_columns(schema: Schema, cap: int,
                          idx_rows: List[Tuple[int, bytes]], now: float,
                          dict_registry: Dict, dict_key: Tuple
                          ) -> Optional[Dict[str, PropColumn]]:
    """Fast path: one nbc_decode_batch FFI call decodes every row into
    column buffers (native/src/codec.cc — the C++ codec hot path, role
    parity with the reference's C++ RowReader). Returns None when the
    native library is unavailable; semantics match the Python path
    (newest rows only arrive here; TTL-expired rows fully nulled)."""
    from .. import native
    if not native.available():
        return None
    try:
        i64, f64, soff, slen, nulls, blob = native.decode_batch(
            [f.type.value for f in schema.fields], idx_rows, cap)
    except Exception:
        return None
    # TTL: a row whose ttl prop expired is invisible — null every field
    if schema.ttl_col and schema.ttl_duration > 0:
        ti = schema.field_index(schema.ttl_col)
        # only numeric ttl cols expire — the Python/storage paths treat a
        # non-numeric ttl value as never-expired (their isinstance check
        # admits int/float/bool, so BOOL stays in the numeric set here)
        if ti >= 0 and schema.fields[ti].type in (
                PropType.INT, PropType.VID, PropType.TIMESTAMP,
                PropType.DOUBLE, PropType.BOOL):
            tt = schema.fields[ti].type
            tv = f64[ti] if tt == PropType.DOUBLE else i64[ti]
            expired = (~nulls[ti]) & (tv + schema.ttl_duration < now)
            nulls[:, expired] = True
    # strings decode strictly up front; a row with invalid UTF-8 becomes
    # wholly invisible, matching the Python path's whole-row skip on
    # decode failure
    str_vals: Dict[int, Dict[int, str]] = {}
    for fi, f in enumerate(schema.fields):
        if f.type != PropType.STRING:
            continue
        vals: Dict[int, str] = {}
        for i in np.nonzero(~nulls[fi])[0]:
            b = blob[soff[fi, i]:soff[fi, i] + slen[fi, i]]
            try:
                vals[int(i)] = b.decode("utf-8")
            except UnicodeDecodeError:
                nulls[:, i] = True
        str_vals[fi] = vals
    out: Dict[str, PropColumn] = {}
    for fi, f in enumerate(schema.fields):
        t = f.type
        present = ~nulls[fi]
        pos = np.nonzero(present)[0]
        host = np.empty(cap, dtype=object)  # object-empty = None-filled
        device_ok = True
        device_vals = None
        str_dict = None
        if t == PropType.DOUBLE:
            vals = f64[fi]
            host[pos] = np.array(vals[pos].tolist(), dtype=object)
            device_vals = np.where(present, vals, np.nan).astype(np.float32)
        elif t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
            vals = i64[fi]
            host[pos] = np.array(vals[pos].tolist(), dtype=object)
            if pos.size and (vals[pos].min() < _I32_MIN
                             or vals[pos].max() > _I32_MAX):
                device_ok = False  # host-only column (filter falls back)
            else:
                device_vals = np.where(present, vals, 0).astype(np.int32)
        elif t == PropType.BOOL:
            vals = i64[fi] != 0
            host[pos] = np.array(vals[pos].tolist(), dtype=object)
            device_vals = np.where(present, vals, False)
        elif t == PropType.STRING:
            if dict_registry is not None and dict_key is not None:
                str_dict = dict_registry.setdefault(dict_key + (f.name,), {})
            else:
                str_dict = {}
            codes = np.full(cap, -1, dtype=np.int32)
            for i, s in str_vals[fi].items():
                if nulls[fi, i]:
                    continue  # row nulled by a later field's bad UTF-8
                host[i] = s
                codes[i] = str_dict.setdefault(s, len(str_dict))
            device_vals = codes
        else:
            device_ok = False
        out[f.name] = PropColumn(f.name, t, host, device_ok, device_vals,
                                 present, str_dict)
    return out


def _build_columns(schema: Schema, cap: int,
                   idx_rows: List[Tuple[int, bytes]], now: float,
                   dict_registry: Dict = None, dict_key: Tuple = None
                   ) -> Dict[str, PropColumn]:
    """Decode rows into columnar arrays aligned at the given indices,
    respecting schema versions and TTL."""
    fast = _native_build_columns(schema, cap, idx_rows, now,
                                 dict_registry, dict_key)
    if fast is not None:
        return fast
    out: Dict[str, PropColumn] = {}
    n_fields = schema.num_fields()
    host_cols: List[List[Any]] = [[None] * cap for _ in range(n_fields)]
    ttl = schema.ttl_col is not None and schema.ttl_duration > 0
    for idx, raw in idx_rows:
        try:
            reader = RowReader(schema, raw)
            row = reader.to_dict()
        except Exception:
            continue
        if ttl:
            ts = row.get(schema.ttl_col)
            if isinstance(ts, (int, float)) and ts + schema.ttl_duration < now:
                continue
        for fi, f in enumerate(schema.fields):
            host_cols[fi][idx] = row.get(f.name)
    for fi, f in enumerate(schema.fields):
        vals = host_cols[fi]
        host = np.array(vals, dtype=object)
        device_ok = True
        device_vals = None
        str_dict = None
        t = f.type
        if t == PropType.DOUBLE:
            device_vals = np.array([v if v is not None else np.nan
                                    for v in vals], dtype=np.float32)
        elif t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
            ints = [v if v is not None else 0 for v in vals]
            if ints and (min(ints) < _I32_MIN or max(ints) > _I32_MAX):
                device_ok = False  # host-only column (filter falls back)
            else:
                device_vals = np.array(ints, dtype=np.int32)
        elif t == PropType.BOOL:
            device_vals = np.array([bool(v) for v in vals], dtype=bool)
        elif t == PropType.STRING:
            if dict_registry is not None and dict_key is not None:
                str_dict = dict_registry.setdefault(dict_key + (f.name,), {})
            else:
                str_dict = {}
            codes = np.full(cap, -1, dtype=np.int32)
            for i, v in enumerate(vals):
                if v is None:
                    continue
                code = str_dict.setdefault(v, len(str_dict))
                codes[i] = code
            device_vals = codes
        else:
            device_ok = False
        present = np.array([v is not None for v in vals], dtype=bool)
        out[f.name] = PropColumn(f.name, t, host, device_ok, device_vals,
                                 present, str_dict)
    return out
