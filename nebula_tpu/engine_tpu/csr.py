"""CSR snapshot builder: KV partitions → device-resident edge arrays.

This is the TPU-native storage engine behind the same seam where the
reference plugs alternative engines below the storage service (the
HBaseStore plugin slot, ref kvstore/plugins/hbase/ + SURVEY.md §2.5):
partition edge lists become CSR arrays in device memory, property
columns become aligned columnar arrays, and traversal runs as dense
masked gathers/scatters instead of RocksDB prefix iteration.

Layout decisions (TPU-first):
- Every partition is padded to the same (cap_v, cap_e) so the whole
  space stacks to [P, cap_v] / [P, cap_e] arrays — jittable on one chip
  and shard_map-able over a mesh without reshapes. Caps round up to
  multiples of 128 (lane width).
- Device arrays never hold 64-bit vids. Destinations are pre-resolved
  at build time to (dst_part, dst_local) and fused into one int32
  global index `dst_part * cap_v + dst_local`; padded/invalid edges
  point at a dump slot P*cap_v. The 64-bit vid/rank columns live in
  host numpy mirrors used only for result materialization.
- Version dedup and TTL visibility are applied at build time — the scan
  sees exactly what the CPU read path would see (newest version per
  logical edge/tag row, expired rows dropped).
- Numeric props: DOUBLE → float32, INT/TIMESTAMP → int32 when every
  value fits (else the column is marked host-only), BOOL → bool.
  STRING → int32 dictionary codes (per column dict, equality-only
  device filters). Full-fidelity values stay in the host mirrors.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..codec.row import RowReader, peek_schema_version
from ..codec.schema import PropType, Schema
from ..common import keys as ku
from ..kvstore.scan import RowsBlock, ScanCols, scan_cols as _scan_cols

LANE = 128

# ---------------------------------------------------------------------------
# narrow-width edge packing (docs/manual/13-device-speed.md)
#
# Local edge indices (edge_src / edge_dst_local, values in [0, cap_v))
# pack to int16 when cap_v fits, and signed edge types to int8 when
# every |etype| in the space fits — roughly halving bytes-per-edge on
# the hop's gather streams. The widths are decided ONCE per build from
# the caps, so every shard (and the stacked device arrays derived from
# them) carries one consistent dtype; anything global-slot-valued
# (gidx, src_sorted, seg boundaries, edge_dst_part) stays int32.
# int32 fallback is preserved for spaces past either cap, and
# NEBULA_TPU_WIDE_CSR=1 (or FORCE_WIDE_DTYPES) pins int32 everywhere —
# the identity harness builds both and compares byte-for-byte.
# ---------------------------------------------------------------------------

FORCE_WIDE_DTYPES = os.environ.get("NEBULA_TPU_WIDE_CSR", "") == "1"
NARROW_IDX_CAP = 1 << 15     # cap_v <= 32768 -> local indices fit int16
NARROW_ETYPE_MAX = 127       # max |signed etype| for int8 packing


def edge_index_dtype(cap_v: int) -> np.dtype:
    """dtype of local-index edge arrays for a given cap_v."""
    if FORCE_WIDE_DTYPES or cap_v > NARROW_IDX_CAP:
        return np.dtype(np.int32)
    return np.dtype(np.int16)


def edge_type_dtype(max_abs_etype: int) -> np.dtype:
    """dtype of the signed edge-type arrays given the largest |etype|
    actually present in the scanned data (0 for an edge-free space)."""
    if FORCE_WIDE_DTYPES or max_abs_etype > NARROW_ETYPE_MAX:
        return np.dtype(np.int32)
    return np.dtype(np.int8)


def _round_up(n: int, m: int = LANE) -> int:
    return max(m, ((n + m - 1) // m) * m)


@dataclass
class PropColumn:
    """One property column, host mirror + device-encodable form.

    `host` is full-fidelity: an object array (strings, and the python
    decode path) OR a plain numeric numpy array (native decode path —
    materializing 10^8 python objects at build time is prohibitive).
    Read single cells through `host_item`, slices through
    `host_gather`: both normalize nulls to None and numpy scalars to
    python values so result rows stay identical to the CPU path.

    Cells are three-state, mirroring the CPU walk's distinction
    (processors.py _StorageExprContext):
      present[i]                 -> usable value (host/device_vals[i])
      ~present[i] & ~missing[i]  -> explicit NULL (the row has the
                                    field, null bit set) — CPU
                                    RelationalExpr null rules apply
      missing[i]                 -> the row's schema version doesn't
                                    have the field, or no row decoded
                                    at this slot. For EDGE columns the
                                    CPU path raises EvalError for both
                                    (drops the row in WHERE, fails the
                                    query in YIELD). For TAG columns a
                                    plain no-row cell reads as the
                                    SCHEMA DEFAULT (ref
                                    VertexHolder::get → getDefaultProp)
                                    while version-lacks-the-prop stays
                                    an error — `version_missing` below
                                    tells the vectorized paths which
                                    mix they're looking at
    `missing is None` is the common fast case: every slot that callers
    can select decoded a row carrying the field — ~present means NULL."""
    name: str
    ptype: PropType
    host: np.ndarray
    device_ok: bool                       # can this column go on device?
    device_vals: Optional[np.ndarray]     # f32/i32/bool codes, aligned
    present: Optional[np.ndarray] = None  # bool, True where value usable
    str_dict: Optional[Dict[str, int]] = None  # string -> code
    missing: Optional[np.ndarray] = None  # bool, see above
    # True iff `missing` may include VERSION-lacks-the-prop cells (the
    # multi-version builders) — for TAG columns those are CPU errors
    # while plain no-row cells read as schema defaults (ref
    # VertexHolder::get → getDefaultProp); vectorized paths decline
    # only when this is set. Delta materialization (tombstones) keeps
    # it False: every such missing cell is a no-row cell.
    version_missing: bool = False


def host_item(col: PropColumn, idx: int):
    """One host-mirror cell as a python value (None when null)."""
    if col.present is not None and not col.present[idx]:
        return None
    v = col.host[idx]
    return v.item() if isinstance(v, np.generic) else v


def host_gather(col: PropColumn, ii: np.ndarray) -> np.ndarray:
    """Host-mirror slice with nulls as None (object array when any null
    or when the mirror itself is object-typed)."""
    vals = col.host[ii]
    if col.present is None:
        return vals
    pres = col.present[ii]
    if pres.all():
        return vals
    out = vals.astype(object)
    out[~pres] = None
    return out


@dataclass
class CsrShard:
    """Host-side CSR for one partition."""
    part_id: int
    vids: np.ndarray                      # int64[nv] sorted; local idx -> vid
    num_edges: int
    # edge arrays, length cap_e (padded tail invalid); local-index and
    # etype arrays are width-packed (int16/int8 when the caps allow,
    # int32 fallback — see edge_index_dtype/edge_type_dtype)
    edge_src: np.ndarray                  # int16|int32 local src index
    edge_etype: np.ndarray                # int8|int32 signed edge type
    edge_rank: np.ndarray                 # int64 (host only)
    edge_dst_vid: np.ndarray              # int64 (host only)
    edge_dst_part: np.ndarray             # int32 0-based part index
    edge_dst_local: np.ndarray            # int16|int32
    edge_valid: np.ndarray                # bool
    # per-(signed etype) columnar edge props (aligned to edge arrays)
    edge_props: Dict[int, Dict[str, PropColumn]] = field(default_factory=dict)
    # per-tag columnar vertex props (aligned to local index)
    tag_props: Dict[int, Dict[str, PropColumn]] = field(default_factory=dict)
    # vids added after build via the delta buffer: vid -> spare local
    # slot in [len(vids), cap_v) (delta.py assigns them sequentially)
    delta_vids: Dict[int, int] = field(default_factory=dict)

    @property
    def num_vids_base(self) -> int:
        """Local slots [0, num_vids_base) belong to build-time vids;
        anything >= is a delta-assigned spare slot."""
        return len(self.vids)


class CsrSnapshot:
    """All partitions of one space, stacked for the device."""

    def __init__(self, space_id: int, shards: List[CsrShard], cap_v: int,
                 cap_e: int, write_version: int):
        import jax.numpy as jnp
        from .traverse import build_kernel
        self.space_id = space_id
        self.shards = shards
        self.num_parts = len(shards)
        self.cap_v = cap_v
        self.cap_e = cap_e
        self.write_version = write_version
        self.built_at = time.time()
        P = self.num_parts
        dump = P * cap_v  # dump slot for invalid edges (sorts to the tail)
        gidx = np.stack([
            np.where(s.edge_valid,
                     s.edge_dst_part.astype(np.int64) * cap_v + s.edge_dst_local,
                     dump).astype(np.int32)
            for s in shards])
        self.np_gidx = gidx  # kept for re-blocked kernels (mesh sharding)
        # Both layouts on device (EdgeKernel): canonical for result
        # materialization + host-permuted dst-sorted copies + segment
        # boundaries for the scatter-free, single-gather-per-hop advance.
        # Stacks are transient — shards retain the per-part host mirrors.
        orders: list = []
        self.kernel = build_kernel(*self._np_edge_stacks(), gidx, P, cap_v,
                                   orders_out=orders)[0]
        # canonical-flat -> sorted position, for delta tombstone
        # point-updates of valid_sorted (delta.py)
        order = orders[0]
        self.kernel_order_inv = np.empty(len(order), np.int32)
        self.kernel_order_inv[order] = np.arange(len(order), dtype=np.int32)
        self.delta = None                # SnapshotDelta once writes land
        self.stale = False               # poisoned mid-apply: must not serve
        self._aligned = None             # lazy batched-path layout
        # mesh execution service state: the per-device EdgeKernel
        # blocks (distributed.shard_snapshot_arrays) and the lazily
        # cached per-device aligned blocks for sharded dispatcher
        # windows (mesh_exec.ensure_sharded_aligned; "failed" caches a
        # build decline so hot windows never retry a doomed build)
        self.sharded_kernel = None
        self._sharded_aligned = None
        self._sharded_aligned_kick = False   # off-lock build started
        self.d_edge_src = self.kernel.src
        self.d_edge_gidx = jnp.asarray(gidx)
        self.d_edge_etype = self.kernel.etype
        self.d_edge_valid = self.kernel.valid
        self.total_edges = int(sum(s.num_edges for s in shards))
        self._device_prop_cache: Dict[Tuple, Any] = {}
        # global string dictionaries: (kind 'e'|'t', prop name) -> {str: code}
        self.str_dicts: Dict[Tuple[str, str], Dict[str, int]] = {}
        # degree-skew stats, computed ONCE per build (workload & data
        # observatory, /heat?vertices=1): out-degree distribution +
        # the hub list — tomorrow's hub-split candidates, named
        # against the cap_e this layout pays for them (ROADMAP item 5)
        self.degree_stats = self._degree_stats()

    def _degree_stats(self, hubs: int = 8) -> Dict[str, Any]:
        """max/p99/mean out-degree over the build-time edges plus the
        top-`hubs` (vid, out_degree) list and their share of cap_e.
        One numpy pass over the host mirrors; delta-added edges are
        not re-counted (the stats describe the built layout)."""
        degs = []
        vids = []
        for s in self.shards:
            n = len(s.vids)
            if n == 0:
                continue
            d = np.bincount(
                s.edge_src[s.edge_valid].astype(np.int64),
                minlength=n)[:n]
            degs.append(d)
            vids.append(s.vids)
        if not degs:
            return {"vertices": 0, "edges": 0, "max": 0, "p99": 0,
                    "mean": 0.0, "cap_e": self.cap_e, "hubs": []}
        deg = np.concatenate(degs)
        vid = np.concatenate(vids)
        top = np.argsort(deg)[::-1][:hubs]
        return {
            "vertices": int(len(deg)),
            "edges": int(deg.sum()),
            "max": int(deg.max()),
            "p99": int(np.percentile(deg, 99)),
            "mean": round(float(deg.mean()), 2),
            "cap_e": self.cap_e,
            "hubs": [{"vid": int(vid[i]), "out_degree": int(deg[i]),
                      "cap_e_share": round(float(deg[i]) / self.cap_e,
                                           4)}
                     for i in top if deg[i] > 0],
        }

    # ------------------------------------------------------------------
    def _np_edge_stacks(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(src, etype, valid) stacked [P, cap_e] — built on demand from
        the per-shard host mirrors (not stored: redundant with shards)."""
        return (np.stack([s.edge_src for s in self.shards]),
                np.stack([s.edge_etype for s in self.shards]),
                np.stack([s.edge_valid for s in self.shards]))

    def gidx_vids(self) -> np.ndarray:
        """host int64[P*cap_v]: global slot -> vid (-1 unused) — the
        inverse of the edge gidx encoding, for materializing grouped
        device reductions keyed by dst slot. Cached per snapshot;
        delta-added vids resolve through the spare-slot maps (slots a
        buffered edge could reference are declined upstream anyway
        while delta adds are live)."""
        m = getattr(self, "_gidx_vids", None)
        if m is None:
            m = np.full(self.num_parts * self.cap_v, -1, np.int64)
            for p, s in enumerate(self.shards):
                m[p * self.cap_v:p * self.cap_v + len(s.vids)] = s.vids
                for vid, loc in s.delta_vids.items():
                    m[p * self.cap_v + loc] = vid
            self._gidx_vids = m
        return m

    # ------------------------------------------------------------------
    def locate(self, vid: int) -> Optional[Tuple[int, int]]:
        """vid -> (0-based part index, local index). Binary search over
        the sorted per-part vid array (no per-vid dict is materialized —
        snapshots at 10M+ vertices would pay seconds building one);
        delta-added vids resolve through the shard's spare-slot map."""
        p = ku.part_id(vid, self.num_parts) - 1
        shard = self.shards[p]
        vids = shard.vids
        i = int(np.searchsorted(vids, vid))
        if i < len(vids) and int(vids[i]) == vid:
            return (p, i)
        local = shard.delta_vids.get(vid)
        if local is not None:
            return (p, local)
        return None

    def aligned_kernel(self):
        """Lazy (AlignedKernel, chunk, group) for the batched frontier-
        matrix path (traverse.multi_hop_count_batch). Built from the
        CURRENT host mirrors, so build-time state and tombstones are
        reflected; delta ADDS are not — callers holding a non-empty
        delta must rebuild or fall back to per-query kernels."""
        if self.delta is not None and self.delta.edge_count > 0:
            raise RuntimeError(
                "aligned_kernel does not include delta-buffer edges; "
                "repack the snapshot or use the per-query kernels")
        if self._aligned is None:
            from .traverse import build_aligned
            gsrc, etype, gdst = self._flat_canonical_edges()
            self._aligned = build_aligned(gsrc, etype, gdst,
                                          self.num_parts * self.cap_v)
        return self._aligned

    def build_aligned_off_side(self):
        """Build the aligned layout WITHOUT caching it — for callers
        that must validate nothing mutated the mirrors mid-build
        (prewarm grafting onto a live snapshot) before installing via
        `_aligned`."""
        from .traverse import build_aligned
        gsrc, etype, gdst = self._flat_canonical_edges()
        return build_aligned(gsrc, etype, gdst,
                             self.num_parts * self.cap_v)

    def aligned_ready(self):
        """The cached aligned layout, or None — NEVER builds. The
        query-path consumer (the cross-session dispatcher) must not pay
        the build; prewarm/repack build it off to the side, and any
        delta apply invalidates the cache (tombstones mutate the
        canonical masks the layout was built from)."""
        if self.delta is not None and self.delta.edge_count > 0:
            return None
        return self._aligned

    def invalidate_aligned(self) -> None:
        self._aligned = None
        # defensive: meshed snapshots rebuild rather than delta-patch,
        # but any mutation of the canonical arrays must drop BOTH
        # aligned caches — and re-arm the one-shot build kick, or the
        # dispatcher could never rebuild the sharded layout
        self._sharded_aligned = None
        self._sharded_aligned_kick = False

    def _flat_canonical_edges(self):
        """Flat (gsrc, etype, gdst) canonical edge arrays in the global
        slot encoding (invalid edges -> the dump slot num_parts*cap_v)
        — the shared input of the single-device and sharded aligned
        layout builds."""
        P = self.num_parts
        src, etype, valid = (a.reshape(-1)
                             for a in self._np_edge_stacks())
        gsrc = (np.repeat(np.arange(P, dtype=np.int64), self.cap_e)
                * self.cap_v + src).astype(np.int32)
        gdst = np.where(valid, self.np_gidx.reshape(-1),
                        P * self.cap_v).astype(np.int64)
        return gsrc, etype, gdst

    def vid_of_slot(self, p0: int, local: int) -> Optional[int]:
        """Inverse of locate (base or delta slot) — delta materialization."""
        shard = self.shards[p0]
        if local < shard.num_vids_base:
            return int(shard.vids[local])
        for vid, loc in shard.delta_vids.items():
            if loc == local:
                return vid
        return None

    def frontier_from_vids(self, vids: List[int]) -> np.ndarray:
        f = np.zeros((self.num_parts, self.cap_v), dtype=bool)
        for vid in vids:
            loc = self.locate(vid)
            if loc is not None:
                f[loc[0], loc[1]] = True
        return f

    def _device_prop(self, kind: str, sid: int, name: str, cap: int):
        """Stacked [P, cap] device array for a filterable prop; shards
        without the column contribute an all-absent zero block (their
        presence masks are False there). None only when a shard that HAS
        the column can't host it on device (e.g. out-of-range ints)."""
        import jax.numpy as jnp
        key = (kind, sid, name)
        if key in self._device_prop_cache:
            return self._device_prop_cache[key]
        cols = []
        dtype = None
        for s in self.shards:
            props = (s.edge_props if kind == "e" else s.tag_props)
            col = props.get(sid, {}).get(name)
            if col is None:
                cols.append(None)
                continue
            if not col.device_ok:
                self._device_prop_cache[key] = None
                return None
            dtype = col.device_vals.dtype
            cols.append(col.device_vals)
        if dtype is None:
            self._device_prop_cache[key] = None
            return None
        filled = [c if c is not None else np.zeros(cap, dtype) for c in cols]
        out = jnp.asarray(np.stack(filled))
        self._device_prop_cache[key] = out
        return out

    def device_edge_prop(self, etype: int, name: str):
        return self._device_prop("e", etype, name, self.cap_e)

    def device_tag_prop(self, tag_id: int, name: str):
        return self._device_prop("t", tag_id, name, self.cap_v)

    def str_code(self, kind: str, name: str, value: str) -> int:
        """Dictionary code of a string constant for device equality
        filters; -1 if the string never occurs (matches nothing).
        Dictionaries are global per (kind, prop) across all shards and
        schema ids, so one code means one string everywhere."""
        return self.str_dicts.get((kind, name), {}).get(value, -1)

    def dtype_widths(self) -> Dict[str, int]:
        """Byte widths of the packed edge arrays (narrow-width packing,
        docs/manual/13-device-speed.md) — surfaced by bench.py so the
        modeled HBM traffic reflects what the kernels actually read."""
        if not self.shards:
            return {"edge_src": 4, "edge_etype": 4, "edge_dst_local": 4}
        s = self.shards[0]
        return {"edge_src": int(s.edge_src.dtype.itemsize),
                "edge_etype": int(s.edge_etype.dtype.itemsize),
                "edge_dst_local": int(s.edge_dst_local.dtype.itemsize)}

    def device_mem(self) -> Dict[str, int]:
        """Live device bytes held by this snapshot's CSR streams, by
        dtype width — the per-snapshot device-memory ledger next to
        bench's tier1_hbm_model ESTIMATE (docs/manual/
        10-observability.md, "Continuous profiling"). Counts the
        resident kernel arrays (both layouts) + the canonical gidx;
        the lazily built aligned/sharded layouts are included when
        live. Transient frontier stacks are accounted separately by
        the FrontierPool's h2d_bytes counter."""
        by_width: Dict[str, int] = {}
        total = 0

        def add(a) -> None:
            nonlocal total
            if a is None:
                return
            if isinstance(a, (tuple, list)):
                for x in a:       # covers NamedTuples (EdgeKernel,
                    add(x)        # AlignedKernel) and block lists
                return
            nb = getattr(a, "nbytes", None)
            dt = getattr(a, "dtype", None)
            if nb is None or dt is None:
                return
            total += int(nb)
            key = str(dt)
            by_width[key] = by_width.get(key, 0) + int(nb)

        add((self.d_edge_src, self.d_edge_gidx,
             self.d_edge_etype, self.d_edge_valid))
        k = self.kernel
        if k is not None:
            add((k.src_sorted, k.etype_sorted, k.valid_sorted,
                 k.seg_starts, k.seg_ends))
        add(self._aligned)
        add(self.sharded_kernel)
        sa = self._sharded_aligned
        if sa is not None and sa != "failed":
            add(sa)
        return {"bytes": total,
                **{f"bytes.{w}": n for w, n in sorted(by_width.items())}}


# ---------------------------------------------------------------------------
# builder — vectorized: the keys are fixed-width big-endian with
# order-preserving biased encodings (common/keys.py), so an entire
# partition scan parses as ONE numpy structured-dtype view and the
# newest-version dedup is an adjacent-difference mask. No per-edge
# Python in pass 1 (the round-1 builder's 4.4 s/M-edge bottleneck).
# ---------------------------------------------------------------------------

_EDGE_DT = np.dtype([("part", ">u4"), ("kind", "u1"), ("src", ">u8"),
                     ("etype", ">u4"), ("rank", ">u8"), ("dst", ">u8"),
                     ("ver", ">u8")])
_VERT_DT = np.dtype([("part", ">u4"), ("kind", "u1"), ("vid", ">u8"),
                     ("tag", ">u4"), ("ver", ">u8")])
_SIGN64 = np.uint64(1 << 63)
_SIGN32 = np.uint32(1 << 31)


def _unbias64(u: np.ndarray) -> np.ndarray:
    """Biased order-preserving u64 -> signed int64 (keys._i64 inverse)."""
    return (np.ascontiguousarray(u, np.uint64) ^ _SIGN64).view(np.int64)


def _unbias32(u: np.ndarray) -> np.ndarray:
    return (np.ascontiguousarray(u, np.uint32) ^ _SIGN32).view(np.int32)


def _dst_part0(dst: np.ndarray, num_parts: int) -> np.ndarray:
    """0-based owner partition — uint64-cast modulo, identical to
    keys.part_id (ref StorageClient.cpp:10-11)."""
    return (dst.view(np.uint64) % np.uint64(num_parts)).astype(np.int32)


def _narrow_to_width(scan: ScanCols, width: int) -> ScanCols:
    """Restrict a scan to keys of exactly `width` bytes, dropping
    foreign-width keys (corruption, future key kinds) — matching the
    native extract's `k.size() != kKeyLen` skip so both builder paths
    see identical data. Indices of the result align with its arrays."""
    good = np.nonzero(scan.klens == width)[0]
    koffs = np.zeros(scan.n, np.int64)
    if scan.n > 1:
        np.cumsum(scan.klens[:-1], out=koffs[1:])
    blob = b"".join(scan.keys_blob[int(koffs[i]):int(koffs[i]) + width]
                    for i in good)
    if scan.vals_blob is not None:
        return ScanCols(len(good), blob,
                        np.full(len(good), width, np.int64),
                        scan.vlens[good], vals_blob=scan.vals_blob,
                        voffs=scan.voffs[good])
    return ScanCols(len(good), blob, np.full(len(good), width, np.int64),
                    scan.vlens[good],
                    vals_list=[scan.vals_list[int(i)] for i in good])


def _visible(scan: ScanCols, dt: np.dtype, group_fields: Tuple[str, ...]):
    """Parse a scan into a structured key array + indices of VISIBLE
    rows: newest version per logical group (first in key order —
    versions are decreasing), tombstones dropped.
    -> (arr | None, vis_idx int64[], scan) — indices address BOTH the
    returned arr and the returned scan (which may be a narrowed copy
    when foreign-width keys had to be dropped)."""
    if scan.n == 0:
        return None, np.empty(0, np.int64), scan
    if len(scan.keys_blob) != scan.n * dt.itemsize:
        scan = _narrow_to_width(scan, dt.itemsize)
        if scan.n == 0:
            return None, np.empty(0, np.int64), scan
    arr = np.frombuffer(scan.keys_blob, dtype=dt)
    n = len(arr)
    first = np.ones(n, bool)
    if n > 1:
        diff = np.zeros(n - 1, bool)
        for f in group_fields:
            col = arr[f]
            diff |= col[1:] != col[:-1]
        first[1:] = diff
    return arr, np.nonzero(first & (scan.vlens > 0))[0], scan


def build_snapshot(store, sm, space_id: int, num_parts: int) -> CsrSnapshot:
    """Scan every partition's KV range and assemble the CSR snapshot.

    The scan applies the same read semantics as the CPU getBound path:
    newest-version-wins within a (src, etype, rank, dst) group, TTL
    expiry honored (ref: storage/QueryBaseProcessor.inl:380-458)."""
    engine = store.space_engine(space_id)
    if engine is None:
        raise ValueError(f"space {space_id} not found")
    write_version = engine.write_version
    shards, cap_v, cap_e, dict_registry = build_shards(
        _EngineScanSource(engine), sm, space_id, num_parts)
    snap = CsrSnapshot(space_id, shards, cap_v, cap_e, write_version)
    snap.str_dicts = dict_registry
    return snap


class _EngineScanSource:
    """ScanSource over a local KV engine (one engine per space)."""

    def __init__(self, engine):
        self._engine = engine

    def scan(self, part: int, kind: int) -> ScanCols:
        return _scan_cols(self._engine, ku.part_data_prefix(part, kind))

    def extract(self, num_parts: int, want_values: bool):
        """Native one-call pass-1 extraction (ncsr_build) when the
        engine is the C++ one; None -> caller uses the scan path."""
        h = getattr(self._engine, "native_handle", None)
        if h is None:
            return None
        from .. import native
        if not native.available():
            return None
        try:
            return native.extract_csr(h, num_parts, want_values)
        except native.NativeBuildError:
            return None  # e.g. allocation failure: generic path retries


def _space_has_props(sm, space_id: int) -> bool:
    """Any tag/edge schema with fields? (prop-free spaces skip value
    retention in the native extract entirely)."""
    for t in sm.all_tag_ids(space_id):
        r = sm.tag_schema(space_id, t)
        if r.ok() and r.value().fields:
            return True
    for t in sm.all_edge_types(space_id):
        r = sm.edge_schema(space_id, abs(t))
        if r.ok() and r.value().fields:
            return True
    return False


def build_shards(source, sm, space_id: int, num_parts: int
                 ) -> Tuple[List[CsrShard], int, int, Dict]:
    """Assemble per-part CsrShards from any ScanSource (an object with
    `scan(part, kind) -> ScanCols` — local engine or the remote
    snapshot-sync RPC). A source that also offers `extract()` (native
    C++ engine) takes the one-call pass-1 path instead.
    Returns (shards, cap_v, cap_e, str_dicts)."""
    ex_fn = getattr(source, "extract", None)
    if ex_fn is not None:
        ext = ex_fn(num_parts, _space_has_props(sm, space_id))
        if ext is not None:
            try:
                return _build_shards_native(ext, sm, space_id, num_parts)
            finally:
                ext.close()
    now = time.time()
    P = num_parts

    # ---- pass 1: scan + parse + visibility, all vectorized ------------
    vert_scans = []   # (arr|None, vis_idx, ScanCols)
    edge_scans = []
    for p in range(1, P + 1):
        vert_scans.append(_visible(source.scan(p, ku.KIND_VERTEX),
                                   _VERT_DT, ("vid", "tag")))
        edge_scans.append(_visible(source.scan(p, ku.KIND_EDGE),
                                   _EDGE_DT, ("src", "etype", "rank",
                                              "dst")))

    # ---- per-part vid sets: vertex rows + edge srcs + incoming dsts ---
    vid_chunks: List[List[np.ndarray]] = [[] for _ in range(P)]
    edge_fields: List[Optional[Tuple]] = [None] * P  # parsed once, reused
    for p0 in range(P):
        varr, vidx, _ = vert_scans[p0]
        if varr is not None and len(vidx):
            vid_chunks[p0].append(_unbias64(varr["vid"][vidx]))
        earr, eidx, _ = edge_scans[p0]
        if earr is not None and len(eidx):
            src = _unbias64(earr["src"][eidx])
            vid_chunks[p0].append(src)
            # destinations must have a local slot in their own partition
            dst = _unbias64(earr["dst"][eidx])
            dpart = _dst_part0(dst, P)
            order = np.argsort(dpart, kind="stable")
            bounds = np.searchsorted(dpart[order], np.arange(P + 1))
            edge_fields[p0] = (src, dst, dpart, order, bounds)
            for q in range(P):
                chunk = dst[order[bounds[q]:bounds[q + 1]]]
                if len(chunk):
                    vid_chunks[q].append(chunk)
    vids_per_part = [
        np.unique(np.concatenate(ch)) if ch else np.empty(0, np.int64)
        for ch in vid_chunks]

    cap_v = _round_up(max((len(v) for v in vids_per_part), default=1))
    cap_e = _round_up(max((len(ei) for _, ei, _ in edge_scans), default=1))
    # narrow-width packing: widths decided from the caps/data BEFORE any
    # shard allocates, so all shards stack to one consistent dtype
    max_et = 0
    for earr, eidx, _ in edge_scans:
        if earr is not None and len(eidx):
            max_et = max(max_et,
                         int(np.abs(_unbias32(earr["etype"][eidx])).max()))
    idx_dt = edge_index_dtype(cap_v)
    et_dt = edge_type_dtype(max_et)

    def edge_schema(et: int) -> Optional[Schema]:
        r = sm.edge_schema(space_id, et)
        return r.value() if r.ok() else None

    # string dictionaries must be GLOBAL across shards AND schema ids so
    # a code identifies one string everywhere a prop of that name is
    # merged into a single device column: (kind, prop name) -> dict
    dict_registry: Dict[Tuple[str, str], Dict[str, int]] = {}
    shards: List[CsrShard] = []
    for p0 in range(P):
        vids_sorted = vids_per_part[p0]
        earr, eidx, escan = edge_scans[p0]
        ne = len(eidx)
        edge_src = np.zeros(cap_e, idx_dt)
        edge_etype = np.zeros(cap_e, et_dt)
        edge_rank = np.zeros(cap_e, np.int64)
        edge_dst_vid = np.zeros(cap_e, np.int64)
        edge_dst_part = np.zeros(cap_e, np.int32)
        edge_dst_local = np.zeros(cap_e, idx_dt)
        edge_valid = np.zeros(cap_e, bool)
        et = np.empty(0, np.int32)
        if ne:
            # scan order is already canonical (src, etype, rank, dst) —
            # the biased key encodings sort numerically, so no re-sort
            src, dst, dpart, order, bounds = edge_fields[p0]
            et = _unbias32(earr["etype"][eidx])
            edge_src[:ne] = np.searchsorted(vids_sorted, src)
            edge_etype[:ne] = et
            edge_rank[:ne] = _unbias64(earr["rank"][eidx])
            edge_dst_vid[:ne] = dst
            edge_dst_part[:ne] = dpart
            for q in range(P):
                sel = order[bounds[q]:bounds[q + 1]]
                if len(sel):
                    edge_dst_local[sel] = np.searchsorted(
                        vids_per_part[q], dst[sel])
            edge_valid[:ne] = True
        shard = CsrShard(p0 + 1, vids_sorted, ne, edge_src, edge_etype,
                         edge_rank, edge_dst_vid, edge_dst_part,
                         edge_dst_local, edge_valid)
        shards.append(shard)

        # ---- pass 2: property columns (skipped for prop-free schemas) --
        if ne:
            for t in np.unique(et):
                schema = edge_schema(int(t))
                if schema is None or not schema.fields:
                    continue
                sel = np.nonzero(et == t)[0]
                rows = RowsBlock.from_scan(escan, eidx[sel], sel)
                row_dead = np.zeros(cap_e, bool)
                cols = _build_columns(
                    schema, cap_e, rows, now, dict_registry, ("e",),
                    schema_at=lambda v, _t=int(t): _ver_schema(
                        sm.edge_schema, space_id, _t, v),
                    row_dead=row_dead)
                if cols:
                    shard.edge_props[int(t)] = cols
                _mark_ttl_dead_edges(schema, row_dead, sel, edge_valid)
        varr, vidx, vscan = vert_scans[p0]
        if varr is not None and len(vidx):
            tags = _unbias32(varr["tag"][vidx])
            vlocal = np.searchsorted(vids_sorted,
                                     _unbias64(varr["vid"][vidx]))
            for t in np.unique(tags):
                sr = sm.tag_schema(space_id, int(t))
                if not sr.ok() or not sr.value().fields:
                    continue
                sel = np.nonzero(tags == t)[0]
                rows = RowsBlock.from_scan(vscan, vidx[sel], vlocal[sel])
                cols = _build_columns(
                    sr.value(), cap_v, rows, now, dict_registry, ("t",),
                    schema_at=lambda v, _t=int(t): _ver_schema(
                        sm.tag_schema, space_id, _t, v))
                if cols:
                    shard.tag_props[int(t)] = cols
    return shards, cap_v, cap_e, dict_registry


def _build_shards_native(ext, sm, space_id: int, P: int
                         ) -> Tuple[List[CsrShard], int, int, Dict]:
    """Shards from a native CsrExtract: pass 1 (scan, dedup, parse,
    local-index resolution) already ran in C++; here only padding into
    the [cap] layout and property-column decode remain."""
    now = time.time()
    per_part = [(ext.vids(p0), ext.edges(p0)) for p0 in range(P)]
    cap_v = _round_up(max((len(v) for v, _ in per_part), default=1))
    cap_e = _round_up(max((len(e[1]) for _, e in per_part), default=1))
    max_et = max((int(np.abs(e[1]).max()) for _, e in per_part
                  if len(e[1])), default=0)
    idx_dt = edge_index_dtype(cap_v)
    et_dt = edge_type_dtype(max_et)
    dict_registry: Dict[Tuple[str, str], Dict[str, int]] = {}
    shards: List[CsrShard] = []
    for p0 in range(P):
        vids_sorted, (src_l, et, rank, dst_v, dst_p, dst_l) = per_part[p0]
        ne = len(et)
        edge_src = np.zeros(cap_e, idx_dt)
        edge_etype = np.zeros(cap_e, et_dt)
        edge_rank = np.zeros(cap_e, np.int64)
        edge_dst_vid = np.zeros(cap_e, np.int64)
        edge_dst_part = np.zeros(cap_e, np.int32)
        edge_dst_local = np.zeros(cap_e, idx_dt)
        edge_valid = np.zeros(cap_e, bool)
        if ne:
            edge_src[:ne] = src_l
            edge_etype[:ne] = et
            edge_rank[:ne] = rank
            edge_dst_vid[:ne] = dst_v
            edge_dst_part[:ne] = dst_p
            edge_dst_local[:ne] = dst_l
            edge_valid[:ne] = True
        shard = CsrShard(p0 + 1, vids_sorted, ne, edge_src, edge_etype,
                         edge_rank, edge_dst_vid, edge_dst_part,
                         edge_dst_local, edge_valid)
        shards.append(shard)
        if ne:
            ev = ext.edge_vals(p0)
            if ev is not None:
                blob, offs, lens = ev
                for t in np.unique(et):
                    r = sm.edge_schema(space_id, int(t))
                    if not r.ok() or not r.value().fields:
                        continue
                    sel = np.nonzero(et == t)[0]
                    rows = RowsBlock(blob, offs[sel], lens[sel], sel)
                    row_dead = np.zeros(cap_e, bool)
                    cols = _build_columns(
                        r.value(), cap_e, rows, now, dict_registry, ("e",),
                        schema_at=lambda v, _t=int(t): _ver_schema(
                            sm.edge_schema, space_id, _t, v),
                        row_dead=row_dead)
                    if cols:
                        shard.edge_props[int(t)] = cols
                    _mark_ttl_dead_edges(r.value(), row_dead, sel,
                                         edge_valid)
        vlocal, vtag = ext.vert_rows(p0)
        if len(vtag):
            vv = ext.vert_vals(p0)
            if vv is not None:
                blob, offs, lens = vv
                for t in np.unique(vtag):
                    sr = sm.tag_schema(space_id, int(t))
                    if not sr.ok() or not sr.value().fields:
                        continue
                    sel = np.nonzero(vtag == t)[0]
                    rows = RowsBlock(blob, offs[sel], lens[sel],
                                     vlocal[sel])
                    cols = _build_columns(
                        sr.value(), cap_v, rows, now, dict_registry,
                        ("t",),
                        schema_at=lambda v, _t=int(t): _ver_schema(
                            sm.tag_schema, space_id, _t, v))
                    if cols:
                        shard.tag_props[int(t)] = cols
    return shards, cap_v, cap_e, dict_registry


_I32_MIN, _I32_MAX = -(1 << 31), (1 << 31) - 1


def _ver_schema(getter, space_id: int, type_id: int,
                version: int) -> Optional[Schema]:
    """Versioned schema lookup for _build_columns' schema_at."""
    r = getter(space_id, abs(type_id), version)
    return r.value() if r.ok() else None


def _mark_ttl_dead_edges(schema: Schema, row_dead: np.ndarray,
                         sel: np.ndarray, edge_valid: np.ndarray) -> None:
    """Clear edge_valid for rows the column builders DROPPED (TTL-
    expired or undecodable), via their explicit `row_dead` mask —
    shared by BOTH shard builders (the native-extract path previously
    skipped edge TTL invalidation entirely, leaving expired edges
    device-visible).

    The traversal must not serve dropped edges (the CPU scan checks
    TTL per row, processors.py/get_bound). Inference from the cell
    masks is NOT used: a cell can be missing merely because its row's
    schema VERSION lacks the ttl col (post-ALTER rows — including
    versions with no shared columns at all), and the CPU reads
    `row.get(ttl_col) is None` as never-expired
    (processors.py:152-155), so only explicitly-dropped rows count.
    Gated on the schema carrying TTL, like the CPU read path."""
    if not (schema.ttl_col and schema.ttl_duration > 0):
        return
    dead = row_dead[sel]
    if dead.any():
        edge_valid[sel[dead]] = False


def _ttl_dead(schema: Schema, i64: np.ndarray, f64: np.ndarray,
              nulls: np.ndarray, now: float) -> np.ndarray:
    """TTL-expired mask over decoded column buffers (shared by the
    single- and multi-version native paths). Only numeric ttl cols
    expire — the Python/storage paths treat a non-numeric ttl value as
    never-expired (their isinstance check admits int/float/bool, so
    BOOL stays in the numeric set here)."""
    if schema.ttl_col and schema.ttl_duration > 0:
        ti = schema.field_index(schema.ttl_col)
        if ti >= 0 and schema.fields[ti].type in (
                PropType.INT, PropType.VID, PropType.TIMESTAMP,
                PropType.DOUBLE, PropType.BOOL):
            tt = schema.fields[ti].type
            tv = f64[ti] if tt == PropType.DOUBLE else i64[ti]
            return (~nulls[ti]) & (tv + schema.ttl_duration < now)
    return np.zeros(nulls.shape[1], bool)


def _native_build_columns(schema: Schema, cap: int, rows: "RowsBlock",
                          now: float, dict_registry: Dict, dict_key: Tuple,
                          row_dead: Optional[np.ndarray] = None
                          ) -> Optional[Dict[str, PropColumn]]:
    """Fast path: one nbc_decode_batch FFI call decodes every row into
    column buffers (native/src/codec.cc — the C++ codec hot path, role
    parity with the reference's C++ RowReader). Returns None when the
    native library is unavailable; semantics match the Python path
    (newest rows only arrive here; TTL-expired rows fully nulled)."""
    from .. import native
    if not native.available():
        return None
    if isinstance(rows, list):
        rows = RowsBlock.from_pairs(rows)
    try:
        i64, f64, soff, slen, nulls, blob = native.decode_rows(
            [f.type.value for f in schema.fields], rows.blob, rows.offs,
            rows.lens, rows.idxs, cap)
    except Exception:
        return None
    # TTL: a row whose ttl prop expired is invisible — null every field
    expired = _ttl_dead(schema, i64, f64, nulls, now)
    if expired.any():
        nulls[:, expired] = True
        if row_dead is not None:
            row_dead[expired] = True
    # strings decode strictly up front; a row with invalid UTF-8 becomes
    # wholly invisible, matching the Python path's whole-row skip on
    # decode failure
    str_vals: Dict[int, Dict[int, str]] = {}
    for fi, f in enumerate(schema.fields):
        if f.type != PropType.STRING:
            continue
        vals: Dict[int, str] = {}
        for i in np.nonzero(~nulls[fi])[0]:
            b = blob[soff[fi, i]:soff[fi, i] + slen[fi, i]]
            try:
                vals[int(i)] = b.decode("utf-8")
            except UnicodeDecodeError:
                nulls[:, i] = True
                if row_dead is not None:
                    row_dead[i] = True
        str_vals[fi] = vals
    out: Dict[str, PropColumn] = {}
    for fi, f in enumerate(schema.fields):
        t = f.type
        present = ~nulls[fi]
        pos = np.nonzero(present)[0]
        host = np.empty(cap, dtype=object)  # object-empty = None-filled
        device_ok = True
        device_vals = None
        str_dict = None
        # numeric mirrors stay NUMPY (see PropColumn doc: no per-value
        # python objects at snapshot scale); nulls ride `present`
        if t == PropType.DOUBLE:
            vals = f64[fi]
            host = np.where(present, vals, 0.0)
            device_vals = np.where(present, vals, np.nan).astype(np.float32)
        elif t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
            vals = i64[fi]
            host = np.where(present, vals, 0)
            if pos.size and (vals[pos].min() < _I32_MIN
                             or vals[pos].max() > _I32_MAX):
                device_ok = False  # host-only column (filter falls back)
            else:
                device_vals = np.where(present, vals, 0).astype(np.int32)
        elif t == PropType.BOOL:
            vals = i64[fi] != 0
            host = np.where(present, vals, False)
            device_vals = np.where(present, vals, False)
        elif t == PropType.STRING:
            if dict_registry is not None and dict_key is not None:
                str_dict = dict_registry.setdefault(dict_key + (f.name,), {})
            else:
                str_dict = {}
            codes = np.full(cap, -1, dtype=np.int32)
            for i, s in str_vals[fi].items():
                if nulls[fi, i]:
                    continue  # row nulled by a later field's bad UTF-8
                host[i] = s
                codes[i] = str_dict.setdefault(s, len(str_dict))
            device_vals = codes
        else:
            device_ok = False
        out[f.name] = PropColumn(f.name, t, host, device_ok, device_vals,
                                 present, str_dict)
    return out


def _row_versions(rows: "RowsBlock") -> np.ndarray:
    """Schema version of every row (vectorized peek_schema_version):
    byte 0 is the version length, little-endian version bytes follow."""
    n = len(rows.idxs)
    if n == 0:
        return np.zeros(0, np.int64)
    b = np.frombuffer(rows.blob, np.uint8)
    offs = rows.offs
    vl = b[offs].astype(np.int64)
    ver = np.zeros(n, np.int64)
    for k in range(int(vl.max())):
        sel = vl > k
        ver[sel] |= b[offs[sel] + 1 + k].astype(np.int64) << (8 * k)
    return ver


def _finish_column(name: str, t: PropType, vals: List[Any], cap: int,
                   dict_registry: Dict, dict_key: Tuple,
                   missing: Optional[np.ndarray],
                   version_missing: bool = False) -> PropColumn:
    """Assemble one PropColumn from a None-holed python value list."""
    host = np.array(vals, dtype=object)
    device_ok = True
    device_vals = None
    str_dict = None
    if t == PropType.DOUBLE:
        device_vals = np.array([v if v is not None else np.nan
                                for v in vals], dtype=np.float32)
    elif t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
        ints = [v if v is not None else 0 for v in vals]
        if ints and (min(ints) < _I32_MIN or max(ints) > _I32_MAX):
            device_ok = False  # host-only column (filter falls back)
        else:
            device_vals = np.array(ints, dtype=np.int32)
    elif t == PropType.BOOL:
        device_vals = np.array([bool(v) for v in vals], dtype=bool)
    elif t == PropType.STRING:
        if dict_registry is not None and dict_key is not None:
            str_dict = dict_registry.setdefault(dict_key + (name,), {})
        else:
            str_dict = {}
        codes = np.full(cap, -1, dtype=np.int32)
        for i, v in enumerate(vals):
            if v is None:
                continue
            codes[i] = str_dict.setdefault(v, len(str_dict))
        device_vals = codes
    else:
        device_ok = False
    present = np.array([v is not None for v in vals], dtype=bool)
    return PropColumn(name, t, host, device_ok, device_vals, present,
                      str_dict, missing, version_missing=version_missing)


def _native_build_columns_multi(schemas_by_ver: Dict[int, Schema],
                                field_types: Dict[str, PropType],
                                conflicted: set, cap: int,
                                rows: "RowsBlock", vers: np.ndarray,
                                now: float, dict_registry: Dict,
                                dict_key: Tuple,
                                row_dead: Optional[np.ndarray] = None
                                ) -> Optional[Dict[str, PropColumn]]:
    """Mixed-version fast path: one nbc_decode_batch call PER VERSION
    GROUP (each with its version's field list), merged into union
    columns with `missing` masks — a post-ALTER space rebuilds at
    native speed instead of per-row Python. Semantics mirror the
    python multi path: TTL-expired / undecodable rows are invisible
    (missing), cells whose row version lacks the field are missing,
    retyped (conflicted) fields stay host-only."""
    from .. import native
    if not native.available():
        return None
    names = list(field_types)
    miss = {n: np.ones(cap, bool) for n in names}
    pres = {n: np.zeros(cap, bool) for n in names}
    val64 = {}
    valf = {}
    valb = {}
    str_cells: Dict[str, Dict[int, str]] = {}
    obj = {n: np.empty(cap, object) for n in conflicted}
    for n, t in field_types.items():
        if n in conflicted:
            continue
        if t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
            val64[n] = np.zeros(cap, np.int64)
        elif t == PropType.DOUBLE:
            valf[n] = np.zeros(cap, np.float64)
        elif t == PropType.BOOL:
            valb[n] = np.zeros(cap, bool)
        elif t == PropType.STRING:
            str_cells[n] = {}
        else:
            return None   # unsupported type: python path decides
    for ver, sv in schemas_by_ver.items():
        sel = np.nonzero(vers == ver)[0]
        if not len(sel) or not sv.fields:
            continue
        sub_idx = rows.idxs[sel]
        try:
            i64, f64, soff, slen, nulls, blob = native.decode_rows(
                [f.type.value for f in sv.fields], rows.blob,
                rows.offs[sel], rows.lens[sel], sub_idx, cap)
        except Exception:
            return None
        covered = sub_idx.astype(np.int64)
        # rows of THIS group gone invisible (TTL / bad UTF-8)
        dead = _ttl_dead(sv, i64, f64, nulls, now)
        # strings decode strictly; invalid UTF-8 kills the whole row
        # (the python path's whole-row skip on decode failure)
        group_strs: Dict[int, Dict[int, str]] = {}
        for fi, f in enumerate(sv.fields):
            if f.type != PropType.STRING:
                continue
            vals: Dict[int, str] = {}
            for i in covered[~nulls[fi][covered] & ~dead[covered]]:
                i = int(i)
                b = blob[soff[fi, i]:soff[fi, i] + slen[fi, i]]
                try:
                    vals[i] = b.decode("utf-8")
                except UnicodeDecodeError:
                    dead[i] = True
            group_strs[fi] = vals
        alive = covered[~dead[covered]]
        if row_dead is not None:
            row_dead[covered[dead[covered]]] = True
        for fi, f in enumerate(sv.fields):
            n = f.name
            p = ~nulls[fi][alive]
            miss[n][alive] = False
            pres[n][alive] = p
            t = f.type
            if n in conflicted:
                if t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
                    obj[n][alive] = i64[fi][alive]
                elif t == PropType.DOUBLE:
                    obj[n][alive] = f64[fi][alive]
                elif t == PropType.BOOL:
                    obj[n][alive] = i64[fi][alive] != 0
                elif t == PropType.STRING:
                    for i, s in group_strs[fi].items():
                        if not dead[i]:
                            obj[n][i] = s
                obj[n][alive[~p]] = None
            elif t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
                val64[n][alive] = np.where(p, i64[fi][alive], 0)
            elif t == PropType.DOUBLE:
                valf[n][alive] = np.where(p, f64[fi][alive], 0.0)
            elif t == PropType.BOOL:
                valb[n][alive] = np.where(p, i64[fi][alive] != 0, False)
            elif t == PropType.STRING:
                # drop rows a LATER field's bad UTF-8 killed — their
                # earlier string values must not leak into the column
                # or intern into the shared dict
                str_cells[n].update({i: s for i, s in
                                     group_strs[fi].items()
                                     if not dead[i]})
    out: Dict[str, PropColumn] = {}
    for n in names:
        t = field_types[n]
        m, pr = miss[n], pres[n]
        if n in conflicted:
            out[n] = PropColumn(n, t, obj[n], False, None, pr, None, m,
                                version_missing=True)
            continue
        if t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
            vals = val64[n]
            pos = np.nonzero(pr)[0]
            device_ok = not (pos.size and (
                vals[pos].min() < _I32_MIN or vals[pos].max() > _I32_MAX))
            dv = vals.astype(np.int32) if device_ok else None
            out[n] = PropColumn(n, t, vals, device_ok, dv, pr, None, m,
                                version_missing=True)
        elif t == PropType.DOUBLE:
            vals = valf[n]
            dv = np.where(pr, vals, np.nan).astype(np.float32)
            out[n] = PropColumn(n, t, vals, True, dv, pr, None, m,
                                version_missing=True)
        elif t == PropType.BOOL:
            out[n] = PropColumn(n, t, valb[n], True, valb[n].copy(), pr,
                                None, m, version_missing=True)
        else:   # STRING
            host = np.empty(cap, object)
            if dict_registry is not None and dict_key is not None:
                sd = dict_registry.setdefault(dict_key + (n,), {})
            else:
                sd = {}
            codes = np.full(cap, -1, np.int32)
            for i, s in str_cells[n].items():
                host[i] = s
                codes[i] = sd.setdefault(s, len(sd))
            out[n] = PropColumn(n, t, host, True, codes, pr, sd, m,
                                version_missing=True)
    return out


def _build_columns(schema: Schema, cap: int, rows: "RowsBlock", now: float,
                   dict_registry: Dict = None, dict_key: Tuple = None,
                   schema_at=None,
                   row_dead: Optional[np.ndarray] = None
                   ) -> Dict[str, PropColumn]:
    """Decode rows into columnar arrays aligned at the given indices,
    respecting per-row schema versions and TTL.

    `schema` is the LATEST schema; `schema_at(ver)` resolves an older
    version (None -> fall back to latest, the _decode_row rule,
    processors.py:131-140). When every row carries the latest version
    (the overwhelmingly common case) the single-schema fast path runs —
    native batch decode when available — and `missing` stays None.
    Mixed-version row sets (post-ALTER spaces) take the exact path:
    each row decodes with ITS OWN version's schema, and cells whose row
    version lacks the field are marked `missing` (the CPU walk raises
    EvalError for them; see PropColumn doc)."""
    if isinstance(rows, list):
        rows = RowsBlock.from_pairs(rows)
    vers = _row_versions(rows)
    uvers = np.unique(vers)
    single = len(uvers) == 0 or (
        len(uvers) == 1 and (schema_at is None
                             or int(uvers[0]) == schema.version))
    # the `missing is None` fast representation encodes "~present ⇒ the
    # CPU walk raises" (no row / TTL-expired / undecodable). A nullable
    # field breaks that: an explicit NULL is ~present but must NOT read
    # as err (delta.py materializes missing as ~present on fast-build
    # columns). Schemas with nullable fields therefore always build
    # real `missing` masks, today and for any future DDL that exposes
    # nullable — enforced here rather than assumed at the write path.
    has_nullable = any(f.nullable for f in schema.fields)
    if single and not has_nullable:
        fast = _native_build_columns(schema, cap, rows, now,
                                     dict_registry, dict_key,
                                     row_dead=row_dead)
        if fast is not None:
            return fast
    multi = not single and schema_at is not None
    # union of fields over the versions actually present (the latest
    # schema's type wins a name clash); latest fields always exist so
    # filter/YIELD compiles see the column even when no current-version
    # row landed in this shard
    field_types: Dict[str, PropType] = {f.name: f.type
                                        for f in schema.fields}
    schemas_by_ver: Dict[int, Schema] = {}
    conflicted: set = set()
    if multi:
        for v in (int(x) for x in uvers):
            sv = schema if v == schema.version else schema_at(v)
            if sv is None:
                sv = schema
            schemas_by_ver[v] = sv
            for f in sv.fields:
                prev = field_types.setdefault(f.name, f.type)
                if prev != f.type:
                    # a DROP+ADD (or CHANGE) retyped the field across
                    # versions: per-row values have mixed types — the
                    # column stays host-only (filters fall back to the
                    # exact walk; the CPU path reads per-row types)
                    conflicted.add(f.name)
    if multi:
        fast = _native_build_columns_multi(
            schemas_by_ver, field_types, conflicted, cap, rows, vers,
            now, dict_registry, dict_key, row_dead=row_dead)
        if fast is not None:
            return fast
    names = list(field_types)
    host_cols: Dict[str, List[Any]] = {n: [None] * cap for n in names}
    miss: Optional[Dict[str, np.ndarray]] = (
        {n: np.ones(cap, bool) for n in names}
        if (multi or has_nullable) else None)
    for j, (idx, raw) in enumerate(rows.items()):
        sv = schemas_by_ver.get(int(vers[j]), schema) if multi else schema
        try:
            row = RowReader(sv, raw).to_dict()
        except Exception:
            if row_dead is not None:
                row_dead[idx] = True
            continue
        if sv.ttl_col and sv.ttl_duration > 0:
            ts = row.get(sv.ttl_col)
            if isinstance(ts, (int, float)) and ts + sv.ttl_duration < now:
                if row_dead is not None:
                    row_dead[idx] = True
                continue
        for name, v in row.items():
            host_cols[name][idx] = v
            if miss is not None:
                miss[name][idx] = False
    out: Dict[str, PropColumn] = {}
    for name in names:
        m = miss[name] if miss is not None else None
        if name in conflicted:
            vals = host_cols[name]
            present = np.array([v is not None for v in vals], bool)
            out[name] = PropColumn(name, field_types[name],
                                   np.array(vals, dtype=object), False,
                                   None, present, None, m,
                                   version_missing=multi)
            continue
        out[name] = _finish_column(
            name, field_types[name], host_cols[name], cap,
            dict_registry, dict_key, m, version_missing=multi)
    return out
