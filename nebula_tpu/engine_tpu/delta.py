"""Device-snapshot delta buffer: committed writes patch the CSR in
place instead of forcing a full rebuild.

Role parity with the reference's in-place mutability (`Part::commitLogs`
applies every committed batch straight into the engine and readers see
it immediately, ref kvstore/Part.cpp:208-319; §2.10 P6's delta-buffer
TPU equivalent). The feed is `kvstore/changelog.py`'s resolved logical
entries; this module applies them to a CsrSnapshot:

- Edge ADD (no canonical slot): appended to a fixed-capacity ELL
  buffer keyed by DESTINATION slot — up to K lanes per dst. Keying by
  dst keeps the hop union scatter-free (traverse.DeltaKernel): the
  kernel just gathers frontier[src[v, k]] per lane.
- Edge DELETE of a canonical edge: tombstone — the kernel's
  valid/valid_sorted masks are point-updated on device (the segment
  boundaries never change, so no re-sort).
- Edge prop UPDATE of a canonical edge: host prop mirrors are patched
  and the stacked device prop cache invalidated (filter columns
  re-upload lazily).
- Vertex rows: patched into the tag prop columns; NEW vids get spare
  local slots (cap_v is lane-rounded, so shards almost always have
  spare slots) tracked in `CsrShard.delta_vids`.

Capacity exhaustion (ELL lanes, spare slots) fails the apply — the
engine then falls back to a rebuild (repack), which folds the delta
into a fresh base. All application is idempotent: entries carry the
CURRENT visible state of their group, so replays converge.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..codec.row import RowReader
from ..codec.schema import PropType
from ..common import keys as ku

_SIGN64 = np.uint64(1 << 63)
_SIGN32 = np.uint32(1 << 31)
_I32_MIN, _I32_MAX = -(1 << 31), (1 << 31) - 1


def _bias64(v: np.ndarray) -> np.ndarray:
    return (np.ascontiguousarray(v, np.int64).view(np.uint64) ^ _SIGN64)


def _bias32(v: np.ndarray) -> np.ndarray:
    return (np.ascontiguousarray(v, np.int32).view(np.uint32) ^ _SIGN32)


_CANON_DT = np.dtype([("s", ">u4"), ("e", ">u4"), ("r", ">u8"),
                      ("d", ">u8")])


def _canon_keys(shard) -> np.ndarray:
    """Packed big-endian (src_local, etype, rank, dst) keys of the
    shard's canonical edges, viewable as fixed-width byte strings whose
    lexicographic order equals the canonical sort order (the key codec
    is order-preserving) — binary-searchable for point lookups."""
    canon = getattr(shard, "_canon_keys", None)
    if canon is not None:
        return canon
    ne = shard.num_edges
    a = np.empty(ne, _CANON_DT)
    a["s"] = shard.edge_src[:ne].astype(np.uint32)
    a["e"] = _bias32(shard.edge_etype[:ne])
    a["r"] = _bias64(shard.edge_rank[:ne])
    a["d"] = _bias64(shard.edge_dst_vid[:ne])
    canon = a.view("S24")
    shard._canon_keys = canon
    return canon


def _canon_find(shard, src_local: int, etype: int, rank: int,
                dst: int) -> Optional[int]:
    """Canonical edge index of (src_local, etype, rank, dst), or None."""
    if shard.num_edges == 0:
        return None
    key = np.empty(1, _CANON_DT)
    key["s"], key["e"] = src_local, _bias32(np.int32(etype))
    key["r"], key["d"] = _bias64(np.int64(rank)), _bias64(np.int64(dst))
    canon = _canon_keys(shard)
    i = int(np.searchsorted(canon, key.view("S24")[0]))
    if i < len(canon) and canon[i] == key.view("S24")[0]:
        return i
    return None


class SnapshotDelta:
    """Host-side state of the add-buffer + tombstones for one snapshot.
    Device mirrors are re-derived lazily after each apply batch."""

    def __init__(self, snap, lanes: int = 4, max_edges: Optional[int] = None):
        n_slots = snap.num_parts * snap.cap_v
        self.n_slots = n_slots
        self.K = lanes
        # fan-in bound: reverse-copy rows make every fan-OUT from one
        # vertex a fan-IN onto its dst slot, so lanes must be able to
        # grow well past the average degree; cap by a ~64MB host/device
        # budget so huge snapshots don't balloon (overflow => repack)
        self.k_max = int(min(64, max(8, (64 << 20) // (9 * n_slots))))
        self.h_src = np.zeros((n_slots, lanes), np.int32)
        self.h_etype = np.zeros((n_slots, lanes), np.int32)
        self.h_ok = np.zeros((n_slots, lanes), bool)
        self.edge_count = 0
        self.tomb_count = 0
        self.max_edges = max_edges if max_edges is not None \
            else max(1024, n_slots // 8)
        # (part, src, etype, rank, dst) -> (gdst, lane)
        self.map: Dict[Tuple, Tuple[int, int]] = {}
        # (gdst, lane) -> (src_vid, etype, rank, dst_vid, props dict)
        self.info: Dict[Tuple[int, int], Tuple] = {}
        # src global slot -> set of (gdst, lane) — path reconstruction
        self.by_src: Dict[int, set] = {}
        self._device = None

    def device(self):
        """traverse.DeltaKernel for the current host state (cached)."""
        if self._device is None:
            import jax.numpy as jnp
            from .traverse import DeltaKernel
            self._device = DeltaKernel(jnp.asarray(self.h_src),
                                       jnp.asarray(self.h_etype),
                                       jnp.asarray(self.h_ok))
        return self._device

    # -- mutation primitives (host) ------------------------------------
    def add_edge(self, gkey: Tuple, gsrc: int, gdst: int, src_vid: int,
                 etype: int, rank: int, dst_vid: int, props: dict) -> bool:
        slot = self.map.get(gkey)
        if slot is not None:                 # prop update of a delta edge
            self.info[slot] = (src_vid, etype, rank, dst_vid, props)
            return True
        if self.edge_count >= self.max_edges:
            return False
        lane = int(np.argmin(self.h_ok[gdst]))
        if self.h_ok[gdst, lane]:
            if self.K >= self.k_max:
                return False                 # lane budget exhausted: repack
            lane = self.K                    # first lane added by growth
            self._grow_lanes()               # (k_max may clamp below 2K)
        self.h_src[gdst, lane] = gsrc
        self.h_etype[gdst, lane] = etype
        self.h_ok[gdst, lane] = True
        self.map[gkey] = (gdst, lane)
        self.info[(gdst, lane)] = (src_vid, etype, rank, dst_vid, props)
        self.by_src.setdefault(gsrc, set()).add((gdst, lane))
        self.edge_count += 1
        self._device = None
        return True

    def _grow_lanes(self) -> None:
        """Double K (a hot destination filled its lanes); existing
        (gdst, lane) coordinates stay valid."""
        k2 = min(self.K * 2, self.k_max)
        for name in ("h_src", "h_etype", "h_ok"):
            old = getattr(self, name)
            new = np.zeros((self.n_slots, k2), old.dtype)
            new[:, :self.K] = old
            setattr(self, name, new)
        self.K = k2
        self._device = None

    def remove_edge(self, gkey: Tuple, gsrc: int) -> None:
        slot = self.map.pop(gkey, None)
        if slot is None:
            return
        self.h_ok[slot] = False
        self.info.pop(slot, None)
        s = self.by_src.get(gsrc)
        if s is not None:
            s.discard(slot)
        self.edge_count -= 1
        self._device = None


def _decode_props(sm, space_id: int, kind: str, type_id: int,
                  row: bytes, now: float) -> Optional[dict]:
    """Row bytes -> props dict with the builder's TTL semantics (None =
    invisible: undecodable or TTL-expired). Decodes with the ROW's own
    schema version (processors.py _decode_row rule) — keys the row's
    version doesn't carry are simply absent from the dict, and the
    patch marks those cells `missing` (CPU raises EvalError there)."""
    from ..codec.row import peek_schema_version
    getter = sm.tag_schema if kind == "v" else sm.edge_schema
    latest = getter(space_id, type_id)
    if not latest.ok():
        return {}
    schema = latest.value()
    if not schema.fields:
        return {}
    try:
        ver = peek_schema_version(row)
        if ver != schema.version:
            rv = getter(space_id, type_id, ver)
            if rv.ok():
                schema = rv.value()
        props = RowReader(schema, row).to_dict()
    except Exception:
        return None
    if schema.ttl_col and schema.ttl_duration > 0:
        ts = props.get(schema.ttl_col)
        if isinstance(ts, (int, float)) and ts + schema.ttl_duration < now:
            return None
    return props


def _encode_device_val(col, value):
    """Python value -> the column's device encoding (None = can't)."""
    t = col.ptype
    if value is None:
        return None
    if t == PropType.DOUBLE:
        return np.float32(value)
    if t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
        if not (_I32_MIN <= int(value) <= _I32_MAX):
            return None
        return np.int32(value)
    if t == PropType.BOOL:
        return bool(value)
    if t == PropType.STRING and col.str_dict is not None:
        return np.int32(col.str_dict.setdefault(value,
                                                len(col.str_dict)))
    return None


def _patch_prop_columns(snap, cols: Dict, idx: int, props: Optional[dict],
                        visible: bool) -> None:
    """Write one row's values into existing PropColumn mirrors at idx.

    Three-state (PropColumn doc): a key absent from the row's schema
    version — or the whole row invisible (tombstone/TTL) — marks the
    cell `missing` (CPU raises EvalError); a key present with None is
    an explicit null."""
    for name, col in cols.items():
        known = visible and props is not None and name in props
        v = props.get(name) if known else None
        if not known:
            if col.missing is None:
                # materializing the mask on a fast-build column: its
                # ~present cells were all err (no-row) — preserve that.
                # Sound because _build_columns never takes the
                # missing=None fast path for schemas with nullable
                # fields, so no ~present cell here is an explicit NULL
                col.missing = (~col.present if col.present is not None
                               else np.zeros(len(col.host), bool))
            col.missing[idx] = True
            if visible and props is not None:
                # a VISIBLE row whose schema version lacks this key:
                # the CPU raises for it (unlike tombstone/TTL no-row
                # cells, which read as schema defaults for tags) —
                # flag the mask so vectorized tag paths decline
                col.version_missing = True
        elif col.missing is not None:
            col.missing[idx] = False
        if col.host.dtype == object:
            col.host[idx] = v
        else:   # numeric mirror: nulls ride `present`, cell stores 0
            col.host[idx] = 0 if v is None else v
        if col.present is not None:
            col.present[idx] = v is not None
        if col.device_vals is not None:
            enc = _encode_device_val(col, v)
            if enc is None and v is not None:
                col.device_ok = False   # out-of-range: host-only now
            elif enc is not None:
                col.device_vals[idx] = enc
            else:
                # v is None (tombstone / null / version-missing):
                # restore the BUILD-TIME absent encoding — stale
                # values here would leak into the vectorized tag
                # paths, which assume absent cells encode defaults
                if col.ptype == PropType.STRING:
                    col.device_vals[idx] = -1
                elif col.ptype == PropType.DOUBLE:
                    col.device_vals[idx] = np.float32(np.nan)
                elif col.ptype == PropType.BOOL:
                    col.device_vals[idx] = False
                else:
                    col.device_vals[idx] = 0
    snap._device_prop_cache.clear()


def _ensure_prop_columns(snap, shard, kind: str, sm, space_id: int,
                         type_id: int, cap: int) -> Optional[Dict]:
    """Prop columns dict for (shard, tag/etype), creating empty aligned
    columns when this shard had no rows of that type at build time."""
    store = shard.tag_props if kind == "v" else shard.edge_props
    r = (sm.tag_schema(space_id, type_id) if kind == "v"
         else sm.edge_schema(space_id, type_id))
    cols = store.get(type_id)
    if cols is not None:
        # reconcile fields an ALTER added after the snapshot was built:
        # absent-everywhere columns (err for every existing row — their
        # versions lack the field) that incoming writes then fill
        if r.ok() and any(f.name not in cols for f in r.value().fields):
            fresh = _new_columns(snap, kind,
                                 [f for f in r.value().fields
                                  if f.name not in cols], cap)
            cols.update(fresh)
        return cols
    if not r.ok() or not r.value().fields:
        return None
    cols = _new_columns(snap, kind, r.value().fields, cap)
    store[type_id] = cols
    return cols


def _new_columns(snap, kind: str, fields, cap: int) -> Dict:
    from .csr import PropColumn
    cols = {}
    for f in fields:
        host = np.empty(cap, dtype=object)
        present = np.zeros(cap, bool)
        t = f.type
        str_dict = None
        if t == PropType.DOUBLE:
            dv = np.full(cap, np.nan, np.float32)
        elif t in (PropType.INT, PropType.VID, PropType.TIMESTAMP):
            dv = np.zeros(cap, np.int32)
        elif t == PropType.BOOL:
            dv = np.zeros(cap, bool)
        elif t == PropType.STRING:
            dv = np.full(cap, -1, np.int32)
            str_dict = snap.str_dicts.setdefault(
                ("t" if kind == "v" else "e", f.name), {})
        else:
            cols[f.name] = PropColumn(f.name, t, host, False, None, present)
            continue
        cols[f.name] = PropColumn(f.name, t, host, True, dv, present,
                                  str_dict)
    return cols


def apply_entries(snap, sm, entries: List[tuple], now: float) -> bool:
    """Apply resolved logical entries to the snapshot. False = capacity
    exhausted or unappliable — caller must repack (the snapshot may be
    partially patched and MUST NOT serve until rebuilt)."""
    delta = snap.delta
    if delta is None:
        delta = snap.delta = SnapshotDelta(snap)
    space_id = snap.space_id
    cap_v = snap.cap_v
    tomb: List[int] = []      # flat canonical indices to clear
    untomb: List[int] = []    # flat canonical indices to restore
    for ent in entries:
        if ent[0] == "e":
            _, part, src, etype, rank, dst, row = ent
            p0 = part - 1
            if not (0 <= p0 < snap.num_parts):
                return False
            shard = snap.shards[p0]
            visible = row is not None
            props = None
            if visible:
                props = _decode_props(sm, space_id, "e", abs(etype), row,
                                      now)
                if props is None:
                    visible = False      # TTL-expired / undecodable
            src_loc = snap.locate(src)
            canon = None
            if src_loc is not None and src_loc[0] == p0 \
                    and src_loc[1] < shard.num_vids_base:
                canon = _canon_find(shard, src_loc[1], etype, rank, dst)
                # a dst assigned a DELTA slot can't be a canonical edge
                dst_loc0 = snap.locate(dst)
                if canon is not None and (
                        dst_loc0 is None
                        or dst_loc0[1] >= snap.shards[dst_loc0[0]].num_vids_base):
                    canon = None
            gkey = (part, src, etype, rank, dst)
            if canon is not None:
                flat = p0 * snap.cap_e + canon
                if visible:
                    if not shard.edge_valid[canon]:
                        shard.edge_valid[canon] = True
                        untomb.append(flat)
                        delta.tomb_count -= 1
                    cols = _ensure_prop_columns(snap, shard, "e", sm,
                                                space_id, etype, snap.cap_e)
                    if cols is not None:
                        _patch_prop_columns(snap, cols, canon, props, True)
                else:
                    if shard.edge_valid[canon]:
                        shard.edge_valid[canon] = False
                        tomb.append(flat)
                        delta.tomb_count += 1
                continue
            # non-canonical: delta add / delta remove
            if not visible:
                src_loc2 = snap.locate(src)
                gsrc = (src_loc2[0] * cap_v + src_loc2[1]) \
                    if src_loc2 is not None else -1
                delta.remove_edge(gkey, gsrc)
                continue
            sl = _locate_or_add(snap, src)
            dl = _locate_or_add(snap, dst)
            if sl is None or dl is None:
                return False             # spare slots exhausted: repack
            gsrc = sl[0] * cap_v + sl[1]
            gdst = dl[0] * cap_v + dl[1]
            if not delta.add_edge(gkey, gsrc, gdst, src, etype, rank, dst,
                                  props or {}):
                return False             # ELL lanes exhausted: repack
        elif ent[0] == "v":
            _, part, vid, tag, row = ent
            visible = row is not None
            props = None
            if visible:
                props = _decode_props(sm, space_id, "v", tag, row, now)
                if props is None:
                    visible = False
            loc = snap.locate(vid)
            if loc is None:
                if not visible:
                    continue             # delete of an unknown vertex
                loc = _locate_or_add(snap, vid)
                if loc is None:
                    return False
            shard = snap.shards[loc[0]]
            cols = _ensure_prop_columns(snap, shard, "v", sm, space_id,
                                        tag, cap_v)
            if cols is not None:
                _patch_prop_columns(snap, cols, loc[1], props, visible)
        else:
            return False
    if tomb or untomb:
        _apply_valid_updates(snap, tomb, untomb)
    return True


def _locate_or_add(snap, vid: int) -> Optional[Tuple[int, int]]:
    """(part0, local) of vid, assigning a spare slot in its owner shard
    when new; None when the shard is out of spare slots."""
    loc = snap.locate(vid)
    if loc is not None:
        return loc
    p0 = ku.part_id(vid, snap.num_parts) - 1
    shard = snap.shards[p0]
    local = shard.num_vids_base + len(shard.delta_vids)
    if local >= snap.cap_v:
        return None
    shard.delta_vids[vid] = local
    return (p0, local)


def _apply_valid_updates(snap, tomb: List[int], untomb: List[int]) -> None:
    """Point-update the kernel's valid masks on device (one batched
    functional update per apply; segment boundaries are unaffected
    because sorting keys ignore validity)."""
    import jax.numpy as jnp
    k = snap.kernel
    order_inv = snap.kernel_order_inv
    P = snap.num_parts
    valid = k.valid.reshape(-1)
    valid_sorted = k.valid_sorted
    if tomb:
        t = np.asarray(tomb, np.int32)
        valid = valid.at[jnp.asarray(t)].set(False)
        valid_sorted = valid_sorted.at[jnp.asarray(order_inv[t])].set(False)
    if untomb:
        u = np.asarray(untomb, np.int32)
        valid = valid.at[jnp.asarray(u)].set(True)
        valid_sorted = valid_sorted.at[jnp.asarray(order_inv[u])].set(True)
    snap.kernel = k._replace(valid=valid.reshape(P, snap.cap_e),
                             valid_sorted=valid_sorted)
    snap._aligned = None   # batched layout must see the tombstones too
