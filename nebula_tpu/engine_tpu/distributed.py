"""Multi-device traversal: shard_map over the partition axis + all_to_all.

The TPU-native replacement for the reference's scatter/gather RPC fan-out
(`StorageClient::collectResponse`, ref storage/client/StorageClient
.inl:73-160): partitions are sharded across the device mesh, each device
expands its local partitions' edges, and the cross-partition frontier
exchange that the reference does with one thrift RPC per peer host per
hop becomes ONE `lax.all_to_all` over ICI per hop — inside the same
compiled loop, no host round-trips.

Like the single-chip kernels (traverse.py), the advance is scatter-free
and gather-minimal: each device holds an EdgeKernel for ITS block of
edges (`build_kernel(..., num_blocks=D)`) whose dst-sorted copies were
permuted on the host at build time, so its contribution to every
partition's next frontier is ONE [E_local] gather + cumsum + two
[P*cap_v] boundary gathers. The [P*cap_v] hit vector is then split
into per-device blocks and transposed with all_to_all; the receiving
device ORs the D contributions into its local frontier.

Layout: with P partitions over D devices (P % D == 0), device d owns the
contiguous partition block [d*P/D, (d+1)*P/D). This mirrors how the
scaling-book recipe maps sharded SpMV: annotate shardings, let XLA
insert the collective, keep the loop on device.
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .shard_compat import pvary, shard_map
from .traverse import (LANES, AlignedKernel, EdgeKernel, _deg_req,
                       _edge_ok, _packed_hits, _packed_src_eff, hop_hits)

AXIS = "parts"


def make_mesh(devices: Optional[List] = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (AXIS,))


def _local_hits(frontier, k: EdgeKernel, ok_sorted):
    """One hop on one device's partition block: the full-space hit
    vector (this device's contribution to every partition) plus the
    hop's local active-edge count.

    frontier: bool[localP, cap_v]; k: this block's EdgeKernel
    -> (hits bool[P*cap_v], active_count int32)
    """
    return hop_hits(frontier, k.src_sorted, ok_sorted,
                    k.seg_starts, k.seg_ends)


def _exchange(flat_hits, num_devices, local_block):
    """all_to_all transpose: [P*cap_v] hits -> OR-reduced local frontier."""
    by_dev = flat_hits.reshape(num_devices, local_block)
    recv = lax.all_to_all(by_dev[None], AXIS, split_axis=1, concat_axis=0)
    # recv: [D, 1, local_block] — contributions from every device
    return recv.reshape(num_devices, local_block).any(axis=0)


# The shard_map'd kernels are built ONCE per (mesh, partition split)
# and jit-cached — a per-call closure would defeat jax.jit's cache and
# recompile on every query (the single-chip kernels get this for free
# from module-level @jax.jit).

@lru_cache(maxsize=64)
def _multi_hop_fn(mesh: Mesh, num_devices: int, parts_per_dev: int,
                  cap_v: int):
    local_block = parts_per_dev * cap_v

    @partial(shard_map, mesh=mesh,
             in_specs=(P(AXIS), None, P(AXIS), None),
             out_specs=(P(AXIS), P(AXIS)))
    def run(frontier, steps_, kern_, req):
        k = jax.tree.map(lambda a: a[0], kern_)  # drop block dim
        ok_sorted = _edge_ok(k.etype_sorted, k.valid_sorted, req)

        def body(_, f):
            hits, _n = _local_hits(f, k, ok_sorted)
            nxt = _exchange(hits, num_devices, local_block)
            return nxt.reshape(parts_per_dev, cap_v)

        f = lax.fori_loop(0, steps_ - 1, body, frontier)
        edge_ok = _edge_ok(k.etype, k.valid, req)
        final_active = jnp.take_along_axis(f, k.src, axis=1) & edge_ok
        return f, final_active

    return jax.jit(run)


def multi_hop_sharded(mesh: Mesh, frontier0, steps, kern: EdgeKernel,
                      req_types) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Distributed GO: returns (final_frontier [P,cap_v], final_active
    [P,cap_e] in canonical edge order), both sharded over the mesh
    partition axis.

    kern comes from stack_kernels(build_kernel(..., num_blocks=D)) —
    every field carries a leading per-device block dim. P must divide
    by mesh size.
    """
    num_devices = mesh.devices.size
    num_parts, cap_v = frontier0.shape
    assert num_parts % num_devices == 0
    fn = _multi_hop_fn(mesh, num_devices, num_parts // num_devices, cap_v)
    return fn(frontier0, steps, kern, req_types)


@lru_cache(maxsize=64)
def _count_fn(mesh: Mesh, num_devices: int, parts_per_dev: int,
              cap_v: int):
    local_block = parts_per_dev * cap_v

    @partial(shard_map, mesh=mesh,
             in_specs=(P(AXIS), None, P(AXIS), None),
             out_specs=P())
    def run(frontier, steps_, kern_, req):
        k = jax.tree.map(lambda a: a[0], kern_)
        ok_sorted = _edge_ok(k.etype_sorted, k.valid_sorted, req)

        def body(_, state):
            f, total = state
            hits, n = _local_hits(f, k, ok_sorted)
            total = total + n.astype(jnp.int64)
            nxt = _exchange(hits, num_devices, local_block)
            return nxt.reshape(parts_per_dev, cap_v), total

        # the carry must start device-varying to match the loop output
        # (shard_map vma typing)
        zero = pvary(jnp.zeros((), jnp.int64), (AXIS,))
        _, total = lax.fori_loop(0, steps_, body, (frontier, zero))
        return lax.psum(total, AXIS)

    return jax.jit(run)


def multi_hop_count_sharded(mesh: Mesh, frontier0, steps, kern: EdgeKernel,
                            req_types) -> jnp.ndarray:
    """Distributed total-edges-traversed counter (bench metric)."""
    num_devices = mesh.devices.size
    num_parts, cap_v = frontier0.shape
    assert num_parts % num_devices == 0
    fn = _count_fn(mesh, num_devices, num_parts // num_devices, cap_v)
    return fn(frontier0, steps, kern, req_types)


@lru_cache(maxsize=64)
def _bfs_dist_fn(mesh: Mesh, num_devices: int, parts_per_dev: int,
                 cap_v: int):
    local_block = parts_per_dev * cap_v

    @partial(shard_map, mesh=mesh,
             in_specs=(P(AXIS), None, P(AXIS), None),
             out_specs=P(AXIS))
    def run(frontier, steps_, kern_, req):
        k = jax.tree.map(lambda a: a[0], kern_)
        ok_sorted = _edge_ok(k.etype_sorted, k.valid_sorted, req)
        dist0 = jnp.where(frontier, 0, -1).astype(jnp.int32)

        def cond(state):
            f, _dist, step = state
            alive = lax.psum(f.any().astype(jnp.int32), AXIS) > 0
            return (step < steps_) & alive

        def body(state):
            f, dist, step = state
            hits, _n = _local_hits(f, k, ok_sorted)
            nxt = _exchange(hits, num_devices, local_block)
            nxt = nxt.reshape(parts_per_dev, cap_v)
            fresh = nxt & (dist < 0)
            dist = jnp.where(fresh, step + 1, dist)
            return fresh, dist, step + 1

        # step must start device-varying to match the loop's carry
        # typing under shard_map (same vma rule as the count kernel)
        step0 = pvary(jnp.int32(0), (AXIS,))
        _, dist, _ = lax.while_loop(cond, body, (frontier, dist0, step0))
        return dist

    return jax.jit(run)


def bfs_dist_sharded(mesh: Mesh, frontier0, max_steps, kern: EdgeKernel,
                     req_types) -> jnp.ndarray:
    """Distributed BFS depth map (shortest-path primitive): dist[p, v] =
    first step at which v was reached (0 for sources, -1 unreached),
    sharded over the mesh partition axis. Termination is a global
    psum'd frontier-emptiness test, so every device exits the
    while_loop on the same step."""
    num_devices = mesh.devices.size
    num_parts, cap_v = frontier0.shape
    assert num_parts % num_devices == 0
    fn = _bfs_dist_fn(mesh, num_devices, num_parts // num_devices, cap_v)
    return fn(frontier0, max_steps, kern, req_types)


@lru_cache(maxsize=64)
def _batch_count_fn(mesh: Mesh, num_devices: int, n_slots: int,
                    chunk: int, group: int):
    """Distributed form of the flagship batched counter
    (traverse.multi_hop_count_batch_packed): the [n_slots+1, 128]
    frontier matrix is REPLICATED (154MB at SNB scale — data-parallel
    replication, not sharding), each device takes a packed hop over its
    OWN aligned edge block, per-hop frontier merge is one elementwise
    pmax over the hit matrix (the OR across devices), and per-lane
    counts come from the device-local out-degrees psum'd at the end —
    the same collective shape the scaling-book recipe gives a
    replicated-activation sharded-weight matmul."""

    @partial(shard_map, mesh=mesh,
             in_specs=(None, None, P(AXIS), None),
             out_specs=P())
    def run(F0, steps_, ak_, req):
        ak = jax.tree.map(lambda a: a[0], ak_)   # this device's block
        src_eff = _packed_src_eff(ak, req, n_slots, chunk, group)
        deg_req = _deg_req(ak, req)              # block-local degrees
        g_idx = ak.cbound // group
        j_idx = ak.cbound % group

        def body(_, state):
            f, total = state
            cnt = (f[:n_slots].astype(jnp.int32)
                   * deg_req[:, None]).sum(axis=0, dtype=jnp.int32)
            total = total + cnt.astype(jnp.int64)
            hits = _packed_hits(f, src_eff, g_idx, j_idx, n_slots,
                                chunk, group).astype(jnp.int8)
            merged = lax.pmax(hits, AXIS)        # OR across devices
            return jnp.pad(merged, ((0, 1), (0, 0))), total

        # the frontier carry stays axis-INVARIANT: pmax's merge output
        # is identical on every device; only the count is varying
        zero = pvary(jnp.zeros((LANES,), jnp.int64), (AXIS,))
        _, total = lax.fori_loop(0, steps_, body, (F0, zero))
        return lax.psum(total, AXIS)

    return jax.jit(run)


def multi_hop_count_batch_sharded(mesh: Mesh, frontiers0, steps,
                                  ak: AlignedKernel, req_types,
                                  chunk: int, group: int) -> jnp.ndarray:
    """Distributed batched GO counter: frontiers0 bool[B, P, cap_v]
    (B <= 128), ak from traverse.build_aligned_blocks stacked with a
    leading per-device dim sharded over the mesh. -> int64[B]."""
    B, num_parts, cap_v = frontiers0.shape
    if B > LANES:
        raise ValueError(f"batch {B} > {LANES} lanes per dispatch")
    ns = num_parts * cap_v
    F = np.zeros((ns + 1, LANES), np.int8)
    F[:ns, :B] = np.asarray(frontiers0).reshape(B, -1).T
    fn = _batch_count_fn(mesh, mesh.devices.size, ns, chunk, group)
    return fn(jnp.asarray(F), steps, ak, req_types)[:B]


def shard_snapshot_arrays(mesh: Mesh, snap) -> "EdgeKernel":
    """Build the per-device-block EdgeKernel for a CsrSnapshot and place
    it with the mesh sharding (leading block dim sharded over AXIS);
    also attaches it as snap.sharded_kernel."""
    from .traverse import build_kernel, stack_kernels
    sharding = NamedSharding(mesh, P(AXIS))
    D = mesh.devices.size
    kerns = build_kernel(*snap._np_edge_stacks(), snap.np_gidx,
                         snap.num_parts, snap.cap_v, num_blocks=D)
    kern = stack_kernels(kerns)
    kern = jax.tree.map(lambda a: jax.device_put(a, sharding), kern)
    snap.sharded_kernel = kern
    return kern


def shard_aligned_blocks(mesh: Mesh, snap):
    """Per-device-block aligned layouts for the batched counter, placed
    with the mesh sharding: -> (AlignedKernel[D, ...], chunk, group)."""
    from .traverse import build_aligned_blocks
    D = mesh.devices.size
    num_parts, cap_v, cap_e = snap.num_parts, snap.cap_v, snap.cap_e
    assert num_parts % D == 0
    if snap.delta is not None and snap.delta.edge_count > 0:
        # same contract as CsrSnapshot.aligned_kernel: the aligned
        # layouts cover only canonical edges — counting over a snapshot
        # with pending delta ADDs would silently miss them
        raise RuntimeError(
            "shard_aligned_blocks does not include delta-buffer edges; "
            "repack the snapshot or use the per-query kernels")
    gsrc, etype, gdst = snap._flat_canonical_edges()
    block_of = np.repeat(np.arange(num_parts) // (num_parts // D), cap_e)
    ak, chunk, group = build_aligned_blocks(gsrc, etype, gdst,
                                            num_parts * cap_v, D, block_of)
    sharding = NamedSharding(mesh, P(AXIS))
    ak = jax.tree.map(lambda a: jax.device_put(a, sharding), ak)
    return ak, chunk, group
