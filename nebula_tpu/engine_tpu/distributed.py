"""Multi-device traversal: shard_map over the partition axis + all_to_all.

The TPU-native replacement for the reference's scatter/gather RPC fan-out
(`StorageClient::collectResponse`, ref storage/client/StorageClient
.inl:73-160): partitions are sharded across the device mesh, each device
expands its local partitions' edges, and the cross-partition frontier
exchange that the reference does with one thrift RPC per peer host per
hop becomes ONE `lax.all_to_all` over ICI per hop — inside the same
compiled loop, no host round-trips.

Like the single-chip kernels (traverse.py), the advance is scatter-free:
each device holds a static dst-sort permutation over ITS block of
edges (`build_segments(..., num_blocks=D)`), so its contribution to
every partition's next frontier is one permute-gather + cumsum + two
[P*cap_v] boundary gathers — linear in local edges + global vertex
slots. The [P*cap_v] hit vector is then split into per-device blocks
and transposed with all_to_all; the receiving device ORs the D
contributions into its local frontier.

Layout: with P partitions over D devices (P % D == 0), device d owns the
contiguous partition block [d*P/D, (d+1)*P/D). This mirrors how the
scaling-book recipe maps sharded SpMV: annotate shardings, let XLA
insert the collective, keep the loop on device.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "parts"


def make_mesh(devices: Optional[List] = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (AXIS,))


def _local_hits(frontier, edge_src, edge_ok, order, seg_starts, seg_ends):
    """One hop on one device's partition block: the full-space hit
    vector (this device's contribution to every partition) plus the
    local active-edge mask.

    frontier: bool[localP, cap_v]; order: int32[1, localP*cap_e];
    seg_*: int32[1, P*cap_v]
    -> (hits bool[P*cap_v], active bool[localP, cap_e])
    """
    active = jnp.take_along_axis(frontier, edge_src, axis=1) & edge_ok
    flat = active.reshape(-1)[order[0]]
    S0 = jnp.pad(jnp.cumsum(flat.astype(jnp.int32)), (1, 0))
    return (S0[seg_ends[0]] - S0[seg_starts[0]]) > 0, active


def _exchange(flat_hits, num_devices, local_block):
    """all_to_all transpose: [P*cap_v] hits -> OR-reduced local frontier."""
    by_dev = flat_hits.reshape(num_devices, local_block)
    recv = lax.all_to_all(by_dev[None], AXIS, split_axis=1, concat_axis=0)
    # recv: [D, 1, local_block] — contributions from every device
    return recv.reshape(num_devices, local_block).any(axis=0)


def multi_hop_sharded(mesh: Mesh, frontier0, steps, edge_src, edge_etype,
                      edge_valid, order, seg_starts, seg_ends, req_types
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Distributed GO: returns (final_frontier [P,cap_v], final_active
    [P,cap_e] in canonical edge order), both sharded over the mesh
    partition axis.

    Edge arrays are global [P, ...]; order/seg_starts/seg_ends come from
    build_segments(gidx, P, cap_v, num_blocks=D) — one row per device.
    P must divide by mesh size.
    """
    num_devices = mesh.devices.size
    num_parts, cap_v = frontier0.shape
    assert num_parts % num_devices == 0
    parts_per_dev = num_parts // num_devices
    local_block = parts_per_dev * cap_v

    from jax import shard_map

    @partial(shard_map, mesh=mesh,
             in_specs=(P(AXIS), None, P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                       P(AXIS), P(AXIS), None),
             out_specs=(P(AXIS), P(AXIS)))
    def run(frontier, steps_, src, etype, valid, order_, starts, ends, req):
        edge_ok = (etype[None] == req[:, None, None]).any(0) & valid

        def body(_, f):
            hits, _active = _local_hits(f, src, edge_ok, order_, starts, ends)
            nxt = _exchange(hits, num_devices, local_block)
            return nxt.reshape(parts_per_dev, cap_v)

        f = lax.fori_loop(0, steps_ - 1, body, frontier)
        final_active = jnp.take_along_axis(f, src, axis=1) & edge_ok
        return f, final_active

    return jax.jit(run)(frontier0, steps, edge_src, edge_etype, edge_valid,
                        order, seg_starts, seg_ends, req_types)


def multi_hop_count_sharded(mesh: Mesh, frontier0, steps, edge_src,
                            edge_etype, edge_valid, order, seg_starts,
                            seg_ends, req_types) -> jnp.ndarray:
    """Distributed total-edges-traversed counter (bench metric)."""
    num_devices = mesh.devices.size
    num_parts, cap_v = frontier0.shape
    assert num_parts % num_devices == 0
    parts_per_dev = num_parts // num_devices
    local_block = parts_per_dev * cap_v

    from jax import shard_map

    @partial(shard_map, mesh=mesh,
             in_specs=(P(AXIS), None, P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                       P(AXIS), P(AXIS), None),
             out_specs=P())
    def run(frontier, steps_, src, etype, valid, order_, starts, ends, req):
        edge_ok = (etype[None] == req[:, None, None]).any(0) & valid

        def body(_, state):
            f, total = state
            hits, active = _local_hits(f, src, edge_ok, order_, starts, ends)
            total = total + active.sum(dtype=jnp.int64)
            nxt = _exchange(hits, num_devices, local_block)
            return nxt.reshape(parts_per_dev, cap_v), total

        # the carry must start device-varying to match the loop output
        # (shard_map vma typing)
        zero = lax.pcast(jnp.zeros((), jnp.int64), (AXIS,), to="varying")
        _, total = lax.fori_loop(0, steps_, body, (frontier, zero))
        return lax.psum(total, AXIS)

    return jax.jit(run)(frontier0, steps, edge_src, edge_etype, edge_valid,
                        order, seg_starts, seg_ends, req_types)


def shard_snapshot_arrays(mesh: Mesh, snap) -> None:
    """Re-place a CsrSnapshot's device arrays with the mesh sharding and
    attach per-device block segments (d_border/d_bseg_starts/
    d_bseg_ends) so the sharded kernels consume them without host
    transfers."""
    from .traverse import build_segments
    sharding = NamedSharding(mesh, P(AXIS))
    D = mesh.devices.size
    order, starts, ends = build_segments(snap.np_gidx, snap.num_parts,
                                         snap.cap_v, num_blocks=D)
    snap.d_border = jax.device_put(jnp.asarray(order), sharding)
    snap.d_bseg_starts = jax.device_put(jnp.asarray(starts), sharding)
    snap.d_bseg_ends = jax.device_put(jnp.asarray(ends), sharding)
    snap.d_edge_src = jax.device_put(snap.d_edge_src, sharding)
    snap.d_edge_etype = jax.device_put(snap.d_edge_etype, sharding)
    snap.d_edge_valid = jax.device_put(snap.d_edge_valid, sharding)
    snap.d_edge_gidx = jax.device_put(snap.d_edge_gidx, sharding)
